"""Multi-tenant serving (ISSUE 17): the batched-LoRA bgmv kernel,
int8-quantized paged KV, adapter hot-swap lifecycle and per-tenant
quota — each behind its own kill switch with the flags-off path as the
bit-compatible / token-exact oracle, plus the composed fuzz drill
(quant + radix donation + COW + speculative rollback + drain/resume)
and the bench-gate direction pins for the new units."""

import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import no_grad
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.ops import pallas as pallas_ops
from paddle_tpu.serving import (LoadSpec, Request, SamplingParams,
                                ServingConfig, ServingEngine,
                                build_requests, load_drain_snapshot,
                                requests_from_snapshot)
from paddle_tpu.serving.kv_cache import (PagedKVCache, dequant_pages,
                                         gather_pages, gather_pages_quant,
                                         write_pages, write_pages_quant)
from paddle_tpu.serving.lora import LoRAManager, save_adapter_checkpoint

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


def _engine(model, **kw):
    cfg = dict(max_batch_slots=3, block_size=4, max_context_len=64,
               prefill_buckets=(8, 16), batch_buckets=(1, 2))
    cfg.update(kw)
    return ServingEngine(model, ServingConfig(**cfg))


def _golden(model, prompt, n):
    seq = np.asarray(prompt, np.int32)
    for _ in range(n):
        with no_grad():
            lg = model(paddle.to_tensor(seq[None, :])).numpy()
        seq = np.concatenate([seq, [np.int32(lg[0, -1].argmax())]])
    return seq


def _adapter(rng, rank=4, scale=0.5, L=2, E=64, O=192):
    """gpt_tiny-shaped (a, b) weights; scale 0.5 is large enough to
    flip greedy argmaxes (pinned below), tiny enough to stay finite."""
    return (rng.standard_normal((L, rank, E)).astype(np.float32) * scale,
            rng.standard_normal((L, rank, O)).astype(np.float32) * scale)


# ---------------------------------------------------------------------------
# bgmv kernel: oracle math, parity, zero row, kill switch
# ---------------------------------------------------------------------------


def _bgmv_ref(x, a, b, ids):
    out = np.zeros((x.shape[0], x.shape[1], b.shape[2]), np.float32)
    for i, ad in enumerate(ids):
        out[i] = (x[i].astype(np.float64) @ a[ad].T.astype(np.float64)
                  @ b[ad].astype(np.float64)).astype(np.float32)
    return out


def _bgmv_inputs(rng, B=4, S=2, E=32, r=4, O=24, A=3):
    x = rng.standard_normal((B, S, E)).astype(np.float32)
    a = rng.standard_normal((A, r, E)).astype(np.float32)
    b = rng.standard_normal((A, r, O)).astype(np.float32)
    a[0] = b[0] = 0.0                   # the reserved zero adapter
    ids = rng.integers(0, A, (B,)).astype(np.int32)
    return x, a, b, ids


def test_bgmv_xla_oracle_matches_per_row_math():
    from paddle_tpu.ops.pallas.bgmv import bgmv_xla
    rng = np.random.default_rng(0)
    x, a, b, ids = _bgmv_inputs(rng)
    got = np.asarray(bgmv_xla(jnp.asarray(x), jnp.asarray(a),
                              jnp.asarray(b), jnp.asarray(ids)))
    np.testing.assert_allclose(got, _bgmv_ref(x, a, b, ids),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.pallas
def test_bgmv_kernel_parity_with_oracle():
    from paddle_tpu.ops.pallas.bgmv import bgmv, bgmv_xla
    rng = np.random.default_rng(1)
    for B, S, E, r, O, A in ((4, 1, 32, 4, 24, 5), (3, 2, 64, 8, 48, 2)):
        x, a, b, ids = _bgmv_inputs(rng, B, S, E, r, O, A)
        args = tuple(jnp.asarray(t) for t in (x, a, b, ids))
        np.testing.assert_allclose(
            np.asarray(bgmv(*args)), np.asarray(bgmv_xla(*args)),
            rtol=1e-5, atol=1e-5)


@pytest.mark.pallas
def test_bgmv_zero_row_delta_is_exactly_zero():
    """Row 0 is the reserved zero adapter: base-model slots in a mixed
    batch contribute a delta of exactly 0.0, both paths."""
    from paddle_tpu.ops.pallas.bgmv import bgmv, bgmv_xla
    rng = np.random.default_rng(2)
    x, a, b, _ = _bgmv_inputs(rng)
    ids = jnp.zeros((x.shape[0],), jnp.int32)
    for fn in (bgmv, bgmv_xla):
        out = np.asarray(fn(jnp.asarray(x), jnp.asarray(a),
                            jnp.asarray(b), ids))
        assert (out == 0.0).all()


def test_bgmv_kill_switch_counted():
    with flag_scope("pallas_interpret", True), \
            flag_scope("pallas_bgmv", False):
        assert not pallas_ops.kernel_enabled("bgmv")
    assert ("bgmv", "flag_off") in pallas_ops.PALLAS_STATS
    # CPU backend without the interpreter (the tier-1 default): fallback
    assert not pallas_ops.kernel_enabled("bgmv")
    assert ("bgmv", "cpu_backend") in pallas_ops.PALLAS_STATS


# ---------------------------------------------------------------------------
# int8 paged-KV quantization primitives
# ---------------------------------------------------------------------------


def _quant_state(rng, B=2, n=(7, 3), P=8, bs=4, H=2, D=8):
    MB = 4
    tbl = np.zeros((B, MB), np.int32)
    tbl[0, :2] = [1, 2]
    tbl[1, :1] = [3]
    new = [rng.standard_normal((1, n[b], H, D)).astype(np.float32) * 3
           for b in range(B)]
    return tbl, new, P, bs, H, D


def test_write_pages_quant_round_trip_error_bound():
    """Per-(position, head) absmax int8: dequantized values sit within
    half a quantization step (absmax/127/2 per position+head row)."""
    rng = np.random.default_rng(3)
    tbl, new, P, bs, H, D = _quant_state(rng)
    pages = jnp.zeros((P, bs, H, D), jnp.int8)
    scales = jnp.zeros((P, bs, H), jnp.float32)
    for b in range(2):
        pages, scales = write_pages_quant(
            pages, scales, jnp.asarray(new[b]),
            jnp.asarray(tbl[b:b + 1]), jnp.zeros((1,), jnp.int32))
    deq = np.asarray(dequant_pages(pages, scales))
    for b, blocks in ((0, [1, 2]), (1, [3])):
        x = new[b][0]                                   # [n, H, D]
        nb = len(blocks)
        got = np.concatenate([deq[p] for p in blocks])[:x.shape[0]]
        step = np.abs(x).max(axis=-1, keepdims=True) / 127.0
        assert (np.abs(got - x) <= step * 0.5 + 1e-7).all()
        assert nb * bs >= x.shape[0]


def test_gather_pages_quant_matches_dequant_then_gather():
    rng = np.random.default_rng(4)
    tbl, new, P, bs, H, D = _quant_state(rng)
    pages = jnp.zeros((P, bs, H, D), jnp.int8)
    scales = jnp.zeros((P, bs, H), jnp.float32)
    for b in range(2):
        pages, scales = write_pages_quant(
            pages, scales, jnp.asarray(new[b]),
            jnp.asarray(tbl[b:b + 1]), jnp.zeros((1,), jnp.int32))
    got = np.asarray(gather_pages_quant(pages, scales, jnp.asarray(tbl)))
    ref = np.asarray(gather_pages(dequant_pages(pages, scales),
                                  jnp.asarray(tbl)))
    np.testing.assert_array_equal(got, ref)


def test_quant_cache_pools_and_footprint_accounting():
    """FLAGS_serve_kv_quant=int8 at construction: pools become
    (int8 pages, f32 scales) tuples and kv_bytes_per_token() accounts
    pages + scales; flags off: plain full-precision arrays."""
    mk = lambda: PagedKVCache(2, 4, 16, num_pages=6, block_size=4,
                              max_slots=2, max_blocks_per_slot=4)
    with flag_scope("serve_kv_quant", "int8"):
        qc = mk()
    assert qc.quant == "int8"
    assert isinstance(qc.k, tuple) and qc.k[0].dtype == jnp.int8
    assert qc.k[1].dtype == jnp.float32
    # 2 (k+v) * L * (H*D int8 + H f32 scales)
    assert qc.kv_bytes_per_token() == 2 * 2 * (4 * 16 + 4 * 4)
    fc = mk()
    assert fc.quant == "" and not isinstance(fc.k, tuple)
    assert fc.kv_bytes_per_token() == 2 * 2 * 4 * 16 * fc.k.dtype.itemsize
    assert qc.kv_bytes_per_token() < 0.4 * fc.kv_bytes_per_token()
    with flag_scope("serve_kv_quant", "fp4"), \
            pytest.raises(ValueError, match="serve_kv_quant"):
        mk()


# ---------------------------------------------------------------------------
# LoRAManager lifecycle
# ---------------------------------------------------------------------------


def test_lora_manager_load_unload_refcount():
    rng = np.random.default_rng(5)
    mgr = LoRAManager(2, 64, 192, max_adapters=2, rank=4)
    r1 = mgr.load_adapter("t0/a", weights=_adapter(rng))
    r2 = mgr.load_adapter("t1/b", weights=_adapter(rng))
    assert (r1, r2) == (1, 2) and mgr.num_loaded == 2
    assert mgr.load_adapter("t0/a", weights=_adapter(rng)) == r1  # no-op
    assert mgr.swaps == 2
    # pool full
    with pytest.raises(RuntimeError, match="pool full"):
        mgr.load_adapter("t2/c", weights=_adapter(rng))
    # held adapters refuse to unload
    assert mgr.acquire("t0/a") == r1
    with pytest.raises(RuntimeError, match="referenced"):
        mgr.unload_adapter("t0/a")
    mgr.release("t0/a")
    mgr.unload_adapter("t0/a")
    assert mgr.row("t0/a") is None
    # the freed row is zeroed: a stale id selects the zero delta
    assert float(jnp.abs(mgr.a[:, r1]).max()) == 0.0
    assert float(jnp.abs(mgr.b[:, r1]).max()) == 0.0
    assert mgr.load_adapter("t2/c", weights=_adapter(rng)) == r1  # reused
    with pytest.raises(RuntimeError, match="without a live reference"):
        mgr.release("t1/b")
    # rows_for maps None -> the zero adapter
    rows = np.asarray(mgr.rows_for([None, "t1/b", "t2/c"]))
    np.testing.assert_array_equal(rows, [0, r2, r1])


def test_lora_manager_rejects_bad_shapes_and_sources():
    rng = np.random.default_rng(6)
    mgr = LoRAManager(2, 64, 192, max_adapters=1, rank=4)
    a, b = _adapter(rng)
    with pytest.raises(ValueError, match="this manager serves"):
        mgr.load_adapter("bad", weights=(a[:, :2], b))
    with pytest.raises(ValueError, match="exactly one"):
        mgr.load_adapter("bad", weights=(a, b), path="/nope")
    assert mgr.num_loaded == 0          # nothing partially loaded


def test_lora_checkpoint_round_trip_and_atomic_fail(tmp_path):
    rng = np.random.default_rng(7)
    a, b = _adapter(rng)
    path = str(tmp_path / "adapter")
    save_adapter_checkpoint(path, a, b)
    mgr = LoRAManager(2, 64, 192, max_adapters=1, rank=4)
    row = mgr.load_adapter("ck", path=path)
    np.testing.assert_allclose(np.asarray(mgr.a[:, row]), a, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(mgr.b[:, row]), b, rtol=1e-6)
    # a torn checkpoint fails manifest verification BEFORE the pools
    # mutate (the ckpt.write.torn failure mode: a data file lost its
    # tail after its size was recorded)
    mgr.unload_adapter("ck")
    import os
    from paddle_tpu.distributed.checkpoint import read_manifest
    files = read_manifest(path)["files"]
    victim = max(files, key=lambda r: files[r]["size"])
    with open(os.path.join(path, victim), "r+b") as f:
        f.truncate(files[victim]["size"] // 2)
    with pytest.raises(ValueError, match="verification"):
        mgr.load_adapter("ck", path=path)
    assert mgr.num_loaded == 0
    assert float(jnp.abs(mgr.a).max()) == 0.0


# ---------------------------------------------------------------------------
# engine: kv-quant parity, LoRA identity/effect, flags-off pins
# ---------------------------------------------------------------------------


def _prompts(rng, k=3):
    return [rng.integers(2, 250, (int(n),)).tolist()
            for n in rng.integers(5, 14, (k,))]


def test_kv_quant_greedy_token_parity(tiny_model):
    """Greedy decode under FLAGS_serve_kv_quant=int8 is token-identical
    to the full-precision oracle on the bench-sized workload (the
    documented acceptance bound: token parity, not bitwise logits)."""
    prompts = _prompts(np.random.default_rng(8))
    off = _engine(tiny_model)
    ref = [o.tolist() for o in off.generate(prompts, max_new_tokens=8)]
    off.shutdown()
    assert ref[0][-8:] == _golden(tiny_model, prompts[0], 8)[-8:].tolist()
    with flag_scope("serve_kv_quant", "int8"):
        q = _engine(tiny_model)
    got = [o.tolist() for o in q.generate(prompts, max_new_tokens=8)]
    q.shutdown()
    assert got == ref


def test_flags_off_engine_is_bit_identical_pre_pr(tiny_model):
    """Defaults = pre-ISSUE-17 engine: plain ndarray pools, no LoRA
    manager, empty lora program signature, and greedy outputs equal the
    step-by-step golden."""
    eng = _engine(tiny_model)
    assert eng.cache.quant == "" and not isinstance(eng.cache.k, tuple)
    assert eng.lora is None
    assert eng._lora_sig(3) == () and eng._lora_args([None] * 3) == ()
    prompts = _prompts(np.random.default_rng(9), k=2)
    outs = eng.generate(prompts, max_new_tokens=6)
    eng.shutdown()
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(o, _golden(tiny_model, p, 6))


def test_lora_zero_adapter_and_quant_compose_to_identity(tiny_model):
    """Base (adapter-less) requests on a LoRA+quant engine ride the
    zero adapter: outputs match the plain engine token for token."""
    prompts = _prompts(np.random.default_rng(10))
    plain = _engine(tiny_model)
    ref = [o.tolist() for o in plain.generate(prompts, max_new_tokens=8)]
    plain.shutdown()
    with flag_scope("serve_kv_quant", "int8"):
        eng = _engine(tiny_model, lora_adapters=2, lora_rank=4)
    eng.lora.load_adapter("t0/a", weights=_adapter(
        np.random.default_rng(11)))
    got = [o.tolist() for o in eng.generate(prompts, max_new_tokens=8)]
    eng.shutdown()
    assert got == ref


def test_adapter_requests_change_outputs_and_release_refs(tiny_model):
    rng = np.random.default_rng(12)
    prompts = _prompts(rng)
    eng = _engine(tiny_model, lora_adapters=2, lora_rank=4)
    eng.lora.load_adapter("t0/a", weights=_adapter(rng))
    base = [eng.submit(Request(p, max_new_tokens=6)) for p in prompts]
    tuned = [eng.submit(Request(p, max_new_tokens=6, adapter="t0/a"))
             for p in prompts]
    eng.run()
    assert all(st.outcome == "completed" for st in base + tuned)
    b = [st.generated for st in base]
    t = [st.generated for st in tuned]
    assert b != t                       # the adapter really decodes
    for p, st in zip(prompts, base):    # base slots: exact zero delta
        np.testing.assert_array_equal(
            np.asarray(st.generated), _golden(tiny_model, p, 6)[len(p):])
    # every slot reference was released at termination -> unload works
    assert eng.lora.refcount("t0/a") == 0
    eng.lora.unload_adapter("t0/a")
    eng.shutdown()


def test_unknown_adapter_rejected_at_submit(tiny_model):
    eng = _engine(tiny_model, lora_adapters=1)
    with pytest.raises(ValueError, match="not loaded"):
        eng.submit(Request([1, 2, 3], adapter="nope"))
    plain = _engine(tiny_model)
    with pytest.raises(ValueError, match="no LoRA manager"):
        plain.submit(Request([1, 2, 3], adapter="any"))
    eng.shutdown()
    plain.shutdown()


def test_adapter_unloaded_between_submit_and_admission_fails_loudly(
        tiny_model):
    eng = _engine(tiny_model, lora_adapters=1, lora_rank=4)
    eng.lora.load_adapter("t0/a", weights=_adapter(
        np.random.default_rng(13)))
    st = eng.submit(Request([5, 6, 7], max_new_tokens=4, adapter="t0/a"))
    eng.lora.unload_adapter("t0/a")     # not yet admitted: refcount 0
    eng.run()
    assert st.outcome == "failed"
    eng.shutdown()


# ---------------------------------------------------------------------------
# per-tenant quota
# ---------------------------------------------------------------------------


def test_tenant_quota_caps_slots_without_starving_others(tiny_model):
    eng = _engine(tiny_model, tenant_quota=1)
    sched = eng.scheduler
    a = [eng.submit(Request([2 + i, 3, 4], max_new_tokens=6, tenant="a"))
         for i in range(3)]
    b = eng.submit(Request([9, 10, 11], max_new_tokens=6, tenant="b"))
    eng.step()
    active = [st.request.tenant for _, st in sched.active()]
    # tenant a holds exactly 1 of its 3; b admitted PAST the blocked a's
    assert active.count("a") == 1 and active.count("b") == 1
    assert sched.tenant_deferrals.get("a", 0) > 0
    assert "b" not in sched.tenant_deferrals
    eng.run()
    assert all(st.outcome == "completed" for st in a + [b])
    assert sched.stats["quota_deferred"] == sum(
        sched.tenant_deferrals.values())
    eng.shutdown()


def test_untenanted_requests_never_quota_limited(tiny_model):
    eng = _engine(tiny_model, tenant_quota=1)
    sts = [eng.submit(Request([3 + i, 4, 5], max_new_tokens=4))
           for i in range(3)]
    eng.step()
    assert len(eng.scheduler.active()) == 3
    assert eng.scheduler.tenant_deferrals == {}
    eng.run()
    assert all(st.outcome == "completed" for st in sts)
    eng.shutdown()


# ---------------------------------------------------------------------------
# loadgen: adapter_pool rides a side RNG
# ---------------------------------------------------------------------------


def test_loadgen_adapter_pool_pin_and_side_rng():
    base = LoadSpec(num_requests=24, rate_rps=50.0, prompt_len_range=(4, 8),
                    seed=5, shared_prefix_len=8, prefix_pool_size=2,
                    tenants=3)
    import dataclasses
    armed = dataclasses.replace(base, adapter_pool=2)
    off = build_requests(base)
    on = build_requests(armed)
    # arming adapters perturbs NOTHING the default spec draws
    assert [t for t, _ in off] == [t for t, _ in on]
    for (_, r0), (_, r1) in zip(off, on):
        np.testing.assert_array_equal(r0.prompt, r1.prompt)
        assert r0.max_new_tokens == r1.max_new_tokens
        assert r0.tenant is None and r0.adapter is None   # pinned off
        assert r1.tenant is not None
        t = int(r1.tenant[len("tenant"):])
        assert r1.adapter in {f"tenant{t}/adapter{k}" for k in range(2)}
    # deterministic per seed
    again = build_requests(dataclasses.replace(base, adapter_pool=2))
    assert [r.adapter for _, r in on] == [r.adapter for _, r in again]
    with pytest.raises(ValueError, match="tenants"):
        build_requests(LoadSpec(adapter_pool=2))


# ---------------------------------------------------------------------------
# check_bench: the new units gate in the right direction
# ---------------------------------------------------------------------------


def test_check_bench_directions_for_multitenant_units():
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "..", "tools"))
    import check_bench
    assert check_bench.lower_is_better("bytes/token")
    assert check_bench.lower_is_better("bytes/slot")
    assert not check_bench.lower_is_better("adapters")
    old = [{"metric": "serve_kv_bytes_per_token", "value": 100.0,
            "unit": "bytes/token"},
           {"metric": "serve_lora_adapters_per_chip", "value": 8.0,
            "unit": "adapters"}]
    worse = [{"metric": "serve_kv_bytes_per_token", "value": 120.0,
              "unit": "bytes/token"},
             {"metric": "serve_lora_adapters_per_chip", "value": 6.0,
              "unit": "adapters"}]
    problems = check_bench.compare_common(old, worse)
    assert len(problems) == 2
    better = [{"metric": "serve_kv_bytes_per_token", "value": 80.0,
               "unit": "bytes/token"},
              {"metric": "serve_lora_adapters_per_chip", "value": 10.0,
               "unit": "adapters"}]
    assert check_bench.compare_common(old, better) == []


# ---------------------------------------------------------------------------
# monitor_report: the per-tenant table claims its series
# ---------------------------------------------------------------------------


def test_monitor_report_renders_tenant_table(tiny_model, tmp_path):
    import json
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "..", "tools"))
    import monitor_report
    from paddle_tpu.monitor import scoped_registry
    with scoped_registry() as reg, flag_scope("monitor", True):
        with flag_scope("serve_kv_quant", "int8"):
            eng = _engine(tiny_model, lora_adapters=2, lora_rank=4,
                          tenant_quota=1)
        eng.lora.load_adapter("t0/a", weights=_adapter(
            np.random.default_rng(14)))
        sts = [eng.submit(Request([7 + i, 8, 9], max_new_tokens=4,
                                  tenant="acme", adapter="t0/a"))
               for i in range(3)]
        eng.run()
        assert all(st.outcome == "completed" for st in sts)
        path = str(tmp_path / "m.jsonl")
        reg.dump_jsonl(path)
        eng.shutdown()
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    out = monitor_report.render(rows, serve=True)
    assert "Tenants" in out and "acme" in out
    assert "Multi-tenant pool (LoRA + quantized KV)" in out
    assert "LoRA adapters loaded" in out
    assert "quantized KV bytes/token" in out
    # claimed by the tenant section, NOT re-rendered by the catch-all
    assert "serve_tenant_requests_total" not in out


# ---------------------------------------------------------------------------
# the composed drill: quant + radix + COW + spec rollback + drain/resume
# ---------------------------------------------------------------------------


def test_kv_quant_composed_paths_token_exact_and_leak_free(
        tiny_model, tmp_path):
    """Seeded drill over the FULL composed surface: int8 KV + radix
    donation/COW + chunked prefill + speculative rollback
    (truncate_slot on quantized pages) + a constrained pool (forced
    eviction) + a mid-run drain/resume. Greedy outputs stay
    token-identical to the flags-off step-by-step golden and the page
    pool drains to zero — quantized pages move through every path
    unchanged."""
    rng = np.random.default_rng(42)
    prefixes = [rng.integers(2, 250, (8,)).tolist() for _ in range(2)]
    prompts = [prefixes[int(rng.integers(0, 2))]
               + rng.integers(2, 250, (int(rng.integers(2, 7)),)).tolist()
               for _ in range(6)]
    goldens = [_golden(tiny_model, p, 5) for p in prompts]

    def build():
        with flag_scope("serve_kv_quant", "int8"), \
                flag_scope("serve_prefix_cache", True), \
                flag_scope("serve_prefill_chunk", 4), \
                flag_scope("serve_spec_k", 2):
            return _engine(tiny_model, num_pages=24,
                           prefill_buckets=(4, 8, 16))
    eng = build()
    states = [eng.submit(Request(p, max_new_tokens=5)) for p in prompts]
    for _ in range(3):                  # partway in, then SIGTERM
        eng.step()
    report = eng.drain(snapshot_dir=str(tmp_path / "d"), budget_s=0.0)
    assert report.snapshotted > 0
    eng.shutdown()

    done = {tuple(st.request.prompt.tolist()): st.generated
            for st in states if st.outcome == "completed"}
    _, specs = load_drain_snapshot(str(tmp_path / "d"))
    eng2 = build()                      # successor, same composed flags
    resumed = [eng2.submit(r) for r in requests_from_snapshot(specs)]
    eng2.run()
    full = dict(done)
    for st in resumed:
        assert st.outcome == "completed"
        # the resumed effective prompt = original prompt + committed
        # tokens; stitch back to the original request
        seq = st.request.prompt.tolist() + list(st.generated)
        for p in prompts:
            if seq[:len(p)] == list(p):
                full.setdefault(tuple(p), seq[len(p):])
    for p, g in zip(prompts, goldens):
        assert full[tuple(p)] == g[len(p):].tolist(), p
    # zero page leaks: evicting the radix tree returns every page
    eng2.cache.prefix_cache.evict_for(10_000)
    assert eng2.cache.allocator.pages_in_use == 0
    eng2.shutdown()
