"""Small namespace modules: device, reader, cost_model, sysconfig,
compat, callbacks, autograd functional transforms."""

import numpy as np

import paddle_tpu as paddle


def test_device_namespace():
    assert isinstance(paddle.device.get_device(), str)
    assert "cpu" in paddle.device.get_all_device_type()
    assert paddle.device.cuda.device_count() >= 1
    paddle.device.cuda.synchronize()
    assert paddle.device.cuda.memory_allocated() >= 0


def test_reader_decorators():
    r = lambda: iter(range(10))
    assert list(paddle.reader.firstn(r, 3)()) == [0, 1, 2]
    assert list(paddle.reader.chain(r, r)()) == list(range(10)) * 2
    assert sorted(paddle.reader.shuffle(r, 5)()) == list(range(10))
    assert list(paddle.reader.map_readers(lambda a, b: a + b, r, r)()) == \
        [2 * i for i in range(10)]
    assert list(paddle.reader.buffered(r, 4)()) == list(range(10))
    c = paddle.reader.cache(r)
    assert list(c()) == list(range(10)) and list(c()) == list(range(10))
    assert list(paddle.reader.compose(r, r)()) == \
        [(i, i) for i in range(10)]
    out = list(paddle.reader.xmap_readers(lambda x: x * 3, r, 2, 4,
                                          order=True)())
    assert out == [3 * i for i in range(10)]


def test_cost_model_measures_matmul():
    import jax.numpy as jnp
    cm = paddle.cost_model.CostModel()
    a = np.ones((128, 128), np.float32)
    res = cm.profile_measure(lambda x: jnp.matmul(x, x), [a], iters=3)
    assert res["flops"] >= 2 * 128 ** 3 * 0.9
    assert res["wall_ms"] > 0


def test_compat_and_sysconfig():
    assert paddle.compat.to_text(b"abc") == "abc"
    assert paddle.compat.to_bytes("abc") == b"abc"
    assert paddle.compat.to_text([b"a", b"b"]) == ["a", "b"]
    assert isinstance(paddle.sysconfig.get_include(), str)


def test_callbacks_namespace():
    assert hasattr(paddle.callbacks, "ModelCheckpoint")
    assert hasattr(paddle.callbacks, "EarlyStopping")
