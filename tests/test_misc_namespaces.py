"""Small namespace modules: device, reader, cost_model, sysconfig,
compat, callbacks, autograd functional transforms."""

import numpy as np

import paddle_tpu as paddle


def test_device_namespace():
    assert isinstance(paddle.device.get_device(), str)
    assert "cpu" in paddle.device.get_all_device_type()
    assert paddle.device.cuda.device_count() >= 1
    paddle.device.cuda.synchronize()
    assert paddle.device.cuda.memory_allocated() >= 0


def test_reader_decorators():
    r = lambda: iter(range(10))
    assert list(paddle.reader.firstn(r, 3)()) == [0, 1, 2]
    assert list(paddle.reader.chain(r, r)()) == list(range(10)) * 2
    assert sorted(paddle.reader.shuffle(r, 5)()) == list(range(10))
    assert list(paddle.reader.map_readers(lambda a, b: a + b, r, r)()) == \
        [2 * i for i in range(10)]
    assert list(paddle.reader.buffered(r, 4)()) == list(range(10))
    c = paddle.reader.cache(r)
    assert list(c()) == list(range(10)) and list(c()) == list(range(10))
    assert list(paddle.reader.compose(r, r)()) == \
        [(i, i) for i in range(10)]
    out = list(paddle.reader.xmap_readers(lambda x: x * 3, r, 2, 4,
                                          order=True)())
    assert out == [3 * i for i in range(10)]


def test_cost_model_measures_matmul():
    import jax.numpy as jnp
    cm = paddle.cost_model.CostModel()
    a = np.ones((128, 128), np.float32)
    res = cm.profile_measure(lambda x: jnp.matmul(x, x), [a], iters=3)
    assert res["flops"] >= 2 * 128 ** 3 * 0.9
    assert res["wall_ms"] > 0


def test_compat_and_sysconfig():
    assert paddle.compat.to_text(b"abc") == "abc"
    assert paddle.compat.to_bytes("abc") == b"abc"
    assert paddle.compat.to_text([b"a", b"b"]) == ["a", "b"]
    assert isinstance(paddle.sysconfig.get_include(), str)


def test_callbacks_namespace():
    assert hasattr(paddle.callbacks, "ModelCheckpoint")
    assert hasattr(paddle.callbacks, "EarlyStopping")


def test_get_worker_info_in_workers():
    from paddle_tpu.io import DataLoader, get_worker_info

    assert get_worker_info() is None          # main process

    class DS:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            info = get_worker_info()
            assert info is not None and info.num_workers == 2
            return np.array([i, info.id], np.int64)

    dl = DataLoader(DS(), batch_size=4, num_workers=2, shuffle=False)
    seen_workers = set()
    for batch in dl:
        arr = batch.numpy() if hasattr(batch, "numpy") else \
            np.asarray(batch)
        seen_workers.update(arr.reshape(-1, 2)[:, 1].tolist())
    assert seen_workers <= {0, 1} and len(seen_workers) >= 1


def test_new_vision_transforms():
    from paddle_tpu.vision import transforms as T

    img = np.random.default_rng(0).uniform(0, 255, (3, 16, 16)) \
        .astype(np.float32)
    np.random.seed(0)
    for t in [T.ColorJitter(0.2, 0.2, 0.2, 0.1), T.Grayscale(3),
              T.RandomVerticalFlip(1.0), T.RandomRotation(30),
              T.BrightnessTransform(0.5), T.ContrastTransform(0.5),
              T.SaturationTransform(0.5), T.HueTransform(0.25)]:
        out = t(img)
        assert out.shape == img.shape, type(t).__name__
        assert np.isfinite(out).all(), type(t).__name__
    rc = T.RandomResizedCrop(8)
    out = rc(img)
    assert out.shape == (3, 8, 8)
    flipped = T.RandomVerticalFlip(1.0)(img)
    np.testing.assert_allclose(flipped, img[:, ::-1], atol=1e-6)
    gray = T.Grayscale(1)(img)
    assert gray.shape == (1, 16, 16)


def test_model_forward_and_mode():
    import paddle_tpu as paddle
    from paddle_tpu import nn

    net = nn.Linear(4, 2)
    m = paddle.Model(net)
    assert m.mode == "train"
    m.mode = "eval"
    assert not net.training
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    assert m.forward(x).shape == [2, 2]
