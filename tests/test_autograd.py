"""Eager autograd engine tests (BasicEngine analogue coverage)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import check_grad


def test_simple_backward():
    x = paddle.to_tensor([1.0, 2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_chain_and_fanout():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    a = x * 3.0
    b = x * 5.0
    y = a + b  # dy/dx = 8
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), 8.0)


def test_grad_accumulation_across_backwards():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), 5.0)


def test_stop_gradient_blocks():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = paddle.to_tensor([3.0, 4.0])  # stop_gradient default True
    z = (x * y).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3, 4])
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = (x * 2).detach()
    z = (y * x).sum()
    z.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4])


def test_no_grad_context():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient
    assert y._node is None


def test_backward_through_getitem_and_concat():
    x = paddle.to_tensor(np.ones((3, 2), np.float32), stop_gradient=False)
    y = paddle.concat([x[0:1], x[2:3]], axis=0).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(),
                               [[1, 1], [0, 0], [1, 1]])


def test_multi_output_op_backward():
    x = paddle.to_tensor(np.array([[3.0, 1.0, 2.0]], np.float32),
                         stop_gradient=False)
    vals, idx = paddle.topk(x, 2)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 0, 1]])


def test_matmul_grad_numeric():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(4, 2).astype(np.float32)
    check_grad(paddle.matmul, [a, b], input_idx=0)
    check_grad(paddle.matmul, [a, b], input_idx=1)


def test_tanh_exp_grads_numeric():
    x = np.random.randn(5).astype(np.float32) * 0.5
    check_grad(paddle.tanh, [x])
    check_grad(paddle.exp, [x])


def test_softmax_grad_numeric():
    import paddle_tpu.nn.functional as F
    x = np.random.randn(3, 5).astype(np.float32)
    check_grad(F.softmax, [x], rtol=2e-2, atol=2e-3)


def test_register_hook():
    x = paddle.to_tensor([1.0, 1.0], stop_gradient=False)
    seen = {}

    def hook(g):
        seen["grad"] = g.numpy().copy()
        return g * 2

    x.register_hook(hook)
    (x * 3).sum().backward()
    np.testing.assert_allclose(seen["grad"], [3, 3])
    np.testing.assert_allclose(x.grad.numpy(), [6, 6])


def test_paddle_grad_api():
    x = paddle.to_tensor([2.0], stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, x)
    np.testing.assert_allclose(g.numpy(), [4.0])
    assert x.grad is None  # .grad untouched


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
    y = Double.apply(x)
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 2])


def test_retain_grads_intermediate():
    x = paddle.to_tensor([1.0], stop_gradient=False)
    y = x * 2
    y.retain_grads()
    z = y * 3
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), [3.0])


def test_functional_transforms_jacobian_hessian_vjp_jvp():
    """reference: python/paddle/autograd/functional.py."""
    import numpy as np
    import paddle_tpu as paddle

    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))

    def f(a):
        return (a ** 3).sum()

    h = paddle.autograd.hessian(f, x)
    np.testing.assert_allclose(h.numpy(), np.diag(6 * np.array([1., 2., 3.])),
                               atol=1e-5)
    j = paddle.autograd.jacobian(lambda a: a ** 2, x)
    np.testing.assert_allclose(j.numpy(), np.diag([2., 4., 6.]), atol=1e-5)
    out, g = paddle.autograd.vjp(f, x)
    np.testing.assert_allclose(g.numpy(), 3 * np.array([1., 4., 9.]),
                               atol=1e-5)
    _, t = paddle.autograd.jvp(
        lambda a: a * a, x,
        paddle.to_tensor(np.array([0., 1., 0.], np.float32)))
    np.testing.assert_allclose(t.numpy(), [0., 4., 0.], atol=1e-5)
    # multi-input jacobian returns one per input
    def g2(a, b):
        return a * b
    ja, jb = paddle.autograd.jacobian(
        g2, [x, paddle.to_tensor(np.array([2., 2., 2.], np.float32))])
    np.testing.assert_allclose(ja.numpy(), np.diag([2., 2., 2.]), atol=1e-5)
    np.testing.assert_allclose(jb.numpy(), np.diag([1., 2., 3.]), atol=1e-5)
