"""ERNIE model tests incl. hybrid-parallel (TP+ZeRO) training on the
8-device CPU mesh — BASELINE config 5's shape at toy scale.
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.models.ernie import (ErnieConfig, ErnieForPretraining,
                                     ernie_tiny)
from paddle_tpu.optimizer import AdamW


def _batch(cfg, B=4, S=32, M=5, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (B, S)).astype(np.int32)
    tt = np.zeros((B, S), np.int32)
    pos = np.stack([rng.choice(S, M, replace=False)
                    for _ in range(B)]).astype(np.int32)
    labels = rng.randint(0, cfg.vocab_size, (B, M)).astype(np.int32)
    sop = rng.randint(0, 2, (B,)).astype(np.int64)
    return ids, tt, pos, labels, sop


def test_forward_shapes_and_task_embedding():
    cfg = ernie_tiny()
    paddle.seed(0)
    m = ErnieForPretraining(cfg)
    m.eval()
    ids, tt, pos, labels, sop = _batch(cfg)
    mlm, sop_scores = m(paddle.to_tensor(ids), paddle.to_tensor(tt),
                        masked_positions=paddle.to_tensor(pos))
    assert tuple(mlm.shape) == (4, 5, cfg.vocab_size)
    assert tuple(sop_scores.shape) == (4, 2)
    # task-type embedding changes the representation
    task = np.ones((4, 32), np.int32)
    mlm2, _ = m(paddle.to_tensor(ids), paddle.to_tensor(tt),
                masked_positions=paddle.to_tensor(pos),
                task_type_ids=paddle.to_tensor(task))
    assert float(np.abs(mlm.numpy() - mlm2.numpy()).max()) > 1e-6


def test_pretraining_convergence_jitted():
    cfg = ernie_tiny()
    paddle.seed(1)
    m = ErnieForPretraining(cfg)
    m.train()

    def loss_fn(layer, ids, tt, pos, labels, sop):
        mlm, sops = layer(ids, tt, masked_positions=pos)
        return layer.loss(mlm, sops, labels, sop)

    step = TrainStep(m, loss_fn, AdamW(learning_rate=3e-3,
                                       parameters=m.parameters()))
    data = _batch(cfg, seed=2)
    losses = [float(step(*data)) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.8, losses[::5]


def test_hybrid_tp_zero_on_mesh():
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.distributed.spmd import apply_hybrid_specs, make_mesh

    cfg = ernie_tiny(hidden_size=64, num_heads=4, intermediate_size=128)
    paddle.seed(3)
    m = ErnieForPretraining(cfg)
    m.train()
    apply_hybrid_specs(m, mp_axis="mp")
    mesh = make_mesh({"dp": 2, "sharding": 2, "mp": 2})
    dist_env.set_mesh(mesh)

    def loss_fn(layer, ids, tt, pos, labels, sop):
        mlm, sops = layer(ids, tt, masked_positions=pos)
        return layer.loss(mlm, sops, labels, sop)

    step = TrainStep(m, loss_fn,
                     AdamW(learning_rate=1e-3, parameters=m.parameters()),
                     mesh=mesh, data_spec=P(("dp", "sharding")),
                     zero_axis="sharding")
    # initial placements (post-step placements are XLA's to refine):
    # TP param really sharded over mp (out-dim split over mp=2)
    q_w = step.params["ernie.encoder.layers.0.self_attn.q_proj.weight"]
    assert {s.data.shape for s in q_w.addressable_shards} == {(64, 32)}
    # ZeRO: adam moment of the (mp-sharded) embedding ALSO split over
    # 'sharding' on its first free dim
    emb_m = step.opt_state["ernie.embeddings.word_embeddings.weight"][0]
    assert {s.data.shape for s in emb_m.addressable_shards} == \
        {(cfg.vocab_size // 2, 32)}

    data = _batch(cfg, B=8, seed=4)
    losses = [float(step(*data)) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_ernie_hybrid_dryrun_on_virtual_mesh():
    """BASELINE config 5 shape (dp x sharding x mp + AMP O1 + ZeRO Adam)
    — the driver's dryrun_multichip config C, kept green in CI."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "graft_entry", os.path.join(os.path.dirname(__file__), "..",
                                    "__graft_entry__.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    import numpy as np
    loss = mod._run_ernie_hybrid(8)
    assert np.isfinite(loss)
