"""Native C++ blocking-queue tests (the reader-core replacement).

reference analogue: reader/blocking_queue_test.cc — send/receive order,
capacity blocking, close semantics, multi-threaded producers/consumers.
"""

import pickle
import threading
import time

import numpy as np
import pytest

from paddle_tpu.io.native_queue import (NativeBlockingQueue, QueueClosed,
                                        native_available)

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="g++ toolchain unavailable")


def test_fifo_roundtrip():
    q = NativeBlockingQueue(4)
    for i in range(4):
        q.put(f"item{i}".encode())
    assert q.qsize() == 4
    assert [q.get() for _ in range(4)] == [b"item0", b"item1", b"item2",
                                          b"item3"]


def test_capacity_blocks_until_consumed():
    q = NativeBlockingQueue(1)
    q.put(b"a")
    done = []

    def producer():
        q.put(b"b")              # must block until 'a' is consumed
        done.append(time.time())

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.2)
    assert not done              # still blocked
    t0 = time.time()
    assert q.get() == b"a"
    t.join(timeout=5)
    assert done and done[0] >= t0
    assert q.get() == b"b"


def test_close_drains_then_raises():
    q = NativeBlockingQueue(4)
    q.put(b"x")
    q.close()
    with pytest.raises(QueueClosed):
        q.put(b"y")              # no sends after close
    assert q.get() == b"x"       # drains existing
    with pytest.raises(QueueClosed):
        q.get()


def test_get_timeout():
    q = NativeBlockingQueue(2)
    t0 = time.time()
    with pytest.raises(TimeoutError):
        q.get(timeout=0.2)
    assert 0.1 < time.time() - t0 < 5.0


def test_numpy_batch_transport():
    q = NativeBlockingQueue(8)
    batch = {"x": np.arange(1024, dtype=np.float32).reshape(32, 32),
             "y": np.ones(32, np.int64)}
    q.put(pickle.dumps(batch, protocol=4))
    out = pickle.loads(q.get())
    np.testing.assert_array_equal(out["x"], batch["x"])
    np.testing.assert_array_equal(out["y"], batch["y"])


def test_multithreaded_producers_consumers():
    q = NativeBlockingQueue(16)
    N_PER, THREADS = 200, 4
    received = []
    lock = threading.Lock()

    def producer(tid):
        for i in range(N_PER):
            q.put(f"{tid}:{i}".encode())

    def consumer():
        while True:
            try:
                item = q.get(timeout=5.0)
            except QueueClosed:
                return
            with lock:
                received.append(item)

    ps = [threading.Thread(target=producer, args=(t,))
          for t in range(THREADS)]
    cs = [threading.Thread(target=consumer) for _ in range(2)]
    for t in ps + cs:
        t.start()
    for t in ps:
        t.join(timeout=30)
    q.close()
    for t in cs:
        t.join(timeout=30)
    assert len(received) == N_PER * THREADS
    assert len(set(received)) == N_PER * THREADS   # no dup, no loss
