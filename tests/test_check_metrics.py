"""tools/check_metrics.py — metric-name drift gate (ISSUE 11
satellite): the repo's emitted registry metrics and the
docs/OBSERVABILITY.md Metric inventory must stay in sync, enforced as
a tier-1 test."""

import os

import pytest

import tools.check_metrics as cm

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_metric_inventory_in_sync():
    """THE gate: every emitted metric documented, every documented
    metric emitted. A failure message names the drift."""
    problems, emitted, documented = cm.check(REPO)
    assert problems == [], "\n".join(problems)
    assert len(emitted) >= 50               # the scanner actually scans
    assert emitted.keys() == documented


def test_cli_exit_code():
    assert cm.main(["--root", REPO]) == 0


def _fake_repo(tmp_path, source: str, doc_names):
    (tmp_path / "paddle_tpu").mkdir()
    (tmp_path / "paddle_tpu" / "mod.py").write_text(source)
    (tmp_path / "bench.py").write_text("")
    (tmp_path / "docs").mkdir()
    rows = "\n".join(f"| `{n}` | counter | | x |" for n in doc_names)
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text(
        "# t\n\n## Metric inventory\n\n| Metric | Type | Labels | "
        f"Meaning |\n|---|---|---|---|\n{rows}\n\n## Next\n`not_me_x`\n")
    return str(tmp_path)


SRC = '''
reg.counter("requests_total", "help text").inc()
reg.histogram("lat_seconds" if warm
              else "cold_lat_seconds",
              "dispatch latency").observe(dt)
reg.gauge(
    "queue_depth",
    "waiting requests").set(3)
for k in names:
    # emits-metrics: dyn_a_total, dyn_b_total
    reg.counter(k).inc()
'''


def test_scanner_literal_conditional_and_annotated(tmp_path):
    root = _fake_repo(tmp_path, SRC, [])
    emitted = cm.emitted_metrics(root)
    assert set(emitted) == {"requests_total", "lat_seconds",
                            "cold_lat_seconds", "queue_depth",
                            "dyn_a_total", "dyn_b_total"}
    # help strings (contain spaces) never leak in as names
    assert "help" not in emitted


def test_undocumented_metric_fails(tmp_path):
    root = _fake_repo(tmp_path, SRC,
                      ["requests_total", "lat_seconds",
                       "cold_lat_seconds", "dyn_a_total",
                       "dyn_b_total"])        # queue_depth missing
    problems, _, _ = cm.check(root)
    assert len(problems) == 1
    assert "UNDOCUMENTED" in problems[0]
    assert "queue_depth" in problems[0]
    assert "mod.py" in problems[0]


def test_documented_but_gone_fails(tmp_path):
    root = _fake_repo(tmp_path, SRC,
                      ["requests_total", "lat_seconds",
                       "cold_lat_seconds", "queue_depth",
                       "dyn_a_total", "dyn_b_total",
                       "ghost_metric_total"])
    problems, _, _ = cm.check(root)
    assert len(problems) == 1
    assert "DOCUMENTED-BUT-GONE" in problems[0]
    assert "ghost_metric_total" in problems[0]
    # names outside the inventory section don't count as documented
    _, _, documented = cm.check(root)
    assert "not_me_x" not in documented


def test_missing_section_is_loud(tmp_path):
    root = _fake_repo(tmp_path, SRC, [])
    (tmp_path / "docs" / "OBSERVABILITY.md").write_text("# nothing\n")
    with pytest.raises(ValueError, match="Metric inventory"):
        cm.check(root)
