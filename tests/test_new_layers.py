"""Tests for the final layer-zoo additions (max-unpool, hsigmoid,
pairwise distance, adaptive max pool 3d).

reference analogues: test_unpool_op.py, test_hsigmoid_op.py,
test_pairwise_distance.py, test_adaptive_max_pool3d.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn


def test_max_pool2d_return_mask_and_unpool_roundtrip():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    out, mask = F.max_pool2d(paddle.to_tensor(x), kernel_size=2, stride=2,
                             return_mask=True)
    assert tuple(out.shape) == (2, 3, 4, 4)
    assert tuple(mask.shape) == (2, 3, 4, 4)
    # indices point at the max of each window
    flat = x.reshape(2, 3, 64)
    np.testing.assert_allclose(
        np.take_along_axis(flat, mask.numpy().reshape(2, 3, 16), axis=2),
        out.numpy().reshape(2, 3, 16), rtol=1e-6)

    up = nn.MaxUnPool2D(kernel_size=2, stride=2)(out, mask)
    assert tuple(up.shape) == (2, 3, 8, 8)
    # unpooled values land exactly at the argmax positions, zeros elsewhere
    nz = up.numpy() != 0
    assert nz.sum() <= 2 * 3 * 16
    np.testing.assert_allclose(up.numpy().reshape(2, 3, 64).sum(-1),
                               out.numpy().reshape(2, 3, 16).sum(-1),
                               rtol=1e-5)


def test_adaptive_max_pool3d():
    x = np.random.RandomState(1).randn(2, 3, 8, 8, 8).astype(np.float32)
    out = nn.AdaptiveMaxPool3D(output_size=4)(paddle.to_tensor(x))
    assert tuple(out.shape) == (2, 3, 4, 4, 4)
    ref = x.reshape(2, 3, 4, 2, 4, 2, 4, 2).max(axis=(3, 5, 7))
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-6)


def test_pairwise_distance_matches_numpy():
    rng = np.random.RandomState(2)
    a = rng.randn(5, 7).astype(np.float32)
    b = rng.randn(5, 7).astype(np.float32)
    got = nn.PairwiseDistance(p=2.0)(paddle.to_tensor(a),
                                     paddle.to_tensor(b)).numpy()
    ref = np.linalg.norm(a - b + 1e-6, axis=-1)
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    got_inf = nn.PairwiseDistance(p=float("inf"))(
        paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(got_inf, np.abs(a - b + 1e-6).max(-1),
                               rtol=1e-5)


def test_hsigmoid_loss_shapes_and_training():
    paddle.seed(3)
    N, D, C = 8, 16, 10
    layer = nn.HSigmoidLoss(feature_size=D, num_classes=C)
    x = paddle.to_tensor(np.random.RandomState(4).randn(N, D)
                         .astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(5).randint(0, C, (N,))
                         .astype(np.int64))
    loss = layer(x, y)
    assert tuple(loss.shape) == (N, 1)
    assert np.isfinite(loss.numpy()).all()

    # trains: same-class inputs should drive their path loss down
    opt = paddle.optimizer.Adam(learning_rate=0.1,
                                parameters=layer.parameters())
    xf = paddle.to_tensor(np.ones((4, D), np.float32))
    yf = paddle.to_tensor(np.zeros((4,), np.int64))
    first = None
    for _ in range(30):
        loss = layer(xf, yf).mean()
        if first is None:
            first = float(loss)
        loss.backward()
        opt.step()
        opt.clear_grad()
    assert float(loss) < first * 0.3, (first, float(loss))


def test_hsigmoid_custom_path():
    # two-class custom tree: one internal node, code bit = class id
    N, D = 4, 8
    layer = nn.HSigmoidLoss(feature_size=D, num_classes=2)
    x = paddle.to_tensor(np.random.RandomState(6).randn(N, D)
                         .astype(np.float32))
    y = paddle.to_tensor(np.array([0, 1, 0, 1], np.int64))
    pt = np.zeros((N, 1), np.int64)            # all through node 0
    pc = np.array([[0], [1], [0], [1]], np.float32)
    loss = layer(x, y, path_table=pt, path_code=pc)
    assert tuple(loss.shape) == (N, 1)
    assert np.isfinite(loss.numpy()).all()
