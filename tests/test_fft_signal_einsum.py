"""fft / signal / einsum / class_center_sample API tests.

Analogue of the reference's spectral + einsum op tests
(reference: test_fft.py — numpy parity over norms/axes; test_signal.py
stft/istft round-trip; test_einsum_op.py).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import fft, signal


def test_fft_roundtrip_and_numpy_parity():
    x = np.random.RandomState(0).randn(4, 32).astype(np.float32)
    got = fft.fft(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, np.fft.fft(x), rtol=1e-4, atol=1e-4)
    back = fft.ifft(fft.fft(paddle.to_tensor(x))).numpy()
    np.testing.assert_allclose(back.real, x, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("norm", ["backward", "ortho", "forward"])
def test_rfft_norms(norm):
    x = np.random.RandomState(1).randn(16).astype(np.float32)
    got = fft.rfft(paddle.to_tensor(x), norm=norm).numpy()
    np.testing.assert_allclose(got, np.fft.rfft(x, norm=norm),
                               rtol=1e-4, atol=1e-4)


def test_fft2_fftn_fftshift_fftfreq():
    x = np.random.RandomState(2).randn(4, 8, 8).astype(np.float32)
    np.testing.assert_allclose(fft.fft2(paddle.to_tensor(x)).numpy(),
                               np.fft.fft2(x), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(fft.fftn(paddle.to_tensor(x)).numpy(),
                               np.fft.fftn(x), rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(fft.fftshift(paddle.to_tensor(x)).numpy(),
                               np.fft.fftshift(x), rtol=1e-6)
    np.testing.assert_allclose(fft.fftfreq(10, 0.5).numpy(),
                               np.fft.fftfreq(10, 0.5).astype(np.float32))


def test_irfft_matches_numpy():
    x = np.random.RandomState(3).randn(16).astype(np.float32)
    spec = np.fft.rfft(x)
    got = fft.irfft(paddle.to_tensor(spec)).numpy()
    np.testing.assert_allclose(got, x, rtol=1e-4, atol=1e-4)


def test_hfft2_ihfft2_roundtrip():
    x = np.random.RandomState(9).randn(4, 6).astype(np.float32)
    spec = fft.ihfft2(paddle.to_tensor(x))
    back = fft.hfft2(spec, s=(4, 6)).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-5)


def test_hfft_matches_numpy():
    x = np.random.RandomState(10).randn(9).astype(np.complex64)
    got = fft.hfft(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, np.fft.hfft(x), rtol=1e-3, atol=1e-3)


def test_fft_bad_norm_raises():
    with pytest.raises(ValueError, match="norm"):
        fft.fft(paddle.to_tensor(np.zeros(4, np.float32)), norm="bogus")


def test_frame_overlap_add_inverse():
    x = np.random.RandomState(4).randn(2, 64).astype(np.float32)
    framed = signal.frame(paddle.to_tensor(x), frame_length=16,
                          hop_length=16)          # non-overlapping
    assert tuple(framed.shape) == (2, 16, 4)
    back = signal.overlap_add(framed, hop_length=16).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-6)


def test_stft_istft_roundtrip():
    x = np.random.RandomState(5).randn(2, 512).astype(np.float32)
    n_fft, hop = 64, 16
    window = np.hanning(n_fft).astype(np.float32)
    spec = signal.stft(paddle.to_tensor(x), n_fft=n_fft, hop_length=hop,
                       window=paddle.to_tensor(window))
    assert tuple(spec.shape) == (2, n_fft // 2 + 1, 512 // hop + 1)
    back = signal.istft(spec, n_fft=n_fft, hop_length=hop,
                        window=paddle.to_tensor(window),
                        length=512).numpy()
    np.testing.assert_allclose(back, x, rtol=1e-3, atol=1e-3)


def test_einsum_matmul_trace_and_grad():
    a = np.random.RandomState(6).randn(4, 5).astype(np.float32)
    b = np.random.RandomState(7).randn(5, 3).astype(np.float32)
    got = paddle.einsum("ij,jk->ik", paddle.to_tensor(a),
                        paddle.to_tensor(b)).numpy()
    np.testing.assert_allclose(got, a @ b, rtol=1e-4, atol=1e-4)
    # implicit form + trace
    sq = np.random.RandomState(8).randn(6, 6).astype(np.float32)
    np.testing.assert_allclose(
        paddle.einsum("ii", paddle.to_tensor(sq)).numpy(),
        np.trace(sq), rtol=1e-5)
    # grads flow
    ta = paddle.to_tensor(a)
    ta.stop_gradient = False
    paddle.einsum("ij,jk->ik", ta, paddle.to_tensor(b)).sum().backward()
    np.testing.assert_allclose(np.asarray(ta.grad._data),
                               np.tile(b.sum(1), (4, 1)), rtol=1e-4)


def test_class_center_sample():
    paddle.seed(7)
    labels = np.array([3, 7, 7, 42, 3], np.int64)
    remapped, sampled = F.class_center_sample(
        paddle.to_tensor(labels), num_classes=100, num_samples=10)
    s = sampled.numpy()
    assert len(s) == 10 and len(set(s.tolist())) == 10
    for c in (3, 7, 42):
        assert c in s                        # positives always kept
    r = remapped.numpy()
    assert (s[r] == labels).all()            # remap is consistent
