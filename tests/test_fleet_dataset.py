"""InMemoryDataset / QueueDataset (reference:
distributed/fleet/dataset/dataset.py over data_feed.cc)."""

import numpy as np

from paddle_tpu.distributed.fleet import InMemoryDataset, QueueDataset


def _write_multislot(path, rows):
    with open(path, "w") as f:
        for label, feats in rows:
            f.write(f"1 {label} {len(feats)} " +
                    " ".join(str(v) for v in feats) + "\n")


def test_queue_dataset_streams_batches(tmp_path):
    rows = [(i % 2, [i, i + 0.5]) for i in range(7)]
    _write_multislot(tmp_path / "a.txt", rows[:4])
    _write_multislot(tmp_path / "b.txt", rows[4:])
    ds = QueueDataset()
    ds.init(batch_size=3)
    ds.set_filelist([str(tmp_path / "a.txt"), str(tmp_path / "b.txt")])
    batches = list(ds)
    assert len(batches) == 3 and len(batches[-1][0]) == 1
    labels, feats = batches[0]
    np.testing.assert_allclose(labels[:, 0], [0, 1, 0])
    np.testing.assert_allclose(feats[1], [1.0, 1.5])


def test_inmemory_load_shuffle_release(tmp_path):
    rows = [(i, [float(i)]) for i in range(20)]
    _write_multislot(tmp_path / "d.txt", rows)
    ds = InMemoryDataset()
    ds.init(batch_size=5, drop_last=True)
    ds.set_filelist([str(tmp_path / "d.txt")])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 20
    first = [b[0][:, 0].tolist() for b in ds]
    ds.local_shuffle()
    second = [b[0][:, 0].tolist() for b in ds]
    assert sorted(sum(first, [])) == sorted(sum(second, []))
    assert first != second                       # order changed
    ds.release_memory()
    assert ds.get_memory_data_size() == 0


def test_pipe_command_and_custom_parse(tmp_path):
    with open(tmp_path / "raw.txt", "w") as f:
        f.write("x 1,2\nx 3,4\n")
    ds = QueueDataset()
    # real shell pipeline, like the reference's pipe_command contract
    ds.init(batch_size=2, pipe_command="sed 's/^x //'",
            parse_fn=lambda line: [np.asarray(
                [float(v) for v in line.split(",")], np.float32)])
    ds.set_filelist([str(tmp_path / "raw.txt")])
    (batch,) = list(ds)
    np.testing.assert_allclose(batch[0], [[1, 2], [3, 4]])


def test_global_shuffle_single_trainer_keeps_all(tmp_path):
    rows = [(i, [float(i)]) for i in range(6)]
    _write_multislot(tmp_path / "g.txt", rows)
    ds = InMemoryDataset()
    ds.init(batch_size=2)
    ds.set_filelist([str(tmp_path / "g.txt")])
    ds.load_into_memory()
    ds.global_shuffle()
    assert ds.get_shuffle_data_size() == 6
