"""Profiler shim coverage (ISSUE 3 satellite — none existed before):
RecordEvent aggregation, chrome-trace export validity, the
make_scheduler state machine, Profiler windows/on_trace_ready, summary
sorting, and the profile_train_step keys."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import profiler as prof
from paddle_tpu.optimizer import SGD


@pytest.fixture(autouse=True)
def _profiler_reset():
    """Every test starts and ends with the profiler inactive."""
    yield
    prof.stop_profiler()


# ---------------------------------------------------------------------------
# RecordEvent aggregation + summary
# ---------------------------------------------------------------------------

def test_record_event_aggregation():
    prof.start_profiler()
    with prof.RecordEvent("outer"):
        with prof.RecordEvent("inner"):
            pass
        with prof.RecordEvent("inner"):
            pass
    prof.stop_profiler()
    table = prof.summary()
    assert "outer" in table and "inner" in table
    inner = [ln for ln in table.splitlines() if ln.startswith("inner")][0]
    assert inner.split()[1] == "2"          # calls column
    outer = [ln for ln in table.splitlines() if ln.startswith("outer")][0]
    assert outer.split()[1] == "1"


def test_record_event_ignored_when_inactive():
    prof.start_profiler()
    prof.stop_profiler()
    baseline = prof.summary()
    with prof.RecordEvent("ghost"):
        pass
    assert "ghost" not in prof.summary()
    assert prof.summary() == baseline


def test_op_hook_bounded_when_inactive():
    """Satellite pin: _op_hook must not leak events/timeline entries when
    the profiler was never started (long eager runs)."""
    prof.start_profiler()
    prof.stop_profiler()
    n_events = len(prof._events)
    n_timeline = len(prof._timeline)
    prof._op_hook("leaky_op", 0.001)
    assert len(prof._events) == n_events
    assert len(prof._timeline) == n_timeline


def test_summary_sorting_keys():
    prof.start_profiler()
    import time
    with prof.RecordEvent("slow_once"):
        time.sleep(0.02)
    for _ in range(5):
        with prof.RecordEvent("fast_many"):
            pass
    prof.stop_profiler()
    by_total = prof.summary(sorted_by="total").splitlines()
    assert by_total[1].startswith("slow_once")
    by_calls = prof.summary(sorted_by="calls").splitlines()
    assert by_calls[1].startswith("fast_many")
    by_avg = prof.summary(sorted_by="avg").splitlines()
    assert by_avg[1].startswith("slow_once")
    with pytest.raises(ValueError):
        prof.summary(sorted_by="nope")


def test_stop_profiler_writes_profile_path(tmp_path):
    path = str(tmp_path / "profile.txt")
    prof.start_profiler()
    with prof.RecordEvent("evt"):
        pass
    prof.stop_profiler(sorted_key="calls", profile_path=path)
    text = open(path).read()
    assert "Event" in text and "evt" in text


# ---------------------------------------------------------------------------
# chrome trace export
# ---------------------------------------------------------------------------

def test_chrome_trace_export_is_valid_json(tmp_path):
    path = str(tmp_path / "trace.json")
    prof.start_profiler()
    with prof.RecordEvent("step"):
        with prof.RecordEvent("matmul"):
            pass
    prof.stop_profiler()
    out = prof.export_chrome_tracing(path)
    assert out == path
    with open(path) as f:
        doc = json.load(f)                   # JSON loads
    events = doc["traceEvents"]
    assert len(events) >= 2
    names = {e["name"] for e in events}
    assert {"step", "matmul"} <= names
    for e in events:
        assert e["ph"] == "X"
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0


def test_chrome_trace_handler_factory(tmp_path):
    d = str(tmp_path / "traces")
    handler = prof.export_chrome_tracing(d, worker_name="w0")
    assert callable(handler)
    p = prof.Profiler(scheduler=prof.make_scheduler(closed=0, ready=0,
                                                    record=2, repeat=1),
                      on_trace_ready=handler, timer_only=True)
    p.start()
    for _ in range(3):
        with prof.RecordEvent("tick"):
            pass
        p.step()
    p.stop()
    files = os.listdir(d)
    assert files == ["w0_chrome_trace_1.json"]
    with open(os.path.join(d, files[0])) as f:
        assert "traceEvents" in json.load(f)


# ---------------------------------------------------------------------------
# scheduler state machine
# ---------------------------------------------------------------------------

def test_make_scheduler_state_sequence():
    S = prof.ProfilerState
    sch = prof.make_scheduler(closed=1, ready=1, record=2, repeat=2,
                              skip_first=2)
    states = [sch(i) for i in range(12)]
    assert states == [
        S.CLOSED, S.CLOSED,                              # skip_first
        S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN,  # cycle 1
        S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN,  # cycle 2
        S.CLOSED, S.CLOSED,                              # repeat exhausted
    ]
    # repeat=0 cycles forever
    sch2 = prof.make_scheduler(closed=0, ready=0, record=1)
    assert [sch2(i) for i in range(3)] == [S.RECORD_AND_RETURN] * 3
    with pytest.raises(ValueError):
        prof.make_scheduler(closed=1, ready=0, record=0)
    with pytest.raises(ValueError):
        prof.make_scheduler(closed=-1, ready=0, record=1)


def test_profiler_scheduler_windows_and_on_trace_ready():
    ready_steps = []
    p = prof.Profiler(
        scheduler=prof.make_scheduler(closed=1, ready=1, record=2,
                                      repeat=2),
        on_trace_ready=lambda pr: ready_steps.append(pr.step_num),
        timer_only=True)
    p.start()
    assert p.state == prof.ProfilerState.CLOSED
    seen_states = []
    for _ in range(10):
        with prof.RecordEvent("tick"):
            pass
        seen_states.append(p.state)
        p.step()
    p.stop()
    assert p.windows == 2
    assert ready_steps == [3, 7]            # window closes AFTER its last
    assert seen_states.count(prof.ProfilerState.RECORD) == 2
    assert seen_states.count(prof.ProfilerState.RECORD_AND_RETURN) == 2
    # each window aggregated its own events only (2 record steps)
    table = prof.summary()
    tick = [ln for ln in table.splitlines() if ln.startswith("tick")][0]
    assert tick.split()[1] == "2"


def test_profiler_tuple_scheduler_and_unscheduled():
    S = prof.ProfilerState
    fired = []
    p = prof.Profiler(scheduler=(1, 3),
                      on_trace_ready=lambda pr: fired.append(pr.step_num),
                      timer_only=True)
    p.start()
    assert p.state == S.CLOSED
    p.step()                                 # -> step 1: RECORD
    assert p.state == S.RECORD
    p.step()                                 # -> step 2: RECORD_AND_RETURN
    assert p.state == S.RECORD_AND_RETURN
    p.step()                                 # window closes
    assert fired == [2] and p.windows == 1   # handler sees the last
    assert p.state == S.CLOSED               # record step's number
    p.stop()

    # unscheduled profiler: one window spanning start..stop
    fired2 = []
    p2 = prof.Profiler(on_trace_ready=lambda pr: fired2.append(True),
                       timer_only=True)
    with p2:
        with prof.RecordEvent("body"):
            pass
    assert fired2 == [True] and p2.windows == 1
    assert "body" in prof.summary()


def test_profiler_stop_mid_window_exports():
    """A loop that breaks mid-RECORD must not lose the window: stop()
    exports the partial window (reference Profiler.stop() parity)."""
    fired = []
    p = prof.Profiler(scheduler=(0, 5),
                      on_trace_ready=lambda pr: fired.append(pr.step_num),
                      timer_only=True)
    p.start()
    for _ in range(3):                       # breaks before step 5
        with prof.RecordEvent("tick"):
            pass
        p.step()
    assert p.state == prof.ProfilerState.RECORD
    p.stop()
    assert fired == [3] and p.windows == 1
    tick = [ln for ln in prof.summary().splitlines()
            if ln.startswith("tick")][0]
    assert tick.split()[1] == "3"


def test_profiler_export_and_tensorboard_handler(tmp_path):
    d = str(tmp_path / "tb")
    handler = prof.export_tensorboard(d, worker_name="w0")
    p = prof.Profiler(on_trace_ready=handler, timer_only=True)
    assert p.log_dir == d                    # handler carries the xplane dir
    with p:
        with prof.RecordEvent("evt"):
            pass
    assert os.path.exists(os.path.join(d, "w0_summary_1.txt"))
    out = p.export(str(tmp_path / "host.json"))
    with open(out) as f:
        assert "traceEvents" in json.load(f)
    with pytest.raises(ValueError):
        p.export(str(tmp_path / "x.pb"), format="protobuf")


# ---------------------------------------------------------------------------
# profile_train_step
# ---------------------------------------------------------------------------

def test_profile_train_step_key_presence():
    from paddle_tpu.jit.to_static import TrainStep
    paddle.seed(0)
    m = nn.Linear(4, 2)

    def loss_fn(layer, x, y):
        return ((layer(x) - y) ** 2).mean()

    step = TrainStep(m, loss_fn,
                     SGD(learning_rate=0.1, parameters=m.parameters()))
    rng = np.random.RandomState(0)
    batch = (rng.rand(4, 4).astype(np.float32),
             rng.rand(4, 2).astype(np.float32))
    res = prof.profile_train_step(step, batch, iters=2, warmup=1)
    assert set(res) == {"compile_s", "host_ms", "dispatch_ms", "step_ms",
                       "device_ms_est"}
    assert res["compile_s"] > 0
    assert res["step_ms"] > 0
    assert res["device_ms_est"] >= 0
