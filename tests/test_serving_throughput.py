"""Frontier decode throughput (ISSUE 15): radix prefix cache over the
paged KV pools, chunked prefill, speculative decoding — each behind its
own kill switch with the flags-off path as the token-exact oracle, plus
refcounted BlockAllocator invariants under the scheduler fuzz."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import no_grad
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.monitor import scoped_registry
from paddle_tpu.serving import (BlockAllocator, EngineDrained,
                                LoadSpec, RadixPrefixCache, Request,
                                SamplingParams, ServingConfig,
                                ServingEngine, build_requests,
                                load_drain_snapshot, propose_ngram,
                                requests_from_snapshot)
from paddle_tpu.serving.kv_cache import PagedKVCache
from paddle_tpu.serving.resilience import request_spec
from paddle_tpu.serving.scheduler import BucketTable, Scheduler
from paddle_tpu.testing import chaos

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


def _engine(model, **kw):
    cfg = dict(max_batch_slots=3, block_size=4, max_context_len=64,
               prefill_buckets=(8, 16), batch_buckets=(1, 2))
    cfg.update(kw)
    return ServingEngine(model, ServingConfig(**cfg))


def _golden(model, prompt, n):
    seq = np.asarray(prompt, np.int32)
    for _ in range(n):
        with no_grad():
            lg = model(paddle.to_tensor(seq[None, :])).numpy()
        seq = np.concatenate([seq, [np.int32(lg[0, -1].argmax())]])
    return seq


#: a prompt whose greedy continuation the n-gram drafter can predict
#: (trailing n-gram recurs), plus generic shared-prefix prompts
REP_PROMPT = [3, 4, 5, 3, 4, 5, 3, 4]


# ---------------------------------------------------------------------------
# refcounted BlockAllocator
# ---------------------------------------------------------------------------


def test_allocator_refcounts():
    a = BlockAllocator(num_pages=6)
    got = a.alloc(2)
    assert [a.refcount(p) for p in got] == [1, 1]
    a.incref(got[0])
    assert a.refcount(got[0]) == 2
    a.free(got)                       # got[0] -> rc 1, got[1] -> freed
    assert a.refcount(got[0]) == 1 and a.refcount(got[1]) == 0
    assert a.pages_in_use == 1
    a.free([got[0]])
    assert a.pages_in_use == 0
    with pytest.raises(ValueError):
        a.free([got[0]])              # double free is loud
    with pytest.raises(ValueError):
        a.incref(got[1])              # incref needs an allocated page


def test_allocator_shared_page_never_reenters_free_list_early():
    a = BlockAllocator(num_pages=4)
    got = a.alloc(3)                  # pool exhausted
    a.incref(got[1])
    a.free(got)                       # got[1] still referenced
    assert a.refcount(got[1]) == 1
    re = a.alloc(3)                   # only 2 free -> all-or-nothing
    assert re is None
    assert sorted(a.alloc(2)) == sorted([got[0], got[2]])


# ---------------------------------------------------------------------------
# radix tree unit behaviour
# ---------------------------------------------------------------------------


def _host_cache(num_pages=12, block_size=4, max_slots=3):
    return PagedKVCache(1, 1, 4, num_pages=num_pages,
                        block_size=block_size, max_slots=max_slots,
                        max_blocks_per_slot=6)


def test_radix_donate_match_dedup_evict():
    cache = _host_cache()
    pc = RadixPrefixCache(cache)
    cache.prefix_cache = pc
    alloc = cache.allocator
    toks = list(range(10, 22))        # 3 full pages at bs=4
    pages = alloc.alloc(3)
    assert pc.donate(toks, pages) == 3
    assert pc.cached_pages == 3 and alloc.pages_in_use == 3

    # full-prefix query: capped one token short -> only 2 pages match
    n, hit = pc.match(toks)
    assert n == 8 and len(hit) == 2 and hit == pages[:2]
    assert [alloc.refcount(p) for p in hit] == [2, 2]
    alloc.free(hit)

    # longer query with an extra tail matches all 3 pages
    n, hit = pc.match(toks + [99, 98])
    assert n == 12 and hit == pages
    alloc.free(hit)

    # duplicate donation drops the duplicate refs, tree unchanged
    dup = alloc.alloc(3)
    assert pc.donate(toks, dup) == 3
    assert pc.cached_pages == 3
    assert all(alloc.refcount(p) == 0 for p in dup)

    # divergent branch shares the common prefix node
    toks2 = toks[:4] + [77, 78, 79, 80]
    pg2 = alloc.alloc(2)
    assert pc.donate(toks2, pg2) == 2
    assert pc.cached_pages == 4       # shared head + one new leaf
    assert alloc.refcount(pg2[0]) == 0 and alloc.refcount(pg2[1]) == 1

    # eviction storm: drop everything; no page leaks, free list whole
    freed = pc.evict_for(100)
    assert freed == 4 and pc.cached_pages == 0
    assert alloc.pages_in_use == 0


def test_radix_eviction_respects_live_slot_refs():
    cache = _host_cache()
    pc = RadixPrefixCache(cache)
    cache.prefix_cache = pc
    alloc = cache.allocator
    toks = list(range(30, 38))
    pages = alloc.alloc(2)
    pc.donate(toks, pages)
    n, hit = pc.match(toks + [1, 2])
    assert hit == pages
    pc.evict_for(100)                 # tree drops its refs...
    assert pc.cached_pages == 0
    # ...but the matched slot still holds the pages
    assert all(alloc.refcount(p) == 1 for p in pages)
    assert alloc.pages_in_use == 2
    alloc.free(pages)
    assert alloc.pages_in_use == 0


def test_alloc_slot_failure_drops_shared_refs():
    cache = _host_cache(num_pages=4)  # 3 allocatable
    pc = RadixPrefixCache(cache)
    cache.prefix_cache = pc
    alloc = cache.allocator
    pages = alloc.alloc(2)
    pc.donate(list(range(8)), pages)
    n, hit = pc.match(list(range(8)) + [5, 6, 7, 8, 9])
    assert len(hit) == 2
    # needs 3 blocks beyond the shared 2 with only 1 free: allocation
    # pressure first evicts the tree (whose pages are the shared ones,
    # still match-referenced, so eviction frees nothing) and the alloc
    # still fails — the failed admission must then drop the match refs
    # so NOTHING leaks: every page back on the free list
    ok = cache.alloc_slot(0, 20, shared_pages=hit)
    assert not ok
    assert pc.cached_pages == 0             # evicted under pressure
    assert all(alloc.refcount(p) == 0 for p in pages)
    assert alloc.pages_in_use == 0


def test_truncate_slot_releases_only_tail_pages():
    cache = _host_cache()
    alloc = cache.allocator
    assert cache.alloc_slot(0, 20)    # 5 blocks
    assert alloc.pages_in_use == 5
    assert cache.truncate_slot(0, 9) == 2      # 9 tokens -> 3 blocks
    assert alloc.pages_in_use == 3
    assert cache.truncate_slot(0, 9) == 0      # idempotent
    table = np.asarray(cache.table_array())
    assert (table[0, 3:] == 0).all()


# ---------------------------------------------------------------------------
# n-gram drafter
# ---------------------------------------------------------------------------


def test_propose_ngram_prompt_lookup():
    # trailing [3,4] recurs -> continuation [5,3,4] follows it
    d = propose_ngram([3, 4, 5, 3, 4], k=3)
    assert d.tolist() == [5, 3, 4]
    # longest n-gram wins: trailing trigram picks the right branch
    d = propose_ngram([1, 2, 3, 9, 2, 3, 7, 1, 2, 3], k=2, max_ngram=3)
    assert d.tolist() == [9, 2]
    # no recurrence -> empty
    assert propose_ngram([1, 2, 3, 4, 5], k=4).size == 0
    # k caps the draft
    assert propose_ngram([3, 4, 5, 3, 4], k=1).tolist() == [5]
    assert propose_ngram([7], k=2).size == 0


# ---------------------------------------------------------------------------
# token-exact oracle pins (each feature alone, then composed)
# ---------------------------------------------------------------------------


def _prompt_set(rng):
    pre = rng.integers(2, 250, (10,)).tolist()
    return [pre + rng.integers(2, 250, (4,)).tolist(),
            pre + rng.integers(2, 250, (7,)).tolist(),
            REP_PROMPT,
            rng.integers(2, 250, (5,)).tolist()]


@pytest.fixture(scope="module")
def oracle(tiny_model):
    rng = np.random.default_rng(42)
    prompts = _prompt_set(rng)
    eng = _engine(tiny_model)
    outs = eng.generate(prompts, max_new_tokens=6)
    base = {"prompts": prompts,
            "outs": [o.tolist() for o in outs],
            "decode_dispatches": eng._stats["decode_dispatches"],
            "prefill_tokens": eng._stats["prefill_tokens"]}
    eng.shutdown()
    for p, o in zip(prompts, outs):
        assert np.array_equal(o, _golden(tiny_model, p, 6))
    return base


def test_prefix_hit_admission_token_exact(tiny_model, oracle):
    with flag_scope("serve_prefix_cache", True):
        eng = _engine(tiny_model)
    outs = []
    for p in oracle["prompts"]:       # sequential -> later ones hit
        outs.append(eng.generate([p], max_new_tokens=6)[0].tolist())
    assert outs == oracle["outs"]
    pc = eng.prefix_cache
    assert pc.stats["hit_tokens"] > 0 and pc.stats["hits"] >= 1
    # the hit prompts paid fewer prefill tokens than the cold oracle
    assert eng._stats["prefill_tokens"] \
        < oracle["prefill_tokens"] + pc.stats["hit_tokens"]
    s = eng.metrics_summary()
    assert s["prefix_hit_pct"] > 0
    eng.shutdown()


def test_prefix_shared_pages_never_mutated(tiny_model):
    """COW: after a hit admission decodes on top of shared pages, the
    shared pages' device content is bit-identical to before."""
    rng = np.random.default_rng(3)
    pre = rng.integers(2, 250, (12,)).tolist()
    with flag_scope("serve_prefix_cache", True):
        eng = _engine(tiny_model)
    eng.generate([pre + [7, 8, 9]], max_new_tokens=4)
    pc = eng.prefix_cache
    shared = sorted(p for p in pc._nodes)
    assert shared
    k_before = np.asarray(eng.cache.k[:, shared])
    v_before = np.asarray(eng.cache.v[:, shared])
    eng.generate([pre + [11, 12]], max_new_tokens=6)
    assert pc.stats["hits"] >= 1
    np.testing.assert_array_equal(k_before,
                                  np.asarray(eng.cache.k[:, shared]))
    np.testing.assert_array_equal(v_before,
                                  np.asarray(eng.cache.v[:, shared]))
    eng.shutdown()


def test_chunked_prefill_token_exact_and_interleaved(tiny_model, oracle):
    with flag_scope("serve_prefill_chunk", 4):
        eng = _engine(tiny_model)
    outs = [o.tolist()
            for o in eng.generate(oracle["prompts"], max_new_tokens=6)]
    assert outs == oracle["outs"]
    assert eng._stats["prefill_chunks"] > len(oracle["prompts"])
    eng.shutdown()

    # fairness: a short request admitted next to a long chunking
    # prefill gets decode iterations BETWEEN the long one's chunks —
    # it finishes while the long prompt is still prefilling
    long_p = np.random.default_rng(5).integers(2, 250, (48,)).tolist()
    with flag_scope("serve_prefill_chunk", 4):
        eng2 = _engine(tiny_model, max_context_len=64,
                       prefill_buckets=(4, 8, 16, 64))
    st_long = eng2.submit(Request(long_p, max_new_tokens=4))
    st_short = eng2.submit(Request([5, 6, 7], max_new_tokens=2))
    while not st_short.terminal:
        eng2.step()
        assert st_long.prefill_pos <= 48
    # the short stream completed while the long prompt was mid-chunk
    assert st_long.prefilling and not st_long.terminal
    eng2.run()
    assert st_long.outcome == "completed"
    out = np.concatenate([st_long.request.prompt,
                          np.asarray(st_long.generated, np.int32)])
    assert np.array_equal(out, _golden(tiny_model, long_p, 4))
    eng2.shutdown()


def test_interleaved_decode_never_writes_prefilling_slot_pages(
        tiny_model):
    """An interleaved decode/verify dispatch masks non-decodable rows'
    SAMPLING only — its per-row K/V scatter is unconditional. The
    dispatch must therefore carry an all-scratch table row for a
    mid-chunk prefilling slot, or its (pos=0, token=0) row silently
    overwrites the slot's first real — possibly COW-shared — page
    (caught by review; pinned on device content, not just outputs)."""
    long_p = np.random.default_rng(6).integers(2, 250, (48,)).tolist()
    with flag_scope("serve_prefill_chunk", 4), \
            flag_scope("serve_spec_k", 2):
        eng = _engine(tiny_model, max_context_len=64,
                      prefill_buckets=(4, 8, 16, 64))
    # the long prompt prefills ALONE first: its chunk steps run no
    # decode at all, so the snapshot below is pristine chunk output
    st_long = eng.submit(Request(long_p, max_new_tokens=2))
    eng.step()
    assert st_long.prefilling
    head = eng.cache._slot_pages[st_long.slot][0]
    k_before = np.asarray(eng.cache.k[:, head])
    v_before = np.asarray(eng.cache.v[:, head])
    # now a short request joins, completes its prefill and DECODES in
    # the same iterations the long prompt is still chunking through —
    # each of those decode/verify dispatches would scatter (pos=0,
    # token=0) garbage into the long slot's head page if its real
    # table row were aboard
    st_short = eng.submit(Request(REP_PROMPT, max_new_tokens=8))
    while st_long.prefilling:
        eng.step()
        np.testing.assert_array_equal(
            k_before, np.asarray(eng.cache.k[:, head]))
        np.testing.assert_array_equal(
            v_before, np.asarray(eng.cache.v[:, head]))
    assert st_short.generated        # decodes really interleaved
    eng.run()
    out = np.concatenate([st_long.request.prompt,
                          np.asarray(st_long.generated, np.int32)])
    assert np.array_equal(out, _golden(tiny_model, long_p, 2))
    assert np.array_equal(
        np.concatenate([st_short.request.prompt,
                        np.asarray(st_short.generated, np.int32)]),
        _golden(tiny_model, REP_PROMPT, 8))
    eng.shutdown()


def test_spec_decode_token_exact_fewer_dispatches(tiny_model):
    eng = _engine(tiny_model)
    base = eng.generate([REP_PROMPT], max_new_tokens=10)[0]
    base_dispatches = eng._stats["decode_dispatches"]
    eng.shutdown()
    with flag_scope("serve_spec_k", 3):
        eng2 = _engine(tiny_model)
    out = eng2.generate([REP_PROMPT], max_new_tokens=10)[0]
    assert np.array_equal(out, base)
    st = eng2._stats
    assert st["spec_proposed"] > 0 and st["spec_accepted"] > 0
    assert st["verify_dispatches"] > 0
    # accepted drafts rode shared verify dispatches: strictly fewer
    # decode-phase dispatches than one-token-per-dispatch
    assert st["decode_dispatches"] < base_dispatches
    s = eng2.metrics_summary()
    assert s["spec_accept_pct"] > 0
    eng2.shutdown()


def test_spec_rollback_truncates_rejected_tail(tiny_model):
    """A draft the verifier rejects is rolled back: counters record the
    rollback and the slot's pages cover only committed tokens."""
    with flag_scope("serve_spec_k", 4), flag_scope("serve_spec_ngram", 1):
        eng = _engine(tiny_model)
    # 1-gram lookup on a prompt whose repetition the model's greedy
    # continuation does NOT follow forever -> some drafts miss
    rng = np.random.default_rng(9)
    p = rng.integers(2, 250, (6,)).tolist()
    prompt = p + p[:3]
    out = eng.generate([prompt], max_new_tokens=8)[0]
    assert np.array_equal(out, _golden(tiny_model, prompt, 8))
    st = eng._stats
    assert st["spec_proposed"] == st["spec_accepted"] \
        + st["spec_rolled_back"]
    assert eng.cache.allocator.pages_in_use == 0      # all released
    eng.shutdown()


def test_sampled_slots_ride_verify_row0(tiny_model):
    """Mixed greedy+sampled batches compose under serve_spec_k: the
    sampled slot runs stochastic accept/reject over the shared verify
    dispatch (ISSUE 16) while the greedy slot's stream stays pinned to
    the oracle."""
    with flag_scope("serve_spec_k", 3):
        eng = _engine(tiny_model)
    sts = [eng.submit(Request(REP_PROMPT, max_new_tokens=6)),
           eng.submit(Request([9, 8, 7, 6], max_new_tokens=6,
                              sampling=SamplingParams(temperature=0.8,
                                                      top_k=40)))]
    eng.run()
    assert all(st.outcome == "completed" for st in sts)
    assert len(sts[1].generated) == 6
    # the greedy slot's stream is still the oracle's
    out = np.concatenate([sts[0].request.prompt,
                          np.asarray(sts[0].generated, np.int32)])
    assert np.array_equal(out, _golden(tiny_model, REP_PROMPT, 6))
    eng.shutdown()


def test_all_three_composed_token_exact(tiny_model, oracle):
    with flag_scope("serve_prefix_cache", True), \
            flag_scope("serve_prefill_chunk", 4), \
            flag_scope("serve_spec_k", 3):
        eng = _engine(tiny_model)
    outs = []
    for p in oracle["prompts"]:
        outs.append(eng.generate([p], max_new_tokens=6)[0].tolist())
    assert outs == oracle["outs"]
    assert eng.prefix_cache.stats["hit_tokens"] > 0
    assert eng._stats["prefill_chunks"] > 0
    assert eng._stats["spec_proposed"] > 0
    assert eng.cache.allocator.pages_in_use \
        == eng.prefix_cache.cached_pages      # only the tree holds pages
    eng.shutdown()
    assert eng.cache.allocator.pages_in_use == 0


def test_flags_off_no_new_series_or_dispatches(tiny_model):
    """Zero-overhead contract: with all three flags at their defaults
    the engine adds no prefix/spec/chunk registry series and performs
    the same dispatch sequence as before ISSUE 15."""
    with scoped_registry() as reg:
        eng = _engine(tiny_model)
        assert eng.prefix_cache is None
        eng.generate([[5, 6, 7, 8], [9, 10, 11]], max_new_tokens=4)
        names = set(reg.names())
        eng.shutdown()
    assert not any(n.startswith(("serve_prefix_", "serve_spec_"))
                   or n == "serve_prefill_chunks_total"
                   for n in names)


def test_stochastic_spec_sampling_distribution_parity(tiny_model):
    """ISSUE 16: sampled slots run stochastic accept/reject residual
    sampling over the verify dispatch (Leviathan et al.) — the marginal
    token distribution must be IDENTICAL to plain sampled decode, not
    merely plausible. Drive M identical sampled requests through a
    plain engine and a serve_spec_k engine and compare per-position
    marginal histograms by total-variation distance."""
    M, BATCH, NEW = 400, 20, 4
    sp = SamplingParams(temperature=0.7, top_k=4)

    def marginals(spec_k):
        ctx = (flag_scope("serve_spec_k", spec_k) if spec_k
               else _null_ctx())
        with ctx:
            eng = _engine(tiny_model)
        counts = np.zeros((NEW, 256))
        for _ in range(M // BATCH):
            outs = eng.generate([REP_PROMPT] * BATCH,
                                max_new_tokens=NEW, sampling=sp)
            for o in outs:
                for pos in range(NEW):
                    counts[pos, int(o[len(REP_PROMPT) + pos])] += 1
        stats = dict(eng._stats)
        eng.shutdown()
        return counts / M, stats

    plain, _ = marginals(0)
    spec, st = marginals(3)
    # the spec path must actually have run: drafts proposed AND some
    # accepted via the stochastic rule (a never-accepts bug would still
    # pass the distribution check — rejects resample the residual)
    assert st["spec_proposed"] > 0 and st["spec_accepted"] > 0
    for pos in range(NEW):
        tv = 0.5 * np.abs(plain[pos] - spec[pos]).sum()
        assert tv < 0.2, f"position {pos}: TV {tv:.3f}"


def _null_ctx():
    import contextlib
    return contextlib.nullcontext()


# ---------------------------------------------------------------------------
# scheduler fuzz with the prefix cache armed: refcount invariants
# ---------------------------------------------------------------------------


def test_scheduler_fuzz_refcount_invariants():
    """260 random interleavings of submit/admit/decode/finish/cancel/
    preempt with donation + COW matches live: no page is on the free
    list while any slot or the tree maps it, refcounts equal the
    mapping count, writes never start below the shared coverage, and an
    eviction storm leaks nothing."""
    cache = _host_cache(num_pages=14, block_size=4, max_slots=3)
    pc = RadixPrefixCache(cache)
    cache.prefix_cache = pc
    sched = Scheduler(cache, BucketTable((8, 16, 24), (1, 2)),
                      max_queue=32)
    alloc = cache.allocator
    rng = np.random.default_rng(777)
    submitted = []
    # a few hot prefixes so matches actually occur
    prefixes = [rng.integers(1, 99, (8,)).tolist() for _ in range(3)]

    def check_invariants():
        free = list(alloc._free)
        assert len(free) == len(set(free))
        mapped = {}
        for slot, pages in enumerate(cache._slot_pages):
            for p in pages:
                mapped[p] = mapped.get(p, 0) + 1
        for p in pc._nodes:
            mapped[p] = mapped.get(p, 0) + 1
        # refcount == number of mappings, for every allocated page
        assert mapped == dict(alloc._rc)
        # free list disjoint from every mapping
        assert not set(mapped) & set(free)
        assert alloc.pages_in_use == len(mapped)
        # COW: no slot's prefill cursor sits below its shared coverage
        for slot, st in ((i, s) for i, s in enumerate(sched.slots)
                         if s is not None):
            assert st.prefill_pos >= \
                cache.slot_shared_blocks(slot) * cache.block_size

    for it in range(260):
        op = int(rng.integers(0, 7))
        if op == 0:
            pre = prefixes[int(rng.integers(0, len(prefixes)))]
            tail = rng.integers(1, 99,
                                (int(rng.integers(1, 5)),)).tolist()
            try:
                submitted.append(sched.submit(Request(
                    pre + tail,
                    max_new_tokens=int(rng.integers(1, 6)))))
            except Exception:
                pass
        elif op == 1:
            sched.plan_admissions()
            # simulate the engine's prefill completing instantly
            for _, st in sched.active():
                if st.prefilling:
                    st.prefill_pos = st.prefill_len
        elif op == 2:
            sched.ensure_decode_capacity()
            for _, st in list(sched.active()):
                if st.prefilling:
                    continue
                st.generated.append(int(rng.integers(1, 99)))
                if st.is_done():
                    sched.finish(st)
        elif op == 3 and submitted:
            st = submitted[int(rng.integers(0, len(submitted)))]
            sched.cancel(st.request.request_id)
        elif op == 4:
            act = sched.active()
            if act and rng.random() < 0.4:
                _, st = act[int(rng.integers(0, len(act)))]
                sched.fail(st, "fuzz")
        elif op == 5:
            # eviction pressure
            pc.evict_for(int(rng.integers(1, 4)))
        elif op == 6:
            pool = sched.waiting + [s for _, s in sched.active()]
            if pool and rng.random() < 0.2:
                sched.drain_release(
                    pool[int(rng.integers(0, len(pool)))])
        check_invariants()

    guard = 0
    while sched.has_work:
        sched.plan_admissions()
        for _, st in sched.active():
            if st.prefilling:
                st.prefill_pos = st.prefill_len
        sched.ensure_decode_capacity()
        for _, st in list(sched.active()):
            st.generated.append(1)
            if st.is_done():
                sched.finish(st)
        check_invariants()
        guard += 1
        assert guard < 2000
    # eviction storm drains the tree; nothing leaks
    pc.evict_for(10_000)
    check_invariants()
    assert alloc.pages_in_use == 0


# ---------------------------------------------------------------------------
# drain snapshots: chunked progress + in-flight drafts survive
# ---------------------------------------------------------------------------


def test_request_spec_records_chunk_progress_and_drafts():
    cache = _host_cache()
    sched = Scheduler(cache, BucketTable((8, 16, 24), (1, 2)))
    st = sched.submit(Request(list(range(1, 13)), max_new_tokens=4))
    sched.plan_admissions()
    st.prefill_pos = 8                 # mid-chunk
    spec = request_spec(st)
    assert spec["prefill_pos"] == 8 and spec["draft"] == []
    st.prefill_pos = st.prefill_len
    st.generated.append(42)
    st.draft = [7, 8]
    spec = request_spec(st)
    assert spec["draft"] == [7, 8]
    assert spec["generated"] == [42]   # drafts never count as committed
    # restore ignores uncommitted drafts: the effective prompt is
    # prompt+generated only
    reqs = requests_from_snapshot([spec])
    assert reqs[0].prompt.tolist() == list(range(1, 13)) + [42]
    assert reqs[0].max_new_tokens == 3


def test_drain_mid_chunk_resumes_token_exact(tiny_model, tmp_path):
    """SIGTERM mid-chunked-prefill: the snapshot records prefill
    progress and the backlog re-runs token-exactly on a successor —
    including through a TORN second commit that must fall back to the
    valid mid-chunk snapshot (the PR 8 drill extended to ISSUE 15)."""
    long_p = np.random.default_rng(8).integers(2, 250, (40,)).tolist()
    golden = _golden(tiny_model, long_p, 4)
    snap = str(tmp_path / "drain")

    def drain_mid_chunk(torn: bool):
        with flag_scope("serve_prefill_chunk", 4), \
                flag_scope("serve_spec_k", 3):
            eng = _engine(tiny_model, max_context_len=64,
                          prefill_buckets=(4, 8, 16, 64))
        st = eng.submit(Request(long_p, max_new_tokens=4))
        eng.step()
        eng.step()                      # a couple of chunks in
        assert st.prefilling and 0 < st.prefill_pos < len(long_p)
        if torn:
            with chaos.chaos_scope("ckpt.write.torn@1"):
                report = eng.drain(snapshot_dir=snap, budget_s=0.0)
        else:
            report = eng.drain(snapshot_dir=snap, budget_s=0.0)
        assert report.snapshotted == 1 and st.outcome == "drained"
        eng.shutdown()
        return st

    st1 = drain_mid_chunk(torn=False)
    drain_mid_chunk(torn=True)          # torn commit of drain_2
    path, specs = load_drain_snapshot(snap)
    assert path.endswith("drain_1")     # fell back past the torn dir
    assert specs and specs[0]["prefill_pos"] == st1.prefill_pos
    assert specs[0]["generated"] == [] and specs[0]["draft"] == []
    # successor: plain flags-off engine re-runs the backlog
    eng2 = _engine(tiny_model, max_context_len=64,
                   prefill_buckets=(4, 8, 16, 64))
    [req] = requests_from_snapshot(specs)
    st2 = eng2.submit(req)
    eng2.run()
    out = np.concatenate([req.prompt,
                          np.asarray(st2.generated, np.int32)])
    assert np.array_equal(out, golden)
    eng2.shutdown()


# ---------------------------------------------------------------------------
# loadgen chat workload
# ---------------------------------------------------------------------------


def test_loadgen_shared_prefix_pool_zipf():
    spec = LoadSpec(num_requests=40, rate_rps=100.0,
                    prompt_len_range=(4, 8), seed=3,
                    shared_prefix_len=12, prefix_pool_size=4,
                    prefix_zipf=1.3)
    reqs = [r for _, r in build_requests(spec)]
    heads = {tuple(r.prompt[:12].tolist()) for r in reqs}
    assert 1 < len(heads) <= 4                 # pool-sized reuse
    assert all(r.prompt.size >= 12 + 4 for r in reqs)
    # deterministic per seed
    reqs2 = [r for _, r in build_requests(spec)]
    assert all(np.array_equal(a.prompt, b.prompt)
               for a, b in zip(reqs, reqs2))
    # hot head: the most reused prefix dominates (zipf, rank 0)
    counts = {}
    for r in reqs:
        counts[tuple(r.prompt[:12].tolist())] = \
            counts.get(tuple(r.prompt[:12].tolist()), 0) + 1
    assert max(counts.values()) >= 40 // 3


def test_loadgen_default_spec_byte_identical():
    """shared_prefix_len=0 (default) draws NOTHING extra: traffic is
    byte-identical with the feature compiled in."""
    a = build_requests(LoadSpec(num_requests=12, seed=5))
    b = build_requests(LoadSpec(num_requests=12, seed=5,
                                shared_prefix_len=0,
                                prefix_pool_size=99, prefix_zipf=9.9))
    for (ta, ra), (tb, rb) in zip(a, b):
        assert ta == tb and np.array_equal(ra.prompt, rb.prompt)
        assert ra.max_new_tokens == rb.max_new_tokens


# ---------------------------------------------------------------------------
# observability: report render + phase surfacing
# ---------------------------------------------------------------------------


def test_monitor_report_renders_prefix_and_spec_tables(tiny_model,
                                                       tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import monitor_report
    with scoped_registry() as reg:
        with flag_scope("serve_prefix_cache", True), \
                flag_scope("serve_spec_k", 3):
            eng = _engine(tiny_model)
        eng.generate([REP_PROMPT], max_new_tokens=6)
        eng.generate([REP_PROMPT + [3]], max_new_tokens=4)
        path = str(tmp_path / "m.jsonl")
        reg.dump_jsonl(path)
        eng.shutdown()
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    out = monitor_report.render(rows, serve=True)
    assert "Prefix cache (radix tree over KV pages)" in out
    assert "Speculative decoding (n-gram drafts)" in out
    assert "tokens served from cache" in out
    assert "% acceptance" in out


def test_statusz_slot_phase(tiny_model):
    with flag_scope("serve_prefill_chunk", 4):
        eng = _engine(tiny_model, max_context_len=64,
                      prefill_buckets=(4, 8, 16, 64))
    long_p = np.random.default_rng(4).integers(2, 250, (32,)).tolist()
    st = eng.submit(Request(long_p, max_new_tokens=2))
    eng.step()
    state = eng.scheduler.state()
    assert state["slots"][0]["phase"] == "prefilling"
    assert 0 < state["slots"][0]["prefill_pos"] < 32
    eng.run()
    assert st.outcome == "completed"
    eng.shutdown()
