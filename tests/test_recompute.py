"""Activation-recompute parity tests.

Analogue of the reference's recompute tests
(reference: test_dygraph_recompute.py — loss/grad parity with and without
recompute, RNG consistency with dropout). Here jax.checkpoint does the
rematerialization; grads must be bit-comparable either way.
"""

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.utils import recompute


def _mlp():
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 16))


def test_eager_grad_parity():
    paddle.seed(7)
    blk = _mlp()
    x_np = np.random.RandomState(0).randn(4, 16).astype(np.float32)

    x = paddle.to_tensor(x_np)
    x.stop_gradient = False
    loss = blk(x).sum()
    loss.backward()
    ref_grads = {k: np.asarray(p.grad._data)
                 for k, p in blk.named_parameters()}
    ref_gx = np.asarray(x.grad._data)

    blk.clear_gradients()
    x2 = paddle.to_tensor(x_np)
    x2.stop_gradient = False
    loss2 = recompute(blk, x2).sum()
    loss2.backward()
    np.testing.assert_allclose(float(loss), float(loss2), rtol=1e-6)
    for k, p in blk.named_parameters():
        np.testing.assert_allclose(ref_grads[k], np.asarray(p.grad._data),
                                   rtol=1e-6, err_msg=k)
    np.testing.assert_allclose(ref_gx, np.asarray(x2.grad._data), rtol=1e-6)


def test_closure_captured_layer_gets_grads():
    paddle.seed(8)
    blk = nn.Linear(8, 8)
    x = paddle.to_tensor(np.random.RandomState(1).randn(4, 8)
                         .astype(np.float32))
    x.stop_gradient = False
    loss = recompute(lambda t: F.relu(blk(t)), x).sum()
    loss.backward()
    assert blk.weight.grad is not None
    assert blk.bias.grad is not None
    assert x.grad is not None


def test_dropout_mask_consistent_between_fwd_and_remat():
    # the rematerialized forward must replay the SAME dropout mask the
    # primal forward drew (keys are split at trace time)
    paddle.seed(9)
    blk = nn.Sequential(nn.Linear(16, 16), nn.Dropout(0.5))
    x = paddle.to_tensor(np.random.RandomState(2).randn(4, 16)
                         .astype(np.float32))
    x.stop_gradient = False
    out = recompute(blk, x)
    loss = out.sum()
    loss.backward()
    # if masks diverged, grad wrt x would not match the dropout pattern of
    # the forward output: zeros in out must imply zero grad columns through
    # the dropped units — check grad finite and nonzero overall instead of
    # brittle elementwise structure:
    g = np.asarray(x.grad._data)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_recompute_sequential_param_grads():
    from paddle_tpu.distributed.fleet.utils import recompute_sequential

    paddle.seed(12)
    layers = [nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 8), nn.ReLU(),
              nn.Linear(8, 4)]
    x = paddle.to_tensor(np.random.RandomState(5).randn(4, 8)
                         .astype(np.float32))
    out = recompute_sequential({"segments": 2}, layers, x)
    out.sum().backward()
    for lyr in layers:
        for _, p in lyr.named_parameters():
            assert p.grad is not None, "segment params lost from grad path"
            assert np.isfinite(np.asarray(p.grad._data)).all()


def test_jitted_trainstep_with_recompute_converges():
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForPretraining,
                                       GPTPretrainingCriterion)
    from paddle_tpu.optimizer import AdamW

    paddle.seed(10)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                    max_position_embeddings=64, use_recompute=True)
    m = GPTForPretraining(cfg)
    m.train()
    crit = GPTPretrainingCriterion()
    step = TrainStep(m, lambda l, i, t: crit(l(i), t),
                     AdamW(learning_rate=1e-3, parameters=m.parameters()))
    ids = np.random.RandomState(3).randint(0, 128, (2, 32)).astype(np.int32)
    losses = [float(step(ids, ids)) for _ in range(5)]
    assert losses[-1] < losses[0]


def test_recompute_vs_plain_jit_loss_parity():
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForPretraining,
                                       GPTPretrainingCriterion)
    from paddle_tpu.optimizer import AdamW

    losses = {}
    for use_rc in (False, True):
        paddle.seed(11)
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=4, max_position_embeddings=64,
                        hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                        use_recompute=use_rc)
        m = GPTForPretraining(cfg)
        m.train()
        crit = GPTPretrainingCriterion()
        step = TrainStep(m, lambda l, i, t: crit(l(i), t),
                         AdamW(learning_rate=1e-3,
                               parameters=m.parameters()))
        ids = np.random.RandomState(4).randint(0, 128, (2, 32)) \
            .astype(np.int32)
        losses[use_rc] = [float(step(ids, ids)) for _ in range(3)]
    np.testing.assert_allclose(losses[False], losses[True], rtol=1e-5)
