"""Tensor-parallel layer tests on the 8-device CPU mesh.

Analogue of the reference's mp-layer parity tests
(reference: test_parallel_dygraph_mp_layers.py — sharded layers vs a
single-device gold model within tolerance). Here the TP run executes the
GSPMD partitioning over a real 8-way mesh and must match the dense gold.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.distributed.meta_parallel import (ColumnParallelLinear,
                                                  ParallelCrossEntropy,
                                                  RowParallelLinear,
                                                  VocabParallelEmbedding)

N = 8


@pytest.fixture(scope="module", autouse=True)
def mp_mesh():
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": N}
    fleet.init(is_collective=True, strategy=strategy)
    yield fleet.get_hybrid_communicate_group()
    from paddle_tpu.distributed import env as dist_env
    dist_env.reset()


def _sharded_forward(layer, x_np):
    """jit the layer forward with params laid out per their specs."""
    dist.apply_param_shardings(layer)
    static = paddle.jit.to_static(layer)
    with paddle.no_grad():
        out = static(paddle.to_tensor(x_np))
    return out.numpy() if not isinstance(out, (tuple, list)) else out


def test_vocab_parallel_embedding_matches_dense(mp_mesh):
    V, D = 64, 16
    rng = np.random.RandomState(0)
    table = rng.randn(V, D).astype(np.float32)
    ids = rng.randint(0, V, (4, 12)).astype(np.int32)

    layer = VocabParallelEmbedding(V, D)
    layer.weight._data = jnp.asarray(table)
    # weight is actually sharded over the vocab dim
    dist.apply_param_shardings(layer)
    shard_shapes = {s.data.shape for s in layer.weight._data.addressable_shards}
    assert shard_shapes == {(V // N, D)}

    out = _sharded_forward(layer, ids)
    np.testing.assert_allclose(out, table[ids], rtol=1e-6)


def test_column_parallel_linear_matches_dense(mp_mesh):
    I, O = 16, 32
    rng = np.random.RandomState(1)
    w = rng.randn(I, O).astype(np.float32)
    b = rng.randn(O).astype(np.float32)
    x = rng.randn(6, I).astype(np.float32)

    layer = ColumnParallelLinear(I, O, gather_output=True)
    layer.weight._data = jnp.asarray(w)
    layer.bias._data = jnp.asarray(b)
    dist.apply_param_shardings(layer)
    assert {s.data.shape for s in layer.weight._data.addressable_shards} == \
        {(I, O // N)}

    out = _sharded_forward(layer, x)
    np.testing.assert_allclose(out, x @ w + b, rtol=1e-4, atol=1e-5)


def test_row_parallel_linear_matches_dense(mp_mesh):
    I, O = 32, 16
    rng = np.random.RandomState(2)
    w = rng.randn(I, O).astype(np.float32)
    b = rng.randn(O).astype(np.float32)
    x = rng.randn(6, I).astype(np.float32)

    layer = RowParallelLinear(I, O)
    layer.weight._data = jnp.asarray(w)
    layer.bias._data = jnp.asarray(b)
    dist.apply_param_shardings(layer)
    assert {s.data.shape for s in layer.weight._data.addressable_shards} == \
        {(I // N, O)}

    out = _sharded_forward(layer, x)
    np.testing.assert_allclose(out, x @ w + b, rtol=1e-4, atol=1e-5)


def test_column_into_row_mlp(mp_mesh):
    """gather_output=False -> input_is_parallel=True composition: the
    activation stays sharded between the two layers (reference: no c_concat
    between column and row layers in a transformer MLP)."""
    I, H = 16, 64
    rng = np.random.RandomState(3)
    w1 = rng.randn(I, H).astype(np.float32)
    w2 = rng.randn(H, I).astype(np.float32)
    x = rng.randn(4, I).astype(np.float32)

    class MLP(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.up = ColumnParallelLinear(I, H, gather_output=False,
                                           has_bias=False)
            self.down = RowParallelLinear(H, I, input_is_parallel=True,
                                          has_bias=False)

        def forward(self, x):
            return self.down(paddle.nn.functional.relu(self.up(x)))

    mlp = MLP()
    mlp.up.weight._data = jnp.asarray(w1)
    mlp.down.weight._data = jnp.asarray(w2)

    out = _sharded_forward(mlp, x)
    expected = np.maximum(x @ w1, 0.0) @ w2
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_parallel_cross_entropy_matches_dense(mp_mesh):
    B, V = 8, 64
    rng = np.random.RandomState(4)
    logits = rng.randn(B, V).astype(np.float32)
    labels = rng.randint(0, V, (B,)).astype(np.int64)

    # gold: dense softmax CE
    import torch
    gold = torch.nn.functional.cross_entropy(
        torch.tensor(logits), torch.tensor(labels), reduction="none").numpy()

    ce = ParallelCrossEntropy()
    mesh = mp_mesh.mesh
    lg = jax.device_put(jnp.asarray(logits), NamedSharding(mesh, P(None, "mp")))
    out = ce(paddle.to_tensor(lg), paddle.to_tensor(labels))
    np.testing.assert_allclose(out.numpy()[:, 0], gold, rtol=1e-5, atol=1e-6)


def test_parallel_cross_entropy_grad_matches_dense(mp_mesh):
    B, V = 4, 32
    rng = np.random.RandomState(5)
    logits = rng.randn(B, V).astype(np.float32)
    labels = rng.randint(0, V, (B,)).astype(np.int64)

    ce = ParallelCrossEntropy()
    t = paddle.to_tensor(logits, stop_gradient=False)
    loss = ce(t, paddle.to_tensor(labels)).mean()
    loss.backward()

    import torch
    tt = torch.tensor(logits, requires_grad=True)
    tloss = torch.nn.functional.cross_entropy(tt, torch.tensor(labels))
    tloss.backward()
    np.testing.assert_allclose(t.grad.numpy(), tt.grad.numpy(),
                               rtol=1e-4, atol=1e-6)


def test_split_api(mp_mesh):
    x = paddle.to_tensor(np.random.RandomState(6).randn(4, 16).astype(np.float32))
    out = dist.split(x, (16, 32), operation="linear", axis=1)
    assert out.shape == [4, 32]


def test_rng_tracker_streams(mp_mesh):
    from paddle_tpu.distributed.meta_parallel.parallel_layers import (
        get_rng_state_tracker, model_parallel_random_seed)
    model_parallel_random_seed(42)
    tracker = get_rng_state_tracker()
    x = paddle.to_tensor(np.ones((1000,), np.float32))
    paddle.seed(7)
    with tracker.rng_state():  # local stream
        a = paddle.nn.functional.dropout(x, 0.5).numpy()
    paddle.seed(7)
    b = paddle.nn.functional.dropout(x, 0.5).numpy()  # global stream
    assert (a != b).any()  # streams differ
    paddle.seed(7)
    with tracker.rng_state():
        a2 = paddle.nn.functional.dropout(x, 0.5).numpy()
    np.testing.assert_array_equal(a, a2)  # deterministic per stream
