"""Real ONNX export: jaxpr -> hand-emitted ModelProto, verified by the
bundled decoder + numpy runtime (reference: python/paddle/onnx/export.py
via paddle2onnx)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, onnx_export
from paddle_tpu.core.tensor import no_grad
from paddle_tpu.jit.input_spec import InputSpec


class MLP(paddle.nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 16)
        self.fc2 = nn.Linear(16, 4)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        return F.softmax(self.fc2(F.relu(self.fc1(x))), axis=-1)


def test_mlp_numeric_parity(tmp_path):
    paddle.seed(0)
    m = MLP()
    p = onnx_export.export(m, str(tmp_path / "mlp"),
                           input_spec=[InputSpec((2, 8), "float32")])
    assert p.endswith(".onnx")
    model = onnx_export.load_model(p)
    assert model.ir_version == 8 and model.opset == 13
    assert model.inputs == ["x0"] and len(model.outputs) == 1
    x = np.random.default_rng(0).normal(size=(2, 8)).astype(np.float32)
    (out,) = onnx_export.run_model(model, {"x0": x})
    with no_grad():
        ref = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_lenet_conv_pool_parity(tmp_path):
    from paddle_tpu.vision.models import LeNet

    paddle.seed(1)
    m = LeNet()
    m.eval()
    p = onnx_export.export(m, str(tmp_path / "lenet"),
                           input_spec=[InputSpec((2, 1, 28, 28),
                                                 "float32")])
    model = onnx_export.load_model(p)
    ops = {n.op for n in model.nodes}
    assert {"Conv", "MaxPool", "MatMul"} <= ops
    x = np.random.default_rng(1).normal(size=(2, 1, 28, 28)) \
        .astype(np.float32)
    (out,) = onnx_export.run_model(model, {"x0": x})
    with no_grad():
        ref = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_unsupported_primitive_raises_with_name(tmp_path):
    class Cumsum(paddle.nn.Layer):
        def forward(self, x):
            return paddle.cumsum(x, axis=-1)

    with pytest.raises(onnx_export.UnsupportedOnnxExport,
                       match="cumsum"):
        onnx_export.export(Cumsum(), str(tmp_path / "bad"),
                           input_spec=[InputSpec((2, 4), "float32")])


def test_paddle_onnx_export_fallback_warns(tmp_path):
    class Cumsum(paddle.nn.Layer):
        def forward(self, x):
            return paddle.cumsum(x, axis=-1)

    with pytest.warns(UserWarning, match="StableHLO"):
        out = paddle.onnx.export(Cumsum(), str(tmp_path / "fb"),
                                 input_spec=[InputSpec((2, 4), "float32")])
    assert out.endswith(".mlir")

    # and the happy path returns a real .onnx file
    p = paddle.onnx.export(MLP(), str(tmp_path / "ok"),
                           input_spec=[InputSpec((1, 8), "float32")])
    assert p.endswith(".onnx")
    import os
    assert os.path.getsize(p) > 500


def test_wire_format_roundtrip_details(tmp_path):
    """The emitted bytes parse back with correct structure (initializer
    dtypes/shapes, node attributes)."""
    paddle.seed(2)
    m = MLP()
    p = onnx_export.export(m, str(tmp_path / "wire"),
                           input_spec=[InputSpec((3, 8), "float32")])
    model = onnx_export.load_model(p)
    inits = model.initializers
    shapes = sorted(tuple(v.shape) for v in inits.values()
                    if v.ndim == 2)
    assert (8, 16) in shapes and (16, 4) in shapes
    # every node input resolves to a graph input, initializer, or a
    # previous node output
    known = set(model.inputs) | set(inits)
    for n in model.nodes:
        for i in n.inputs:
            assert i in known, (n.op, i)
        known.update(n.outputs)


def test_opset13_forms_and_validation(tmp_path):
    """Review regressions: ReduceMax carries axes as an ATTRIBUTE at
    opset 13; low opsets and unknown configs are rejected."""

    class RMax(paddle.nn.Layer):
        def forward(self, x):
            return paddle.max(x, axis=-1)

    p = onnx_export.export(RMax(), str(tmp_path / "rmax"),
                           input_spec=[InputSpec((2, 4), "float32")])
    model = onnx_export.load_model(p)
    rmax = [n for n in model.nodes if n.op == "ReduceMax"][0]
    assert len(rmax.inputs) == 1 and "axes" in rmax.attrs
    x = np.random.default_rng(3).normal(size=(2, 4)).astype(np.float32)
    (out,) = onnx_export.run_model(model, {"x0": x})
    np.testing.assert_allclose(out, x.max(-1), atol=1e-6)

    with pytest.raises(ValueError, match="opset"):
        onnx_export.export(MLP(), str(tmp_path / "old"),
                           input_spec=[InputSpec((1, 8), "float32")],
                           opset_version=9)
    with pytest.raises(ValueError, match="options"):
        paddle.onnx.export(MLP(), str(tmp_path / "cfg"),
                           input_spec=[InputSpec((1, 8), "float32")],
                           export_params=False)


def test_bert_tiny_transformer_export_parity(tmp_path):
    """A full transformer (embeddings, attention einsums as general
    dot_general, LayerNorm, gelu, tied MLM head) exports and the decoded
    graph matches the model numerically."""
    from paddle_tpu.models.bert import BertForMaskedLM, bert_tiny

    paddle.seed(0)
    m = BertForMaskedLM(bert_tiny())
    m.eval()
    p = onnx_export.export(m, str(tmp_path / "bert"),
                           input_spec=[InputSpec((2, 128), "int32")])
    model = onnx_export.load_model(p)
    ops = {n.op for n in model.nodes}
    assert {"MatMul", "Gather", "Erf", "Transpose"} <= ops
    ids = np.random.default_rng(0).integers(0, 256, (2, 128)) \
        .astype(np.int32)
    (out,) = onnx_export.run_model(model, {"x0": ids})
    with no_grad():
        ref = m(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_gpt_tiny_causal_export_parity(tmp_path):
    from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny

    paddle.seed(1)
    m = GPTForPretraining(gpt_tiny())
    m.eval()
    p = onnx_export.export(m, str(tmp_path / "gpt"),
                           input_spec=[InputSpec((2, 64), "int32")])
    model = onnx_export.load_model(p)
    ids = np.random.default_rng(1).integers(0, 256, (2, 64)) \
        .astype(np.int32)
    (out,) = onnx_export.run_model(model, {"x0": ids})
    with no_grad():
        ref = m(paddle.to_tensor(ids)).numpy()
    np.testing.assert_allclose(out, ref, atol=3e-4, rtol=3e-4)


def test_ernie_multi_output_export_parity(tmp_path):
    """Multi-output graph: ERNIE's (MLM scores, SOP logits) both export
    and execute to parity."""
    from paddle_tpu.models.ernie import ErnieForPretraining, ernie_tiny

    paddle.seed(2)
    m = ErnieForPretraining(ernie_tiny())
    m.eval()
    p = onnx_export.export(m, str(tmp_path / "ernie"),
                           input_spec=[InputSpec((2, 64), "int32")])
    model = onnx_export.load_model(p)
    assert len(model.outputs) == 2
    ids = np.random.default_rng(2).integers(0, 256, (2, 64)) \
        .astype(np.int32)
    outs = onnx_export.run_model(model, {"x0": ids})
    with no_grad():
        refs = m(paddle.to_tensor(ids))
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(o, r.numpy(), atol=3e-4, rtol=3e-4)


def test_resnet18_export_parity(tmp_path):
    """CV family: ResNet-18 (convs, eval-mode BN, residual adds, pools)
    exports and executes to parity."""
    from paddle_tpu.vision.models import resnet18

    paddle.seed(3)
    m = resnet18(num_classes=10)
    m.eval()
    p = onnx_export.export(m, str(tmp_path / "r18"),
                           input_spec=[InputSpec((1, 3, 64, 64),
                                                 "float32")])
    model = onnx_export.load_model(p)
    assert {"Conv", "MaxPool"} <= {n.op for n in model.nodes}
    x = np.random.default_rng(3).normal(size=(1, 3, 64, 64)) \
        .astype(np.float32)
    (out,) = onnx_export.run_model(model, {"x0": x})
    with no_grad():
        ref = m(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, atol=1e-3, rtol=1e-3)


def test_dynamic_batch_dim_param_bert(tmp_path):
    """A None batch dim exports as a symbolic ``dim_param``; the bundled
    runtime executes TWO batch sizes from ONE file to numeric parity
    (round-4 verdict task: dynamic batch via dim_param)."""
    from paddle_tpu.models.bert import BertForMaskedLM, bert_tiny
    from paddle_tpu.onnx_export import proto

    paddle.seed(0)
    m = BertForMaskedLM(bert_tiny())
    m.eval()
    p = onnx_export.export(m, str(tmp_path / "bert_dyn"),
                           input_spec=[InputSpec((None, 128), "int32")])
    model = onnx_export.load_model(p)
    # the input's leading dim is a dim_param named "batch"
    with open(p, "rb") as f:
        mfields = proto.parse_message(f.read())
    g = proto.parse_message(mfields[7][0])
    vi = proto.parse_message(g[11][0])
    tensor_type = proto.parse_message(
        proto.parse_message(vi[2][0])[1][0])
    shape_msg = proto.parse_message(tensor_type[2][0])
    dim0 = proto.parse_message(shape_msg[1][0])
    assert dim0[2][0].decode() == "batch", dim0
    # runtime executes two batch sizes from the same file
    rng = np.random.default_rng(1)
    for B in (2, 5):
        ids = rng.integers(0, 256, (B, 128)).astype(np.int32)
        (out,) = onnx_export.run_model(model, {"x0": ids})
        with no_grad():
            ref = m(paddle.to_tensor(ids)).numpy()
        assert out.shape[0] == B
        np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_dynamic_batch_mlp_and_gather_paths(tmp_path):
    """Dynamic batch through the simple-MatMul path + embedding Gather +
    broadcast/iota lowering."""
    from paddle_tpu import nn

    paddle.seed(1)
    net = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    net.eval()
    p = onnx_export.export(net, str(tmp_path / "mlp_dyn"),
                           input_spec=[InputSpec((None, 16), "float32")])
    model = onnx_export.load_model(p)
    rng = np.random.default_rng(2)
    for B in (1, 7):
        x = rng.normal(size=(B, 16)).astype(np.float32)
        (out,) = onnx_export.run_model(model, {"x0": x})
        with no_grad():
            ref = net(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)
