"""Serving runtime (paddle_tpu.serving, ISSUE 6): paged KV decode,
continuous batching, AOT serving signatures, load generator, metrics."""

import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import no_grad
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.monitor import scoped_registry
from paddle_tpu.serving import (BlockAllocator, BucketTable, LoadSpec,
                                Request, SamplingParams, ServingConfig,
                                ServingEngine, StreamingDetokenizer,
                                build_requests)

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


def _engine(model, **kw):
    cfg = dict(max_batch_slots=3, block_size=4, max_context_len=64,
               prefill_buckets=(8, 16), batch_buckets=(1, 2))
    cfg.update(kw)
    return ServingEngine(model, ServingConfig(**cfg))


def _golden(model, prompt, n):
    """Re-derive every generated token by full uncached forwards."""
    seq = np.asarray(prompt, np.int32)
    for _ in range(n):
        with no_grad():
            lg = model(paddle.to_tensor(seq[None, :])).numpy()
        seq = np.concatenate([seq, [np.int32(lg[0, -1].argmax())]])
    return seq


# ---------------------------------------------------------------------------
# host-side building blocks
# ---------------------------------------------------------------------------


def test_block_allocator():
    a = BlockAllocator(num_pages=5)            # page 0 reserved
    assert a.free_pages == 4 and a.pages_in_use == 0
    got = a.alloc(3)
    assert len(got) == 3 and 0 not in got
    assert a.alloc(2) is None                  # all-or-nothing
    assert a.pages_in_use == 3
    a.free(got[:2])
    assert a.free_pages == 3
    with pytest.raises(ValueError):
        a.free([0])                            # scratch page never freed


def test_bucket_table():
    t = BucketTable((8, 16, 32), (1, 2, 4))
    assert t.len_bucket(3) == 8
    assert t.len_bucket(16) == 16
    assert t.len_bucket(17) == 32
    with pytest.raises(ValueError):
        t.len_bucket(33)
    assert t.batch_bucket(1) == 1
    assert t.batch_bucket(3) == 4
    assert t.batch_bucket(9) == 4              # clamps to the largest
    assert len(t.signatures()) == 9


def test_request_validation(tiny_model):
    with pytest.raises(ValueError):
        Request([], max_new_tokens=4)
    with pytest.raises(ValueError):
        Request([1, 2], max_new_tokens=0)
    eng = _engine(tiny_model)
    with pytest.raises(ValueError):            # exceeds slot capacity
        eng.submit(Request(np.arange(60), max_new_tokens=10))
    # a request that can never hold its pages even alone must be
    # rejected at submit, not spin admission forever (livelock guard)
    small = _engine(tiny_model, num_pages=4, max_context_len=40,
                    prefill_buckets=(40,))
    with pytest.raises(ValueError, match="KV pages"):
        small.submit(Request(np.arange(2, 32), max_new_tokens=8))
    # the admission limit is the CONFIGURED window, not the cache's
    # block-rounded capacity (block 4 rounds 30 up to 32 physically)
    odd = _engine(tiny_model, block_size=4, max_context_len=30,
                  prefill_buckets=(30,))
    with pytest.raises(ValueError, match="context"):
        odd.submit(Request(np.arange(2, 28), max_new_tokens=6))  # 32 > 30


def test_serving_config_not_mutated_across_engines(tiny_model):
    cfg = ServingConfig(max_batch_slots=2, block_size=4,
                        max_context_len=512)
    e1 = ServingEngine(tiny_model, cfg)
    # gpt_tiny's max_position_embeddings=128 clamps the ENGINE's copy,
    # never the caller's config object
    assert e1.config.max_context_len == 128
    assert cfg.max_context_len == 512
    assert cfg.prefill_buckets is None and cfg.num_pages is None


def test_sampling_greedy_matches_argmax():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.serving.sampling import sample_tokens
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(4, 32)).astype(np.float32))
    toks = sample_tokens(logits, jax.random.key(0),
                         jnp.zeros((4,), jnp.float32),
                         jnp.zeros((4,), jnp.int32),
                         jnp.ones((4,), jnp.float32))
    np.testing.assert_array_equal(np.asarray(toks),
                                  np.asarray(logits).argmax(-1))
    # top_k=1 is greedy regardless of temperature
    toks1 = sample_tokens(logits, jax.random.key(1),
                          jnp.full((4,), 1.3, jnp.float32),
                          jnp.ones((4,), jnp.int32),
                          jnp.ones((4,), jnp.float32))
    np.testing.assert_array_equal(np.asarray(toks1),
                                  np.asarray(logits).argmax(-1))


# ---------------------------------------------------------------------------
# decode parity (acceptance: token-exact vs the full-context forward)
# ---------------------------------------------------------------------------


def test_paged_decode_token_exact_scan_layout(tiny_model):
    """prefill+decode split under scan == full forward, several prompt/
    generation lengths, slots finishing early."""
    eng = _engine(tiny_model)
    rng = np.random.default_rng(1)
    prompts = [rng.integers(2, 250, (n,)).astype(np.int32)
               for n in (3, 7, 14)]
    outs = eng.generate(prompts, max_new_tokens=6)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _golden(tiny_model, p, 6))


def test_paged_decode_loop_layout_matches_scan(tiny_model):
    from paddle_tpu.nn import scan as nn_scan
    rng = np.random.default_rng(2)
    prompts = [rng.integers(2, 250, (n,)).astype(np.int32)
               for n in (5, 11)]
    scan_out = _engine(tiny_model).generate(prompts, max_new_tokens=5)
    nn_scan.reset_scan_stats()
    with flag_scope("scan_decode", False), warnings.catch_warnings(
            record=True) as w:
        warnings.simplefilter("always")
        loop_out = _engine(tiny_model).generate(prompts, max_new_tokens=5)
    for a, b in zip(scan_out, loop_out):
        np.testing.assert_array_equal(a, b)
    # the kill switch is a RECORDED degradation, not a silent one
    assert nn_scan.SCAN_STATS["fallbacks"] >= 1
    msgs = [str(x.message) for x in w
            if "scan-over-layers fell back" in str(x.message)]
    assert len(msgs) == 1              # one-time warning, not per step


def test_mixed_finish_early_eos(tiny_model):
    rng = np.random.default_rng(3)
    p0 = rng.integers(2, 250, (6,)).astype(np.int32)
    p1 = rng.integers(2, 250, (9,)).astype(np.int32)
    eos = int(_golden(tiny_model, p0, 1)[-1])  # req 0's first token
    eng = _engine(tiny_model)
    st0 = eng.submit(Request(p0, max_new_tokens=8, eos_token_id=eos))
    st1 = eng.submit(Request(p1, max_new_tokens=8))
    eng.run()
    assert st0.generated == [eos]              # stopped at eos, token kept
    assert len(st1.generated) == 8
    np.testing.assert_array_equal(
        np.concatenate([p1, st1.generated]), _golden(tiny_model, p1, 8))
    assert eng.cache.allocator.pages_in_use == 0


# ---------------------------------------------------------------------------
# continuous batching (acceptance: >= 2 requests share one decode dispatch,
# streams stay correct, compile count bounded by the bucket table)
# ---------------------------------------------------------------------------


def test_continuous_batching_shares_decode_dispatch(tiny_model):
    eng = _engine(tiny_model)
    rng = np.random.default_rng(4)
    prompts = [rng.integers(2, 250, (6,)).astype(np.int32)
               for _ in range(3)]
    streams = {i: [] for i in range(3)}
    states = []
    for i, p in enumerate(prompts):
        states.append(eng.submit(Request(
            p, max_new_tokens=5,
            on_token=lambda req, tok, txt, i=i: streams[i].append(tok))))
    eng.run()
    s = eng.stats()
    # 3 requests x 5 tokens = 15 tokens out of 3 (prefill-sampled) + 4
    # decode dispatches: batching demonstrably shared the decode program
    assert s["decode_batch_max"] >= 2
    assert s["decode_dispatches"] < s["tokens_generated"]
    for i, (p, st) in enumerate(zip(prompts, states)):
        assert streams[i] == st.generated
        np.testing.assert_array_equal(
            np.concatenate([p, st.generated]),
            _golden(tiny_model, p, 5))


def test_compile_count_bounded_by_bucket_table(tiny_model):
    from paddle_tpu.utils import CompileCounter
    eng = _engine(tiny_model)
    rng = np.random.default_rng(5)
    prompts = [rng.integers(2, 250, (n,)).astype(np.int32)
               for n in (4, 12)]
    eng.generate(prompts, max_new_tokens=3)
    s1 = eng.stats()
    # every program is a bucket-table signature (+ the one decode)
    assert s1["resident_programs"] <= len(eng.buckets.signatures()) + 1
    compiles_before = s1["program_compiles"]
    with CompileCounter() as c:
        eng.generate([rng.integers(2, 250, (n,)).astype(np.int32)
                      for n in (5, 10)], max_new_tokens=3)
    # same buckets -> ZERO new serving programs and zero re-traces
    assert eng.stats()["program_compiles"] == compiles_before
    assert c.jaxpr_traces == 0
    assert c.backend_compiles == 0


def test_slot_turnover_more_requests_than_slots(tiny_model):
    eng = _engine(tiny_model, max_batch_slots=2)
    rng = np.random.default_rng(6)
    prompts = [rng.integers(2, 250, (5,)).astype(np.int32)
               for _ in range(5)]
    outs = eng.generate(prompts, max_new_tokens=4)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _golden(tiny_model, p, 4))
    s = eng.stats()
    assert s["completed"] == 5
    assert eng.cache.allocator.pages_in_use == 0
    assert eng.scheduler.queue_depth == 0


def test_padded_prefill_rows_never_touch_live_slots(tiny_model):
    """A prefill group smaller than its batch bucket carries padded rows;
    their garbage K/V must land on the scratch page, not in an active
    slot's pages (regression: padded rows once reused slot 0's block
    table)."""
    eng = _engine(tiny_model, max_batch_slots=4, batch_buckets=(1, 4))
    rng = np.random.default_rng(14)
    p0 = rng.integers(2, 250, (6,)).astype(np.int32)
    st0 = eng.submit(Request(p0, max_new_tokens=8))
    eng.step()                      # slot 0 admitted + first decode
    assert len(st0.generated) >= 1
    # 3 more arrive -> one prefill group of 3 padded up to batch bucket 4
    others = [rng.integers(2, 250, (6,)).astype(np.int32)
              for _ in range(3)]
    sts = [eng.submit(Request(p, max_new_tokens=4)) for p in others]
    eng.run()
    np.testing.assert_array_equal(
        np.concatenate([p0, st0.generated]), _golden(tiny_model, p0, 8))
    for p, st in zip(others, sts):
        np.testing.assert_array_equal(
            np.concatenate([p, st.generated]), _golden(tiny_model, p, 4))


def test_preemption_recompute_keeps_greedy_streams_exact(tiny_model):
    # pool of 9 usable pages, two requests needing 6 blocks each at the
    # end -> the newest-admitted must be preempted and recomputed
    eng = _engine(tiny_model, max_batch_slots=2, block_size=4,
                  max_context_len=24, num_pages=10,
                  prefill_buckets=(16, 24))
    rng = np.random.default_rng(7)
    prompts = [rng.integers(2, 250, (10,)).astype(np.int32)
               for _ in range(2)]
    outs = eng.generate(prompts, max_new_tokens=12)
    assert eng.stats()["preemptions"] >= 1
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _golden(tiny_model, p, 12))
    assert eng.cache.allocator.pages_in_use == 0


def test_mixed_sampling_one_dispatch(tiny_model):
    """Greedy and sampled requests share the decode program (per-slot
    sampling params are arguments, not signatures)."""
    eng = _engine(tiny_model)
    rng = np.random.default_rng(8)
    p0 = rng.integers(2, 250, (6,)).astype(np.int32)
    p1 = rng.integers(2, 250, (6,)).astype(np.int32)
    st0 = eng.submit(Request(p0, max_new_tokens=5))           # greedy
    st1 = eng.submit(Request(p1, max_new_tokens=5,
                             sampling=SamplingParams(temperature=0.9,
                                                     top_k=20)))
    eng.run()
    np.testing.assert_array_equal(
        np.concatenate([p0, st0.generated]), _golden(tiny_model, p0, 5))
    assert all(0 <= t < 256 for t in st1.generated)
    assert eng.stats()["resident_programs"] == \
        len({("prefill", 2, 8), ("decode",)})  # one prefill + one decode


def test_sampling_reproducible_across_engines(tiny_model):
    rng = np.random.default_rng(9)
    prompts = [rng.integers(2, 250, (6,)).astype(np.int32)]
    sp = SamplingParams(temperature=0.8, top_k=12)
    a = _engine(tiny_model, seed=7).generate(prompts, max_new_tokens=6,
                                             sampling=sp)
    b = _engine(tiny_model, seed=7).generate(prompts, max_new_tokens=6,
                                             sampling=sp)
    c = _engine(tiny_model, seed=8).generate(prompts, max_new_tokens=6,
                                             sampling=sp)
    np.testing.assert_array_equal(a[0], b[0])
    assert not np.array_equal(a[0], c[0])


# ---------------------------------------------------------------------------
# streaming, metrics, load generator, tooling
# ---------------------------------------------------------------------------


def test_streaming_detokenization(tiny_model):
    vocab = [f"w{i}" if i % 3 else f"##p{i}" for i in range(256)]
    detok = StreamingDetokenizer(vocab)
    eng = _engine(tiny_model)
    eng.config.detokenizer = detok
    rng = np.random.default_rng(10)
    p = rng.integers(2, 250, (5,)).astype(np.int32)
    pieces = []
    st = eng.submit(Request(p, max_new_tokens=4,
                            on_token=lambda r, t, txt: pieces.append(txt)))
    eng.run()
    assert len(pieces) == 4
    assert "".join(pieces) == detok.decode(st.generated)
    # wordpiece join: '##'-pieces glue, others get a space separator
    assert detok.decode([4, 6]) == "w4" + "p6"
    assert detok.decode([4, 5]) == "w4 w5"


def test_metrics_flow_through_registry(tiny_model):
    with scoped_registry() as reg:
        eng = _engine(tiny_model)
        rng = np.random.default_rng(11)
        eng.generate([rng.integers(2, 250, (6,)).astype(np.int32)
                      for _ in range(2)], max_new_tokens=4)
        assert reg.get("serve_ttft_seconds").count() == 2
        assert reg.get("serve_tpot_seconds").count() == 2
        assert reg.get("serve_e2e_seconds").count() == 2
        assert reg.get("serve_decode_step_seconds").count() >= 3
        assert reg.get("serve_requests_total").value(
            event="completed") == 2
        assert reg.get("serve_queue_depth").value() == 0
        assert reg.get("serve_active_slots").value() == 0
        assert reg.get("serve_kv_pages_in_use").value() == 0
        assert reg.get("serve_tokens_generated_total").value() == 8
    summary = eng.metrics_summary()
    assert summary["requests_completed"] == 2
    assert summary["tokens_generated"] == 8
    assert summary["tokens_per_sec"] and summary["tokens_per_sec"] > 0
    assert summary["decode_step_p99_s"] >= summary["decode_step_p50_s"]


def test_loadgen_deterministic_and_open_loop():
    spec = LoadSpec(num_requests=5, rate_rps=100.0,
                    prompt_len_range=(4, 8), max_new_range=(2, 4),
                    vocab_size=256, seed=3)
    a = build_requests(spec)
    b = build_requests(spec)
    assert [t for t, _ in a] == [t for t, _ in b]
    assert a[0][0] == 0.0
    for (_, ra), (_, rb) in zip(a, b):
        np.testing.assert_array_equal(ra.prompt, rb.prompt)
        assert ra.max_new_tokens == rb.max_new_tokens
    assert all(x <= y for x, y in zip([t for t, _ in a],
                                      [t for t, _ in a][1:]))


def test_run_open_loop_summary(tiny_model):
    from paddle_tpu.serving import run_open_loop
    eng = _engine(tiny_model)
    spec = LoadSpec(num_requests=4, rate_rps=1000.0,
                    prompt_len_range=(4, 10), max_new_range=(2, 4),
                    vocab_size=256, seed=4)
    summary = run_open_loop(eng, spec)
    assert summary["requests_completed"] == 4
    assert summary["num_requests"] == 4
    assert summary["tokens_per_sec"] > 0
    assert summary["offered_rate_rps"] == pytest.approx(1000.0)


def test_monitor_report_serve_section(tiny_model, tmp_path):
    import importlib.util
    import os
    import sys
    with scoped_registry() as reg:
        eng = _engine(tiny_model)
        rng = np.random.default_rng(12)
        eng.generate([rng.integers(2, 250, (6,)).astype(np.int32)],
                     max_new_tokens=3)
        path = str(tmp_path / "serve.jsonl")
        reg.dump_jsonl(path)
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    spec = importlib.util.spec_from_file_location(
        "monitor_report", os.path.join(tools, "monitor_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from paddle_tpu.monitor import load_jsonl
    out = mod.render(load_jsonl(path), serve=True)
    assert "Serving latency" in out
    assert "ttft_seconds" in out
    assert "Decode batching" in out
    assert "serve_queue_depth" in out


def test_check_bench_gates_serve_record():
    import importlib.util
    import os
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    spec = importlib.util.spec_from_file_location(
        "check_bench", os.path.join(tools, "check_bench.py"))
    cb = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(cb)
    old = [{"metric": "serve_gpt2_345m_tokens_per_sec", "value": 100.0,
            "unit": "tokens/s", "vs_baseline": 1.0},
           {"metric": "serve_gpt2_345m_decode_p99_ms", "value": 50.0,
            "unit": "ms", "vs_baseline": 1.0}]
    ok = [{"metric": "serve_gpt2_345m_tokens_per_sec", "value": 98.0,
           "unit": "tokens/s", "vs_baseline": 1.0},
          {"metric": "serve_gpt2_345m_decode_p99_ms", "value": 52.0,
           "unit": "ms", "vs_baseline": 1.0}]
    assert cb.compare(old, ok) == []
    bad = [{"metric": "serve_gpt2_345m_tokens_per_sec", "value": 60.0,
            "unit": "tokens/s", "vs_baseline": 1.0},
           {"metric": "serve_gpt2_345m_decode_p99_ms", "value": 80.0,
            "unit": "ms", "vs_baseline": 1.0}]
    problems = cb.compare(old, bad)
    assert len(problems) == 2          # throughput drop AND p99 growth


# ---------------------------------------------------------------------------
# scan-fallback telemetry (ISSUE 6 satellite)
# ---------------------------------------------------------------------------


def test_legacy_static_cache_decode_records_fallback(tiny_model):
    from paddle_tpu.nn import scan as nn_scan
    nn_scan.reset_scan_stats()
    with scoped_registry() as reg, flag_scope("monitor", True), \
            warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        prompt = np.full((1, 4), 7, np.int32)
        tiny_model.generate(prompt, max_new_tokens=3,
                            decode_strategy="greedy_search")
        ctr = reg.get("scan_fallback_total")
        assert ctr is not None
        assert ctr.value(reason="legacy_static_cache", stack="gpt") >= 1
    assert nn_scan.SCAN_STATS["fallbacks"] >= 1
    msgs = [x for x in w
            if "scan-over-layers fell back" in str(x.message)]
    assert len(msgs) == 1              # once, not once per decode step


def test_serving_reset_clears_engines(tiny_model):
    import paddle_tpu.serving as serving
    from paddle_tpu.serving.engine import _LIVE_ENGINES
    eng = _engine(tiny_model)
    assert eng in _LIVE_ENGINES
    serving.reset()
    assert len(_LIVE_ENGINES) == 0
    assert Request([1, 2]).request_id == 0   # id counter restarted


def test_create_serving_engine_from_inference_config(tiny_model):
    from paddle_tpu import inference
    import jax.numpy as jnp
    cfg = inference.Config.from_layer(tiny_model, input_spec=[])
    cfg.enable_tpu_bf16()
    eng = inference.create_serving_engine(
        cfg, ServingConfig(max_batch_slots=2, block_size=4,
                           max_context_len=32, prefill_buckets=(8,),
                           batch_buckets=(1,)))
    assert all(v.dtype == jnp.bfloat16 for v in eng.params.values()
               if jnp.issubdtype(v.dtype, jnp.floating))
    rng = np.random.default_rng(13)
    out = eng.generate([rng.integers(2, 250, (5,)).astype(np.int32)],
                       max_new_tokens=3)
    assert out[0].shape == (8,)
