"""Flagship language-model tests: GPT TP parity on the 8-device mesh,
train-step convergence, KV-cache decode, BERT MLM.

Analogue of the reference's hybrid-parallel model tests
(test_parallel_dygraph_dataparallel.py / hybrid_parallel_gpt tests):
sharded runs must match a single-device gold model.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
import paddle_tpu.distributed.fleet as fleet
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import env as dist_env
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.models import (BertForMaskedLM, GPTForPretraining,
                               GPTPretrainingCriterion, bert_tiny, gpt_tiny)
from paddle_tpu.optimizer import AdamW


def _tiny():
    return gpt_tiny(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=64)


@pytest.fixture
def clean_mesh():
    yield
    dist_env.set_mesh(None)


def test_gpt_forward_backward_eager():
    cfg = _tiny()
    m = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()
    rng = np.random.RandomState(0)
    ids = Tensor(rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32))
    labels = Tensor(rng.randint(0, cfg.vocab_size, (2, 16)).astype(np.int32))
    logits = m(ids)
    assert tuple(logits.shape) == (2, 16, cfg.vocab_size)
    loss = crit(logits, labels)
    loss.backward()
    g = m.gpt.word_embeddings.weight.grad
    assert g is not None and np.isfinite(np.asarray(g._data)).all()
    assert float(np.asarray(loss._data)) == pytest.approx(
        np.log(cfg.vocab_size), rel=0.15)


def test_gpt_tp_parity_vs_dense(clean_mesh):
    """Sharded (dp=2, mp=4) logits == single-device dense logits."""
    cfg = gpt_tiny(vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
                   max_position_embeddings=64)
    m = GPTForPretraining(cfg)
    rng = np.random.RandomState(1)
    ids_np = rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32)

    with paddle.no_grad():
        gold = m(Tensor(ids_np)).numpy()

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = fleet.get_hybrid_communicate_group().mesh
    dist.apply_param_shardings(m, mesh)

    # qkv weight really is head-sharded over mp: H=4 heads split 4-ways
    qkv = m.gpt.layers[0].attn.qkv_weight._data
    shard_shapes = {s.data.shape for s in qkv.addressable_shards}
    assert shard_shapes == {(32, 3, 1, 8)}

    static = paddle.jit.to_static(m)
    with paddle.no_grad():
        out = static(Tensor(ids_np)).numpy()
    np.testing.assert_allclose(out, gold, rtol=2e-4, atol=2e-4)


def test_gpt_train_step_loss_decreases():
    cfg = _tiny()
    m = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()
    opt = AdamW(learning_rate=1e-2)

    def loss_fn(layer, ids, labels):
        return crit(layer(ids), labels)

    step = TrainStep(m, loss_fn, opt)
    rng = np.random.RandomState(2)
    ids = Tensor(rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32))
    labels = Tensor(rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32))
    losses = [float(np.asarray(step(ids, labels)._data)) for _ in range(15)]
    assert losses[-1] < losses[0] * 0.7, losses


def test_gpt_sharded_train_step_zero1(clean_mesh):
    """Full SPMD train step over dp×mp with ZeRO slots sharded over dp."""
    cfg = _tiny()
    m = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()
    opt = AdamW(learning_rate=1e-2)

    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = fleet.get_hybrid_communicate_group().mesh

    def loss_fn(layer, ids, labels):
        return crit(layer(ids), labels)

    step = TrainStep(m, loss_fn, opt, mesh=mesh, data_spec=P("dp"),
                     zero_axis="dp")

    # ZeRO-1: adam slots for the (replicated-dim0) mlp w_in [32, 128~mp]
    # get dim0 sharded over dp
    key = [k for k in step.opt_state if "w_in" in k][0]
    slot = step.opt_state[key][0]
    assert {s.data.shape for s in slot.addressable_shards} == {(8, 64)}

    rng = np.random.RandomState(3)
    ids = Tensor(rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32))
    labels = Tensor(rng.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32))
    losses = [float(np.asarray(step(ids, labels)._data)) for _ in range(10)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses


def test_gpt_kv_cache_decode_matches_full():
    cfg = _tiny()
    m = GPTForPretraining(cfg)
    m.eval()
    rng = np.random.RandomState(4)
    ids_np = rng.randint(0, cfg.vocab_size, (2, 8)).astype(np.int32)

    with paddle.no_grad():
        full = m(Tensor(ids_np)).numpy()

        # prefill on the first 4 tokens, then decode one token at a time
        caches = [(Tensor(np.zeros((2, 0, cfg.num_heads, cfg.head_dim),
                                   np.float32)),) * 2
                  for _ in range(cfg.num_layers)]
        caches = [tuple(c) for c in caches]
        logits, caches = m(Tensor(ids_np[:, :4]), caches=caches)
        np.testing.assert_allclose(logits.numpy(), full[:, :4], rtol=1e-4,
                                   atol=1e-4)
        for t in range(4, 8):
            logits, caches = m(Tensor(ids_np[:, t:t + 1]), caches=caches)
            np.testing.assert_allclose(logits.numpy()[:, 0], full[:, t],
                                       rtol=1e-4, atol=1e-4)


def test_bert_mlm_train_step():
    cfg = bert_tiny(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    intermediate_size=64, max_position_embeddings=64)
    m = BertForMaskedLM(cfg)
    opt = AdamW(learning_rate=1e-2)

    def loss_fn(layer, ids, pos, labels):
        scores = layer(ids, masked_positions=pos)
        return layer.loss(scores, labels)

    step = TrainStep(m, loss_fn, opt)
    rng = np.random.RandomState(5)
    ids = Tensor(rng.randint(0, cfg.vocab_size, (4, 16)).astype(np.int32))
    pos = Tensor(rng.randint(0, 16, (4, 3)).astype(np.int32))
    labels = Tensor(rng.randint(0, cfg.vocab_size, (4, 3)).astype(np.int32))
    losses = [float(np.asarray(step(ids, pos, labels)._data))
              for _ in range(15)]
    assert losses[-1] < losses[0] * 0.7, losses
