"""Tensor API tests (modelled on reference test_math_op_patch.py etc.)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_basic():
    t = paddle.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert str(t.dtype) == "float32"
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])


def test_creation_ops():
    assert paddle.zeros([2, 3]).numpy().sum() == 0
    assert paddle.ones([2, 3]).numpy().sum() == 6
    np.testing.assert_array_equal(paddle.arange(5).numpy(), np.arange(5))
    assert paddle.full([2], 7.0).numpy().tolist() == [7.0, 7.0]
    assert paddle.eye(3).numpy().trace() == 3


def test_arithmetic_dunders():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * b).numpy(), [4, 10, 18])
    np.testing.assert_allclose((b / a).numpy(), [4, 2.5, 2])
    np.testing.assert_allclose((a ** 2).numpy(), [1, 4, 9])
    np.testing.assert_allclose((2 + a).numpy(), [3, 4, 5])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    np.testing.assert_allclose((10 - a).numpy(), [9, 8, 7])


def test_matmul():
    a = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32))
    b = paddle.to_tensor(np.random.randn(4, 5).astype(np.float32))
    np.testing.assert_allclose((a @ b).numpy(), a.numpy() @ b.numpy(), rtol=1e-5)
    c = paddle.matmul(a, b)
    np.testing.assert_allclose(c.numpy(), a.numpy() @ b.numpy(), rtol=1e-5)
    d = paddle.matmul(b, a, transpose_x=True, transpose_y=True)
    np.testing.assert_allclose(d.numpy(), b.numpy().T @ a.numpy().T, rtol=1e-5)


def test_reductions():
    x = np.random.randn(3, 4).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(t.sum().numpy(), x.sum(), rtol=1e-5)
    np.testing.assert_allclose(t.mean(axis=0).numpy(), x.mean(0), rtol=1e-5)
    np.testing.assert_allclose(t.max(axis=1).numpy(), x.max(1), rtol=1e-5)
    np.testing.assert_allclose(paddle.logsumexp(t).numpy(),
                               np.log(np.exp(x.astype(np.float64)).sum()),
                               rtol=1e-4)


def test_manipulation():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    t = paddle.to_tensor(x)
    assert paddle.reshape(t, [6, 4]).shape == [6, 4]
    assert paddle.transpose(t, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(t, 1).shape == [2, 12]
    parts = paddle.split(t, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    st = paddle.stack([t, t], axis=0)
    assert st.shape == [2, 2, 3, 4]
    cc = paddle.concat([t, t], axis=2)
    assert cc.shape == [2, 3, 8]
    assert paddle.squeeze(paddle.unsqueeze(t, 0), 0).shape == [2, 3, 4]


def test_indexing_and_setitem():
    t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    np.testing.assert_array_equal(t[1].numpy(), [4, 5, 6, 7])
    np.testing.assert_array_equal(t[:, 2].numpy(), [2, 6, 10])
    t[0, 0] = 100.0
    assert t.numpy()[0, 0] == 100.0


def test_comparison_and_logic():
    a = paddle.to_tensor([1.0, 2.0, 3.0])
    b = paddle.to_tensor([3.0, 2.0, 1.0])
    np.testing.assert_array_equal((a > b).numpy(), [False, False, True])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    assert bool(paddle.allclose(a, a))
    np.testing.assert_array_equal(
        paddle.where(a > b, a, b).numpy(), [3, 2, 3])


def test_search_ops():
    x = np.random.randn(4, 6).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_array_equal(paddle.argmax(t, axis=1).numpy(), x.argmax(1))
    vals, idx = paddle.topk(t, 3, axis=1)
    np.testing.assert_allclose(vals.numpy(), -np.sort(-x, axis=1)[:, :3], rtol=1e-6)
    s = paddle.sort(t, axis=1)
    np.testing.assert_allclose(s.numpy(), np.sort(x, 1), rtol=1e-6)


def test_cast_and_astype():
    t = paddle.to_tensor([1.5, 2.5])
    assert str(t.astype("int32").dtype) == "int32"
    assert str(t.astype(paddle.float16).dtype) == "float16"
    bf = t.astype("bfloat16")
    assert "bfloat16" in str(bf.dtype)


def test_linalg():
    a = np.random.randn(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    t = paddle.to_tensor(spd)
    L = paddle.cholesky(t)
    np.testing.assert_allclose((L @ L.t()).numpy(), spd, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(paddle.inv(t).numpy(), np.linalg.inv(spd),
                               rtol=1e-3, atol=1e-4)
    u, s, vt = paddle.svd(paddle.to_tensor(a))
    np.testing.assert_allclose(
        (u @ paddle.diag(s) @ vt).numpy(), a, rtol=1e-3, atol=1e-4)


def test_random_reproducibility():
    paddle.seed(7)
    a = paddle.randn([4, 4]).numpy()
    paddle.seed(7)
    b = paddle.randn([4, 4]).numpy()
    np.testing.assert_array_equal(a, b)
    c = paddle.randn([4, 4]).numpy()
    assert not np.array_equal(b, c)


def test_stat_ops():
    x = np.random.randn(5, 7).astype(np.float32)
    t = paddle.to_tensor(x)
    np.testing.assert_allclose(paddle.std(t).numpy(), x.std(ddof=1), rtol=1e-4)
    np.testing.assert_allclose(paddle.var(t, axis=0).numpy(), x.var(0, ddof=1),
                               rtol=1e-4)
    np.testing.assert_allclose(paddle.median(t).numpy(), np.median(x), rtol=1e-5)
