"""Fused Pallas dropout (ops/pallas/dropout.py) — interpreter-run on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.pallas.dropout import fused_dropout


@pytest.mark.parametrize("shape", [(512, 128), (48, 33, 77), (70000,)])
def test_mask_statistics_and_scaling(shape):
    key = jax.random.key(7)
    x = jnp.ones(shape, jnp.float32)
    out = np.asarray(fused_dropout(x, 0.3, key))
    kept = out != 0.0
    # kept values scaled by 1/(1-p)
    np.testing.assert_allclose(out[kept], 1.0 / 0.7, rtol=1e-6)
    # keep rate ~ 1-p
    assert abs(kept.mean() - 0.7) < 0.02, kept.mean()


def test_backward_regenerates_identical_mask():
    key = jax.random.key(3)
    x = jnp.asarray(np.random.default_rng(0)
                    .normal(size=(256, 512)).astype(np.float32))

    def loss(a):
        return jnp.sum(fused_dropout(a, 0.4, key) * 2.0)

    out = fused_dropout(x, 0.4, key)
    g = jax.grad(loss)(x)
    # gradient = 2/(1-p) exactly where the forward kept the element
    kept = np.asarray(out) != 0.0
    np.testing.assert_allclose(np.asarray(g)[kept], 2.0 / 0.6, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g)[~kept], 0.0)


def test_different_keys_different_masks():
    x = jnp.ones((512, 128), jnp.float32)
    a = np.asarray(fused_dropout(x, 0.5, jax.random.key(0)))
    b = np.asarray(fused_dropout(x, 0.5, jax.random.key(1)))
    assert (a != b).any()


def test_edge_rates():
    x = jnp.ones((8, 8), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(fused_dropout(x, 0.0, jax.random.key(0))), 1.0)
    np.testing.assert_allclose(
        np.asarray(fused_dropout(x, 1.0, jax.random.key(0))), 0.0)


def test_bf16_dtype_preserved():
    x = jnp.ones((512, 128), jnp.bfloat16)
    out = fused_dropout(x, 0.2, jax.random.key(2))
    assert out.dtype == jnp.bfloat16


def test_wide_activation_block_bounded():
    """Review regression: wide trailing dims must shrink the row block
    (512-row blocks at C=4096 would blow VMEM on TPU)."""
    key = jax.random.key(5)
    x = jnp.ones((256, 4096), jnp.float32)
    out = np.asarray(fused_dropout(x, 0.25, key))
    kept = out != 0.0
    assert abs(kept.mean() - 0.75) < 0.02


def test_F_dropout_dispatches_to_fused(monkeypatch):
    """F.dropout routes eligible arrays to the fused kernel (gate wiring
    covered without TPU hardware by faking the backend check)."""
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.ops.pallas import dropout as fd

    calls = {}
    real = fd.fused_dropout

    def spy(a, rate, key):
        calls["rate"] = rate
        calls["shape"] = tuple(a.shape)
        return real(a, rate, key)

    monkeypatch.setattr(fd, "fused_dropout", spy)
    # keep the kernel on the interpreter while faking the gate's backend
    monkeypatch.setattr(fd, "_interpret", lambda: True)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    paddle.seed(0)
    x = paddle.to_tensor(np.ones((64, 1024), np.float32))
    x.stop_gradient = False
    y = F.dropout(x, p=0.3, training=True)
    assert calls == {"rate": 0.3, "shape": (64, 1024)}
    y.sum().backward()
    g = np.asarray(x.grad._data)
    out = y.numpy()
    # mask consistency through the tape: grad nonzero exactly where kept
    np.testing.assert_array_equal(g != 0, out != 0)
