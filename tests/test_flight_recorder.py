"""Crash flight recorder (ISSUE 4): ring-buffer bounds, dump-on-exception
and dump-on-watchdog-trip produce valid JSON, fingerprint fields, and the
monitor-off zero-overhead contract."""

import glob
import json
import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.monitor import NonFiniteError, flight_recorder as FR
from paddle_tpu.optimizer import SGD


def _mse(layer, x, y):
    return ((layer(x) - y) ** 2).mean()


def _linear_step(**kw):
    paddle.seed(7)
    m = nn.Linear(4, 2)
    opt = SGD(learning_rate=0.1, parameters=m.parameters())
    return TrainStep(m, _mse, opt, **kw)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.rand(8, 4).astype(np.float32),
            rng.rand(8, 2).astype(np.float32))


# ---------------------------------------------------------------------------
# ring buffer + dump mechanics
# ---------------------------------------------------------------------------

def test_ring_buffer_bounds():
    fr = FR.FlightRecorder(capacity=4)
    for i in range(10):
        fr.record_step(i, loss=float(i), kind="step")
    steps = fr.steps
    assert len(steps) == 4                      # bounded
    assert [r["step"] for r in steps] == [6, 7, 8, 9]   # newest survive
    for i in range(500):
        fr.record_event("compile", kind="step")
    assert len(fr.events) <= 128
    assert fr.record_count == 510


def test_dump_roundtrip_and_fingerprint(tmp_path):
    fr = FR.FlightRecorder(capacity=8, dump_dir=str(tmp_path))
    fr.record_step(1, loss=0.5, wall_ms=1.2, dispatch_ms=0.3)
    fr.record_step(2, loss=float("nan"))        # non-finite must survive
    fr.record_event("recompile", kind="step", step=2)
    path = fr.dump(reason="explicit")
    assert os.path.dirname(path) == str(tmp_path)
    with open(path) as f:
        doc = json.load(f)                      # strictly valid JSON
    assert doc["reason"] == "explicit"
    assert doc["capacity"] == 8
    fp = doc["fingerprint"]
    import jax
    assert fp["jax_version"] == jax.__version__
    assert fp["backend"] == "cpu"
    assert fp["device_count"] == len(jax.devices())
    assert fp["pid"] == os.getpid()
    assert fp["python"] == sys.version.split()[0]
    assert fp["paddle_tpu_version"]
    assert "git_sha" in fp
    # flags snapshot travels with the dump
    assert doc["flags"]["monitor"] is False
    assert [r["step"] for r in doc["steps"]] == [1, 2]
    assert doc["steps"][0]["wall_ms"] == pytest.approx(1.2)
    assert doc["steps"][1]["loss"] == "nan"     # stringified non-finite
    assert doc["steps"][0]["seed"] == 1234      # conftest paddle.seed
    assert doc["events"][0]["event"] == "recompile"
    assert FR.load_dump(path) == doc
    # second dump overwrites (newest state of this process wins)
    fr.record_step(3, loss=0.1)
    assert fr.dump() == fr.default_path()
    assert len(FR.load_dump(fr.default_path())["steps"]) == 3


def test_dump_on_unhandled_exception(tmp_path):
    fr = FR.FlightRecorder(capacity=4, dump_dir=str(tmp_path))
    FR.set_flight_recorder(fr)
    fr.record_step(41, loss=1.0)
    prev_hook = sys.excepthook
    fr.install(enable_faulthandler=False)
    try:
        assert sys.excepthook is not prev_hook
        # simulate the interpreter dying on an uncaught error
        try:
            raise ValueError("boom at step 41")
        except ValueError:
            sys.excepthook(*sys.exc_info())
        doc = FR.load_dump(fr.default_path())
        assert doc["reason"] == "unhandled_exception"
        assert "ValueError: boom at step 41" in doc["exception"]
        assert doc["steps"][-1]["step"] == 41
    finally:
        fr.uninstall()
    assert sys.excepthook is prev_hook          # chain restored


def test_faulthandler_sidecar(tmp_path):
    import faulthandler
    fr = FR.FlightRecorder(dump_dir=str(tmp_path))
    fr.install(excepthook=False, enable_faulthandler=True)
    try:
        assert faulthandler.is_enabled()
        sidecar = fr.default_path(suffix=".traceback")
        assert os.path.exists(sidecar)
    finally:
        fr.uninstall()
        faulthandler.enable()   # restore pytest's own handler


# ---------------------------------------------------------------------------
# TrainStep integration
# ---------------------------------------------------------------------------

def test_monitor_off_zero_recorder_writes():
    """Both FLAGS_monitor and FLAGS_flight_recorder off: the hot path
    never touches the recorder (same contract as the metrics registry)."""
    step = _linear_step()
    x, y = _batch()
    fr = FR.get_flight_recorder()
    before = fr.record_count
    for _ in range(4):
        step(x, y)
    assert fr.record_count == before
    assert fr.steps == []


def test_flag_records_steps_without_monitor():
    x, y = _batch()
    with flag_scope("flight_recorder", True):
        step = _linear_step()
        for _ in range(3):
            step(x, y)
    fr = FR.get_flight_recorder()
    steps = fr.steps
    assert [r["step"] for r in steps] == [1, 2, 3]
    assert all(r["kind"] == "step" for r in steps)
    # monitor off -> timings unknown, loss still held (read at dump time)
    assert steps[0]["wall_ms"] is None
    events = fr.events
    assert events and events[0]["event"] == "compile"
    doc = json.loads(open(fr.dump()).read())
    assert isinstance(doc["steps"][0]["loss"], float)


def test_monitor_flag_also_records_with_timings():
    x, y = _batch()
    with flag_scope("monitor", True):
        step = _linear_step()
        step(x, y)
    steps = FR.get_flight_recorder().steps
    assert len(steps) == 1
    assert steps[0]["wall_ms"] > 0
    assert steps[0]["dispatch_ms"] > 0


def test_grad_accum_records_microsteps_and_apply():
    paddle.seed(7)
    m = nn.Linear(4, 2)
    step = TrainStep(m, _mse, SGD(learning_rate=0.1,
                                  parameters=m.parameters()),
                     grad_accum_steps=2)
    x, y = _batch()
    with flag_scope("flight_recorder", True):
        for _ in range(4):
            step(x, y)
    kinds = [r["kind"] for r in FR.get_flight_recorder().steps]
    assert kinds == ["accum", "apply", "accum", "apply"]


def test_watchdog_trip_dumps_flight_recorder(tmp_path):
    """Acceptance: a forced NaN-watchdog trip leaves a parseable dump
    naming the trip step."""
    step = _linear_step(check_numerics=True)
    x, y = _batch()
    step(x, y)
    step(x, y)
    xbad = x.copy()
    xbad[0, 0] = np.inf
    with pytest.raises(NonFiniteError) as ei:
        step(xbad, y)
    assert "flight recorder dump:" in str(ei.value)
    dumps = glob.glob(os.path.join(str(tmp_path), "flight_recorder_*.json"))
    assert len(dumps) == 1                       # conftest routed dir here
    doc = FR.load_dump(dumps[0])
    assert doc["reason"] == "nan_watchdog"
    assert doc["trip_step"] == 3                 # the step that tripped
    assert doc["offender"] == "bias"
    trip_events = [e for e in doc["events"] if e["event"] == "trip"]
    assert trip_events and trip_events[0]["step"] == 3
    # fingerprint rides along even when the ring was otherwise cold
    assert doc["fingerprint"]["jax_version"]


def test_collectives_recorded_as_events():
    import jax.numpy as jnp
    from paddle_tpu.distributed import collective as C
    g = C.new_group([0, 1])
    x = jnp.ones((2, 4), jnp.float32)
    C.all_reduce(x, group=g)                     # recorder off: no event
    assert FR.get_flight_recorder().events == []
    with flag_scope("flight_recorder", True):
        C.all_reduce(x, group=g)
    events = FR.get_flight_recorder().events
    assert len(events) == 1
    ev = events[0]
    assert ev["event"] == "collective"
    assert ev["op"] == "all_reduce"
    assert ev["bytes"] == x.nbytes
    assert ev["nranks"] == 2


# ---------------------------------------------------------------------------
# report rendering
# ---------------------------------------------------------------------------

def test_monitor_report_flight_mode(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "monitor_report", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "monitor_report.py"))
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)

    fr = FR.FlightRecorder(capacity=8, dump_dir=str(tmp_path))
    for i in range(5):
        fr.record_step(i, loss=0.5 - 0.1 * i, wall_ms=2.0,
                       dispatch_ms=1.0)
    fr.record_event("recompile", kind="step", step=3)
    path = fr.dump(reason="nan_watchdog", trip_step=4)
    out = report.render_flight(FR.load_dump(path), last=3)
    assert "Flight recorder dump" in out
    assert "nan_watchdog" in out
    assert "trip at step 4" in out
    assert "recompile" in out
    assert "Step records (last 3 of 5" in out
    assert "jax_version=" in out
    # CLI end-to-end
    assert report.main(["--flight", path]) == 0
    assert report.main(["--flight", str(tmp_path / "missing.json")]) == 2
