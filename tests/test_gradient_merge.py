"""Gradient merge (k-step accumulation) + rejected-strategy tests.

reference: fleet/meta_optimizers/gradient_merge_optimizer.py (accumulate
into persistent buffers, optimizer gated on step % k);
localsgd_optimizer.py / dgc_optimizer.py are interconnect optimizations
that are counterproductive on ICI and must fail loudly, not no-op.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import fleet
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.nn import functional as F
from paddle_tpu.optimizer import SGD


def _model_and_data():
    paddle.seed(21)
    model = nn.Linear(8, 4)

    def loss_fn(layer, x, y):
        return ((layer(x) - y) ** 2).mean()

    rng = np.random.default_rng(0)
    xs = rng.standard_normal((16, 8)).astype(np.float32)
    ys = rng.standard_normal((16, 4)).astype(np.float32)
    return model, loss_fn, xs, ys


def test_four_microsteps_equal_one_big_batch():
    """k_steps=4 with avg: four quarter-batches produce EXACTLY the update
    of one step on the full batch (mean-reduced loss, SGD)."""
    model, loss_fn, xs, ys = _model_and_data()
    w0 = {k: np.asarray(p._data) for k, p in model.named_parameters()}

    step = TrainStep(model, loss_fn, SGD(learning_rate=0.1),
                     grad_accum_steps=4)
    for i in range(4):
        step(Tensor(xs[i * 4:(i + 1) * 4]), Tensor(ys[i * 4:(i + 1) * 4]))
    merged = {k: np.asarray(v) for k, v in step.params.items()}
    assert step.step_count == 1      # ONE optimizer step for 4 microsteps

    # reference: single big-batch step from the same init
    model2, loss_fn2, _, _ = _model_and_data()
    for k, p in model2.named_parameters():
        p._data = w0[k]
    big = TrainStep(model2, loss_fn2, SGD(learning_rate=0.1))
    big(Tensor(xs), Tensor(ys))
    for k, v in big.params.items():
        np.testing.assert_allclose(merged[k], np.asarray(v),
                                   rtol=1e-5, atol=1e-6)


def test_no_update_until_kth_microstep():
    model, loss_fn, xs, ys = _model_and_data()
    w0 = {k: np.asarray(p._data) for k, p in model.named_parameters()}
    step = TrainStep(model, loss_fn, SGD(learning_rate=0.1),
                     grad_accum_steps=3)
    for i in range(2):
        step(Tensor(xs[:4]), Tensor(ys[:4]))
        for k, v in step.params.items():
            np.testing.assert_array_equal(np.asarray(v), w0[k])
    step(Tensor(xs[:4]), Tensor(ys[:4]))
    assert any(not np.array_equal(np.asarray(v), w0[k])
               for k, v in step.params.items())


def test_strategy_gradient_merge_wires_trainstep():
    """strategy.gradient_merge=True + k_steps flows into TrainStep through
    fleet.distributed_optimizer — the boundary where strategy applies
    (reference: fleet_base.py:830 meta-optimizer chain)."""
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 4, "avg": True}
    fleet.init(is_collective=True, strategy=strategy)

    model, loss_fn, xs, ys = _model_and_data()
    opt = fleet.distributed_optimizer(SGD(learning_rate=0.1))
    step = TrainStep(model, loss_fn, opt)
    assert step.grad_accum_steps == 4
    w0 = {k: np.asarray(p._data) for k, p in model.named_parameters()}
    step(Tensor(xs[:4]), Tensor(ys[:4]))
    for k, v in step.params.items():        # first microstep: no update
        np.testing.assert_array_equal(np.asarray(v), w0[k])


def test_bare_trainstep_unaffected_by_fleet_strategy():
    """A TrainStep over a BARE optimizer must update on step 1 even after
    fleet.init with gradient_merge — the strategy is scoped to
    fleet.distributed_optimizer, never a process-global rewiring (the
    round-4 leak: a later unrelated TrainStep silently became a 4-step
    accumulator)."""
    strategy = fleet.DistributedStrategy()
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 4, "avg": True}
    fleet.init(is_collective=True, strategy=strategy)

    model, loss_fn, xs, ys = _model_and_data()
    step = TrainStep(model, loss_fn, SGD(learning_rate=0.1))
    assert step.grad_accum_steps == 1
    w0 = {k: np.asarray(p._data) for k, p in model.named_parameters()}
    step(Tensor(xs[:4]), Tensor(ys[:4]))
    assert any(not np.array_equal(np.asarray(v), w0[k])
               for k, v in step.params.items())


def test_strategy_snapshot_frozen_at_distributed_optimizer():
    """Mutating the strategy AFTER distributed_optimizer must not change
    an already-wrapped optimizer (snapshot semantics)."""
    strategy = fleet.DistributedStrategy()
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(SGD(learning_rate=0.1), strategy)
    strategy.gradient_merge = True
    strategy.gradient_merge_configs = {"k_steps": 4}
    model, loss_fn, _, _ = _model_and_data()
    step = TrainStep(model, loss_fn, opt)
    assert step.grad_accum_steps == 1


def test_dgc_raises():
    strategy = fleet.DistributedStrategy()
    with pytest.raises(NotImplementedError, match="gradient compression"):
        strategy.dgc = True
    # setting False stays a no-op (config parity); localsgd is implemented
    strategy.localsgd = True
    assert strategy.localsgd
    strategy.localsgd = False
    strategy.dgc = False
