"""Flash-attention kernel correctness vs the XLA sdpa composition.

Analogue of the reference's fused-attention parity tests
(reference: test_fused_attention_op.py — fused kernel vs the unfused
composition within tolerance). Runs the same Pallas kernels through the
interpreter on CPU; the TPU path compiles the identical kernel code.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.attention import _sdpa_xla
from paddle_tpu.ops.pallas.flash_attention import flash_attention

B, S, H, D = 2, 256, 2, 64


def _qkv(seed=0, dtype=np.float32, s=S):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, s, H, D).astype(dtype) * 0.5  # noqa: E731
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


def _ref(q, k, v, mask=None, causal=False):
    with jax.default_matmul_precision("highest"):
        return _sdpa_xla(q, k, v, mask, 0.0, causal, None)


def test_forward_matches_xla():
    q, k, v = _qkv()
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_forward_causal():
    q, k, v = _qkv(1)
    out = flash_attention(q, k, v, causal=True)
    ref = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_forward_key_padding_bias():
    q, k, v = _qkv(2)
    keep = np.ones((B, 1, 1, S), np.float32)
    keep[:, :, :, S // 2:] = 0.0          # mask out second half of keys
    bias = (1.0 - keep) * -1e30
    out = flash_attention(q, k, v, bias=jnp.asarray(bias))
    ref = _ref(q, k, v, mask=jnp.asarray(bias))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_xla(causal):
    q, k, v = _qkv(3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_grads_with_bias():
    q, k, v = _qkv(4)
    keep = np.ones((B, 1, 1, S), np.float32)
    keep[:, :, :, -64:] = 0.0
    bias = jnp.asarray((1.0 - keep) * -1e30)

    g_flash = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, bias=bias) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(_ref(q, k, v, mask=bias) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4, err_msg=f"d{name}")


def test_learned_bias_gradient():
    # a trainable additive bias (ALiBi-style, finite values) must receive
    # the true gradient on the flash path, matching the XLA composition
    q, k, v = _qkv(10)
    rng = np.random.RandomState(11)
    bias = jnp.asarray(rng.randn(B, 1, 1, S).astype(np.float32))

    db_flash = jax.grad(
        lambda b_: jnp.sum(flash_attention(q, k, v, bias=b_) ** 2))(bias)
    db_ref = jax.grad(
        lambda b_: jnp.sum(_ref(q, k, v, mask=b_) ** 2))(bias)
    assert float(jnp.max(jnp.abs(db_ref))) > 1e-3   # non-trivial gradient
    np.testing.assert_allclose(np.asarray(db_flash), np.asarray(db_ref),
                               atol=5e-4, rtol=5e-4)


def test_rectangular_seq_lens():
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(B, 128, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, 384, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, 384, H, D).astype(np.float32))
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_rectangular_causal_bottom_right():
    # chunked prefill: 128 new queries against a 384-long KV cache; causal
    # alignment must be bottom-right (row i sees keys <= i + Sk - Sq)
    rng = np.random.RandomState(8)
    q = jnp.asarray(rng.randn(B, 128, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, 384, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, 384, H, D).astype(np.float32))
    out = flash_attention(q, k, v, causal=True)
    ref = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # and grads
    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        _ref(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_non_dividing_seq_len_picks_smaller_block():
    # S=768 does not divide the 512 default block; kernel must pick 384
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(1, 768, 2, D).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 768, 2, D).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 768, 2, D).astype(np.float32))
    out = flash_attention(q, k, v, causal=True)
    ref = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bf16_inputs():
    q, k, v = _qkv(6, dtype=np.float32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb)
    assert out.dtype == jnp.bfloat16
    ref = _ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out.astype(jnp.float32)),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


def _host_keep(S, b, h, rate):
    """Reconstruct the kernel's stateless dropout mask on the host (same
    murmur3-finalizer hash over absolute coordinates, uint64 arithmetic)."""
    rows = np.arange(S, dtype=np.uint64)[:, None]
    cols = np.arange(S, dtype=np.uint64)[None, :]
    M = np.uint64(0xFFFFFFFF)
    bh = (np.uint64(b) * np.uint64(0xAC564B05)
          + np.uint64(h) * np.uint64(19349663)) & M
    x = ((rows * np.uint64(0x9E3779B1)) & M) \
        ^ ((cols * np.uint64(0x85EBCA6B)) & M) ^ bh
    x &= M
    x ^= x >> np.uint64(16)
    x = (x * np.uint64(0x85EBCA6B)) & M
    x ^= x >> np.uint64(13)
    x = (x * np.uint64(0xC2B2AE35)) & M
    x ^= x >> np.uint64(16)
    thresh = np.uint64(min(rate, 0.999999) * 4294967296.0)
    return (x >= thresh).astype(np.float32) / (1.0 - rate)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [256, 128])
def test_dropout_matches_host_mask_reference(causal, block):
    # in-kernel dropout (stateless hash) vs a pure-JAX reference using the
    # reconstructed mask: forward AND analytic grads must agree — this is
    # the fwd/bwd mask-consistency proof (backward REGENERATES the mask).
    # block=128 gives a 2x2 tile grid, exercising the transposed dkv grid
    # and the per-tile coordinate mixing; B=2 exercises the batch fold.
    from paddle_tpu.ops.pallas.flash_attention import _flash

    Bv, Sv, Hv, Dv = 2, 256, 2, 64
    rate = 0.3
    rng = np.random.RandomState(12)
    # _flash takes the framework [B, S, H, D] layout directly
    mk = lambda: jnp.asarray(  # noqa: E731
        rng.randn(Bv, Sv, Hv, Dv).astype(np.float32)) * 0.3
    q, k, v = mk(), mk(), mk()
    seed_f = jnp.zeros((2,), jnp.float32)
    keep = jnp.asarray(np.stack([np.stack(
        [_host_keep(Sv, b, h, rate) for h in range(Hv)])
        for b in range(Bv)]))
    G = jnp.asarray(rng.randn(Bv, Sv, Hv, Dv).astype(np.float32))
    cm = jnp.tril(jnp.ones((Sv, Sv), bool))

    def ref_loss(q_, k_, v_):
        s = jnp.einsum("bqhd,bkhd->bhqk", q_, k_) * 0.125
        if causal:
            s = jnp.where(cm[None, None], s, -1e30)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
        return jnp.sum(jnp.einsum("bhqk,bkhd->bqhd", p * keep, v_) * G)

    def kern_loss(q_, k_, v_):
        return jnp.sum(_flash(q_, k_, v_, None, seed_f, 0.125, causal,
                              block, block, rate) * G)

    o_k = _flash(q, k, v, None, seed_f, 0.125, causal, block, block, rate)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * 0.125
    if causal:
        s = jnp.where(cm[None, None], s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    o_r = jnp.einsum("bhqk,bkhd->bqhd", p * keep, v)
    np.testing.assert_allclose(np.asarray(o_k), np.asarray(o_r),
                               atol=2e-5, rtol=2e-5)

    g_k = jax.grad(kern_loss, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_k, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_dropout_public_api_guards():
    q = jnp.asarray(np.random.RandomState(0)
                    .randn(1, 256, 2, 64).astype(np.float32))
    # missing key raises on every backend (interpret path works too)
    with pytest.raises(ValueError, match="dropout_key"):
        flash_attention(q, q, q, dropout_rate=0.5)
    # rate >= 1: defined all-zeros output (XLA-fallback parity), no NaN
    out = flash_attention(q, q, q, dropout_rate=1.0,
                          dropout_key=jax.random.key(0))
    assert float(jnp.abs(out).max()) == 0.0
    # dropout through the public API runs in interpret mode as well
    out = flash_attention(q, q, q, dropout_rate=0.5,
                          dropout_key=jax.random.key(0))
    assert np.isfinite(np.asarray(out)).all()


def test_jit_and_under_trainstep_shapes():
    q, k, v = _qkv(7)
    jitted = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    out = jitted(q, k, v)
    assert out.shape == (B, S, H, D)


def test_env_block_override_validated_and_scoped(monkeypatch):
    """PTPU_FLASH_BLOCK_Q/K overrides: a bad value raises an error NAMING
    the env var; a valid override only applies when the caller left the
    block size at its default (explicit arguments always win)."""
    import pytest

    from paddle_tpu.ops.pallas import flash_attention as fa

    # bad values: named error, raised before any kernel work
    q = jnp.zeros((1, 128, 1, 8), jnp.float32)
    monkeypatch.setenv("PTPU_FLASH_BLOCK_Q", "not_a_number")
    with pytest.raises(ValueError, match="PTPU_FLASH_BLOCK_Q"):
        fa.flash_attention(q, q, q)
    monkeypatch.setenv("PTPU_FLASH_BLOCK_Q", "100")     # not a 128 multiple
    with pytest.raises(ValueError, match="PTPU_FLASH_BLOCK_Q"):
        fa.flash_attention(q, q, q)
    monkeypatch.delenv("PTPU_FLASH_BLOCK_Q")

    # precedence: capture what reaches the kernel without running it
    seen = {}

    def fake_flash(q_, k_, v_, bias, seed_f, scale, causal, bq, bk, rate):
        seen["bq"], seen["bk"] = bq, bk
        return q_

    monkeypatch.setattr(fa, "_flash", fake_flash)
    q = jnp.zeros((1, 512, 1, 8), jnp.float32)
    monkeypatch.setenv("PTPU_FLASH_BLOCK_Q", "128")
    fa.flash_attention(q, q, q)                      # default -> env applies
    assert seen["bq"] == 128
    fa.flash_attention(q, q, q, block_q=256)         # explicit arg wins
    assert seen["bq"] == 256
