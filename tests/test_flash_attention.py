"""Flash-attention kernel correctness vs the XLA sdpa composition.

Analogue of the reference's fused-attention parity tests
(reference: test_fused_attention_op.py — fused kernel vs the unfused
composition within tolerance). Runs the same Pallas kernels through the
interpreter on CPU; the TPU path compiles the identical kernel code.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops.attention import _sdpa_xla
from paddle_tpu.ops.pallas.flash_attention import flash_attention

B, S, H, D = 2, 256, 2, 64


def _qkv(seed=0, dtype=np.float32, s=S):
    rng = np.random.RandomState(seed)
    mk = lambda: rng.randn(B, s, H, D).astype(dtype) * 0.5  # noqa: E731
    return jnp.asarray(mk()), jnp.asarray(mk()), jnp.asarray(mk())


def _ref(q, k, v, mask=None, causal=False):
    with jax.default_matmul_precision("highest"):
        return _sdpa_xla(q, k, v, mask, 0.0, causal, None)


def test_forward_matches_xla():
    q, k, v = _qkv()
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_forward_causal():
    q, k, v = _qkv(1)
    out = flash_attention(q, k, v, causal=True)
    ref = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_forward_key_padding_bias():
    q, k, v = _qkv(2)
    keep = np.ones((B, 1, 1, S), np.float32)
    keep[:, :, :, S // 2:] = 0.0          # mask out second half of keys
    bias = (1.0 - keep) * -1e30
    out = flash_attention(q, k, v, bias=jnp.asarray(bias))
    ref = _ref(q, k, v, mask=jnp.asarray(bias))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_grads_match_xla(causal):
    q, k, v = _qkv(3)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_ref(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name}")


def test_grads_with_bias():
    q, k, v = _qkv(4)
    keep = np.ones((B, 1, 1, S), np.float32)
    keep[:, :, :, -64:] = 0.0
    bias = jnp.asarray((1.0 - keep) * -1e30)

    g_flash = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, bias=bias) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(
        lambda q, k, v: jnp.sum(_ref(q, k, v, mask=bias) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4, err_msg=f"d{name}")


def test_learned_bias_gradient():
    # a trainable additive bias (ALiBi-style, finite values) must receive
    # the true gradient on the flash path, matching the XLA composition
    q, k, v = _qkv(10)
    rng = np.random.RandomState(11)
    bias = jnp.asarray(rng.randn(B, 1, 1, S).astype(np.float32))

    db_flash = jax.grad(
        lambda b_: jnp.sum(flash_attention(q, k, v, bias=b_) ** 2))(bias)
    db_ref = jax.grad(
        lambda b_: jnp.sum(_ref(q, k, v, mask=b_) ** 2))(bias)
    assert float(jnp.max(jnp.abs(db_ref))) > 1e-3   # non-trivial gradient
    np.testing.assert_allclose(np.asarray(db_flash), np.asarray(db_ref),
                               atol=5e-4, rtol=5e-4)


def test_rectangular_seq_lens():
    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(B, 128, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, 384, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, 384, H, D).astype(np.float32))
    out = flash_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(q, k, v)),
                               atol=2e-5, rtol=2e-5)


def test_rectangular_causal_bottom_right():
    # chunked prefill: 128 new queries against a 384-long KV cache; causal
    # alignment must be bottom-right (row i sees keys <= i + Sk - Sq)
    rng = np.random.RandomState(8)
    q = jnp.asarray(rng.randn(B, 128, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, 384, H, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, 384, H, D).astype(np.float32))
    out = flash_attention(q, k, v, causal=True)
    ref = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    # and grads
    g = jax.grad(lambda q, k, v: jnp.sum(
        flash_attention(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda q, k, v: jnp.sum(
        _ref(q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4)


def test_non_dividing_seq_len_picks_smaller_block():
    # S=768 does not divide the 512 default block; kernel must pick 384
    rng = np.random.RandomState(9)
    q = jnp.asarray(rng.randn(1, 768, 2, D).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 768, 2, D).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 768, 2, D).astype(np.float32))
    out = flash_attention(q, k, v, causal=True)
    ref = _ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_bf16_inputs():
    q, k, v = _qkv(6, dtype=np.float32)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = flash_attention(qb, kb, vb)
    assert out.dtype == jnp.bfloat16
    ref = _ref(q, k, v)
    np.testing.assert_allclose(np.asarray(out.astype(jnp.float32)),
                               np.asarray(ref), atol=3e-2, rtol=3e-2)


def test_jit_and_under_trainstep_shapes():
    q, k, v = _qkv(7)
    jitted = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))
    out = jitted(q, k, v)
    assert out.shape == (B, S, H, D)
