"""Live telemetry plane (ISSUE 14): the embedded admin HTTP server
(monitor/server.py), exposition conformance + exemplars, registry
merge, the timeseries ring, and the monitor_top / aggregate_metrics
tools (docs/OBSERVABILITY.md "Live telemetry plane")."""

import json
import os
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.monitor import get_registry, scoped_registry
from paddle_tpu.monitor import server as server_mod
from paddle_tpu.monitor.metrics import (MetricsRegistry,
                                        lint_exposition,
                                        load_registry_jsonl)
from paddle_tpu.monitor.server import AdminServer
from paddle_tpu.monitor.timeseries import (TimeseriesRing,
                                           parse_prometheus)
from paddle_tpu.serving import (LoadSpec, Request, ServingConfig,
                                ServingEngine, build_requests,
                                run_open_loop)
from paddle_tpu.testing import chaos

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


class ManualClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _engine(model, clock=None, **kw):
    cfg = dict(max_batch_slots=3, block_size=4, max_context_len=64,
               prefill_buckets=(8, 16), batch_buckets=(1, 2))
    cfg.update(kw)
    kw2 = {"clock": clock} if clock is not None else {}
    return ServingEngine(model, ServingConfig(**cfg), **kw2)


def _get(url, path, timeout=10):
    with urllib.request.urlopen(url + path, timeout=timeout) as r:
        return r.status, r.read()


def _get_json(url, path):
    st, body = _get(url, path)
    return st, json.loads(body)


@pytest.fixture
def admin():
    srv = AdminServer(port=0).start()
    yield srv
    srv.close()


# ---------------------------------------------------------------------------
# Prometheus exposition conformance (satellite: escaping + lint)
# ---------------------------------------------------------------------------


def test_exposition_escapes_hostile_label_values():
    """A label containing ``"``, ``\\`` or a newline must produce a
    lint-clean (scrapeable) page — the pre-fix emitter produced raw
    values here."""
    reg = MetricsRegistry()
    reg.counter("evil_total", "counts").inc(
        reason='say "no"\nand \\ survive', op="a,b{}")
    reg.gauge("g_metric", 'help with "quotes", \\ and\nnewline').set(1)
    text = reg.to_prometheus()
    assert lint_exposition(text) == []
    # the escaped forms are on the page; no raw newline smears a sample
    assert r'say \"no\"\nand \\ survive' in text
    assert "\nand \\ survive" not in text


def test_exposition_lint_catches_bad_grammar():
    assert lint_exposition('m{l="a\nb"} 1\n')        # raw newline
    assert lint_exposition('m{l="a\\q"} 1\n')        # bad escape
    assert lint_exposition("m{} x\n")                # non-numeric value
    assert lint_exposition("# TYPE m bogus_kind\n")
    assert lint_exposition("# TYPE m counter\nother_name 1\n")
    assert lint_exposition("# TYPE m counter\n"
                           "# TYPE m counter\nm 1\n")  # duplicate TYPE
    # suffix on a non-histogram family
    assert lint_exposition("# TYPE m counter\nm_bucket 1\n")
    ok = ('# HELP m does things\n# TYPE m counter\n'
          'm{l="x"} 3.5 # {trace_id="t-1"} 0.1 12345\n')
    assert lint_exposition(ok) == []


def test_exposition_renders_exemplars():
    """With ``exemplars=True`` (the OpenMetrics-negotiated form),
    histogram exemplars land on their bucket line in the
    ``# {trace_id="..."}`` suffix syntax — and the page still lints.
    The default page is classic text and must NOT carry the suffix
    (plain Prometheus parsers reject it)."""
    reg = MetricsRegistry()
    h = reg.histogram("lat_seconds", "lat", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="trace-a")
    h.observe(7.0, exemplar="trace-b")      # past the last bucket: +Inf
    plain = reg.to_prometheus()
    assert lint_exposition(plain) == [] and "trace_id" not in plain
    text = reg.to_prometheus(exemplars=True)
    assert lint_exposition(text) == []
    bucket_lines = [ln for ln in text.splitlines() if "_bucket" in ln]
    assert any('le="0.1"' in ln and '# {trace_id="trace-a"} 0.05' in ln
               for ln in bucket_lines)
    assert any('le="+Inf"' in ln and 'trace_id="trace-b"' in ln
               for ln in bucket_lines)


def test_whole_default_registry_exposition_lints_after_serve(tiny_model):
    """End-to-end conformance: everything a serve run writes into the
    registry exports as a lint-clean page."""
    with scoped_registry() as reg:
        eng = _engine(tiny_model)
        rng = np.random.default_rng(0)
        for _ in range(3):
            eng.submit(Request(rng.integers(2, 250, (5,)),
                               max_new_tokens=3))
        eng.run()
        assert lint_exposition(reg.to_prometheus()) == []


# ---------------------------------------------------------------------------
# Registry.merge — the multi-host aggregation primitive (satellite)
# ---------------------------------------------------------------------------


def test_merge_sums_counters_and_monotonic_across_restart():
    """Counter merge after a process restart: each segment's total
    contributes once, and the merged value never decreases as more
    segments fold in (monotonicity)."""
    merged = MetricsRegistry()
    seen = []
    for segment_total in (100.0, 30.0, 7.0):   # restart resets to 0
        seg = MetricsRegistry()
        seg.counter("req_total").inc(segment_total, route="gen")
        merged.merge(seg)
        seen.append(merged.get("req_total").value(route="gen"))
    assert seen == [100.0, 130.0, 137.0]
    assert seen == sorted(seen)


def test_merge_gauges_host_label_disambiguation():
    a, b, merged = (MetricsRegistry() for _ in range(3))
    a.gauge("queue_depth").set(3)
    b.gauge("queue_depth").set(11)
    merged.merge(a, host="hostA")
    merged.merge(b, host="hostB")
    g = merged.get("queue_depth")
    assert g.value(host="hostA") == 3.0
    assert g.value(host="hostB") == 11.0
    # without a host label, last write wins (documented)
    plain = MetricsRegistry()
    plain.merge(a)
    plain.merge(b)
    assert plain.get("queue_depth").value() == 11.0


def test_merge_histograms_bucketwise_and_exemplar_newest_wins():
    a, b = MetricsRegistry(), MetricsRegistry()
    ha = a.histogram("lat_seconds", buckets=(0.1, 1.0))
    hb = b.histogram("lat_seconds", buckets=(0.1, 1.0))
    ha.observe(0.05)
    ha.observe(0.5, exemplar="old")
    hb.observe(0.5, exemplar="new")
    hb.observe(2.0)
    merged = MetricsRegistry()
    merged.merge(a)
    merged.merge(b)
    h = merged.get("lat_seconds")
    assert h.count() == 4
    assert h.sum() == pytest.approx(3.05)
    (_, sample), = [(k, v) for k, v in h.samples()]
    assert sample["buckets"] == [[0.1, 1], [1.0, 3]]
    assert h.exemplars()[repr(1.0)]["trace_id"] == "new"


def test_merge_conflicting_bucket_boundaries_raises():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(0.5)
    b.histogram("lat_seconds", buckets=(0.25, 4.0)).observe(0.5)
    merged = MetricsRegistry()
    merged.merge(a)
    with pytest.raises(ValueError, match="conflicting bucket"):
        merged.merge(b)
    # the failed merge didn't corrupt the existing series
    assert merged.get("lat_seconds").count() == 1


def test_merge_kind_clash_raises():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("x_total").inc()
    b.gauge("x_total").set(1)
    merged = MetricsRegistry()
    merged.merge(a)
    with pytest.raises(TypeError):
        merged.merge(b)


def test_load_registry_jsonl_roundtrip(tmp_path):
    reg = MetricsRegistry()
    reg.counter("c_total").inc(5, op="x")
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    h.observe(0.05, exemplar="t1")
    h.observe(0.5)
    reg.gauge("g_depth").set(2)
    p = str(tmp_path / "host.jsonl")
    reg.dump_jsonl(p)
    reg.gauge("g_depth").set(9)          # newest sample must win
    reg.dump_jsonl(p)
    back = load_registry_jsonl(p)
    assert back.get("c_total").value(op="x") == 5.0
    assert back.get("g_depth").value() == 9.0
    assert back.get("h_seconds").count() == 2
    assert back.get("h_seconds").exemplars()[repr(0.1)]["trace_id"] \
        == "t1"
    assert lint_exposition(back.to_prometheus()) == []


def test_load_registry_jsonl_restart_segments_accumulate(tmp_path):
    """One append-only file spanning a process restart: the value drop
    marks the segment boundary, and BOTH segments' totals contribute —
    the loaded counter/histogram never regresses versus an earlier
    aggregation of the same stream (gauges still take the newest)."""
    p = str(tmp_path / "host.jsonl")
    seg1 = MetricsRegistry()
    seg1.counter("req_total").inc(1000)
    h1 = seg1.histogram("lat_seconds", buckets=(0.1, 1.0))
    h1.observe(0.05)
    h1.observe(0.5)
    seg1.gauge("depth").set(7)
    seg1.dump_jsonl(p)
    seg2 = MetricsRegistry()                 # restart: counts from 0
    seg2.counter("req_total").inc(50)
    seg2.histogram("lat_seconds", buckets=(0.1, 1.0)).observe(
        0.05, exemplar="post-restart")
    seg2.gauge("depth").set(2)
    seg2.dump_jsonl(p)
    back = load_registry_jsonl(p)
    assert back.get("req_total").value() == 1050.0
    h = back.get("lat_seconds")
    assert h.count() == 3 and h.sum() == pytest.approx(0.6)
    assert h.exemplars()[repr(0.1)]["trace_id"] == "post-restart"
    assert back.get("depth").value() == 2.0  # gauges: newest wins
    # boundary change mid-file is a conflict, never a silent mis-merge
    seg3 = MetricsRegistry()
    seg3.histogram("lat_seconds", buckets=(0.25,)).observe(0.1)
    seg3.dump_jsonl(p)
    with pytest.raises(ValueError, match="changed mid-file"):
        load_registry_jsonl(p)


def test_aggregate_metrics_tool(tmp_path, capsys):
    import aggregate_metrics
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("tok_total").inc(10)
    a.gauge("depth").set(1)
    b.counter("tok_total").inc(4)
    b.gauge("depth").set(6)
    pa, pb = str(tmp_path / "hostA.jsonl"), str(tmp_path / "hostB.jsonl")
    a.dump_jsonl(pa)
    b.dump_jsonl(pb)
    assert aggregate_metrics.main([pa, pb]) == 0
    out = capsys.readouterr().out
    assert "tok_total 14.0" in out
    assert 'depth{host="hostA"} 1.0' in out
    assert 'depth{host="hostB"} 6.0' in out
    assert lint_exposition(out) == []
    assert "trace_id" not in out            # classic page by default
    assert aggregate_metrics.main(["--openmetrics", pa, pb]) == 0
    om = capsys.readouterr().out
    assert lint_exposition(om) == [] and om.endswith("# EOF\n")
    # conflicting buckets across hosts: exit 1, loud
    c = MetricsRegistry()
    c.histogram("h_seconds", buckets=(0.5,)).observe(0.1)
    d = MetricsRegistry()
    d.histogram("h_seconds", buckets=(0.9,)).observe(0.1)
    pc, pd = str(tmp_path / "c.jsonl"), str(tmp_path / "d.jsonl")
    c.dump_jsonl(pc)
    d.dump_jsonl(pd)
    assert aggregate_metrics.main([pc, pd]) == 1
    assert "MERGE CONFLICT" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# Timeseries ring
# ---------------------------------------------------------------------------


def test_timeseries_rate_delta_and_window():
    reg = MetricsRegistry()
    c = reg.counter("tok_total")
    clock = ManualClock()
    ring = TimeseriesRing(capacity=16, clock=clock)
    for inc in (10, 10, 40):
        c.inc(inc)
        ring.snapshot(reg)
        clock.advance(1.0)
    assert ring.rate("tok_total") == pytest.approx(25.0)   # 50 over 2s
    assert ring.rate("tok_total", window_s=1.0) == pytest.approx(40.0)
    assert ring.delta("tok_total") == pytest.approx(50.0)
    assert ring.latest("tok_total") == 60.0
    assert ring.rate("tok_total", missing="x") is None     # unknown key
    assert ring.rates() == {"tok_total": pytest.approx(25.0)}


def test_timeseries_counter_reset_fold():
    """A writer restart (value drops) must not produce a negative
    rate; the post-reset segment counts from its own baseline."""
    clock = ManualClock()
    ring = TimeseriesRing(clock=clock)
    for v in (100.0, 110.0, 5.0, 20.0):
        ring._ingest([("c_total", {}, "counter", v)], clock.t)
        clock.advance(1.0)
    # segments: +10, (reset), +15 over 3s
    assert ring.rate("c_total") == pytest.approx(25.0 / 3.0)
    assert ring.rate("c_total") >= 0


def test_timeseries_histogram_flattening_and_capacity():
    reg = MetricsRegistry()
    h = reg.histogram("e2e_seconds", buckets=(1.0,))
    clock = ManualClock()
    ring = TimeseriesRing(capacity=4, clock=clock)
    for i in range(10):
        h.observe(0.5)
        ring.snapshot(reg)
        clock.advance(1.0)
    assert ring.kind("e2e_seconds_count") == "counter"
    pts = ring.series("e2e_seconds_count")
    assert len(pts) == 4                    # bounded ring
    assert ring.rate("e2e_seconds_count") == pytest.approx(1.0)
    # windowed mean latency from the two flattened series
    mean = ring.delta("e2e_seconds_sum") / ring.delta("e2e_seconds_count")
    assert mean == pytest.approx(0.5)


def test_parse_prometheus_roundtrip():
    reg = MetricsRegistry()
    reg.counter("a_total", "h").inc(2, op='say "hi"\n\\')
    # literal backslash followed by 'n': escapes to \\n on the page and
    # must decode back to \ + n, NOT a newline (single-pass unescape)
    reg.counter("path_total").inc(1, dir="logs\\nightly")
    reg.gauge("b_depth").set(-1.5)
    h = reg.histogram("c_seconds", buckets=(0.1,))
    h.observe(0.05, exemplar="t9")
    rows = parse_prometheus(reg.to_prometheus())
    d = {(r["name"], tuple(sorted(r["labels"].items()))): r
         for r in rows}
    assert d[("a_total", (("op", 'say "hi"\n\\'),))]["value"] == 2.0
    assert d[("path_total", (("dir", "logs\\nightly"),))]["value"] == 1.0
    assert d[("b_depth", ())]["value"] == -1.5
    assert d[("c_seconds_count", ())]["type"] == "counter"
    # bucket rows survive the round-trip (ISSUE 18: the fleet
    # federator re-assembles histograms from them) and type as the
    # counters they are
    assert d[("c_seconds_bucket", (("le", "0.1"),))]["value"] == 1.0
    assert d[("c_seconds_bucket", (("le", "+Inf"),))]["type"] == "counter"


# ---------------------------------------------------------------------------
# Admin server endpoints
# ---------------------------------------------------------------------------


def test_metrics_endpoint_lints_and_feeds_ring(admin):
    with scoped_registry() as reg:
        reg.counter("demo_total", "demo").inc(3, op="x")
        reg.histogram("demo_seconds", buckets=(0.1,)).observe(
            0.05, exemplar="t1")
        st, body = _get(admin.url, "/metrics")
        assert st == 200
        text = body.decode()
        assert lint_exposition(text) == []
        # classic text/plain page: NO exemplar suffix (the 0.0.4
        # parser real Prometheus selects from the Content-Type would
        # reject it and fail the whole scrape)
        assert "trace_id" not in text
        reg.counter("demo_total").inc(5, op="x")
        _get(admin.url, "/metrics")
        # the plane's own traffic is counted (in the active registry)
        assert reg.get("monitor_http_requests_total") \
            .value(path="/metrics") == 2
    assert admin.ring.snapshots_taken == 2
    assert admin.ring.delta("demo_total", op="x") == 5.0


def test_metrics_endpoint_openmetrics_negotiation(admin):
    """An Accept: application/openmetrics-text scrape gets the
    exemplar-carrying OpenMetrics page with the # EOF trailer."""
    with scoped_registry() as reg:
        reg.histogram("demo_seconds", buckets=(0.1,)).observe(
            0.05, exemplar="t1")
        req = urllib.request.Request(
            admin.url + "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        with urllib.request.urlopen(req, timeout=10) as r:
            assert "application/openmetrics-text" in \
                r.headers["Content-Type"]
            text = r.read().decode()
    assert lint_exposition(text) == []
    assert '# {trace_id="t1"} 0.05' in text
    assert text.endswith("# EOF\n")


def test_healthz_readyz_and_providers(admin):
    assert _get(admin.url, "/healthz")[0] == 200
    st, doc = _get_json(admin.url, "/readyz")
    assert st == 200 and doc["ready"] is True
    admin.register_readiness("engine", lambda: {"state": "draining"})
    admin.register_readiness("other", lambda: None)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(admin.url, "/readyz")
    assert ei.value.code == 503
    doc = json.loads(ei.value.read())
    assert doc["ready"] is False
    assert doc["reasons"]["engine"]["state"] == "draining"
    assert "other" not in doc["reasons"]
    # a raising provider reports, never 500s the endpoint
    admin.unregister_readiness("engine")
    admin.register_readiness("broken",
                             lambda: (_ for _ in ()).throw(OSError("x")))
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(admin.url, "/readyz")
    assert json.loads(ei.value.read())["reasons"]["broken"][
        "state"] == "provider-error"
    admin.unregister_readiness("broken")
    assert _get(admin.url, "/readyz")[0] == 200


def test_statusz_sections_flags_fingerprint(admin):
    admin.register_status("mything", lambda: {"answer": 42})
    admin.register_status("gone", lambda: None)   # stale: dropped
    st, doc = _get_json(admin.url, "/statusz")
    assert st == 200
    assert doc["sections"]["mything"]["answer"] == 42
    assert "gone" not in doc["sections"]
    assert "monitor_port" in doc["flags"]
    assert doc["fingerprint"]["pid"] == os.getpid()
    assert "per_second" in doc["rates"]
    # the stale provider was dropped from the table, not just skipped
    with admin._lock:
        assert "gone" not in admin._status


def test_debug_flight_matches_crash_dump(admin, tmp_path):
    from paddle_tpu.monitor.flight_recorder import get_flight_recorder
    fr = get_flight_recorder()
    fr.record_event("chaos", site="x")
    fr.record_step(7, loss=1.5, kind="step")
    st, doc = _get_json(admin.url, "/debug/flight")
    assert st == 200
    on_disk = json.load(open(fr.dump(str(tmp_path / "d.json"))))
    # same document a crash would dump (modulo reason/timestamps)
    assert doc["steps"] == on_disk["steps"]
    assert doc["events"] == on_disk["events"]
    assert doc["fingerprint"] == on_disk["fingerprint"]
    assert doc["reason"] == "admin_endpoint"


def test_debug_trace_json_and_perfetto(admin):
    from paddle_tpu.monitor import trace as trace_mod
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        tr = trace_mod.start_trace("unit.work", request_id=1)
        sp = tr.start_span("phase")
        tr.end_span(sp)
        trace_mod.get_tracer().finish_trace(tr)
        st, doc = _get_json(admin.url, "/debug/trace")
        assert st == 200
        assert any(t["name"] == "unit.work" for t in doc["traces"])
        st, pdoc = _get_json(admin.url, "/debug/trace?format=perfetto")
        assert st == 200
        names = {e.get("name") for e in pdoc["traceEvents"]}
        assert "phase" in names


def test_debug_profile_returns_chrome_trace(admin):
    from paddle_tpu import profiler as prof
    st, doc = _get_json(admin.url, "/debug/profile?seconds=0.05")
    assert st == 200
    assert isinstance(doc["traceEvents"], list)
    assert doc["captureSeconds"] == pytest.approx(0.05)
    assert not prof._active[0]            # window closed after capture
    # a concurrent user profiler session is refused, not corrupted
    prof.start_profiler()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(admin.url, "/debug/profile?seconds=0.05")
        assert ei.value.code == 409
    finally:
        prof.stop_profiler()


def test_unknown_endpoint_404s(admin):
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(admin.url, "/nope")
    assert ei.value.code == 404


# ---------------------------------------------------------------------------
# Engine lifecycle + readiness (the acceptance drill)
# ---------------------------------------------------------------------------


def _ready_reason(url):
    """(status, reason-dict-or-None) from /readyz, one engine max."""
    try:
        st, doc = _get_json(url, "/readyz")
        return st, None
    except urllib.error.HTTPError as e:
        assert e.code == 503
        doc = json.loads(e.read())
        reasons = [v for k, v in doc["reasons"].items()
                   if k.startswith("serving_engine")]
        return 503, reasons[0] if reasons else None


@pytest.mark.serve
@pytest.mark.chaos
def test_live_engine_admin_plane_acceptance(tiny_model):
    """ISSUE 14 acceptance: a live engine under the chaos loadgen
    answers /metrics (conformance-lint clean), flips /readyz to 503
    within one iteration of entering shedding/draining and back on
    exit, and serves /debug/profile as valid chrome-trace JSON."""
    chaos.configure("serve.request.poison@2", seed=0)
    clock = ManualClock()
    with flag_scope("monitor_port", -1), scoped_registry() as reg:
        eng = _engine(tiny_model, clock=clock, max_batch_slots=1,
                      overload_threshold_s=1.0, overload_alpha=1.0,
                      slo_availability=0.99)
        srv = server_mod.get_server()
        assert srv is not None and srv.running
        url = srv.url
        # -- drive the bursty chaos loadgen through the engine ----------
        schedule = build_requests(LoadSpec(
            num_requests=6, rate_rps=50.0, arrival="mmpp",
            burstiness=2.0, prompt_len_range=(4, 8),
            max_new_range=(2, 3), vocab_size=256, seed=1))
        for _, req in schedule:
            eng.submit(req)
        eng.run()
        # -- /metrics: serve series present, page lint-clean ------------
        st, body = _get(url, "/metrics")
        assert st == 200
        text = body.decode()
        assert lint_exposition(text) == []
        assert "serve_tokens_generated_total" in text
        assert "slo_burn_rate" in text
        # chaos poisoned ≥1 request: its failure is on the page
        assert reg.get("serve_requests_total").value(event="failed") >= 1
        # -- shedding flips /readyz within the iteration it enters ------
        assert _ready_reason(url)[0] == 200
        eng.submit(Request(np.arange(1, 6), max_new_tokens=3))
        eng.submit(Request(np.arange(1, 6), max_new_tokens=3))
        eng.step()
        clock.advance(5.0)               # head-of-queue delay blows up
        eng.step()                       # detector enters shedding HERE
        assert eng._overload.overloaded
        st, reason = _ready_reason(url)
        assert st == 503 and reason["state"] == "shedding"
        eng.run()                        # drain queue; EWMA decays
        for _ in range(8):
            eng.step()
        assert not eng._overload.overloaded
        assert _ready_reason(url)[0] == 200     # ...and back on exit
        # -- /debug/profile on the live process -------------------------
        st, doc = _get_json(url, "/debug/profile?seconds=0.05")
        assert st == 200 and isinstance(doc["traceEvents"], list)
        json.dumps(doc)                  # valid chrome-trace JSON
        # -- statusz carries the engine section -------------------------
        st, sdoc = _get_json(url, "/statusz")
        sect = [v for k, v in sdoc["sections"].items()
                if k.startswith("serving_engine")]
        assert sect and sect[0]["scheduler"]["stats"]["completed"] >= 5
        assert "slo_availability" in sect[0]
        eng.shutdown()


@pytest.mark.serve
def test_readyz_flips_on_draining_and_drained(tiny_model, tmp_path):
    clock = ManualClock()
    with flag_scope("monitor_port", -1):
        eng = _engine(tiny_model, clock=clock,
                      drain_dir=str(tmp_path / "drain"))
        url = server_mod.get_server().url
        assert _ready_reason(url)[0] == 200
        eng._draining = True             # the submit()-visible state
        st, reason = _ready_reason(url)
        assert st == 503 and reason["state"] == "draining"
        eng._draining = False
        eng.drain()                      # no pending work: clean drain
        st, reason = _ready_reason(url)
        assert st == 503 and reason["state"] == "drained"
        eng.shutdown()


@pytest.mark.serve
def test_readyz_reports_watchdog_trip(tiny_model):
    with flag_scope("monitor_port", -1):
        eng = _engine(tiny_model)
        url = server_mod.get_server().url
        eng._watchdog_tripped = {"kind": "decode", "timeout_s": 0.1,
                                 "dispatch": 7}
        st, reason = _ready_reason(url)
        assert st == 503 and reason["state"] == "watchdog-tripped"
        assert reason["kind"] == "decode"
        eng._watchdog_tripped = None
        assert _ready_reason(url)[0] == 200
        eng.shutdown()


@pytest.mark.serve
def test_zero_overhead_pin_no_port_no_plane(tiny_model):
    """ISSUE 14 acceptance: FLAGS_monitor_port unset ⇒ a 50-request
    serve run creates ZERO admin threads, no socket/server object, and
    zero plane-owned registry series."""
    assert server_mod.get_server() is None
    with scoped_registry() as reg:
        eng = _engine(tiny_model, max_batch_slots=3)
        spec = LoadSpec(num_requests=50, rate_rps=500.0,
                        prompt_len_range=(4, 8), max_new_range=(1, 2),
                        vocab_size=256, seed=3)
        summary = run_open_loop(eng, spec)
        assert summary["requests_completed"] == 50
        names = reg.names()
    assert server_mod.get_server() is None
    assert eng._admin is None
    assert not any(t.name.startswith(server_mod.THREAD_PREFIX)
                   for t in threading.enumerate())
    # no plane-owned series: the run wrote only the serve_* telemetry
    # it always writes
    assert not [n for n in names if n.startswith("monitor_")]


def test_collected_engine_is_pruned_not_ready(tiny_model):
    """An engine dropped WITHOUT shutdown() must never linger as a
    ready-reading registration: its weakref'd providers return the
    STALE sentinel and the server prunes them on the next read — the
    200 body's ``checks`` list shows no serving engine left."""
    import gc
    with flag_scope("monitor_port", -1):
        eng = _engine(tiny_model)
        srv = server_mod.get_server()
        key = eng._admin_key
        eng.cache.k = eng.cache.v = None     # drop device pools too
        del eng
        gc.collect()
        st, doc = _get_json(srv.url, "/readyz")
        assert st == 200
        assert not [c for c in doc["checks"]
                    if c.startswith("serving_engine")]
        with srv._lock:                      # pruned, not just skipped
            assert key not in srv._readiness
        st, sdoc = _get_json(srv.url, "/statusz")
        assert not [k for k in sdoc["sections"]
                    if k.startswith("serving_engine")]
        with srv._lock:
            assert key not in srv._status


def test_engine_shutdown_unregisters_providers(tiny_model):
    with flag_scope("monitor_port", -1):
        eng = _engine(tiny_model)
        srv = server_mod.get_server()
        key = eng._admin_key
        with srv._lock:
            assert key in srv._readiness and key in srv._status
        eng.shutdown()
        with srv._lock:
            assert key not in srv._readiness and key not in srv._status


# ---------------------------------------------------------------------------
# monitor_top
# ---------------------------------------------------------------------------


def test_monitor_top_renders_movement():
    import monitor_top
    reg = MetricsRegistry()
    clock = ManualClock()
    ring = TimeseriesRing(clock=clock)
    reg.counter("serve_tokens_generated_total").inc(100)
    reg.gauge("serve_queue_depth").set(4)
    reg.gauge("slo_burn_rate").set(2.5, slo="serve_availability",
                                   window="60s")
    ring.ingest_rows(parse_prometheus(reg.to_prometheus()))
    clock.advance(2.0)
    reg.counter("serve_tokens_generated_total").inc(60)
    ring.ingest_rows(parse_prometheus(reg.to_prometheus()))
    frame = monitor_top.render_frame(ring, "http://h/metrics")
    assert "tokens/s" in frame and "30.0" in frame   # 60 over 2s
    assert "pressure" in frame and "queue" in frame
    assert "SLO burn" in frame and "60s=2.50" in frame


def test_monitor_top_against_live_server(admin, capsys):
    import monitor_top
    with scoped_registry() as reg:
        reg.counter("serve_tokens_generated_total").inc(10)
        rc = monitor_top.main(
            ["--iterations", "2", "--interval", "0.05", "--no-clear",
             admin.url])
    assert rc == 0
    out = capsys.readouterr().out
    assert "monitor_top" in out and "tokens/s" in out


def test_monitor_top_survives_scrape_failure(capsys):
    import monitor_top
    rc = monitor_top.main(["--once", "--no-clear",
                           "http://127.0.0.1:9/metrics"])
    assert rc == 0
    assert "scrape failed" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# monitor_report --slo
# ---------------------------------------------------------------------------


def test_monitor_report_slo_renderer(tmp_path):
    import monitor_report
    from paddle_tpu.monitor.slo import SLOTracker
    reg = MetricsRegistry()
    clock = ManualClock()
    t1 = SLOTracker("serve_availability", 0.99, windows=(60.0, 300.0),
                    clock=clock)
    t1.record(good=90, bad=10)
    t1.publish(reg)
    t2 = SLOTracker("serve_deadline", 0.95, windows=(60.0, 300.0),
                    clock=clock)
    t2.record(good=50)
    t2.publish(reg)
    p = str(tmp_path / "slo.jsonl")
    reg.dump_jsonl(p)
    out = monitor_report.render(
        __import__("paddle_tpu.monitor", fromlist=["load_jsonl"])
        .load_jsonl(p), slo=True)
    assert "SLO error-budget burn" in out
    assert "serve_availability" in out and "serve_deadline" in out
    assert "BLOWN" in out                  # 10% errors vs 1% budget
    assert "burn 60s" in out and "burn 300s" in out
    # empty dump: helpful hint, not a crash
    assert "no slo_* gauges" in monitor_report.render([], slo=True)
