"""Fleet-scale serving (ISSUE 16): TP-sharded decode under a tensor-
parallel mesh, the prefix-affine FleetRouter over N engine replicas,
and chaos-proof migration — replica death and graceful drain both
resume in-flight requests token-exact on survivors, with availability
accounted (nothing dropped, nothing double-counted)."""

import contextlib
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import no_grad
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.serving import (FleetRouter, LoadSpec, Request,
                                RouterConfig, SamplingParams,
                                ServingConfig, ServingEngine,
                                run_fleet_open_loop)

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


def _engine(model, **kw):
    cfg = dict(max_batch_slots=3, block_size=4, max_context_len=64,
               prefill_buckets=(8, 16), batch_buckets=(1, 2))
    cfg.update(kw)
    return ServingEngine(model, ServingConfig(**cfg))


def _fleet(model, n=2, router_kw=None, flags=(), **kw):
    """N replicas behind a router; flags entering scope at engine
    construction (kill switches are read once at init)."""
    with contextlib.ExitStack() as stack:
        for name, val in flags:
            stack.enter_context(flag_scope(name, val))
        reps = {f"r{i}": _engine(model, **kw) for i in range(n)}
        return FleetRouter(reps, RouterConfig(**(router_kw or {})))


def _golden(model, prompt, n):
    seq = np.asarray(prompt, np.int32)
    for _ in range(n):
        with no_grad():
            lg = model(paddle.to_tensor(seq[None, :])).numpy()
        seq = np.concatenate([seq, [np.int32(lg[0, -1].argmax())]])
    return seq


REP_PROMPT = [3, 4, 5, 3, 4, 5, 3, 4]
PROMPTS = [REP_PROMPT, [7, 8, 9, 7, 8, 9, 7, 8], [1, 2, 1, 2, 1, 2]]


# ---------------------------------------------------------------------------
# TP-sharded decode (tentpole a)
# ---------------------------------------------------------------------------


def test_tp_mesh_decode_token_identical(tiny_model):
    """Serving under a 1x2 tensor-parallel mesh: params sharded by the
    hybrid-parallel specs, paged KV sharded over heads, collectives
    inside the compiled programs — outputs token-identical to the
    unsharded engine."""
    import jax
    from paddle_tpu.distributed.spmd import make_mesh

    base = _engine(tiny_model)
    want = [o.tolist() for o in base.generate(PROMPTS, max_new_tokens=6)]
    base.shutdown()

    mesh = make_mesh({"mp": 2}, jax.devices()[:2])
    eng = _engine(tiny_model, mesh=mesh)
    got = [o.tolist() for o in eng.generate(PROMPTS, max_new_tokens=6)]
    assert got == want
    # the paged KV pool is physically sharded over the head axis
    # (trailing Nones may be normalized away by XLA output shardings)
    for arr in (eng.cache.k, eng.cache.v):
        spec = tuple(arr.sharding.spec)
        assert spec[3] == "mp"
        assert all(ax is None for i, ax in enumerate(spec) if i != 3)
    eng.shutdown()


def test_tp_mesh_spec_decode_token_identical(tiny_model):
    """Speculative verify dispatches compile and stay token-exact under
    the mesh too (greedy oracle pin)."""
    import jax
    from paddle_tpu.distributed.spmd import make_mesh

    mesh = make_mesh({"mp": 2}, jax.devices()[:2])
    with flag_scope("serve_spec_k", 3):
        eng = _engine(tiny_model, mesh=mesh)
    out = eng.generate([REP_PROMPT], max_new_tokens=8)[0]
    assert np.array_equal(out, _golden(tiny_model, REP_PROMPT, 8))
    assert eng._stats["spec_proposed"] > 0
    eng.shutdown()


def test_tp_mesh_rejects_indivisible_heads(tiny_model):
    """gpt_tiny has 4 heads; an mp=3 mesh cannot shard them evenly and
    the engine must say so at init, not NaN at serve time."""
    import jax
    from paddle_tpu.distributed.spmd import make_mesh

    mesh = make_mesh({"mp": 3}, jax.devices()[:3])
    with pytest.raises(ValueError, match="num_heads"):
        _engine(tiny_model, mesh=mesh)


# ---------------------------------------------------------------------------
# prefix-affine routing (tentpole b)
# ---------------------------------------------------------------------------


def test_affinity_same_prefix_same_replica(tiny_model):
    """Requests sharing an affinity key (first block of prompt tokens)
    land on ONE replica — that replica's radix tree owns the family."""
    router = _fleet(tiny_model, n=3,
                    router_kw=dict(saturation_queue_depth=999),
                    flags=(("serve_prefix_cache", True),))
    pre = [11, 12, 13, 14]                       # one block (block_size 4)
    recs = [router.submit(Request(pre + [20 + i], max_new_tokens=3))
            for i in range(5)]
    assert len({r.replica for r in recs}) == 1
    # distinct keys spread: 8 different families should not all pile
    # onto a single replica of three
    others = [router.submit(Request([40 + 5 * i] * 4, max_new_tokens=2))
              for i in range(8)]
    assert len({r.replica for r in others}) >= 2
    router.run()
    assert all(r.outcome == "completed" for r in recs + others)
    assert router.summary()["routed_affine"] == 13
    router.shutdown()


def test_p2c_fallback_when_saturated(tiny_model):
    """With every replica reporting saturation the router falls back to
    power-of-two-choices over ready replicas instead of queueing the
    world on the affinity owner."""
    router = _fleet(tiny_model, n=2,
                    router_kw=dict(saturation_queue_depth=0))
    recs = [router.submit(Request(REP_PROMPT, max_new_tokens=2))
            for _ in range(8)]
    s = router.summary()
    assert s["routed_balanced"] == 8 and s["routed_affine"] == 0
    assert len({r.replica for r in recs}) == 2   # spread, not piled
    router.run()
    assert all(r.outcome == "completed" for r in recs)
    router.shutdown()


def test_unready_replica_gets_no_traffic(tiny_model):
    """Ring walk skips not-ready owners: after one replica dies and one
    drains, every key spills to the survivor and the fleet still
    serves."""
    router = _fleet(tiny_model, n=3)
    router.kill_replica("r0")
    router.drain_replica("r1")
    recs = [router.submit(Request([50 + 3 * i] * 4, max_new_tokens=2))
            for i in range(6)]
    assert {r.replica for r in recs} == {"r2"}
    router.run()
    assert all(r.outcome == "completed" for r in recs)
    router.shutdown()


def test_fleet_prefix_hit_parity_with_single_engine(tiny_model):
    """The acceptance criterion: prefix-affine placement keeps the
    FLEET's radix hit rate within 5 points of one engine serving the
    same tenanted workload (naive round-robin would shred it)."""
    spec = LoadSpec(num_requests=24, rate_rps=1e6,
                    prompt_len_range=(4, 10), max_new_range=(3, 6),
                    vocab_size=256, seed=5, sampling=SamplingParams(),
                    shared_prefix_len=8, prefix_pool_size=2,
                    prefix_zipf=1.2, tenants=4)
    hits = {}
    for n in (1, 2):
        router = _fleet(tiny_model, n=n,
                        router_kw=dict(saturation_queue_depth=999),
                        flags=(("serve_prefix_cache", True),))
        summary = run_fleet_open_loop(router, spec)
        hits[n] = summary["fleet_prefix_hit_pct"]
        assert summary["requests_completed"] == 24
        router.shutdown()
    assert hits[1] > 0
    assert abs(hits[2] - hits[1]) <= 5.0


# ---------------------------------------------------------------------------
# chaos-proof migration (tentpole c)
# ---------------------------------------------------------------------------


def test_kill_replica_mid_decode_token_exact(tiny_model):
    """The chaos drill: a replica dies mid-decode with streamed tokens
    outstanding; the router re-homes its in-flight requests from its
    own journal and every stream finishes token-exact vs the
    single-engine oracle — no dropped ids, no duplicates, availability
    100%."""
    oracle = [_golden(tiny_model, p, 8).tolist() for p in PROMPTS]
    router = _fleet(tiny_model, n=2)
    recs = [router.submit(Request(p, max_new_tokens=8)) for p in PROMPTS]
    for _ in range(3):                           # stream a few tokens
        router.step_all()
    victim = next(r.replica for r in recs if not r.done)
    streamed = {r.request_id: list(r.tokens) for r in recs}
    moved = router.kill_replica(victim)
    assert moved >= 1
    router.run()
    assert [r.prompt + r.tokens for r in recs] == oracle
    # journaled prefixes survived verbatim (mid-stream continuation,
    # not a restart of the visible stream)
    for r in recs:
        assert r.tokens[:len(streamed[r.request_id])] \
            == streamed[r.request_id]
    s = router.summary()
    assert s["migrated_death"] == moved
    assert s["duplicate_request_ids"] == 0
    assert s["requests_offered"] == len(PROMPTS)
    assert s["requests_completed"] == len(PROMPTS)
    assert s["availability_pct"] == 100.0
    router.shutdown()


def test_kill_replica_mid_chunk_prefill_token_exact(tiny_model):
    """Death strikes BETWEEN prefill chunks (no token streamed yet):
    the survivor re-prefills from the original prompt and the output is
    still token-exact."""
    router = _fleet(tiny_model, n=2,
                    flags=(("serve_prefill_chunk", 4),))
    prompt = list(range(2, 14))                  # 12 tokens -> 3 chunks
    rec = router.submit(Request(prompt, max_new_tokens=6))
    router.step_all()                            # first chunk only
    victim = router.replicas[rec.replica]
    assert victim.engine._stats["prefill_chunks"] >= 1
    assert not rec.done and rec.tokens == []
    router.kill_replica(rec.replica)
    router.run()
    assert rec.outcome == "completed"
    assert rec.prompt + rec.tokens \
        == _golden(tiny_model, prompt, 6).tolist()
    assert router.summary()["migrated_death"] == 1
    router.shutdown()


def test_drain_replica_snapshots_and_migrates(tiny_model, tmp_path):
    """Graceful hand-off: drain with a zero budget snapshots the
    in-flight request (mid-stream position and trace identity
    included); the router restores it on the survivor token-exact and
    the trace_id survives the hop."""
    with flag_scope("trace", True):
        router = _fleet(tiny_model, n=2,
                        router_kw=dict(drain_dir=str(tmp_path)))
        rec = router.submit(Request(REP_PROMPT, max_new_tokens=8))
        for _ in range(3):
            router.step_all()
        assert 0 < len(rec.tokens) < 8
        tid = rec.trace_id
        assert tid is not None
        report = router.drain_replica(rec.replica, budget_s=0.0)
        assert report["snapshotted"] == 1 and report["migrated"] == 1
        router.run()
    assert rec.outcome == "completed"
    assert rec.prompt + rec.tokens \
        == _golden(tiny_model, REP_PROMPT, 8).tolist()
    assert rec.trace_id == tid and rec.hops == 1
    s = router.summary()
    assert s["migrated_drain"] == 1 and s["availability_pct"] == 100.0
    router.shutdown()


def test_threaded_fleet_serves_and_survives_stop(tiny_model):
    """Threaded driving mode: one serve loop per replica; submissions
    complete without the caller stepping, and stop() is clean."""
    router = _fleet(tiny_model, n=2)
    router.start()
    try:
        recs = [router.submit(Request(p, max_new_tokens=4))
                for p in PROMPTS]
        deadline = time.monotonic() + 60.0
        while not all(r.done for r in recs):
            if time.monotonic() > deadline:
                pytest.fail("threaded fleet did not drain in 60s")
            time.sleep(0.01)
            router._sweep()
    finally:
        router.stop()
    assert all(r.outcome == "completed" for r in recs)
    assert not any(rep.last_error for rep in router.replicas.values())
    router.shutdown()


# ---------------------------------------------------------------------------
# construction contracts + telemetry
# ---------------------------------------------------------------------------


def test_router_rejects_mismatched_block_sizes(tiny_model):
    a = _engine(tiny_model)
    b = _engine(tiny_model, block_size=8)
    with pytest.raises(ValueError, match="block_size"):
        FleetRouter({"a": a, "b": b})
    a.shutdown()
    b.shutdown()


def _merged_docs():
    from paddle_tpu.monitor import trace as trace_mod
    from paddle_tpu.monitor.fleet import merge_fleet_traces
    return merge_fleet_traces(
        trace_mod.get_tracer().snapshot(include_live=True))


def test_router_trace_parents_replica_tree(tiny_model):
    """ISSUE 18: one routed request produces ONE merged span tree — the
    router's fleet.request root with its route span, and the replica's
    serve.request tree parented UNDER the route decision (the Dapper
    join the Request trace context carries)."""
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        router = _fleet(tiny_model, n=2)
        rec = router.submit(Request(REP_PROMPT, max_new_tokens=4))
        router.run()
        router.shutdown()
        docs = _merged_docs()
    doc = next(d for d in docs if d["trace_id"] == rec.trace_id)
    assert doc["name"] == "fleet.request"
    assert doc["merged_from"] == 2 and doc["finished"]
    assert doc["processes"][0] == "router"
    spans = {s["span_id"]: s for s in doc["spans"]}
    route = next(s for s in doc["spans"] if s["name"] == "route")
    serve = next(s for s in doc["spans"]
                 if s["name"] == "serve.request")
    assert serve["parent_id"] == route["span_id"]
    assert serve["process"] == rec.replica
    assert route["attrs"]["replica"] == rec.replica
    assert "affinity_key" in route["attrs"]
    root = spans[route["parent_id"]]
    assert root["name"] == "fleet.request"
    assert root["attrs"]["outcome"] == "completed"
    assert root["attrs"]["hops"] == 0


def test_drain_trace_parent_follows_migrate_hop(tiny_model, tmp_path):
    """Drain keeps ONE trace across the hop: the router opens a migrate
    span, the propagated parent token moves to it, and the resumed
    serve.request tree on the survivor parents under the hop."""
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        router = _fleet(tiny_model, n=2,
                        router_kw=dict(drain_dir=str(tmp_path)))
        rec = router.submit(Request(REP_PROMPT, max_new_tokens=8))
        for _ in range(3):
            router.step_all()
        first_parent = rec.trace_parent
        assert first_parent is not None
        router.drain_replica(rec.replica, budget_s=0.0)
        assert rec.trace_parent != first_parent   # re-parented at hop
        router.run()
        router.shutdown()
        docs = _merged_docs()
    doc = next(d for d in docs if d["trace_id"] == rec.trace_id)
    assert doc["merged_from"] == 3            # router + both replicas
    hop = next(s for s in doc["spans"] if s["name"] == "migrate")
    assert hop["attrs"]["reason"] == "drain"
    serves = [s for s in doc["spans"] if s["name"] == "serve.request"]
    assert len(serves) == 2
    assert hop["span_id"] in {s["parent_id"] for s in serves}


def test_kill_replica_merged_trace_shows_hops(tiny_model):
    """Replica death still reads as ONE distributed trace: a migrate
    span with reason=death under the router root, the survivor's
    serve.request under the hop, and the Perfetto rendering carries one
    process track per participant."""
    from paddle_tpu.monitor import trace as trace_mod

    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        router = _fleet(tiny_model, n=2)
        recs = [router.submit(Request(p, max_new_tokens=8))
                for p in PROMPTS]
        for _ in range(3):
            router.step_all()
        victim = next(r.replica for r in recs if not r.done)
        moved = [r for r in recs
                 if not r.done and r.replica == victim]
        router.kill_replica(victim)
        router.run()
        router.shutdown()
        docs = _merged_docs()
        perf = trace_mod.perfetto_doc(docs,
                                      include_host_timeline=False)
    rec = moved[0]
    assert rec.outcome == "completed" and rec.hops == 1
    doc = next(d for d in docs if d["trace_id"] == rec.trace_id)
    hop = next(s for s in doc["spans"] if s["name"] == "migrate")
    assert hop["attrs"]["reason"] == "death"
    serves = [s for s in doc["spans"] if s["name"] == "serve.request"]
    assert hop["span_id"] in {s["parent_id"] for s in serves}
    assert {s.get("process") for s in doc["spans"]} \
        == {"router", victim, rec.replica}
    tracks = {e["args"]["name"] for e in perf["traceEvents"]
              if e.get("name") == "process_name"}
    assert {"paddle_tpu.trace:router",
            f"paddle_tpu.trace:{victim}",
            f"paddle_tpu.trace:{rec.replica}"} <= tracks


def test_fleet_observability_drill(tiny_model, tmp_path):
    """The ISSUE 18 acceptance drill: tenanted traffic over a
    2-replica fleet with a mid-flight replica kill and a deadline
    blowout, a FleetFederator over the shared registry — the federated
    page is lint-clean and sums to the source, the availability burn
    fires exactly ONE rate-limited incident bundle, and the bundle
    carries the merged fleet trace."""
    import json
    import os

    from paddle_tpu.monitor import scoped_registry
    from paddle_tpu.monitor.fleet import (FederatorConfig,
                                          FleetFederator,
                                          local_registry_target)
    from paddle_tpu.monitor.metrics import lint_exposition

    clk = [1000.0]
    with scoped_registry() as reg, flag_scope("trace", True), \
            flag_scope("trace_sample", 1.0):
        router = _fleet(tiny_model, n=2)
        fed = FleetFederator(
            [local_registry_target("local")],
            FederatorConfig(
                slo_availability=0.9, slo_windows=(60.0, 600.0),
                alert_pairs=((600.0, 60.0, 1.0),),
                incident_dir=str(tmp_path),
                incident_min_interval_s=300.0),
            router=router, clock=lambda: clk[0])
        recs = [router.submit(Request(p, max_new_tokens=6,
                                      tenant=f"t{i % 2}"))
                for i, p in enumerate(PROMPTS)]
        for _ in range(3):
            router.step_all()
        victim = next(r.replica for r in recs if not r.done)
        router.kill_replica(victim)
        # one request past its deadline spends availability budget
        # (expired is a BAD event in the federator's SLO vocabulary)
        doomed = router.submit(Request(REP_PROMPT, max_new_tokens=4,
                                       deadline_s=1e-6))
        time.sleep(0.01)
        router.run()
        assert doomed.outcome == "expired"
        assert all(r.outcome == "completed" for r in recs)

        s1 = fed.scrape_once()
        assert s1["targets_scraped"] == 1
        assert s1["alerts"] and s1["incident"] is not None
        clk[0] += 10.0
        s2 = fed.scrape_once()
        assert s2["incident"] is None        # inside the rate floor

        page = fed.registry.to_prometheus()
        assert lint_exposition(page) == []
        # federated serve_requests_total == the source registry, and
        # every federated serving sample carries the host label
        src = {lb["event"]: float(v) for lb, v in
               reg.snapshot()["serve_requests_total"]["samples"]}
        fed_by_event = {}
        for lb, v in fed.registry.get(
                "serve_requests_total").samples():
            assert lb["host"] == "local"
            fed_by_event[lb["event"]] = \
                fed_by_event.get(lb["event"], 0.0) + float(v)
        assert fed_by_event == src
        # tenant rollup crossed the federation boundary
        tenants = fed._fleet_status()["tenants"]
        assert set(tenants) >= {"t0", "t1"}
        router.shutdown()

    bundles = [d for d in os.listdir(tmp_path)
               if d.startswith("incident_")]
    assert len(bundles) == 1 and bundles[0].endswith("slo_burn")
    bundle = os.path.join(tmp_path, bundles[0])
    files = set(os.listdir(bundle))
    assert {"incident.json", "statusz.json", "metrics.prom",
            "flight.json", "trace_perfetto.json"} <= files
    with open(os.path.join(bundle, "incident.json")) as f:
        inc = json.load(f)
    assert inc["trigger"] == "slo_burn" and inc["alerts"]
    with open(os.path.join(bundle, "trace_perfetto.json")) as f:
        perf = json.load(f)
    tracks = {e["args"]["name"] for e in perf["traceEvents"]
              if e.get("name") == "process_name"}
    assert "paddle_tpu.trace:router" in tracks


def test_fleet_observability_off_by_default(tiny_model):
    """Zero-overhead pin: with FLAGS_fleet_monitor_* at defaults the
    router fast path allocates no federator, no scrape thread and no
    spans."""
    import threading

    from paddle_tpu.monitor import trace as trace_mod
    from paddle_tpu.monitor.fleet import (SCRAPE_THREAD_PREFIX,
                                          get_federator)

    router = _fleet(tiny_model, n=2)
    router.generate([REP_PROMPT], max_new_tokens=3)
    router.shutdown()
    assert get_federator() is None
    assert not [t for t in threading.enumerate()
                if t.name.startswith(SCRAPE_THREAD_PREFIX)]
    assert trace_mod.TRACE_STATS["spans_allocated"] == 0


def test_fleet_gauges_published(tiny_model):
    """summary() publishes the per-replica gauges the --fleet report
    renders: queue depth, prefix hit%, shed, and fleet size by state."""
    from paddle_tpu.monitor import scoped_registry

    with scoped_registry() as reg:
        router = _fleet(tiny_model, n=2,
                        flags=(("serve_prefix_cache", True),))
        router.generate([REP_PROMPT], max_new_tokens=3)
        router.kill_replica("r1")
        router.summary()
        snap = reg.snapshot()
        router.shutdown()
    states = {tuple(sorted(lb.items())): v for lb, v in
              snap["serve_router_replicas"]["samples"]}
    assert states[(("state", "alive"),)] == 1
    assert states[(("state", "ready"),)] == 1
    assert any(lb.get("replica") == "r0" for lb, _ in
               snap["serve_router_replica_queue_depth"]["samples"])
