"""Serving resilience layer (ISSUE 8): deadlines + cancellation,
admission control / load shedding, graceful drain, fault isolation,
decode watchdog, chaos-verified SLOs."""

import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import no_grad
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.monitor import scoped_registry
from paddle_tpu.serving import (DecodeWatchdogError, EngineDrained,
                                LoadSpec, OverloadDetector, Request,
                                ServerOverloaded, ServingConfig,
                                ServingEngine, TokenBucket,
                                build_requests, load_drain_snapshot,
                                requests_from_snapshot, run_open_loop)
from paddle_tpu.serving.kv_cache import PagedKVCache
from paddle_tpu.serving.scheduler import (TERMINAL_OUTCOMES, BucketTable,
                                          Scheduler)
from paddle_tpu.testing import chaos

pytestmark = [pytest.mark.serve, pytest.mark.chaos]


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


class ManualClock:
    """Controllable clock for deadline/overload tests (engine +
    scheduler share it; latencies then measure virtual time)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _engine(model, clock=None, **kw):
    cfg = dict(max_batch_slots=3, block_size=4, max_context_len=64,
               prefill_buckets=(8, 16), batch_buckets=(1, 2))
    cfg.update(kw)
    kw2 = {"clock": clock} if clock is not None else {}
    return ServingEngine(model, ServingConfig(**cfg), **kw2)


def _golden(model, prompt, n):
    """Re-derive every generated token by full uncached forwards."""
    seq = np.asarray(prompt, np.int32)
    for _ in range(n):
        with no_grad():
            lg = model(paddle.to_tensor(seq[None, :])).numpy()
        seq = np.concatenate([seq, [np.int32(lg[0, -1].argmax())]])
    return seq


def _prompts(rng, n, lo=4, hi=10):
    return [rng.integers(2, 250,
                         (int(rng.integers(lo, hi + 1)),)).astype(np.int32)
            for _ in range(n)]


# ---------------------------------------------------------------------------
# deadlines + cancellation
# ---------------------------------------------------------------------------


def test_queued_deadline_expires_before_any_slot(tiny_model):
    clock = ManualClock()
    eng = _engine(tiny_model, clock=clock, max_batch_slots=1)
    rng = np.random.default_rng(0)
    # slot is busy with a long request; the deadlined one waits
    busy = eng.submit(Request(rng.integers(2, 250, (5,)),
                              max_new_tokens=8))
    doomed = eng.submit(Request(rng.integers(2, 250, (5,)),
                                max_new_tokens=4, deadline_s=0.5))
    eng.step()
    clock.advance(1.0)                     # deadline passes in the queue
    with scoped_registry() as reg:
        eng.run()
    assert doomed.outcome == "expired"
    assert doomed.generated == []          # never touched a slot
    assert doomed.slot is None
    assert busy.outcome == "completed"
    assert reg.get("serve_requests_total").value(event="expired") == 1
    assert eng.scheduler.stats["expired_queued"] == 1   # shed-like
    assert eng.cache.allocator.pages_in_use == 0


def test_inflight_deadline_cancelled_at_boundary_pages_freed(tiny_model):
    clock = ManualClock()
    eng = _engine(tiny_model, clock=clock)
    rng = np.random.default_rng(1)
    p_keep = rng.integers(2, 250, (6,)).astype(np.int32)
    keep = eng.submit(Request(p_keep, max_new_tokens=6))
    doomed = eng.submit(Request(rng.integers(2, 250, (6,)),
                                max_new_tokens=6, deadline_s=0.5))
    eng.step()                             # both admitted, first tokens
    assert len(doomed.generated) >= 1
    in_use = eng.cache.allocator.pages_in_use
    clock.advance(1.0)
    eng.step()                             # boundary sweep expires it
    assert doomed.outcome == "expired"
    # admitted and decoded: counts against availability, never as shed
    assert eng.scheduler.stats["expired_queued"] == 0
    assert eng.cache.allocator.pages_in_use < in_use   # freed immediately
    eng.run()
    assert keep.outcome == "completed"     # survivor streams on, exact
    np.testing.assert_array_equal(
        np.concatenate([p_keep, keep.generated]),
        _golden(tiny_model, p_keep, 6))


def test_deadline_slack_histogram_only_for_deadline_requests(tiny_model):
    eng = _engine(tiny_model)
    rng = np.random.default_rng(2)
    with scoped_registry() as reg:
        eng.generate([rng.integers(2, 250, (5,))], max_new_tokens=2)
        assert reg.get("serve_deadline_slack_seconds") is None
        eng.submit(Request(rng.integers(2, 250, (5,)),
                           max_new_tokens=2, deadline_s=60.0))
        eng.run()
        h = reg.get("serve_deadline_slack_seconds")
        assert h is not None and h.count() == 1


def test_cancel_queued_and_inflight(tiny_model):
    eng = _engine(tiny_model, max_batch_slots=1)
    rng = np.random.default_rng(3)
    stream = []
    running = eng.submit(Request(
        rng.integers(2, 250, (5,)), max_new_tokens=8,
        on_token=lambda r, t, txt: stream.append(t)))
    queued = eng.submit(Request(rng.integers(2, 250, (5,)),
                                max_new_tokens=8))
    eng.step()
    assert eng.cancel(queued.request.request_id)   # queued: immediate
    assert queued.outcome == "cancelled"
    assert eng.cancel(running.request.request_id)  # in-flight: latched
    assert running.outcome is None
    n_at_cancel = len(stream)
    eng.run()
    assert running.outcome == "cancelled"
    assert len(stream) == n_at_cancel              # stream stopped
    assert eng.cache.allocator.pages_in_use == 0
    assert not eng.cancel(queued.request.request_id)   # already terminal
    assert not eng.cancel(987654)                      # unknown id


# ---------------------------------------------------------------------------
# admission control + load shedding
# ---------------------------------------------------------------------------


def _host_scheduler(policy="reject-new", max_queue=2, max_slots=2,
                    num_pages=12, on_event=None, clock=None):
    cache = PagedKVCache(1, 1, 4, num_pages=num_pages, block_size=4,
                         max_slots=max_slots, max_blocks_per_slot=6)
    kw = {"clock": clock} if clock is not None else {}
    return Scheduler(cache, BucketTable((8, 16, 24), (1, 2)),
                     max_queue=max_queue, policy=policy,
                     on_event=on_event, **kw)


def _fill(sched, n=2):
    """Occupy all slots so new submits stay queued."""
    sts = [sched.submit(Request([1, 2, 3], max_new_tokens=4))
           for _ in range(n)]
    sched.plan_admissions()
    return sts


def test_policy_reject_new():
    sched = _host_scheduler(policy="reject-new", max_queue=2)
    _fill(sched)
    q = [sched.submit(Request([1, 2], max_new_tokens=2))
         for _ in range(2)]
    with pytest.raises(ServerOverloaded) as ei:
        sched.submit(Request([1, 2], max_new_tokens=2))
    assert ei.value.reason == "queue_full"
    assert all(st.outcome is None for st in q)     # nobody else harmed


def test_policy_drop_oldest():
    events = []
    sched = _host_scheduler(policy="drop-oldest", max_queue=2,
                            on_event=lambda ev, st: events.append((ev, st)))
    _fill(sched)
    old = sched.submit(Request([1, 2], max_new_tokens=2))
    mid = sched.submit(Request([3, 4], max_new_tokens=2))
    new = sched.submit(Request([5, 6], max_new_tokens=2))  # sheds `old`
    assert old.outcome == "shed"
    assert mid.outcome is None and new.outcome is None
    assert sched.queue_depth == 2
    assert ("shed", old) in events
    assert sched.stats["shed"] == 1


def test_policy_priority_lanes():
    sched = _host_scheduler(policy="priority", max_queue=2)
    _fill(sched)
    low = sched.submit(Request([1, 2], max_new_tokens=2, priority=0))
    high = sched.submit(Request([3, 4], max_new_tokens=2, priority=5))
    # queue ordered by priority lane (high first) regardless of arrival
    assert sched.waiting[0] is high
    # a higher-priority newcomer sheds the lowest-priority waiter...
    vip = sched.submit(Request([5, 6], max_new_tokens=2, priority=9))
    assert low.outcome == "shed"
    assert sched.waiting[0] is vip
    # ...but an equal-or-lower one is rejected instead
    with pytest.raises(ServerOverloaded):
        sched.submit(Request([7, 8], max_new_tokens=2, priority=5))
    assert high.outcome is None


def test_expired_waiters_do_not_hold_queue_capacity():
    """A dead (already-expired) waiter must neither reject a live
    submit nor get mis-shed: submit sweeps expiries before the
    capacity check."""
    clock = ManualClock()
    sched = _host_scheduler(policy="reject-new", max_queue=2,
                            clock=clock)
    _fill(sched)
    dead = [sched.submit(Request([1, 2], max_new_tokens=2,
                                 deadline_s=0.5))
            for _ in range(2)]
    clock.advance(1.0)             # both waiters past their deadline
    live = sched.submit(Request([3, 4], max_new_tokens=2))
    assert all(st.outcome == "expired" for st in dead)   # not "shed"
    assert live.outcome is None and live in sched.waiting
    assert sched.stats["expired"] == 2
    assert sched.stats["shed"] == 0


def test_overload_detector_hysteresis():
    det = OverloadDetector(threshold_s=1.0, alpha=1.0, exit_frac=0.5)
    assert det.observe(0.2) is None and not det.overloaded
    assert det.observe(1.5) == "enter" and det.overloaded
    assert det.observe(1.2) is None          # still above exit band
    assert det.observe(0.7) is None          # inside the hysteresis band
    assert det.observe(0.3) == "exit" and not det.overloaded


def test_overload_shedding_state_on_engine(tiny_model):
    clock = ManualClock()
    eng = _engine(tiny_model, clock=clock, max_batch_slots=1,
                  overload_threshold_s=1.0, overload_alpha=1.0)
    rng = np.random.default_rng(4)
    with scoped_registry() as reg:
        eng.submit(Request(rng.integers(2, 250, (5,)), max_new_tokens=3))
        stuck = eng.submit(Request(rng.integers(2, 250, (5,)),
                                   max_new_tokens=3))
        eng.step()
        clock.advance(5.0)                  # head-of-queue delay blows up
        eng.step()
        assert eng._overload.overloaded
        assert reg.get("serve_overload").value() == 1.0
        with pytest.raises(ServerOverloaded) as ei:
            eng.submit(Request(rng.integers(2, 250, (4,)),
                               max_new_tokens=2))
        assert ei.value.reason == "overload"
        assert reg.get("serve_requests_total").value(
            event="rejected") == 1
        eng.run()                           # queue drains -> delay 0
        assert stuck.outcome == "completed"
        for _ in range(8):                  # EWMA decays below exit
            eng.step()
        assert not eng._overload.overloaded
        assert reg.get("serve_overload").value() == 0.0
        assert reg.get("serve_overload_transitions_total").value(
            state="enter") == 1
        assert reg.get("serve_overload_transitions_total").value(
            state="exit") == 1
    # recovered: admission works again
    eng.submit(Request(rng.integers(2, 250, (4,)), max_new_tokens=2))
    eng.run()


def test_overload_recovers_on_idle_engine(tiny_model):
    """A tripped detector must not latch forever once the engine goes
    idle: drivers only call step() while there is work, so submit()
    itself folds the empty-queue delay sample in while overloaded."""
    clock = ManualClock()
    eng = _engine(tiny_model, clock=clock, max_batch_slots=1,
                  overload_threshold_s=1.0, overload_alpha=0.3)
    rng = np.random.default_rng(6)
    eng.submit(Request(rng.integers(2, 250, (5,)), max_new_tokens=2))
    stuck = eng.submit(Request(rng.integers(2, 250, (5,)),
                               max_new_tokens=2))
    eng.step()
    clock.advance(5.0)
    eng.step()                              # head-of-queue delay trips
    assert eng._overload.overloaded
    eng.run()                               # drains; engine now idle
    assert stuck.outcome == "completed"
    assert not eng.scheduler.has_work
    # the EWMA is still above the exit band: the first idle submit is
    # refused, but each refusal decays the detector...
    with pytest.raises(ServerOverloaded):
        eng.submit(Request(rng.integers(2, 250, (4,)), max_new_tokens=2))
    st = None
    for _ in range(16):
        try:
            st = eng.submit(Request(rng.integers(2, 250, (4,)),
                                    max_new_tokens=2))
            break
        except ServerOverloaded:
            pass
    # ...so the idle engine recovers WITHOUT a single step() call
    assert st is not None and not eng._overload.overloaded
    eng.run()
    assert st.outcome == "completed"


def test_oldest_waiting_under_priority_lanes():
    """The overload detector samples the OLDEST waiter; under the
    priority policy that is not waiting[0] (the head of the highest
    lane), or starving low-priority requests could never trip it."""
    clock = ManualClock()
    sched = _host_scheduler(policy="priority", max_queue=4, clock=clock)
    _fill(sched)
    old_low = sched.submit(Request([1, 2], max_new_tokens=2, priority=0))
    clock.advance(3.0)
    fresh_high = sched.submit(Request([3, 4], max_new_tokens=2,
                                      priority=5))
    assert sched.waiting[0] is fresh_high   # lane order
    assert sched.oldest_waiting_t() == old_low.submitted_t


def test_run_open_loop_gives_up_on_persistent_watchdog_trips():
    """A backend that hangs on EVERY retry is down, not slow: the
    open-loop driver re-raises instead of looping forever (each retry
    would abandon another live dispatch thread)."""
    class _HungEngine:
        class scheduler:
            has_work = True

        def submit(self, request):
            return None

        def step(self):
            raise DecodeWatchdogError("decode", 0.1, 1, 1)

    spec = LoadSpec(num_requests=1, rate_rps=1e6, prompt_len_range=(4, 4),
                    max_new_range=(2, 2), vocab_size=64, seed=0)
    with pytest.raises(DecodeWatchdogError):
        run_open_loop(_HungEngine(), spec)


# ---------------------------------------------------------------------------
# fault isolation
# ---------------------------------------------------------------------------


def test_poisoned_request_fails_alone(tiny_model):
    rng = np.random.default_rng(5)
    prompts = _prompts(rng, 3, 5, 8)
    golden = [_golden(tiny_model, p, 4) for p in prompts]
    with flag_scope("flight_recorder", True), \
            chaos.chaos_scope("serve.request.poison@2"):
        eng = _engine(tiny_model)
        sts = [eng.submit(Request(p, max_new_tokens=4)) for p in prompts]
        eng.run()
        from paddle_tpu.monitor import flight_recorder as fr
        events = [e for e in fr.get_flight_recorder().events
                  if e.get("event") == "request_failed"]
    assert sts[1].poisoned and sts[1].outcome == "failed"
    assert "non-finite" in sts[1].failure
    assert len(events) == 1
    assert events[0]["request_id"] == sts[1].request.request_id
    # the rest of the batch streamed on, token-exact
    for i in (0, 2):
        assert sts[i].outcome == "completed"
        np.testing.assert_array_equal(
            np.concatenate([prompts[i], sts[i].generated]), golden[i])
    assert eng.cache.allocator.pages_in_use == 0


def test_detokenizer_exception_fails_only_its_request(tiny_model):
    rng = np.random.default_rng(6)
    prompts = _prompts(rng, 2, 5, 7)
    golden = [_golden(tiny_model, p, 4) for p in prompts]
    with chaos.chaos_scope("serve.detok.raise@2"):
        eng = _engine(tiny_model)
        sts = [eng.submit(Request(p, max_new_tokens=4,
                                  on_token=lambda r, t, txt: None))
               for p in prompts]
        eng.run()
    outcomes = sorted(st.outcome for st in sts)
    assert outcomes == ["completed", "failed"]
    survivor = next(i for i, st in enumerate(sts)
                    if st.outcome == "completed")
    np.testing.assert_array_equal(
        np.concatenate([prompts[survivor], sts[survivor].generated]),
        golden[survivor])
    assert eng.cache.allocator.pages_in_use == 0


def test_malformed_stop_condition_fails_request(tiny_model):
    rng = np.random.default_rng(7)
    eng = _engine(tiny_model)

    def bad_stop(generated):
        raise TypeError("malformed stop condition")

    st_bad = eng.submit(Request(rng.integers(2, 250, (5,)),
                                max_new_tokens=4, stop=bad_stop))
    st_ok = eng.submit(Request(rng.integers(2, 250, (5,)),
                               max_new_tokens=4,
                               stop=lambda g: len(g) >= 2))
    eng.run()
    assert st_bad.outcome == "failed"
    assert "TypeError" in st_bad.failure
    assert st_ok.outcome == "completed"
    assert len(st_ok.generated) == 2       # custom stop honoured
    assert eng.cache.allocator.pages_in_use == 0


def test_pages_exhaust_chaos_forces_exact_preemption(tiny_model):
    rng = np.random.default_rng(8)
    prompts = _prompts(rng, 2, 6, 8)
    golden = [_golden(tiny_model, p, 6) for p in prompts]
    with chaos.chaos_scope("serve.pages.exhaust@3"):
        eng = _engine(tiny_model)
        outs = eng.generate(prompts, max_new_tokens=6)
    assert eng.stats()["preemptions"] >= 1
    for out, g in zip(outs, golden):
        np.testing.assert_array_equal(out, g)
    assert eng.cache.allocator.pages_in_use == 0


def test_pages_exhaust_preempts_newest_not_slot0_occupant():
    """Slot 0 holding the NEWEST request (normal after slot turnover)
    must not shield it: the chaos dry-pool drill preempts the newest
    admitted — the same victim order as the real dry-pool path."""
    clock = ManualClock()
    sched = _host_scheduler(max_queue=4, clock=clock)
    a, b = _fill(sched)                  # a -> slot 0, b -> slot 1
    clock.advance(1.0)
    sched.finish(a)                      # slot 0 frees
    newer = sched.submit(Request([5, 6, 7], max_new_tokens=4))
    sched.plan_admissions()              # newer reuses slot 0
    assert newer.slot == 0 and b.slot == 1
    assert newer.admitted_t > b.admitted_t
    with chaos.chaos_scope("serve.pages.exhaust@1"):
        sched.ensure_decode_capacity()
    assert newer.outcome is None and newer.slot is None  # preempted
    assert sched.waiting[0] is newer     # requeued at the front
    assert b.slot == 1                   # the older request survives


def test_latched_cancel_survives_preemption_no_readmission():
    """A cancel latched on an in-flight request that is then preempted
    back to the queue must still cancel at admission time — never
    re-allocate pages and burn a prefill dispatch on a client that
    already disconnected."""
    clock = ManualClock()
    sched = _host_scheduler(max_queue=4, clock=clock)
    a = sched.submit(Request([1, 2, 3], max_new_tokens=4))
    sched.plan_admissions()
    clock.advance(0.5)
    b = sched.submit(Request([4, 5, 6], max_new_tokens=4))
    sched.plan_admissions()              # b strictly newest-admitted
    assert sched.cancel(b.request.request_id)   # latched, b in-flight
    with chaos.chaos_scope("serve.pages.exhaust@1"):
        sched.ensure_decode_capacity()   # preempts b, latch and all
    assert b.outcome is None and b in sched.waiting
    assert sched.plan_admissions() == []  # honoured, not re-admitted
    assert b.outcome == "cancelled" and b not in sched.waiting
    assert a.slot is not None and a.outcome is None


# ---------------------------------------------------------------------------
# decode watchdog
# ---------------------------------------------------------------------------


def test_watchdog_converts_hang_into_structured_error(tiny_model):
    rng = np.random.default_rng(9)
    with flag_scope("serve_watchdog_s", 0.4), \
            flag_scope("flight_recorder", True), \
            chaos.chaos_scope("serve.decode.hang@1"):
        eng = _engine(tiny_model)
        st = eng.submit(Request(rng.integers(2, 250, (5,)),
                                max_new_tokens=4))
        with scoped_registry() as reg:
            with pytest.raises(DecodeWatchdogError) as ei:
                eng.run()
            assert ei.value.kind == "decode"
            assert ei.value.timeout_s == pytest.approx(0.4)
            assert ei.value.active_slots == 1
            assert reg.get("serve_watchdog_trips_total").value(
                kind="decode") == 1
        from paddle_tpu.monitor import flight_recorder as fr
        names = [e.get("event")
                 for e in fr.get_flight_recorder().events]
        assert "decode_watchdog" in names
        assert "trip" in names             # dump recorded forensics
        # the hang was host-side (program never ran): retrying the step
        # continues the stream token-exactly
        eng.run()
    assert st.outcome == "completed"
    p = st.request.prompt
    np.testing.assert_array_equal(
        np.concatenate([p, st.generated]), _golden(tiny_model, p, 4))


def test_hang_without_watchdog_budget_is_loud(tiny_model):
    rng = np.random.default_rng(10)
    with chaos.chaos_scope("serve.decode.hang@1"):
        eng = _engine(tiny_model)
        eng.submit(Request(rng.integers(2, 250, (5,)), max_new_tokens=2))
        with pytest.raises(RuntimeError, match="serve_watchdog_s"):
            eng.run()


def test_watchdog_reuses_one_dispatcher_thread(tiny_model):
    """The armed watchdog must not put thread creation on the per-token
    hot path: every guarded dispatch of a healthy run rides ONE
    long-lived worker."""
    rng = np.random.default_rng(23)
    with flag_scope("serve_watchdog_s", 30.0):
        eng = _engine(tiny_model)
        st = eng.submit(Request(rng.integers(2, 250, (5,)),
                                max_new_tokens=4))
        eng.run()
    assert st.outcome == "completed"
    dispatches = (eng._stats["prefill_dispatches"]
                  + eng._stats["decode_dispatches"])
    assert dispatches >= 3
    assert len(eng._watchdog_threads) == 1
    assert eng._watchdog_worker is not None \
        and eng._watchdog_worker.usable
    eng.shutdown()
    assert eng._watchdog_worker is None


def test_prefill_trip_rolls_back_every_unprefilled_group(tiny_model):
    """A watchdog trip in the FIRST of several planned admission groups
    un-admits the later groups too: their slots were assigned but never
    prefilled, so a retried step() would otherwise decode slots with no
    token to feed."""
    rng = np.random.default_rng(24)
    with flag_scope("serve_watchdog_s", 0.4):
        eng = _engine(tiny_model)
        # different len buckets (8 vs 16) => two admission groups
        short = eng.submit(Request(rng.integers(2, 250, (5,)),
                                   max_new_tokens=3))
        long = eng.submit(Request(rng.integers(2, 250, (12,)),
                                  max_new_tokens=3))
        real_get, tripped = eng._get_prefill, []

        def slow_get(nb, sp):
            prog = real_get(nb, sp)

            def wrapper(*a):
                if not tripped:
                    tripped.append(sp)
                    time.sleep(1.5)        # blows the 0.4s budget
                return prog(*a)
            return wrapper

        eng._get_prefill = slow_get
        with pytest.raises(DecodeWatchdogError) as ei:
            eng.step()
        assert ei.value.kind == "prefill" and ei.value.retry_safe
        # BOTH groups rolled back: nothing holds a slot, nothing was
        # mis-counted as a page-pressure preemption
        assert short.slot is None and long.slot is None
        assert short.outcome is None and long.outcome is None
        assert len(eng.scheduler.waiting) == 2
        assert eng.scheduler.stats["preemptions"] == 0
        eng._get_prefill = real_get
        eng.run()                          # retried plan re-prefills
    assert short.outcome == long.outcome == "completed"
    p = short.request.prompt
    np.testing.assert_array_equal(
        np.concatenate([p, short.generated]), _golden(tiny_model, p, 3))


def test_reset_tears_down_abandoned_watchdog_thread(tiny_model):
    import paddle_tpu.serving as serving
    rng = np.random.default_rng(11)
    with flag_scope("serve_watchdog_s", 0.2):
        chaos.configure("serve.decode.hang@1")
        eng = _engine(tiny_model)
        eng.submit(Request(rng.integers(2, 250, (5,)), max_new_tokens=2))
        with pytest.raises(DecodeWatchdogError):
            eng.run()
        threads = list(eng._watchdog_threads)
        assert threads and threads[0].is_alive()   # abandoned in the hang
        serving.reset()                    # must not rely on chaos.reset
        threads[0].join(timeout=2.0)
        assert not threads[0].is_alive()
        assert eng._watchdog_threads == []


def test_reset_restores_drain_signal_handler(tiny_model, tmp_path):
    import paddle_tpu.serving as serving
    before = signal.getsignal(signal.SIGTERM)
    eng = _engine(tiny_model)
    eng.enable_drain(str(tmp_path / "drain"))
    assert signal.getsignal(signal.SIGTERM) is not before
    serving.reset()
    assert signal.getsignal(signal.SIGTERM) is before
    assert eng._drain_latch is None


# ---------------------------------------------------------------------------
# graceful drain (acceptance)
# ---------------------------------------------------------------------------


def test_sigterm_drain_zero_lost_and_backlog_rerun(tiny_model, tmp_path):
    root = str(tmp_path / "drain")
    rng = np.random.default_rng(12)
    prompts = _prompts(rng, 5, 5, 8)
    golden = [_golden(tiny_model, p, 6) for p in prompts]
    eng = _engine(tiny_model, max_batch_slots=2)
    eng.enable_drain(root, budget_s=0.0)   # snapshot in-flight too
    sts = [eng.submit(Request(p, max_new_tokens=6)) for p in prompts]
    eng.step()                             # 2 in flight, 3 queued
    os.kill(os.getpid(), signal.SIGTERM)   # cloud preemption
    with pytest.raises(EngineDrained) as ei:
        eng.run()
    report = ei.value.report
    # zero silently-lost requests: everything completed or snapshotted
    outcomes = [st.outcome for st in sts]
    assert all(o in ("completed", "drained") for o in outcomes)
    assert outcomes.count("drained") == report.snapshotted
    assert report.snapshotted >= 1 and report.path
    assert eng.cache.allocator.pages_in_use == 0
    with pytest.raises(ServerOverloaded):  # admission stays closed
        eng.submit(Request([1, 2], max_new_tokens=2))
    # a fresh engine re-runs the snapshotted backlog to completion —
    # greedy continuations are token-exact with the never-drained run
    path, specs = load_drain_snapshot(root)
    assert path == report.path and len(specs) == report.snapshotted
    eng2 = _engine(tiny_model, max_batch_slots=2)
    by_id = {st.request.request_id: i for i, st in enumerate(sts)}
    resub = requests_from_snapshot(specs)
    sts2 = [eng2.submit(r) for r in resub]
    eng2.run()
    for spec, st2 in zip(specs, sts2):
        assert st2.outcome == "completed"
        i = by_id[spec["request_id"]]
        full = np.concatenate([spec["prompt"], spec["generated"],
                               st2.generated]).astype(np.int32)
        np.testing.assert_array_equal(full, golden[i])


def test_drain_grace_budget_finishes_inflight(tiny_model, tmp_path):
    root = str(tmp_path / "drain")
    rng = np.random.default_rng(13)
    eng = _engine(tiny_model, max_batch_slots=2)
    sts = [eng.submit(Request(p, max_new_tokens=3))
           for p in _prompts(rng, 2, 5, 7)]
    eng.step()
    report = eng.drain(snapshot_dir=root, budget_s=60.0)
    # nothing was queued and the budget covered the tails: all finished
    assert report.completed == 2 and report.snapshotted == 0
    assert report.path is None
    assert all(st.outcome == "completed" for st in sts)


def test_drain_honours_latched_cancel_not_snapshotted(tiny_model,
                                                      tmp_path):
    """A request the client disconnected from ends 'cancelled' at drain
    time — never resurrected on the successor engine as drained work."""
    root = str(tmp_path / "drain")
    rng = np.random.default_rng(26)
    eng = _engine(tiny_model, max_batch_slots=2)
    keep = eng.submit(Request(rng.integers(2, 250, (5,)),
                              max_new_tokens=8))
    gone = eng.submit(Request(rng.integers(2, 250, (5,)),
                              max_new_tokens=8))
    eng.step()                               # both in-flight
    assert eng.cancel(gone.request.request_id)   # latched
    report = eng.drain(snapshot_dir=root, budget_s=0.0)
    assert gone.outcome == "cancelled"
    assert keep.outcome == "drained"
    assert report.snapshotted == 1           # only the live request


def test_drain_refuses_to_discard_without_snapshot_dir(tiny_model):
    rng = np.random.default_rng(14)
    eng = _engine(tiny_model)
    eng.submit(Request(rng.integers(2, 250, (5,)), max_new_tokens=4))
    with pytest.raises(ValueError, match="snapshot_dir"):
        eng.drain(budget_s=0.0)


def test_drain_snapshot_commit_is_atomic_under_torn_write(
        tiny_model, tmp_path):
    root = str(tmp_path / "drain")
    rng = np.random.default_rng(15)
    # first drain commits a valid snapshot
    eng1 = _engine(tiny_model)
    eng1.submit(Request(rng.integers(2, 250, (5,)), max_new_tokens=4))
    r1 = eng1.drain(snapshot_dir=root, budget_s=0.0)
    assert r1.snapshotted == 1
    # second drain's commit is torn mid-write (chaos) — the torn dir
    # must never read as a snapshot; the previous one still loads
    eng2 = _engine(tiny_model)
    eng2.submit(Request(rng.integers(2, 250, (6,)), max_new_tokens=4))
    with chaos.chaos_scope("ckpt.write.torn@1"):
        r2 = eng2.drain(snapshot_dir=root, budget_s=0.0)
    assert r2.path.endswith("drain_2")
    path, specs = load_drain_snapshot(root)
    assert path == r1.path                  # fallback to the valid commit
    assert len(specs) == 1


# ---------------------------------------------------------------------------
# chaos SLO (acceptance)
# ---------------------------------------------------------------------------


def test_chaos_slo_availability_and_token_exactness(tiny_model):
    rng = np.random.default_rng(16)
    prompts = _prompts(rng, 10, 4, 9)
    max_new = 5
    # golden = the UNINJECTED run (batching invariance is pinned by the
    # PR 6 parity suite)
    golden = _engine(tiny_model).generate(prompts, max_new_tokens=max_new)
    spec = ("serve.request.poison:0.1,serve.decode.hang@4,"
            "serve.pages.exhaust:0.2")
    with flag_scope("serve_watchdog_s", 1.0), scoped_registry() as reg, \
            chaos.chaos_scope(spec, seed=3):
        eng = _engine(tiny_model, max_batch_slots=2)
        sts = [eng.submit(Request(p, max_new_tokens=max_new))
               for p in prompts]
        guard = 0
        while eng.scheduler.has_work:
            try:
                eng.step()
            except DecodeWatchdogError:
                pass                       # structured, survivable
            guard += 1
            assert guard < 500, "chaos run failed to converge"
        assert chaos.fired(), "chaos plan never fired"
        # no request ends without a terminal outcome event
        assert all(st.outcome in TERMINAL_OUTCOMES for st in sts)
        ctr = reg.get("serve_requests_total")
        terminal = sum(ctr.value(event=e) for e in TERMINAL_OUTCOMES)
        assert terminal == ctr.value(event="submitted") == len(sts)
    poisoned = [st for st in sts if st.poisoned]
    clean = [(i, st) for i, st in enumerate(sts) if not st.poisoned]
    for st in poisoned:
        assert st.outcome == "failed"
    # SLO: >= 95% of non-poisoned requests complete token-exactly
    exact = 0
    for i, st in clean:
        if st.outcome == "completed" and np.array_equal(
                np.concatenate([prompts[i], st.generated]), golden[i]):
            exact += 1
    assert exact / max(len(clean), 1) >= 0.95
    assert eng.cache.allocator.pages_in_use == 0


# ---------------------------------------------------------------------------
# zero-overhead pin
# ---------------------------------------------------------------------------


def test_resilience_off_adds_no_registry_series_or_dispatches(tiny_model):
    """With deadlines/watchdog/chaos off, the hot path writes no new
    registry series and the dispatch counts match the PR 6 schedule
    (repeat traffic: one bucketed prefill + max_new-1 decode steps)."""
    rng = np.random.default_rng(17)
    prompts = [rng.integers(2, 250, (6,)).astype(np.int32)
               for _ in range(2)]
    with scoped_registry() as reg:
        eng = _engine(tiny_model)
        eng.generate(prompts, max_new_tokens=4)
        names = set(reg.names())
    banned = ("serve_overload", "serve_deadline_slack_seconds",
              "serve_watchdog_trips_total",
              "serve_overload_transitions_total")
    assert not any(n.startswith(b) for n in names for b in banned)
    events = {d["event"] for d
              in reg.get("serve_requests_total").labels_seen()}
    assert events == {"submitted", "completed"}
    s = eng.stats()
    assert s["prefill_dispatches"] == 1    # both rode one bucket
    assert s["decode_dispatches"] == 3     # tokens 2..4
    assert chaos.occurrences("serve.pages.exhaust") == 0  # probes inert


# ---------------------------------------------------------------------------
# scheduler fuzz (satellite): invariants under random interleavings
# ---------------------------------------------------------------------------


def test_scheduler_fuzz_invariants():
    clock = ManualClock()
    events = []
    sched = _host_scheduler(policy="reject-new", max_queue=32,
                            max_slots=3, num_pages=12,
                            on_event=lambda ev, st: events.append((ev, st)),
                            clock=clock)
    cache = sched.cache
    rng = np.random.default_rng(1234)
    submitted = []

    def check_invariants():
        # no slot double-assignment; slot back-pointers consistent
        active = [(i, st) for i, st in enumerate(sched.slots)
                  if st is not None]
        assert len({id(st) for _, st in active}) == len(active)
        for i, st in active:
            assert st.slot == i and st.outcome is None
        # every allocated page accounted exactly once (disjoint slots,
        # no duplicate in the free list => no double-free, no leak)
        alloc = cache.allocator
        free = list(alloc._free)
        assert len(free) == len(set(free))
        pages = [p for lst in cache._slot_pages for p in lst]
        assert len(pages) == len(set(pages))
        assert not set(pages) & set(free)
        assert alloc.pages_in_use == len(pages)
        # terminal exclusivity: exactly one outcome, finished <=>
        # completed, terminal requests hold nothing
        for st in submitted:
            if st.outcome is not None:
                assert st.outcome in TERMINAL_OUTCOMES
                assert st.finished == (st.outcome == "completed")
                assert st.slot is None and st not in sched.waiting
            else:
                assert (st in sched.waiting) ^ (st.slot is not None)

    for it in range(260):
        op = rng.integers(0, 7)
        clock.advance(float(rng.random()) * 0.2)
        if op == 0:                                   # submit
            plen = int(rng.integers(1, 9))
            deadline = (float(rng.uniform(0.1, 3.0))
                        if rng.random() < 0.3 else None)
            try:
                st = sched.submit(Request(
                    rng.integers(1, 99, (plen,)),
                    max_new_tokens=int(rng.integers(1, 9)),
                    deadline_s=deadline))
                submitted.append(st)
            except ServerOverloaded:
                pass
        elif op == 1:
            sched.plan_admissions()
        elif op == 2:                                 # decode-ish step
            sched.ensure_decode_capacity()
            for _, st in list(sched.active()):
                st.generated.append(int(rng.integers(1, 99)))
                if st.is_done():
                    sched.finish(st)
        elif op == 3 and submitted:                   # cancel random
            st = submitted[int(rng.integers(0, len(submitted)))]
            sched.cancel(st.request.request_id)
        elif op == 4:                                 # expiry sweeps
            sched.expire_queued()
            sched.sweep_active()
        elif op == 5:                                 # fault isolation
            act = sched.active()
            if act:
                _, st = act[int(rng.integers(0, len(act)))]
                sched.fail(st, "fuzz")
        elif op == 6:                                 # drain release
            pool = sched.waiting + [st for _, st in sched.active()]
            if pool and rng.random() < 0.2:
                sched.drain_release(
                    pool[int(rng.integers(0, len(pool)))])
        check_invariants()

    # converge: everything reaches a terminal outcome, pool fully free
    guard = 0
    while sched.has_work:
        sched.plan_admissions()
        sched.ensure_decode_capacity()
        for _, st in list(sched.active()):
            st.generated.append(1)
            if st.is_done():
                sched.finish(st)
        sched.expire_queued()
        sched.sweep_active()
        check_invariants()
        guard += 1
        assert guard < 2000
    assert all(st.outcome is not None for st in submitted)
    assert cache.allocator.pages_in_use == 0
    # the event hook saw exactly the terminal transitions
    assert len(events) == len(submitted)
    st_counts = {e: 0 for e in TERMINAL_OUTCOMES}
    for ev, _ in events:
        st_counts[ev] += 1
    assert st_counts == {e: sched.stats[e] for e in TERMINAL_OUTCOMES}


# ---------------------------------------------------------------------------
# loadgen: bursty arrivals, deadline sampling, token bucket
# ---------------------------------------------------------------------------


def test_loadgen_bursty_modes_deterministic_and_mean_preserving():
    base = dict(num_requests=1500, rate_rps=50.0,
                prompt_len_range=(4, 8), max_new_range=(2, 4),
                vocab_size=256, seed=9)
    pois = build_requests(LoadSpec(**base))
    for mode, kw in (("gamma", dict(burstiness=4.0)),
                     ("mmpp", dict(burstiness=3.0, mmpp_switch=0.2))):
        spec = LoadSpec(arrival=mode, **kw, **base)
        a = build_requests(spec)
        b = build_requests(spec)
        assert [t for t, _ in a] == [t for t, _ in b]   # seeded replay
        for (_, ra), (_, rb) in zip(a, b):
            np.testing.assert_array_equal(ra.prompt, rb.prompt)
        gaps = np.diff([t for t, _ in a])
        assert (gaps >= 0).all()
        # same mean rate as the poisson schedule, within sampling noise
        # (mmpp gaps are serially correlated, so the band is generous —
        # but it would still catch a broken mean-rate rescale)
        mean = float(np.mean(gaps))
        assert 0.75 / 50.0 < mean < 1.35 / 50.0
        assert [t for t, _ in a] != [t for t, _ in pois]
    # burstier gaps have a heavier tail than poisson at the same rate
    g = np.diff([t for t, _ in build_requests(
        LoadSpec(arrival="gamma", burstiness=8.0, **base))])
    p = np.diff([t for t, _ in pois])
    assert np.std(g) > 1.5 * np.std(p)


def test_loadgen_deadline_and_priority_sampling():
    spec = LoadSpec(num_requests=40, rate_rps=100.0,
                    prompt_len_range=(4, 8), max_new_range=(2, 4),
                    vocab_size=256, seed=11,
                    deadline_range=(0.5, 2.0),
                    priority_choices=(0, 5))
    reqs = [r for _, r in build_requests(spec)]
    assert all(0.5 <= r.deadline_s <= 2.0 for r in reqs)
    assert {r.priority for r in reqs} == {0, 5}
    # unchanged default: no deadline draws -> None
    plain = [r for _, r in build_requests(LoadSpec(
        num_requests=4, vocab_size=256, seed=11))]
    assert all(r.deadline_s is None and r.priority == 0 for r in plain)


def test_token_bucket():
    tb = TokenBucket(rate=1.0, burst=2)
    assert tb.admit(0.0) and tb.admit(0.0)
    assert not tb.admit(0.0)               # burst spent
    assert tb.admit(1.05)                  # refilled one token
    assert not tb.admit(1.06)
    with pytest.raises(ValueError):
        TokenBucket(rate=0.0, burst=2)


def test_run_open_loop_counts_rejections_and_throttle(tiny_model):
    eng = _engine(tiny_model, max_batch_slots=1, max_queue=1)
    spec = LoadSpec(num_requests=5, rate_rps=1e5,
                    prompt_len_range=(4, 8), max_new_range=(2, 3),
                    vocab_size=256, seed=12)
    summary = run_open_loop(eng, spec)
    # nothing is silently lost: every offered request either completed
    # or was counted as a client-visible refusal
    s = eng.scheduler.stats
    accounted = (summary["requests_completed"]
                 + summary["requests_rejected"] + s["shed"]
                 + s["expired"] + s["failed"])
    assert accounted == 5
    assert summary["requests_rejected"] >= 1       # queue of 1 overflowed
    assert summary["watchdog_trips"] == 0
    # client-side token bucket throttles instead of submitting
    eng2 = _engine(tiny_model, max_batch_slots=1)
    summary2 = run_open_loop(eng2, spec,
                             token_bucket=TokenBucket(rate=1.0, burst=2))
    assert summary2["requests_throttled"] >= 1
    assert (summary2["requests_completed"]
            + summary2["requests_throttled"]
            + summary2["requests_rejected"]) == 5


# ---------------------------------------------------------------------------
# tooling: monitor_report --serve, bench resilience metrics
# ---------------------------------------------------------------------------


def _load_tool(name):
    import importlib.util
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(tools, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_monitor_report_outcomes_and_overload_timeline(
        tiny_model, tmp_path):
    clock = ManualClock()
    path = str(tmp_path / "serve.jsonl")
    with scoped_registry() as reg:
        eng = _engine(tiny_model, clock=clock, max_batch_slots=1,
                      overload_threshold_s=1.0, overload_alpha=1.0)
        rng = np.random.default_rng(18)
        eng.submit(Request(rng.integers(2, 250, (5,)), max_new_tokens=2))
        doomed = eng.submit(Request(rng.integers(2, 250, (5,)),
                                    max_new_tokens=2, deadline_s=0.1))
        # deadline-free straggler keeps the queue non-empty so the
        # overload detector sees the stuck head-of-queue delay
        eng.submit(Request(rng.integers(2, 250, (5,)), max_new_tokens=2))
        eng.step()
        clock.advance(5.0)
        eng.step()                          # expiry + overload enter
        assert doomed.outcome == "expired"
        reg.dump_jsonl(path)
        eng.run()
        for _ in range(8):
            eng.step()                      # overload exit
        reg.dump_jsonl(path)
    mod = _load_tool("monitor_report")
    from paddle_tpu.monitor import load_jsonl
    out = mod.render(load_jsonl(path), serve=True)
    assert "Request outcomes" in out
    assert "expired" in out and "completed" in out
    assert "Overload state timeline" in out
    assert "OVERLOADED (shedding)" in out and "normal" in out


def test_bench_serve_resilience_metric_lines():
    import importlib.util
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(here, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    avail, shed = bench.serve_resilience_metrics({
        "num_requests": 20, "requests_completed": 16,
        "requests_rejected": 2, "requests_shed": 0,
        # 2 expiries total, only 1 of them queued: the in-flight one
        # was admitted, so it hits availability but is NOT shed
        "requests_expired": 2, "requests_expired_queued": 1})
    assert avail == pytest.approx(80.0)
    assert shed == pytest.approx(15.0)
    # the gate treats a growing shed rate as the regression
    cb = _load_tool("check_bench")
    assert "shed%" in cb._ABS_POINT_UNITS
    assert not cb.lower_is_better("%")
    old = [{"metric": "serve_shed_rate", "value": 1.0, "unit": "shed%",
            "vs_baseline": 1.0},
           {"metric": "serve_availability_pct", "value": 99.0,
            "unit": "%", "vs_baseline": 1.0}]
    bad = [{"metric": "serve_shed_rate", "value": 30.0, "unit": "shed%",
            "vs_baseline": 1.0},
           {"metric": "serve_availability_pct", "value": 60.0,
            "unit": "%", "vs_baseline": 1.0}]
    assert len(cb.compare(old, bad)) == 2
    assert cb.compare(old, old) == []
    # shed% gates on ABSOLUTE points, so the healthy all-zero baseline
    # still catches a regression (relative ratio is undefined at 0)
    zero = [{"metric": "serve_shed_rate", "value": 0.0, "unit": "shed%",
             "vs_baseline": 1.0}]
    regressed = [{"metric": "serve_shed_rate", "value": 40.0,
                  "unit": "shed%", "vs_baseline": 1.0}]
    wiggle = [{"metric": "serve_shed_rate", "value": 5.0, "unit": "shed%",
               "vs_baseline": 1.0}]
    assert len(cb.compare(zero, regressed)) == 1
    assert cb.compare(zero, wiggle) == []
