"""Wordpiece tokenizer parity tests.

Golden reference: the HuggingFace transformers BertTokenizer (the same
algorithm the reference's faster_tokenizer_op implements in C++,
faster_tokenizer_op.h:46-121) over a controlled vocab — token-for-token
and id-for-id agreement, plus the fixed-shape batch contract and a BERT
end-to-end forward from raw strings.
"""

import numpy as np
import pytest

from paddle_tpu.text import FasterTokenizer

VOCAB_TOKENS = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
    "the", "quick", "brown", "fox", "jump", "##s", "##ed", "over",
    "lazy", "dog", "un", "##want", "##able", "##ed", "runn", "##ing",
    "!", ",", ".", "?", "hello", "world", "tpu", "##v", "##5",
    "中", "国",
]
VOCAB = {}
for t in VOCAB_TOKENS:
    VOCAB.setdefault(t, len(VOCAB))


@pytest.fixture(scope="module")
def hf_tokenizer(tmp_path_factory):
    transformers = pytest.importorskip("transformers")
    p = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    inv = {v: k for k, v in VOCAB.items()}
    p.write_text("\n".join(inv[i] for i in range(len(inv))) + "\n",
                 encoding="utf-8")
    return transformers.BertTokenizer(str(p), do_lower_case=True)


GOLDEN_TEXTS = [
    "The quick brown fox jumps over the lazy dog!",
    "unwanted running",
    "Hello, WORLD?",
    "tpuv5 is fast",            # unknown word -> [UNK]
    "中国 hello",               # CJK chars split per-character
    "naïve café",               # accents stripped by lowercasing
    "",
]


def test_tokenize_matches_transformers(hf_tokenizer):
    tok = FasterTokenizer(VOCAB, do_lower_case=True)
    for text in GOLDEN_TEXTS:
        assert tok.tokenize(text) == hf_tokenizer.tokenize(text), text


def test_encode_ids_match_transformers(hf_tokenizer):
    tok = FasterTokenizer(VOCAB, do_lower_case=True)
    for text in GOLDEN_TEXTS:
        ours = tok(text, max_seq_len=16, pad_to_max_seq_len=True)
        ref = hf_tokenizer(text, max_length=16, padding="max_length",
                           truncation=True)
        np.testing.assert_array_equal(ours["input_ids"][0],
                                      np.asarray(ref["input_ids"]), text)
        np.testing.assert_array_equal(ours["token_type_ids"][0],
                                      np.asarray(ref["token_type_ids"]))


def test_text_pair_matches_transformers(hf_tokenizer):
    tok = FasterTokenizer(VOCAB, do_lower_case=True)
    a, b = "the quick fox", "jumps over the lazy dog"
    ours = tok(a, text_pair=b, max_seq_len=12, pad_to_max_seq_len=True)
    ref = hf_tokenizer(a, b, max_length=12, padding="max_length",
                       truncation="longest_first")
    np.testing.assert_array_equal(ours["input_ids"][0],
                                  np.asarray(ref["input_ids"]))
    np.testing.assert_array_equal(ours["token_type_ids"][0],
                                  np.asarray(ref["token_type_ids"]))


def test_fixed_shape_batches():
    tok = FasterTokenizer(VOCAB)
    out = tok(["hello world", "the dog", "!"], max_seq_len=10)
    assert out["input_ids"].shape == (3, 10)
    assert out["input_ids"].dtype == np.int32
    assert out["attention_mask"].shape == (3, 10)
    # second call with different lengths: SAME shape (jit cache friendly)
    out2 = tok(["the quick brown fox"], max_seq_len=10)
    assert out2["input_ids"].shape == (1, 10)


def test_bert_end_to_end_from_strings():
    """Raw strings -> FasterTokenizer -> BERT forward, one jit signature."""
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.bert import BertConfig, BertModel

    paddle.seed(0)
    cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                     num_heads=2, intermediate_size=64,
                     max_position_embeddings=32, type_vocab_size=2)
    model = BertModel(cfg)
    model.eval()
    tok = FasterTokenizer(VOCAB)
    batch = tok(["the quick brown fox", "hello world !"], max_seq_len=16)
    seq_out, pooled = model(Tensor(jnp.asarray(batch["input_ids"])),
                            Tensor(jnp.asarray(batch["token_type_ids"])))
    assert tuple(seq_out.shape) == (2, 16, 32)
    assert np.isfinite(np.asarray(seq_out._data)).all()
