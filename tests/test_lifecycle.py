"""Zero-downtime model lifecycle (ISSUE 20): live weight hot-swap with
per-slot weight epochs, shadow/A-B traffic splitting, and the
SLO-guarded promote-or-rollback controller — plus the flags-off
byte-identity pins, the chaos drills for torn/corrupt/dying pushes, and
the tooling surfaces (check_bench swap% unit, monitor_report
--lifecycle)."""

import gc
import json
import os
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.monitor import scoped_registry
from paddle_tpu.serving import (FleetRouter, LifecycleConfig,
                                LifecycleController, LoadSpec, Request,
                                RouterConfig, SamplingParams,
                                ServingConfig, ServingEngine,
                                TrafficSplit, WeightSwapError,
                                assign_arm, build_requests,
                                should_shadow)
from paddle_tpu.testing import chaos

pytestmark = pytest.mark.serve

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


def _engine(model, **kw):
    cfg = dict(max_batch_slots=3, block_size=4, max_context_len=64,
               prefill_buckets=(8, 16), batch_buckets=(1, 2))
    cfg.update(kw)
    return ServingEngine(model, ServingConfig(**cfg))


def _fleet(model, n=2, router_kw=None, flags=(), **kw):
    import contextlib
    with contextlib.ExitStack() as stack:
        for name, val in flags:
            stack.enter_context(flag_scope(name, val))
        reps = {f"r{i}": _engine(model, **kw) for i in range(n)}
        return FleetRouter(reps, RouterConfig(**(router_kw or {})))


def _save_manifest(engine, path, perturb=0.0):
    """The engine's live tree (optionally perturbed) as a committed
    manifest checkpoint — the shape every push must arrive in."""
    import jax.numpy as jnp
    state = {}
    for name, arr in engine.params.items():
        a = jnp.asarray(arr)
        if perturb and jnp.issubdtype(a.dtype, jnp.inexact):
            a = a + jnp.asarray(perturb, a.dtype)
        state[name] = a
    dckpt.save(state, str(path), asynchronous=False)
    return str(path)


PROMPTS = [[3, 4, 5, 3, 4, 5, 3, 4], [7, 8, 9, 7, 8, 9, 7, 8],
           [1, 2, 1, 2, 1, 2]]


# ---------------------------------------------------------------------------
# swap_weights: flag gate, refusal paths, identity cutover
# ---------------------------------------------------------------------------


def test_swap_flag_off_raises(tiny_model, tmp_path):
    eng = _engine(tiny_model)
    with pytest.raises(RuntimeError, match="serve_hot_swap"):
        eng.swap_weights(str(tmp_path))
    with pytest.raises(RuntimeError, match="serve_hot_swap"):
        eng.rollback_weights()
    assert "weights" not in eng._admin_status()
    eng.shutdown()


def test_identity_swap_token_exact_and_rollback_chain(tiny_model,
                                                      tmp_path):
    """An identity push (the live tree re-saved) must be a perfect
    no-op for greedy output; rollback re-stages the retained tree and
    commit drops the anchor for good."""
    with flag_scope("serve_hot_swap", True):
        eng = _engine(tiny_model)
    want = [o.tolist() for o in eng.generate(PROMPTS, max_new_tokens=6)]
    push = _save_manifest(eng, tmp_path / "push")
    info = eng.swap_weights(push)
    # idle engine: between steps IS an iteration boundary — immediate
    assert info["mode"] == "staged" and not info["pending"]
    assert eng.metrics_summary()["weights_epoch"] == 1
    got = [o.tolist() for o in eng.generate(PROMPTS, max_new_tokens=6)]
    assert got == want
    # rollback is a cutover back to the retained tree (epoch 2), and
    # commit afterwards drops the anchor: a second rollback refuses
    eng.rollback_weights()
    assert eng.metrics_summary()["weights_epoch"] == 2
    got = [o.tolist() for o in eng.generate(PROMPTS, max_new_tokens=6)]
    assert got == want
    eng.commit_swap()
    with pytest.raises(WeightSwapError, match="no previous"):
        eng.rollback_weights()
    w = eng._admin_status()["weights"]
    assert w["epoch"] == 2 and w["live_manifest"] is None
    assert w["swaps"]["cutover"] == 2 and w["swaps"]["rolled_back"] == 1
    eng.shutdown()


def test_swap_refuses_torn_manifest_chaos(tiny_model, tmp_path):
    """Chaos site serve.swap.torn_manifest: the push reads as torn and
    MUST refuse with zero side effects — old weights keep serving."""
    with flag_scope("serve_hot_swap", True):
        eng = _engine(tiny_model)
    want = [o.tolist() for o in eng.generate(PROMPTS, max_new_tokens=4)]
    push = _save_manifest(eng, tmp_path / "push")
    with chaos.chaos_scope("serve.swap.torn_manifest@1"):
        with pytest.raises(WeightSwapError, match="torn"):
            eng.swap_weights(push)
        assert chaos.fired()
    assert eng.metrics_summary()["weights_epoch"] == 0
    assert eng.metrics_summary()["weight_swaps_refused"] == 1
    got = [o.tolist() for o in eng.generate(PROMPTS, max_new_tokens=4)]
    assert got == want
    eng.shutdown()


def test_swap_refuses_missing_and_mismatched(tiny_model, tmp_path):
    """Real refusals, no chaos: a manifest that does not exist, and a
    committed one whose tree does not match the live params."""
    with flag_scope("serve_hot_swap", True):
        eng = _engine(tiny_model)
    with pytest.raises(WeightSwapError):
        eng.swap_weights(str(tmp_path / "nope"))
    # right key set, wrong shape on one leaf
    import jax.numpy as jnp
    state = {k: jnp.asarray(v) for k, v in eng.params.items()}
    first = next(iter(state))
    state[first] = jnp.zeros((3, 3), state[first].dtype)
    dckpt.save(state, str(tmp_path / "badshape"), asynchronous=False)
    with pytest.raises(WeightSwapError, match="shape"):
        eng.swap_weights(str(tmp_path / "badshape"))
    # missing + extra keys
    state = {k: jnp.asarray(v) for k, v in eng.params.items()}
    state.pop(first)
    state["not_a_param"] = jnp.zeros((2,), "float32")
    dckpt.save(state, str(tmp_path / "badkeys"), asynchronous=False)
    with pytest.raises(WeightSwapError, match="missing"):
        eng.swap_weights(str(tmp_path / "badkeys"))
    assert eng.metrics_summary()["weight_swaps_refused"] == 3
    assert eng.metrics_summary()["weights_epoch"] == 0
    eng.shutdown()


def test_flags_off_and_armed_unused_byte_identical(tiny_model):
    """The tentpole's no-op contract: a hot-swap-armed engine that
    never swaps runs the SAME dispatches and tokens as a flags-off
    engine, and a flags-off run emits none of the lifecycle series."""
    with scoped_registry() as reg:
        base = _engine(tiny_model)
        want = [o.tolist() for o in base.generate(PROMPTS,
                                                  max_new_tokens=6)]
        base_sum = base.metrics_summary()
        base.shutdown()
        assert "serve_swaps_total" not in reg.snapshot()
        assert "serve_weights_epoch" not in reg.snapshot()
    with flag_scope("serve_hot_swap", True):
        eng = _engine(tiny_model)
    got = [o.tolist() for o in eng.generate(PROMPTS, max_new_tokens=6)]
    armed_sum = eng.metrics_summary()
    eng.shutdown()
    assert got == want
    assert armed_sum["decode_dispatches"] == \
        base_sum["decode_dispatches"]
    assert armed_sum["prefill_chunks"] == base_sum["prefill_chunks"]


# ---------------------------------------------------------------------------
# cross-epoch invariants: the 200-request mid-swap drill
# ---------------------------------------------------------------------------


def test_mid_swap_cross_epoch_drill_200_requests(tiny_model, tmp_path):
    """200 open-loop requests with a REAL weight change pushed mid-run:
    every request in flight (or already done) at the cutover is greedy
    token-identical to a no-swap oracle — slots finish decoding on the
    weights that wrote their KV — and the terminal accounting identity
    closes exactly (submitted == completed + expired + shed +
    cancelled + failed + drained). The retired tree is released once
    its last slot terminates."""
    spec = LoadSpec(num_requests=200, rate_rps=600.0,
                    prompt_len_range=(4, 10), max_new_range=(3, 6),
                    vocab_size=tiny_model.cfg.vocab_size, seed=5,
                    sampling=SamplingParams())

    def drive(engine, swap_at=None, push=None):
        schedule = build_requests(spec)
        tokens = {}
        for idx, (_, req) in enumerate(schedule):
            def cb(r, tok, text, idx=idx):
                tokens.setdefault(idx, []).append(int(tok))
            req.on_token = cb
        done_by_swap = None
        t0 = time.perf_counter()
        i = 0
        states = []
        while i < len(schedule) or engine.scheduler.has_work:
            now = time.perf_counter() - t0
            while i < len(schedule) and schedule[i][0] <= now:
                states.append((i, engine.submit(schedule[i][1])))
                i += 1
            if swap_at is not None and i >= swap_at:
                # pre-swap cohort: everything terminal or resident NOW
                # (the cutover stamps every resident slot, stamped or
                # not, with the old epoch)
                done_by_swap = (
                    {idx for idx, st in states if st.outcome is not None}
                    | {idx for idx, st in states
                       for _, a in engine.scheduler.active()
                       if a is st})
                engine.swap_weights(push)
                swap_at = None
            if engine.scheduler.has_work:
                engine.step()
        return tokens, done_by_swap, engine.metrics_summary()

    oracle = _engine(tiny_model, max_batch_slots=4,
                     batch_buckets=(1, 2, 4))
    want, _, _ = drive(oracle)
    oracle.shutdown()

    with flag_scope("serve_hot_swap", True):
        eng = _engine(tiny_model, max_batch_slots=4,
                      batch_buckets=(1, 2, 4))
    push = _save_manifest(eng, tmp_path / "push", perturb=0.05)
    got, preswap, summary = drive(eng, swap_at=100, push=push)
    assert preswap, "drill never caught requests in flight at cutover"
    for idx in sorted(preswap):
        assert got[idx] == want[idx], \
            f"pre-swap request {idx} diverged from the no-swap oracle"
    # terminal accounting identity — nothing lost, nothing double
    assert summary["requests_submitted"] == 200
    assert summary["requests_submitted"] == (
        summary["requests_completed"] + summary["requests_expired"]
        + summary["requests_shed"] + summary["requests_cancelled"]
        + summary["requests_failed"] + summary["requests_drained"])
    assert summary["weights_epoch"] == 1
    # the old tree was retired and then released with its last slot,
    # and prefix-cache donation (detached through the transition) is
    # live again once the last old-epoch slot leaves
    assert eng._retired == {}
    if eng.prefix_cache is not None:
        assert eng.cache.prefix_cache is not None
    eng.shutdown()


def test_three_live_swaps_under_mmpp_fleet_load(tiny_model, tmp_path):
    """The acceptance drill: 3 consecutive identity swaps across a
    2-replica fleet under bursty mmpp arrivals — availability >= 99.9%
    with zero lost and zero duplicated requests, and every replica
    lands on epoch 3."""
    from paddle_tpu.serving.resilience import ServerOverloaded
    spec = LoadSpec(num_requests=36, rate_rps=300.0,
                    prompt_len_range=(4, 10), max_new_range=(3, 6),
                    vocab_size=tiny_model.cfg.vocab_size, seed=9,
                    sampling=SamplingParams(), arrival="mmpp",
                    burstiness=3.0, mmpp_switch=0.2)
    router = _fleet(tiny_model, n=2,
                    router_kw={"saturation_queue_depth": 12},
                    flags=(("serve_hot_swap", True),))
    push = _save_manifest(router.replicas["r0"].engine,
                          tmp_path / "push")
    schedule = build_requests(spec)
    quarters = [len(schedule) // 4, len(schedule) // 2,
                3 * len(schedule) // 4]
    swaps = 0
    t0 = time.perf_counter()
    i = 0
    while i < len(schedule) or any(
            r.alive and r.engine.scheduler.has_work
            for r in router.replicas.values()):
        now = time.perf_counter() - t0
        while i < len(schedule) and schedule[i][0] <= now:
            try:
                router.submit(schedule[i][1])
            except ServerOverloaded:
                pass
            i += 1
        if swaps < len(quarters) and i >= quarters[swaps]:
            for rep in router.replicas.values():
                info = rep.engine.swap_weights(push)
                if not info.get("pending"):
                    rep.engine.commit_swap()
            swaps += 1
        router.step_all()
    summary = router.summary()
    epochs = {n: r.engine.metrics_summary()["weights_epoch"]
              for n, r in router.replicas.items()}
    router.shutdown()
    assert swaps == 3 and epochs == {"r0": 3, "r1": 3}
    assert summary["availability_pct"] >= 99.9
    assert summary["duplicate_request_ids"] == 0
    assert summary["requests_in_flight"] == 0
    lost = (summary["requests_offered"] - summary["requests_completed"]
            - summary["requests_failed"] - summary["requests_rejected"])
    assert lost == 0


def test_drain_fallback_swap_resubmits_continuations(tiny_model,
                                                     tmp_path):
    """mode="drain": in-flight slots snapshot, release, cut over, and
    resubmit on the new weights — streamed tokens stand, callbacks
    survive the hop, and the drained/resubmitted accounting closes."""
    with flag_scope("serve_hot_swap", True):
        eng = _engine(tiny_model)
    push = _save_manifest(eng, tmp_path / "push")
    # the continuation is a NEW request carrying the ORIGINAL callback
    # object — a per-client closure sees the stream stay contiguous
    # across the hop even though the request id changes
    streams = []

    def _client():
        lst = []
        streams.append(lst)
        return lambda req, tok, text: lst.append(int(tok))

    sts = [eng.submit(Request(p, max_new_tokens=8,
                              on_token=_client()))
           for p in PROMPTS[:2]]
    eng.step()                               # prefill: slots resident
    pre_lens = [len(s) for s in streams]
    info = eng.swap_weights(push, mode="drain")
    assert info["mode"] == "drain"
    assert info["resubmitted"] == 2
    assert eng.metrics_summary()["weights_epoch"] == 1
    eng.run()
    stats = eng.scheduler.stats
    assert stats["drained"] == 2
    # 2 originals + 2 continuations, all accounted
    assert stats["submitted"] == 4
    assert stats["submitted"] == (
        stats["completed"] + stats["expired"] + stats["shed"]
        + stats["cancelled"] + stats["failed"] + stats["drained"])
    for s, pre in zip(streams, pre_lens):
        # each client stream kept growing after the hop, to full budget
        assert len(s) == 8 >= pre
    assert eng._swap_stats["drain_swaps"] == 1
    eng.shutdown()
    del sts


def test_auto_mode_headroom_preflight(tiny_model, tmp_path,
                                      monkeypatch):
    """mode="auto" stages when the device reports headroom (or reports
    nothing — the CPU backend) and falls back to drain when the
    candidate would not fit beside the live + retired trees."""
    from paddle_tpu.monitor import memory as _memory
    with flag_scope("serve_hot_swap", True):
        eng = _engine(tiny_model)
    push = _save_manifest(eng, tmp_path / "push")
    assert eng.swap_weights(push)["mode"] == "staged"   # CPU: no stats
    monkeypatch.setattr(
        _memory, "device_memory_stats",
        lambda device=None: {"bytes_limit": 100,
                             "bytes_in_use": 99})
    assert eng.swap_weights(push)["mode"] == "drain"
    eng.shutdown()


def test_shutdown_unstages_pending_candidate_no_leak(tiny_model,
                                                     tmp_path):
    """A candidate staged behind a busy engine must not outlive
    shutdown(): the staged tree's bytes leave the live-buffer census
    once the engine is torn down (the half-loaded-push leak pin)."""
    import jax.numpy as jnp
    from paddle_tpu.monitor.memory import live_bytes
    with flag_scope("serve_hot_swap", True):
        eng = _engine(tiny_model)
    push = _save_manifest(eng, tmp_path / "push")
    tree_bytes = sum(
        int(np.prod(a.shape)) * jnp.dtype(a.dtype).itemsize
        for a in eng.params.values())
    eng.submit(Request(PROMPTS[0], max_new_tokens=32))
    eng.step()                               # resident slot: busy
    gc.collect()
    before = live_bytes()
    info = eng.swap_weights(push)
    assert info["pending"], "engine was not busy — staging not pending"
    gc.collect()
    staged = live_bytes()
    assert staged >= before + 0.9 * tree_bytes
    eng.shutdown()
    del eng, info
    gc.collect()
    after = live_bytes()
    # the staged tree (at least) was released; shutdown also frees the
    # KV pools, so the census drops by MORE than the candidate's bytes
    assert after <= staged - 0.9 * tree_bytes


# ---------------------------------------------------------------------------
# shadow/A-B traffic splitting
# ---------------------------------------------------------------------------


def test_split_hash_deterministic_and_loadgen_agrees(tiny_model):
    """assign_arm/should_shadow are pure hashes — stable across calls
    and processes — and LoadSpec tagging stamps the SAME assignment the
    router would make, without perturbing the default draws."""
    arms = [assign_arm(i, seed=7, candidate_frac=0.3)
            for i in range(200)]
    assert arms == [assign_arm(i, seed=7, candidate_frac=0.3)
                    for i in range(200)]
    frac = arms.count("candidate") / 200.0
    assert 0.15 < frac < 0.45
    assert assign_arm(5, seed=7, candidate_frac=0.0) == "baseline"
    assert not should_shadow(5, seed=7, shadow_frac=0.0)
    # loadgen: defaults are byte-identical, tags match the hashes
    base = LoadSpec(num_requests=12, rate_rps=50.0, seed=3,
                    vocab_size=64, sampling=SamplingParams())
    import dataclasses
    tagged = dataclasses.replace(base, ab_split=0.3, shadow_frac=0.5,
                                 split_seed=7)
    a = build_requests(base)
    from paddle_tpu.serving import scheduler as _sched
    _sched._reset_request_ids()
    b = build_requests(tagged)
    assert [(t, list(map(int, r.prompt)), r.max_new_tokens)
            for t, r in a] == \
        [(t, list(map(int, r.prompt)), r.max_new_tokens)
         for t, r in b]
    assert all(not hasattr(r, "lifecycle_arm") for _, r in a)
    for _, r in b:
        assert r.lifecycle_arm == assign_arm(int(r.request_id), 7, 0.3)
        assert r.lifecycle_shadow == should_shadow(
            int(r.request_id), 7, 0.5)


def test_traffic_split_flag_off_raises(tiny_model):
    router = _fleet(tiny_model, n=2)
    with pytest.raises(RuntimeError, match="serve_traffic_split"):
        router.set_traffic_split(TrafficSplit(candidate="r1"))
    router.shutdown()
    with pytest.raises(ValueError):
        TrafficSplit(candidate="r1", ab_frac=1.5)


def test_shadow_mirror_measures_but_never_serves(tiny_model, tmp_path):
    """shadow_frac=1.0 over a perturbed candidate: every baseline
    completion mirrors to the candidate, divergence is counted, the
    per-arm series exist — and shadows never touch client callbacks or
    the availability books."""
    with scoped_registry() as reg:
        router = _fleet(tiny_model, n=2,
                        flags=(("serve_hot_swap", True),
                               ("serve_traffic_split", True)))
        push = _save_manifest(router.replicas["r1"].engine,
                              tmp_path / "cand", perturb=0.05)
        router.replicas["r1"].engine.swap_weights(push)
        router.set_traffic_split(TrafficSplit(
            candidate="r1", shadow_frac=1.0, seed=7))
        tokens = []
        recs = [router.submit(Request(
            p, max_new_tokens=6,
            on_token=lambda r, t, x: tokens.append(int(t))))
            for p in PROMPTS]
        router.run()
        summary = router.summary()
        router.shutdown()
        snap = reg.snapshot()
    assert all(r.outcome == "completed" for r in recs)
    assert summary["shadow_mirrored"] == 3
    assert summary["arm_requests"].get("shadow") == 3
    # shadows are invisible to clients and to availability
    assert len(tokens) == sum(len(r.tokens) for r in recs)
    assert summary["requests_offered"] == 3
    assert summary["availability_pct"] == 100.0
    # perturbed weights on greedy mirrors: divergence counted
    assert summary["shadow_divergence"] >= 1
    assert "serve_shadow_divergence_total" in snap
    arm_events = {tuple(sorted(lb.items())) for lb, _ in
                  snap["serve_arm_requests_total"]["samples"]}
    assert (("arm", "baseline"), ("event", "completed")) in arm_events
    assert (("arm", "shadow"), ("event", "completed")) in arm_events
    assert "serve_arm_e2e_seconds" in snap


def test_ab_split_routes_and_matches_loadgen_tags(tiny_model,
                                                  tmp_path):
    """A/B arms route deterministically: candidate-arm requests land on
    the candidate replica, baseline never does, and the router's arm
    assignment agrees with LoadSpec tagging request-by-request."""
    router = _fleet(tiny_model, n=2,
                    flags=(("serve_hot_swap", True),
                           ("serve_traffic_split", True)))
    router.set_traffic_split(TrafficSplit(candidate="r1", ab_frac=0.4,
                                          seed=11))
    spec = LoadSpec(num_requests=16, rate_rps=100.0,
                    prompt_len_range=(4, 10), max_new_range=(2, 4),
                    vocab_size=tiny_model.cfg.vocab_size, seed=2,
                    sampling=SamplingParams(), ab_split=0.4,
                    split_seed=11)
    schedule = build_requests(spec)
    tags = {int(r.request_id): r.lifecycle_arm for _, r in schedule}
    recs = [router.submit(req) for _, req in schedule]
    router.run()
    summary = router.summary()
    router.shutdown()
    assert {r.outcome for r in recs} == {"completed"}
    arms = {r.request_id: r.arm for r in recs}
    assert arms == tags
    assert "candidate" in arms.values() and "baseline" in arms.values()
    for r in recs:
        if r.arm == "candidate":
            assert r.replica == "r1"
        else:
            assert r.replica != "r1"
    assert summary["traffic_split"]["candidate"] == "r1"


# ---------------------------------------------------------------------------
# the SLO-guarded promotion controller
# ---------------------------------------------------------------------------


def test_lifecycle_flag_off_raises(tiny_model):
    router = _fleet(tiny_model, n=2)
    with pytest.raises(RuntimeError, match="serve_lifecycle"):
        LifecycleController(router)
    router.shutdown()


def _controller(router, **cfg):
    with flag_scope("serve_lifecycle", True):
        return LifecycleController(router, LifecycleConfig(**cfg))


def _drive(router, n, max_new=4, seed=4):
    spec = LoadSpec(num_requests=n, rate_rps=400.0,
                    prompt_len_range=(4, 10),
                    max_new_range=(2, max_new),
                    vocab_size=router.replicas["r0"].engine.model
                    .cfg.vocab_size if hasattr(
                        router.replicas["r0"].engine, "model")
                    else 128,
                    seed=seed, sampling=SamplingParams())
    recs = [router.submit(req) for _, req in build_requests(spec)]
    router.run()
    return recs


def test_lifecycle_promotes_good_push_rolling(tiny_model, tmp_path):
    """A healthy identity push bakes on shadow traffic and promotes:
    the split clears, the remaining replicas roll one at a time, every
    engine lands on the new epoch with its anchor committed."""
    router = _fleet(tiny_model, n=2,
                    flags=(("serve_hot_swap", True),
                           ("serve_traffic_split", True)))
    push = _save_manifest(router.replicas["r0"].engine,
                          tmp_path / "push")
    ctrl = _controller(router, bake_window_s=0.0, min_requests=3)
    out = ctrl.begin(push, candidate="r1",
                     split=TrafficSplit(candidate="r1", ab_frac=0.3,
                                        shadow_frac=1.0, seed=7))
    assert out["state"] == "baking" and out["epoch"] == 1
    recs = _drive(router, 12)
    assert all(r.outcome == "completed" for r in recs)
    # router.step_all ticks maybe_decide — with a zero bake window the
    # promotion usually lands during the drive itself
    if ctrl.state != "promoted":
        assert ctrl.maybe_decide() == "promoted"
    assert ctrl.state == "promoted"
    summary = ctrl.summary()
    assert summary["decision"]["rolled"] == ["r0"]
    epochs = {n: r.engine.metrics_summary()["weights_epoch"]
              for n, r in router.replicas.items()}
    assert epochs == {"r0": 1, "r1": 1}
    assert router.summary()["traffic_split"] is None
    # the CANDIDATE's anchor commits at promote (its bake passed); the
    # rolled replica keeps its rollback anchor when the rolling swap
    # landed behind in-flight slots — that one is the operator's call
    with pytest.raises(WeightSwapError):
        router.replicas["r1"].engine.rollback_weights()
    states = [e["to"] for e in ctrl.timeline]
    assert states == ["serving", "staging", "baking", "promoted"]
    router.shutdown()


def test_lifecycle_bad_push_auto_rollback_incident(tiny_model,
                                                   tmp_path):
    """The bad-push drill: chaos plants NaNs into the candidate tree
    AFTER validation; shadow traffic fails on the candidate, the
    nonfinite trigger rolls back within the bake window, baseline
    output is bit-identical throughout, and the forensics land — an
    incident bundle (incident.json + flight.json) and flight events."""
    from paddle_tpu.monitor.flight_recorder import get_flight_recorder
    inc_dir = str(tmp_path / "incidents")
    with flag_scope("flight_recorder", True), \
            flag_scope("flight_recorder_dir", str(tmp_path)):
        router = _fleet(tiny_model, n=2,
                        flags=(("serve_hot_swap", True),
                               ("serve_traffic_split", True)))
        base_eng = router.replicas["r0"].engine
        want = [o.tolist() for o in base_eng.generate(
            PROMPTS, max_new_tokens=4)]
        push = _save_manifest(base_eng, tmp_path / "push")
        ctrl = _controller(router, bake_window_s=30.0, min_requests=3,
                           incident_dir=inc_dir)
        with chaos.chaos_scope("serve.swap.bad_weights@1"):
            out = ctrl.begin(push, candidate="r1")
        assert out["state"] == "baking"
        recs = _drive(router, 10)
        assert ctrl.state == "rolled-back"
        assert ctrl.summary()["decision"]["trigger"] == "nonfinite"
        # baseline traffic never touched the bad weights
        assert all(r.outcome == "completed" for r in recs)
        assert router.summary()["availability_pct"] == 100.0
        got = [o.tolist() for o in base_eng.generate(
            PROMPTS, max_new_tokens=4)]
        assert got == want
        # the candidate rolled back to the pre-push tree: bit-identical
        # to the baseline replica again
        got_c = [o.tolist() for o in
                 router.replicas["r1"].engine.generate(
                     PROMPTS, max_new_tokens=4)]
        assert got_c == want
        events = [e["event"] for e in
                  get_flight_recorder().events]
        router.shutdown()
    assert "lifecycle_rollback" in events
    assert "weights_cutover" in events
    bundles = os.listdir(inc_dir)
    assert len(bundles) == 1 and bundles[0].endswith("nonfinite")
    bdir = os.path.join(inc_dir, bundles[0])
    assert {"incident.json", "flight.json"} <= set(os.listdir(bdir))
    with open(os.path.join(bdir, "incident.json")) as f:
        inc = json.load(f)
    assert inc["decision"] == "rolled-back"
    assert inc["trigger"] == "nonfinite"
    assert inc["arms"]["shadow"]["outcomes"].get("failed", 0) >= 1


def test_lifecycle_refused_push_aborts_to_serving(tiny_model,
                                                  tmp_path):
    router = _fleet(tiny_model, n=2,
                    flags=(("serve_hot_swap", True),
                           ("serve_traffic_split", True)))
    ctrl = _controller(router)
    out = ctrl.begin(str(tmp_path / "nope"), candidate="r1")
    assert out["aborted"] == "refused" and ctrl.state == "serving"
    assert router.summary()["traffic_split"] is None
    # the fleet still serves
    recs = _drive(router, 4)
    assert all(r.outcome == "completed" for r in recs)
    router.shutdown()


def test_chaos_replica_die_mid_swap_aborts(tiny_model, tmp_path):
    """Chaos site serve.swap.replica_die_mid_swap: the candidate dies
    with the swap staged — the push aborts to serving, the dead
    replica's work migrates, and the baseline keeps serving."""
    router = _fleet(tiny_model, n=2,
                    flags=(("serve_hot_swap", True),
                           ("serve_traffic_split", True)))
    push = _save_manifest(router.replicas["r0"].engine,
                          tmp_path / "push")
    ctrl = _controller(router)
    with chaos.chaos_scope("serve.swap.replica_die_mid_swap@1"):
        out = ctrl.begin(push, candidate="r1")
        assert chaos.fired()
    assert out["aborted"] == "replica_died"
    assert ctrl.state == "serving"
    assert not router.replicas["r1"].alive
    recs = _drive(router, 4)
    assert all(r.outcome == "completed" for r in recs)
    assert all(r.replica == "r0" for r in recs)
    router.shutdown()


# ---------------------------------------------------------------------------
# tooling: check_bench swap% direction, monitor_report --lifecycle
# ---------------------------------------------------------------------------


def test_check_bench_swap_pct_absolute_points_higher_better():
    import check_bench
    old = [{"metric": "serve_swap_availability_pct", "value": 100.0,
            "unit": "swap%"}]
    # a 9-point availability outage would hide inside a relative 10%
    # band — the absolute-points unit must catch it
    drop = [{"metric": "serve_swap_availability_pct", "value": 89.0,
             "unit": "swap%"}]
    assert check_bench.compare_common(old, drop, tolerance=0.10)
    within = [{"metric": "serve_swap_availability_pct", "value": 99.0,
               "unit": "swap%"}]
    assert check_bench.compare_common(old, within, tolerance=0.10) == []
    # growth is never a swap% regression
    assert check_bench.compare_common(
        [{"metric": "serve_swap_availability_pct", "value": 90.0,
          "unit": "swap%"}], old, tolerance=0.10) == []


def test_monitor_report_lifecycle_renders(tiny_model, tmp_path):
    """--lifecycle renders the push state, swap counters, per-arm
    tables and the state/epoch timeline from a real registry dump."""
    import monitor_report
    with scoped_registry() as reg:
        router = _fleet(tiny_model, n=2,
                        flags=(("serve_hot_swap", True),
                               ("serve_traffic_split", True)))
        push = _save_manifest(router.replicas["r0"].engine,
                              tmp_path / "push")
        ctrl = _controller(router, bake_window_s=0.0, min_requests=2)
        ctrl.begin(push, candidate="r1")
        recs = _drive(router, 6)
        assert all(r.outcome == "completed" for r in recs)
        if ctrl.state != "promoted":
            assert ctrl.maybe_decide() == "promoted"
        path = str(tmp_path / "m.jsonl")
        reg.dump_jsonl(path)
        router.shutdown()
    with open(path) as f:
        rows = [json.loads(line) for line in f if line.strip()]
    out = monitor_report.render(rows, lifecycle=True)
    assert "Lifecycle (hot-swap push state)" in out
    assert "promoted" in out
    assert "Weight-swap events" in out and "cutover" in out
    assert "Shadow/A-B arms" in out
    assert "Lifecycle timeline" in out
    # sync pin: the tool's standalone fallback can never drift from
    # the canonical state tuple
    from paddle_tpu.serving.lifecycle import STATES
    assert monitor_report._LIFECYCLE_STATES_FALLBACK == tuple(STATES)
