"""Unified training telemetry (ISSUE 3 tentpole): metrics registry
round-trip, TrainStep.stats() compile pins, collective byte/latency
counters, the NaN/Inf watchdog, and the monitor-off overhead guard."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import monitor
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.monitor import (MetricsRegistry, NonFiniteError,
                                scoped_registry)
from paddle_tpu.optimizer import SGD, AdamW


def _mse(layer, x, y):
    return ((layer(x) - y) ** 2).mean()


def _linear_step(check_numerics=False, lr=0.1):
    paddle.seed(7)
    m = nn.Linear(4, 2)
    opt = SGD(learning_rate=lr, parameters=m.parameters())
    return TrainStep(m, _mse, opt, check_numerics=check_numerics)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.rand(8, 4).astype(np.float32),
            rng.rand(8, 2).astype(np.float32))


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "requests")
    c.inc()
    c.inc(2, route="a")
    assert c.value() == 1
    assert c.value(route="a") == 2
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("depth")
    g.set(5)
    g.dec(2)
    assert g.value() == 3
    h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.count() == 4
    assert h.sum() == pytest.approx(5.555)
    assert h.mean() == pytest.approx(5.555 / 4)
    # kind mismatch on an existing name is an error, not a silent clobber
    with pytest.raises(TypeError):
        reg.gauge("req_total")


def test_registry_prometheus_text_roundtrip():
    reg = MetricsRegistry()
    reg.counter("comm_bytes_total", "bytes").inc(4096, op="all_reduce",
                                                 group="dp")
    reg.histogram("step_seconds", buckets=(0.1, 1.0)).observe(0.5)
    text = reg.to_prometheus()
    assert "# TYPE comm_bytes_total counter" in text
    assert 'comm_bytes_total{group="dp",op="all_reduce"} 4096.0' in text
    assert "# TYPE step_seconds histogram" in text
    assert 'step_seconds_bucket{le="+Inf"} 1' in text
    assert "step_seconds_sum 0.5" in text
    assert "step_seconds_count 1" in text
    # cumulative bucket semantics
    assert 'step_seconds_bucket{le="1.0"} 1' in text
    assert 'step_seconds_bucket{le="0.1"} 0' in text


def test_registry_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "metrics.jsonl")
    reg = MetricsRegistry()
    reg.counter("x_total").inc(3, op="a")
    reg.gauge("g").set(2.5)
    reg.histogram("h_seconds", buckets=(0.1,)).observe(0.05)
    reg.dump_jsonl(path, extra={"epoch": 1})
    reg.counter("x_total").inc(1, op="a")          # append-only: 2nd dump
    reg.dump_jsonl(path, extra={"epoch": 2})
    rows = monitor.load_jsonl(path)
    assert all(json.dumps(r) for r in rows)        # valid json lines
    x_rows = [r for r in rows if r["name"] == "x_total"]
    assert [r["value"] for r in x_rows] == [3.0, 4.0]
    assert [r["epoch"] for r in x_rows] == [1, 2]
    h = [r for r in rows if r["name"] == "h_seconds"][-1]
    assert h["count"] == 1 and h["sum"] == pytest.approx(0.05)
    g = [r for r in rows if r["name"] == "g"][-1]
    assert g["value"] == 2.5 and g["type"] == "gauge"


def test_scoped_registry_isolates_default():
    base = monitor.get_registry()
    with scoped_registry() as reg:
        assert monitor.get_registry() is reg
        reg.counter("scoped_total").inc()
        with scoped_registry() as inner:
            assert monitor.get_registry() is inner
        assert monitor.get_registry() is reg
    assert monitor.get_registry() is base
    assert base.get("scoped_total") is None


# ---------------------------------------------------------------------------
# TrainStep telemetry
# ---------------------------------------------------------------------------

def test_monitor_off_adds_no_registry_writes():
    """The overhead guard: with FLAGS_monitor unset (default) the train
    step hot path performs ZERO registry writes."""
    step = _linear_step()
    x, y = _batch()
    with scoped_registry() as reg:
        before = reg.write_count
        for _ in range(4):
            step(x, y)
        assert reg.write_count == before
        assert reg.names() == []


def test_train_step_stats_one_compile_scan_gpt():
    """Acceptance pin: N warm steps of a scan-layer GPT = exactly 1
    compile, 0 recompiles."""
    from paddle_tpu.models.gpt import (GPTForPretraining,
                                       GPTPretrainingCriterion, gpt_tiny)
    paddle.seed(3)
    model = GPTForPretraining(gpt_tiny(num_layers=3, scan_layers=True))
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, ids, labels):
        return crit(layer(ids), labels)

    step = TrainStep(model, loss_fn,
                     AdamW(learning_rate=1e-3,
                           parameters=model.parameters()))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (2, 16)).astype(np.int32)
    labels = rng.randint(0, 256, (2, 16)).astype(np.int32)
    for _ in range(4):
        loss = step(ids, labels)
    assert np.isfinite(float(loss))
    st = step.stats()
    assert st["compiles"] == 1
    assert st["recompiles"] == 0
    assert st["steps"] == 4
    assert st["nonfinite_trips"] == 0


def test_train_step_recompile_detected_on_shape_change():
    step = _linear_step()
    x, y = _batch()
    step(x, y)
    step(x[:4], y[:4])                      # new signature, same kind
    st = step.stats()
    assert st["compiles"] == 2
    assert st["recompiles"] == 1


def test_train_step_monitor_on_records_timings():
    step = _linear_step()
    x, y = _batch()
    step(x, y)                              # compile outside the window
    with scoped_registry() as reg:
        with flag_scope("monitor", True):
            for _ in range(3):
                step(x, y)
        assert reg.counter("train_step_steps_total").value(kind="step") == 3
        h = reg.histogram("train_step_dispatch_seconds")
        assert h.count(kind="step") == 3
        assert reg.histogram("train_step_wall_seconds").count(kind="step") \
            == 3
        # and flipping the flag off stops the stream
        before = reg.write_count
        step(x, y)
        assert reg.write_count == before


def test_grad_accum_sync_boundary_counted():
    paddle.seed(7)
    m = nn.Linear(4, 2)
    opt = SGD(learning_rate=0.1, parameters=m.parameters())
    step = TrainStep(m, _mse, opt, grad_accum_steps=3)
    x, y = _batch()
    with scoped_registry() as reg:
        with flag_scope("monitor", True):
            for _ in range(6):              # two full accumulation windows
                step(x, y)
        assert reg.counter("train_step_grad_accum_syncs_total").value() == 2
        assert reg.counter("train_step_steps_total").value(kind="accum") == 4
        assert reg.counter("train_step_steps_total").value(kind="apply") == 2
    st = step.stats()
    assert st["grad_accum_syncs"] == 2
    assert st["microsteps"] == 6
    assert st["steps"] == 2


# ---------------------------------------------------------------------------
# collective tracing
# ---------------------------------------------------------------------------

def test_eager_all_reduce_records_bytes_and_latency():
    import jax.numpy as jnp
    from paddle_tpu.distributed import collective as C
    g = C.new_group([0, 1, 2, 3])
    x = jnp.arange(16, dtype=jnp.float32).reshape(4, 4)
    labels = dict(op="all_reduce", group=g.axis_name, nranks=4)
    with scoped_registry() as reg:
        out = C.all_reduce(x, group=g)           # cold: builds shard_map
        np.testing.assert_allclose(np.asarray(out)[0],
                                   np.asarray(x).sum(axis=0))
        C.all_reduce(x, group=g)                 # warm dispatch
        assert reg.counter("comm_ops_total").value(**labels) == 2
        assert reg.counter("comm_bytes_total").value(**labels) \
            == 2 * x.nbytes
        # compile-inclusive first call lands in its own histogram so the
        # dispatch-latency series is not skewed by trace+compile time
        cold = reg.histogram("comm_cold_dispatch_seconds")
        assert cold.count(**labels) == 1
        warm = reg.histogram("comm_latency_seconds")
        assert warm.count(**labels) == 1
        assert warm.sum(**labels) > 0


def test_eager_broadcast_and_alltoall_traced():
    import jax.numpy as jnp
    from paddle_tpu.distributed import collective as C
    g = C.new_group([0, 1])
    with scoped_registry() as reg:
        C.broadcast(jnp.ones((2, 3), jnp.float32), src=0, group=g)
        x = jnp.ones((2, 2, 3), jnp.float32)
        C.alltoall(x, group=g)
        ops = {lab["op"] for lab, _ in
               reg.counter("comm_ops_total").samples()}
        # canonical lax op name — the MoE dispatch primitive's telemetry
        assert {"broadcast", "all_to_all"} <= ops
        labels = {"op": "all_to_all", "group": g.axis_name,
                  "nranks": g.nranks}
        assert reg.counter("comm_bytes_total").value(**labels) == x.nbytes
        # first dispatch pays trace+compile -> cold histogram
        assert reg.histogram("comm_cold_dispatch_seconds").count(
            **labels) == 1
        C.alltoall(x, group=g)
        assert reg.histogram("comm_latency_seconds").count(**labels) == 1


def test_alltoall_comm_record_event_span():
    """The all_to_all dispatch emits a comm::all_to_all RecordEvent so
    the collective shows on host timelines when a profiler is open."""
    import jax.numpy as jnp
    from paddle_tpu import profiler as prof
    from paddle_tpu.distributed import collective as C
    g = C.new_group([0, 1])
    prof.start_profiler(log_dir=None)
    try:
        C.alltoall(jnp.ones((2, 2, 3), jnp.float32), group=g)
        names = set(prof._events)
    finally:
        prof.stop_profiler()
    assert "comm::all_to_all" in names, sorted(names)


def test_traced_collectives_do_not_record():
    """Inside jit/shard_map the compiler owns scheduling — the eager
    tracer must not log trace-time pseudo-latencies."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.distributed import collective as C, env
    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("world",))
    g = C.get_group(0)

    with scoped_registry() as reg:
        def body(x):
            return C.all_reduce(x, group=g)

        f = jax.jit(env.shard_map(body, mesh=mesh, in_specs=P("world"),
                                  out_specs=P("world"), check_vma=False))
        with env.axes_bound("world"):
            f(jnp.ones((4, 2), jnp.float32))
        assert reg.get("comm_ops_total") is None


# ---------------------------------------------------------------------------
# NaN/Inf watchdog
# ---------------------------------------------------------------------------

def test_watchdog_names_first_nonfinite_gradient():
    step = _linear_step(check_numerics=True)
    x, y = _batch()
    step(x, y)
    step(x, y)
    xbad = x.copy()
    xbad[0, 0] = np.inf
    with pytest.raises(NonFiniteError) as ei:
        step(xbad, y)
    # sorted-name first offender of Linear(4,2) grads is 'bias'
    assert ei.value.offender == "bias"
    assert ei.value.step == 3
    assert "step 3" in str(ei.value)
    assert "first non-finite gradient: 'bias'" in str(ei.value)
    assert step.stats()["nonfinite_trips"] == 1


def test_watchdog_warn_mode_continues():
    step = _linear_step(check_numerics="warn")
    x, y = _batch()
    step(x, y)
    xbad = x.copy()
    xbad[0, 0] = np.nan
    with pytest.warns(RuntimeWarning, match="non-finite"):
        step(xbad, y)
    # training object is still usable afterwards — and the watchdog keeps
    # flagging that the NaN update poisoned the parameters
    with pytest.warns(RuntimeWarning,
                      match="already non-finite before this step"):
        loss = step(x, y)
    assert loss is not None


def test_watchdog_healthy_run_never_trips():
    step = _linear_step(check_numerics=True)
    x, y = _batch()
    for _ in range(3):
        step(x, y)
    assert step.stats()["nonfinite_trips"] == 0


def test_numerics_helpers():
    tree = {"a": np.ones(3, np.float32),
            "c": np.array([1.0, np.nan], np.float32),
            "b": np.array([np.inf], np.float32),
            "ints": np.array([1, 2], np.int32)}
    assert not monitor.all_finite(tree)
    assert monitor.first_nonfinite(tree) == "b"
    assert monitor.nonfinite_entries(tree) == ["b", "c"]
    assert monitor.all_finite({"a": np.ones(2, np.float32)})
    assert monitor.first_nonfinite({"a": np.ones(2, np.float32)}) is None
    with scoped_registry() as reg:
        with pytest.raises(NonFiniteError) as ei:
            monitor.check_numerics(tree, step=5, what="grad")
        assert ei.value.offender == "b" and ei.value.step == 5
        assert reg.counter("numerics_nonfinite_total").value(what="grad") \
            == 1


def test_watchdog_amp_scaler_skip_integration():
    """A GradScaler-skipped step is dynamic loss scaling working: the
    watchdog records it (handled=amp_skip) but does not raise; the scaler
    counts the skip in the registry."""
    from paddle_tpu.amp import GradScaler
    paddle.seed(1)
    m = nn.Linear(3, 1)
    opt = SGD(learning_rate=0.1, parameters=m.parameters())
    scaler = GradScaler(init_loss_scaling=2.0 ** 10)
    dog = monitor.NaNWatchdog()
    x = paddle.to_tensor(np.array([[1.0, np.inf, 0.0]], np.float32))
    y = paddle.to_tensor(np.array([[1.0]], np.float32))
    with scoped_registry() as reg:
        loss = scaler.scale(((m(x) - y) ** 2).mean())
        loss.backward()
        scaler.unscale_(opt)
        assert scaler.found_inf
        offender = dog.check_grads(m, step=0, scaler=scaler)
        assert offender is not None          # named, not raised
        scaler.step(opt)
        scaler.update()
        assert scaler.skip_count == 1
        assert reg.counter("amp_skipped_steps_total").value() == 1
        assert reg.counter("numerics_nonfinite_total").value(
            what="grad", handled="amp_skip") == 1
    opt.clear_grad()


# ---------------------------------------------------------------------------
# LocalSGD sync boundaries
# ---------------------------------------------------------------------------

def test_localsgd_sync_boundary_counted():
    import jax
    from jax.sharding import Mesh
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        LocalSGDTrainStep)
    paddle.seed(5)
    m = nn.Linear(4, 2)
    opt = SGD(learning_rate=0.05, parameters=m.parameters())
    mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
    step = LocalSGDTrainStep(m, _mse, opt, mesh, k_steps=2, axis="dp")
    x, y = _batch()
    with scoped_registry() as reg:
        with flag_scope("monitor", True):
            for _ in range(4):
                step(x, y)
        assert reg.counter("localsgd_syncs_total").value(axis="dp") == 2
        assert reg.gauge("localsgd_k_steps").value(axis="dp") == 2
    st = step.stats()
    assert st["localsgd_syncs"] == 2
    assert st["local_steps"] == 4
    assert st["num_replicas"] == 2


# ---------------------------------------------------------------------------
# hapi MonitorCallback + report tool
# ---------------------------------------------------------------------------

def test_monitor_callback_streams_jsonl(tmp_path):
    from paddle_tpu.hapi.callbacks import MonitorCallback
    from paddle_tpu.core.flags import get_flag
    path = str(tmp_path / "train.jsonl")
    with scoped_registry() as reg:
        reg.counter("seen_total").inc()
        cb = MonitorCallback(path)
        cb.on_train_begin()
        assert get_flag("monitor") is True   # callback turns telemetry on
        cb.on_epoch_end(0)
        reg.counter("seen_total").inc()
        cb.on_epoch_end(1)
        cb.on_train_end()
    assert get_flag("monitor") is False      # restored after training
    rows = monitor.load_jsonl(path)
    epochs = [r.get("epoch") for r in rows if r["name"] == "seen_total"]
    assert epochs[:2] == [0, 1]
    assert any(r.get("event") == "train_end" for r in rows)
    values = [r["value"] for r in rows if r["name"] == "seen_total"]
    assert values == [1.0, 2.0, 2.0]


def test_monitor_report_renders_tables(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "monitor_report", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "monitor_report.py"))
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)

    path = str(tmp_path / "bench.jsonl")
    reg = MetricsRegistry()
    reg.counter("comm_bytes_total").inc(1 << 20, op="all_reduce",
                                        group="dp", nranks=4)
    reg.counter("comm_ops_total").inc(8, op="all_reduce", group="dp",
                                      nranks=4)
    reg.histogram("comm_latency_seconds").observe(
        0.002, op="all_reduce", group="dp", nranks=4)
    reg.histogram("train_step_dispatch_seconds").observe(0.01, kind="step")
    reg.counter("train_step_recompiles_total").inc(kind="step")
    reg.gauge("jax_backend_compiles").set(17)
    reg.dump_jsonl(path)
    out = report.render(monitor.load_jsonl(path), top=5)
    assert "Slowest events" in out
    assert "train_step_dispatch_seconds" in out
    assert "Compile / trace counters" in out
    assert "jax_backend_compiles" in out
    assert "Collectives" in out
    assert "1.0 MiB" in out
    assert "train_step_recompiles_total" in out
    # CLI entry point works end-to-end
    assert report.main([path]) == 0
    assert report.main([]) == 2
