"""SPMD pipeline-parallel tests: mesh-placed stages, one jitted program.

Analogue of the reference's PP engine tests
(test_parallel_dygraph_pipeline_parallel.py) for the TPU-native
collective-permute pipeline (spmd_pipeline.py): numerical parity with
sequential execution, per-stage parameter placement on the pp mesh axis,
the remat memory bound, and an end-to-end PP(+TP+DP) GPT train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.meta_parallel.spmd_pipeline import (
    PipelineStageStack)

H = 16


class Block(nn.Layer):
    """Residual MLP block (same in/out shape, as the pipeline requires)."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, H)

    def forward(self, x):
        return x + paddle.nn.functional.tanh(self.fc(x))


def _init_pp_mesh(dp=2, pp=2, mp=2):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "pp_degree": pp,
                               "mp_degree": mp}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group().mesh


def test_seq_fallback_matches_blocks():
    """Without a mesh, the stack runs sequentially and matches hand-applied
    per-layer execution of the same stacked parameters."""
    paddle.seed(7)
    stack = PipelineStageStack(Block, num_layers=4)
    x = np.random.default_rng(0).standard_normal((6, H)).astype(np.float32)
    out = stack(Tensor(jnp.asarray(x)))

    h = jnp.asarray(x)
    tmpl = Block()
    for i in range(4):
        sd = stack.layer_state_dict(i)
        for k, p in tmpl.named_parameters():
            p._data = sd[k]
        h = tmpl(Tensor(h))._data
    np.testing.assert_allclose(np.asarray(out._data), np.asarray(h),
                               rtol=1e-6)


def test_pipeline_matches_sequential_forward_and_grad():
    """pp=2 pipelined execution is numerically identical to the sequential
    fallback — forward AND parameter gradients (the 1F1B-parity claim the
    eager engine tests make, here for the mesh-placed program)."""
    paddle.seed(11)
    mesh = _init_pp_mesh(dp=2, pp=2, mp=2)
    stack = PipelineStageStack(Block, num_layers=4, num_microbatches=4)
    from paddle_tpu.distributed.spmd import apply_param_shardings
    apply_param_shardings(stack, mesh)

    x = np.random.default_rng(1).standard_normal((8, H)).astype(np.float32)

    names = list(stack._name_map)
    params = {r: getattr(stack, r)._data for r in names}

    def run(pipelined: bool):
        def loss_fn(pvals):
            for r in names:
                getattr(stack, r)._data = pvals[r]
            if pipelined:
                out = stack(Tensor(jnp.asarray(x)))
            else:
                h = jnp.asarray(x)
                key = jax.random.key(0)
                local = {stack._name_map[r]: pvals[r] for r in names}
                h = stack._stage_apply(local, h, key)
                out = Tensor(h)
            return (out._data.astype(jnp.float32) ** 2).mean()
        return jax.value_and_grad(loss_fn)(params)

    v_pipe, g_pipe = run(True)
    v_seq, g_seq = run(False)
    np.testing.assert_allclose(float(v_pipe), float(v_seq), rtol=1e-5)
    for r in names:
        np.testing.assert_allclose(np.asarray(g_pipe[r]),
                                   np.asarray(g_seq[r]),
                                   rtol=1e-4, atol=1e-5)


def test_stage_parameter_placement():
    """Stacked parameters are physically sharded over the pp axis: each
    stage's devices hold only their layer slice (the analogue of the
    reference's per-stage parameter ownership)."""
    mesh = _init_pp_mesh(dp=2, pp=2, mp=2)
    stack = PipelineStageStack(Block, num_layers=4)
    from paddle_tpu.distributed.spmd import apply_param_shardings
    apply_param_shardings(stack, mesh)

    p = getattr(stack, list(stack._name_map)[0])
    assert tuple(p.spec)[0] == "pp"
    arr = p._data
    assert arr.sharding.spec[0] == "pp"
    L = arr.shape[0]
    for shard in arr.addressable_shards:
        # each shard holds L/pp layers, not all L
        assert shard.data.shape[0] == L // 2
    # the two pipeline stages live on disjoint device sets
    stage_devs = {}
    for shard in arr.addressable_shards:
        stage = shard.index[0].start // (L // 2)
        stage_devs.setdefault(stage, set()).add(shard.device)
    assert set(stage_devs) == {0, 1}
    assert stage_devs[0].isdisjoint(stage_devs[1])


def test_schedule_tick_count_and_remat_memory():
    """The scan runs exactly T = M + S - 1 ticks (fill-drain bubble), and
    remat keeps in-flight activations O(M) stage boundaries rather than
    O(M * L/S) layer internals."""
    mesh = _init_pp_mesh(dp=1, pp=2, mp=1)
    M, S = 8, 2

    def build(remat):
        paddle.seed(3)
        return PipelineStageStack(Block, num_layers=8,
                                  num_microbatches=M, remat=remat)

    x = jnp.asarray(np.random.default_rng(2).standard_normal(
        (16, H)).astype(np.float32))

    def mem_of(stack):
        names = list(stack._name_map)
        params = {r: getattr(stack, r)._data for r in names}

        def loss(pvals, xv):
            for r in names:
                getattr(stack, r)._data = pvals[r]
            return (stack(Tensor(xv))._data ** 2).mean()

        jitted = jax.jit(jax.grad(loss))
        # tick count: the pipelined scan must have length M + S - 1
        jaxpr = jax.make_jaxpr(lambda p, xv: loss(p, xv))(params, x)

        def find_scan(eqns, out):
            for e in eqns:
                if e.primitive.name == "scan":
                    out.append(e)
                for v in e.params.values():
                    inner = getattr(v, "jaxpr", None)
                    if inner is not None and hasattr(inner, "eqns"):
                        find_scan(inner.eqns, out)     # ClosedJaxpr
                    elif hasattr(v, "eqns"):
                        find_scan(v.eqns, out)         # raw Jaxpr
        all_scans = []
        find_scan(jaxpr.jaxpr.eqns, all_scans)
        assert any(e.params.get("length") == M + S - 1 for e in all_scans)
        mem = jitted.lower(params, x).compile().memory_analysis()
        return mem.temp_size_in_bytes

    with_remat = mem_of(build(True))
    without = mem_of(build(False))
    assert with_remat <= without


def test_gpt_pipe_trainstep_pp_tp_dp():
    """End-to-end: GPTForPretrainingPipe on a dp×pp×mp mesh through
    TrainStep (forward + loss + grad + AdamW in ONE jitted program) — loss
    finite and decreasing (BASELINE config 4's PP+TP shape, on the CPU
    mesh)."""
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.models import (GPTForPretrainingPipe,
                                   GPTPretrainingCriterion, gpt_tiny)
    from paddle_tpu.optimizer import AdamW

    paddle.seed(5)
    mesh = _init_pp_mesh(dp=2, pp=2, mp=2)
    cfg = gpt_tiny()
    model = GPTForPretrainingPipe(cfg, num_microbatches=2)
    model = fleet.distributed_model(model)
    crit = GPTPretrainingCriterion()
    opt = AdamW(learning_rate=1e-3, weight_decay=0.01)

    def loss_fn(layer, ids, labels, mask):
        return crit(layer(ids), labels, mask)

    step = TrainStep(model, loss_fn, opt, mesh=mesh,
                     data_spec=P("dp"), zero_axis="dp")
    rng = np.random.default_rng(0)
    B, S = 8, 32
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    mask = np.ones((B, S), np.float32)
    losses = [float(np.asarray(step(Tensor(ids), Tensor(labels),
                                    Tensor(mask))._data))
              for _ in range(8)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses


def test_gpt_pipe_matches_gpt_dense():
    """GPTForPretrainingPipe with weights copied from GPTForPretraining
    produces the same logits (pipeline is a schedule, not a model change)."""
    from paddle_tpu.models import (GPTForPretraining, GPTForPretrainingPipe,
                                   gpt_tiny)

    paddle.seed(9)
    mesh = _init_pp_mesh(dp=1, pp=2, mp=2)
    cfg = gpt_tiny()
    dense = GPTForPretraining(cfg)
    pipe = GPTForPretrainingPipe(cfg, num_microbatches=2)

    # copy: embeddings + final norm directly, blocks restacked
    pipe.word_embeddings.weight._data = \
        dense.gpt.word_embeddings.weight._data
    pipe.position_embeddings.weight._data = \
        dense.gpt.position_embeddings.weight._data
    for k, p in pipe.final_norm.named_parameters():
        p._data = dict(dense.gpt.final_norm.named_parameters())[k]._data
    pipe.blocks.load_from_layers(list(dense.gpt.layers))

    dense.eval()
    pipe.eval()
    ids = np.random.default_rng(3).integers(
        0, cfg.vocab_size, (4, 16)).astype(np.int32)
    out_d = dense(Tensor(jnp.asarray(ids)))
    out_p = pipe(Tensor(jnp.asarray(ids)))
    np.testing.assert_allclose(np.asarray(out_p._data),
                               np.asarray(out_d._data),
                               rtol=2e-4, atol=2e-4)


def test_bad_configs_raise():
    _init_pp_mesh(dp=1, pp=2, mp=1)
    with pytest.raises(ValueError, match="divide"):
        stack = PipelineStageStack(Block, num_layers=3)
        stack(Tensor(jnp.zeros((4, H))))
    with pytest.raises(ValueError, match="microbatch"):
        stack = PipelineStageStack(Block, num_layers=4,
                                   num_microbatches=3)
        stack(Tensor(jnp.zeros((4, H))))
