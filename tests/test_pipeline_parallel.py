"""Pipeline-parallel engine tests.

Analogue of the reference's PP tests
(reference: test_parallel_dygraph_pipeline_parallel.py,
hybrid_parallel_pp_layer.py — segmentation asserts; hybrid_parallel_pp_amp/
alexnet.py — loss parity with the single-process model).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.distributed.meta_parallel import PipelineParallel
from paddle_tpu.distributed.meta_parallel.parallel_layers.pp_layers import (
    LayerDesc, PipelineLayer, SegmentLayers, SharedLayerDesc)

H = 16


def _descs(n_blocks=4):
    descs = [LayerDesc(nn.Linear, H, H)]
    for _ in range(n_blocks):
        descs.append(LayerDesc(nn.Linear, H, H))
        descs.append(LayerDesc(nn.ReLU))
    descs.append(LayerDesc(nn.Linear, H, 4))
    return descs


def test_uniform_segmentation():
    bounds = SegmentLayers([0] * 10, num_parts=4, method="uniform") \
        .do_segment()
    assert bounds == [0, 3, 6, 8, 10]
    sizes = [b - a for a, b in zip(bounds, bounds[1:])]
    assert sum(sizes) == 10 and max(sizes) - min(sizes) <= 1


def test_layer_name_segmentation():
    descs = _descs(4)   # Linear, (Linear, ReLU)*4, Linear
    seg = SegmentLayers(descs, num_parts=2, method="layer:Linear")
    bounds = seg.do_segment()
    assert bounds[0] == 0 and bounds[-1] == len(descs)
    assert len(bounds) == 3


def test_pipeline_layer_builds_all_stages():
    pl = PipelineLayer(_descs(3), num_stages=2,
                       loss_fn=lambda o, y: F.cross_entropy(o, y))
    assert pl.num_stages == 2
    n_params = len(list(pl.named_parameters()))
    assert n_params == 5 * 2   # 5 Linears, weight+bias each


def test_1f1b_schedule_order_and_memory_bound():
    paddle.seed(0)
    pl = PipelineLayer(_descs(2), num_stages=2,
                       loss_fn=lambda o, y: F.cross_entropy(o, y))
    pp = PipelineParallel(pl, accumulate_steps=4)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=pl.parameters())
    x = np.random.RandomState(0).randn(8, H).astype(np.float32)
    y = np.zeros((8,), np.int64)
    pp.train_batch((paddle.to_tensor(x), paddle.to_tensor(y)), opt)

    log = pp._schedule_log
    fwd_first = [e for e in log if e[0] == "F" and e[1] == 0]
    bwd_last = [e for e in log if e[0] == "B" and e[1] == 1]
    assert len(fwd_first) == 4 and len(bwd_last) == 4
    # 1F1B: after warmup (S-1 = 1 forward), each forward is followed by a
    # backward — microbatch 0's backward must happen BEFORE microbatch 3's
    # forward (a GPipe schedule would do all forwards first)
    first_b = next(i for i, e in enumerate(log) if e[0] == "B")
    last_f = max(i for i, e in enumerate(log) if e[0] == "F")
    assert first_b < last_f, "schedule is GPipe-like, not 1F1B"
    # in-flight bound: at any point, #started-forward - #finished-backward
    # microbatches <= num_stages
    live = 0
    peak = 0
    seen_f, seen_b = set(), set()
    for kind, s, mb in log:
        if kind == "F" and mb not in seen_f:
            seen_f.add(mb)
        if kind == "B" and s == 0:
            seen_b.add(mb)
        live = len(seen_f) - len(seen_b)
        peak = max(peak, live)
    assert peak <= pl.num_stages, f"in-flight {peak} > stages"


def test_loss_and_grad_parity_vs_single_model():
    # identical init: build once, deep-copy state into a plain Sequential
    paddle.seed(1)
    loss_fn = lambda o, y: F.cross_entropy(o, y)      # noqa: E731
    pl = PipelineLayer(_descs(2), num_stages=2, loss_fn=loss_fn)
    rng = np.random.RandomState(1)
    x = rng.randn(8, H).astype(np.float32)
    y = rng.randint(0, 4, (8,)).astype(np.int64)

    # single-model reference: same layers called sequentially (stage walk),
    # full batch, one backward
    ref_loss = loss_fn(pl(paddle.to_tensor(x)), paddle.to_tensor(y))
    ref_loss.backward()
    ref_grads = {k: np.asarray(p.grad._data)
                 for k, p in pl.named_parameters()}
    for _, p in pl.named_parameters():
        p.clear_gradient()

    pp = PipelineParallel(pl, accumulate_steps=4)
    pp_loss = pp.forward_backward_pipeline(
        (paddle.to_tensor(x), paddle.to_tensor(y)))
    np.testing.assert_allclose(float(ref_loss), float(pp_loss), rtol=1e-5)
    for k, p in pl.named_parameters():
        np.testing.assert_allclose(ref_grads[k], np.asarray(p.grad._data),
                                   rtol=1e-4, atol=1e-6, err_msg=k)


def test_shared_layer_desc_ties_weights():
    V, D = 12, 8

    class Emb(nn.Layer):
        def __init__(self):
            super().__init__()
            self.table = self.create_parameter((V, D))

        def forward(self, ids):
            return self.table[ids]

    def head_fwd(shared, h):
        # tied LM head: h @ table^T
        return paddle.matmul(h, shared.table, transpose_y=True)

    descs = [
        SharedLayerDesc("embed", Emb),
        LayerDesc(nn.Linear, D, D),
        SharedLayerDesc("embed", Emb, forward_func=head_fwd),
    ]
    pl = PipelineLayer(descs, num_stages=3,
                       loss_fn=lambda o, y: F.cross_entropy(o, y))
    # the table parameter exists exactly once
    tables = [k for k, _ in pl.named_parameters() if "table" in k]
    assert len(tables) == 1
    pp = PipelineParallel(pl, accumulate_steps=2)
    ids = np.random.RandomState(2).randint(0, V, (4,)).astype(np.int64)
    labels = np.random.RandomState(3).randint(0, V, (4,)).astype(np.int64)
    pp.forward_backward_pipeline(
        (paddle.to_tensor(ids), paddle.to_tensor(labels)))
    emb = pl.shared_layer("embed")
    assert emb.table.grad is not None  # grads from BOTH call sites
    assert float(np.abs(np.asarray(emb.table.grad._data)).sum()) > 0


def test_scaler_loss_reported_unscaled():
    from paddle_tpu.amp import GradScaler

    paddle.seed(6)
    pl = PipelineLayer(_descs(1), num_stages=2,
                       loss_fn=lambda o, y: F.cross_entropy(o, y))
    pp = PipelineParallel(pl, accumulate_steps=2)
    rng = np.random.RandomState(7)
    x = rng.randn(4, H).astype(np.float32)
    y = rng.randint(0, 4, (4,)).astype(np.int64)
    data = (paddle.to_tensor(x), paddle.to_tensor(y))

    plain = float(pp.forward_backward_pipeline(data))
    for _, p in pl.named_parameters():
        p.clear_gradient()
    scaler = GradScaler(init_loss_scaling=4096.0)
    scaled = float(pp.forward_backward_pipeline(data, scaler=scaler))
    # the reported loss must be the true loss, not 4096x it
    np.testing.assert_allclose(plain, scaled, rtol=1e-5)


def test_gpt_pipeline_with_tied_embedding_converges():
    # BASELINE config 4 shape at toy scale: GPT via PipelineLayer descs
    # with the embedding table shared between stage 0 and the LM head
    from paddle_tpu.models.gpt import build_gpt_pipe, gpt_tiny

    paddle.seed(9)
    pp = build_gpt_pipe(gpt_tiny(), num_stages=2, accumulate_steps=2)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=pp._layers.parameters())
    rng = np.random.RandomState(0)
    ids = (np.arange(32)[None, :] + rng.randint(0, 256, (4, 1))) % 256
    labels = (ids + 1) % 256
    data = (paddle.to_tensor(ids.astype(np.int32)),
            paddle.to_tensor(labels.astype(np.int32)))
    losses = [float(pp.train_batch(data, opt)) for _ in range(10)]
    assert losses[-1] < losses[0]
    tables = [k for k, _ in pp._layers.named_parameters()
              if "word_embeddings" in k]
    assert len(tables) == 1          # tied, not duplicated


def test_train_batch_converges():
    paddle.seed(4)
    pl = PipelineLayer(_descs(2), num_stages=2,
                       loss_fn=lambda o, y: F.cross_entropy(o, y))
    pp = PipelineParallel(pl, accumulate_steps=2)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=pl.parameters())
    rng = np.random.RandomState(5)
    x = rng.randn(8, H).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int64)
    data = (paddle.to_tensor(x), paddle.to_tensor(y))
    losses = [float(pp.train_batch(data, opt)) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5, losses[::6]
