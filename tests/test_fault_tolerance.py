"""Fault-tolerant training runtime (docs/FAULT_TOLERANCE.md).

Every recovery path is exercised through the deterministic chaos
injector (paddle_tpu.testing.chaos) — nothing here depends on timing
luck:

- atomic checkpoint commit: manifest + rename, verification levels,
  uncommitted/torn directories skipped with fallback to the newest
  valid checkpoint (``checkpoint_fallback`` flight events);
- CheckpointManager: interval saves, SIGTERM preemption with a final
  commit, ``resume()`` restoring a bit-exact training state incl. the
  dataloader position, retention GC that never deletes the last valid
  checkpoint;
- collective timeouts: a chaos-hung eager collective raises
  ``CollectiveTimeoutError`` within the flag budget instead of hanging
  the suite;
- skip-and-continue: ``skip_nonfinite_budget`` rolls back a NaN step
  and continues bit-exactly, raising only after N consecutive trips;
- fs/elastic store retries: exponential backoff with jitter.
"""

import json
import os
import signal
import time
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.distributed.checkpoint import (CheckpointManager,
                                               CheckpointError,
                                               PreemptionSignal,
                                               latest_step,
                                               verify_checkpoint)
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.monitor import flight_recorder as flight
from paddle_tpu.testing import chaos


def _build_step(**kwargs):
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    return TrainStep(model, lambda l, a, b: F.cross_entropy(l(a), b),
                     paddle.optimizer.Adam(learning_rate=1e-2,
                                           parameters=model.parameters()),
                     **kwargs)


def _batch(i):
    rng = np.random.default_rng(50 + i)
    return (rng.standard_normal((8, 8)).astype(np.float32),
            rng.integers(0, 4, (8,)).astype(np.int64))


def _ref_losses(n):
    step = _build_step()
    return [float(step(*_batch(i))) for i in range(n)]


# ---------------------------------------------------------------------------
# Atomic commit protocol
# ---------------------------------------------------------------------------

def test_commit_writes_manifest_and_roundtrips(tmp_path):
    import jax.numpy as jnp
    path = str(tmp_path / "step_2")
    state = {"a": jnp.arange(8.0), "n": 5}
    dckpt.save(state, path, asynchronous=False, step=2)
    assert not os.path.exists(path + dckpt.STAGING_SUFFIX)
    assert os.path.exists(os.path.join(path, dckpt.MANIFEST_NAME))
    assert verify_checkpoint(path, "manifest") is None
    assert verify_checkpoint(path, "full") is None
    m = dckpt.read_manifest(path)
    assert m["step"] == 2
    assert "['a']" in m["leaves"]
    assert m["leaves"]["['a']"]["shape"] == [8]
    # the flags fingerprint answers "what configuration wrote this"
    assert "checkpoint_verify" in m["flags"]
    back = dckpt.load(path)
    np.testing.assert_array_equal(np.asarray(back["a"]), np.arange(8.0))


def test_async_save_commits_at_wait(tmp_path):
    import jax.numpy as jnp
    root = str(tmp_path)
    path = os.path.join(root, "step_3")
    dckpt.save({"a": jnp.ones(4)}, path, asynchronous=True, step=3)
    dckpt.wait()
    assert verify_checkpoint(path) is None
    assert latest_step(root) == 3


def test_latest_step_skips_uncommitted_and_invalid(tmp_path):
    import jax.numpy as jnp
    root = str(tmp_path)
    dckpt.save({"a": jnp.ones(4)}, os.path.join(root, "step_2"),
               asynchronous=False, step=2)
    # an interrupted save leaves only a staging dir: never a candidate
    os.makedirs(os.path.join(root, "step_6.tmp"))
    # a committed-looking dir without a manifest (legacy/torn): skipped
    os.makedirs(os.path.join(root, "step_4"))
    assert latest_step(root) == 2
    assert verify_checkpoint(os.path.join(root, "step_4")) \
        == "uncommitted (no manifest)"
    # FLAGS_checkpoint_verify=off restores legacy manifest-less dirs
    assert verify_checkpoint(os.path.join(root, "step_4"), "off") is None


@pytest.mark.chaos
def test_torn_write_falls_back_to_previous_valid(tmp_path):
    """Acceptance: chaos-torn step_4 → latest_step/load_train_step
    resume from step_2 (never the torn one), visibly as a
    checkpoint_fallback flight event, and the loss curve continues
    bit-exactly."""
    root = str(tmp_path / "ckpts")
    ref = _ref_losses(4)

    step_a = _build_step()
    for i in range(2):
        step_a(*_batch(i))
    dckpt.save_train_step(step_a, os.path.join(root, "step_2"),
                          asynchronous=False)
    for i in range(2, 4):
        step_a(*_batch(i))
    chaos.configure("ckpt.write.torn@1")
    dckpt.save_train_step(step_a, os.path.join(root, "step_4"),
                          asynchronous=False)
    chaos.reset()
    assert chaos.fired() == []  # reset cleared the record too

    reason = verify_checkpoint(os.path.join(root, "step_4"))
    assert reason is not None and "torn" in reason
    with flag_scope("flight_recorder", True):
        assert latest_step(root) == 2
        events = flight.get_flight_recorder().events
    fb = [e for e in events if e["event"] == "checkpoint_fallback"]
    assert fb and fb[0]["step"] == 4 and fb[0]["fallback_to"] == 2

    step_b = _build_step()
    dckpt.load_train_step(step_b, os.path.join(root, f"step_{latest_step(root)}"))
    assert step_b.step_count == 2
    cont = [float(step_b(*_batch(i))) for i in range(2, 4)]
    assert cont == ref[2:4]


@pytest.mark.chaos
def test_manifest_corruption_invalidates(tmp_path):
    import jax.numpy as jnp
    root = str(tmp_path)
    dckpt.save({"a": jnp.ones(4)}, os.path.join(root, "step_2"),
               asynchronous=False, step=2)
    chaos.configure("ckpt.manifest.corrupt@1")
    dckpt.save({"a": jnp.ones(4)}, os.path.join(root, "step_4"),
               asynchronous=False, step=4)
    chaos.reset()
    assert "manifest unreadable" in verify_checkpoint(
        os.path.join(root, "step_4"))
    assert latest_step(root) == 2
    with pytest.raises(CheckpointError, match="refusing to restore"):
        dckpt.load(os.path.join(root, "step_4"))


def test_full_verify_catches_same_size_bit_corruption(tmp_path):
    import jax.numpy as jnp
    path = str(tmp_path / "step_2")
    # CRCs are recorded at commit time only under 'full' (recording
    # costs a re-read of the staged tree)
    with flag_scope("checkpoint_verify", "full"):
        dckpt.save({"a": jnp.arange(64.0)}, path, asynchronous=False,
                   step=2)
    m = dckpt.read_manifest(path)
    assert all("crc32" in e for e in m["files"].values())
    # flip one byte of the largest data file, size unchanged
    victim = max(m["files"], key=lambda r: m["files"][r]["size"])
    vp = os.path.join(path, victim)
    with open(vp, "r+b") as f:
        b = f.read(1)
        f.seek(0)
        f.write(bytes([b[0] ^ 0xFF]))
    assert verify_checkpoint(path, "manifest") is None   # size-level blind
    assert "checksum mismatch" in verify_checkpoint(path, "full")
    with flag_scope("checkpoint_verify", "full"):
        assert latest_step(str(tmp_path)) is None


def test_wait_and_next_save_propagate_commit_failure(tmp_path, monkeypatch):
    """A failed background save must never be silent: wait() (and the
    next save(), which finalizes pending work first) re-raise as
    CheckpointError, and the checkpointer stays usable afterwards."""
    import jax.numpy as jnp

    def boom(*a, **k):
        raise OSError("disk full")

    path = str(tmp_path / "step_1")
    monkeypatch.setattr(dckpt, "_commit", boom)
    dckpt.save({"a": jnp.ones(4)}, path, asynchronous=True, step=1)
    with pytest.raises(CheckpointError, match="commit failed"):
        dckpt.wait()
    # failure #2 surfaces at the NEXT save() (which finalizes pending
    # work first) instead of evaporating
    dckpt.save({"a": jnp.ones(4)}, path, asynchronous=True, step=1)
    with pytest.raises(CheckpointError, match="commit failed"):
        dckpt.save({"a": jnp.ones(4)}, path, asynchronous=True, step=1)
    monkeypatch.undo()
    dckpt.save({"a": jnp.ones(4)}, path, asynchronous=True, step=1)
    dckpt.wait()   # the post-failure save goes through cleanly
    assert verify_checkpoint(path) is None


def test_recommit_to_existing_path_never_leaves_nothing(tmp_path,
                                                        monkeypatch):
    """Re-saving onto an existing committed checkpoint parks the old one
    aside instead of deleting it first: a crash at the worst point (the
    swap) leaves the old content recoverable on disk, and a successful
    re-commit leaves exactly one valid dir and no .old."""
    import jax.numpy as jnp
    path = str(tmp_path / "step_2")
    dckpt.save({"a": jnp.zeros(4)}, path, asynchronous=False, step=2)
    # happy path: replace in place
    dckpt.save({"a": jnp.ones(4)}, path, asynchronous=False, step=2)
    assert verify_checkpoint(path) is None
    np.testing.assert_array_equal(np.asarray(dckpt.load(path)["a"]),
                                  np.ones(4))
    assert not os.path.exists(path + dckpt.REPLACED_SUFFIX)
    # crash at the swap: fail the rename that installs the new dir
    real_rename = os.rename

    def crashy(src, dst):
        if dst == path and src.endswith(dckpt.STAGING_SUFFIX):
            raise OSError("killed at the swap")
        return real_rename(src, dst)

    monkeypatch.setattr(dckpt.os, "rename", crashy)
    with pytest.raises(CheckpointError):
        dckpt.save({"a": jnp.full(4, 7.0)}, path, asynchronous=True,
                   step=2)
        dckpt.wait()
    monkeypatch.undo()
    # the replaced checkpoint survived on disk under .old
    old = path + dckpt.REPLACED_SUFFIX
    assert os.path.isdir(old) and verify_checkpoint(old) is None
    np.testing.assert_array_equal(np.asarray(dckpt.load(old)["a"]),
                                  np.ones(4))


# ---------------------------------------------------------------------------
# CheckpointManager: auto-resume driver
# ---------------------------------------------------------------------------

def test_preemption_resume_is_bit_exact(tmp_path):
    """Acceptance: SIGTERM mid-run → final commit at the next step
    boundary → fresh-process resume() → the remaining loss trajectory is
    BIT-EXACT vs the uninterrupted run (params, opt state, RNG stream
    and dataloader offset all restored)."""
    root = str(tmp_path / "ckpts")
    ref = _ref_losses(6)

    step_a = _build_step()
    losses_a = []
    with pytest.raises(PreemptionSignal) as exc:
        with CheckpointManager(step_a, root, interval_steps=2,
                               keep_n=2) as mgr:
            for i in range(6):
                losses_a.append(float(step_a(*_batch(i))))
                if i == 3:
                    os.kill(os.getpid(), signal.SIGTERM)
                mgr.on_step(dataloader_state={"offset": i + 1})
    assert exc.value.step == 4
    assert losses_a == ref[:4]

    step_b = _build_step()
    with CheckpointManager(step_b, root, interval_steps=2,
                           keep_n=2) as mgr:
        info = mgr.resume()
        assert info["step"] == 4
        assert info["dataloader"] == {"offset": 4}
        losses_b = [float(step_b(*_batch(i)))
                    for i in range(info["dataloader"]["offset"], 6)]
    assert losses_b == ref[4:]


def test_preemption_commits_despite_prior_failed_async_save(tmp_path,
                                                            monkeypatch):
    """A failed interval save must not abort the SIGTERM final commit:
    the grace period's one job is committing the current state."""
    root = str(tmp_path / "ckpts")
    real_commit = dckpt._commit

    def flaky_commit(tmp, final, *a, **k):
        if final.endswith("step_2"):
            raise OSError("transient store failure")
        return real_commit(tmp, final, *a, **k)

    monkeypatch.setattr(dckpt, "_commit", flaky_commit)
    step = _build_step()
    with pytest.raises(PreemptionSignal) as exc:
        with CheckpointManager(step, root, interval_steps=2,
                               keep_n=2) as mgr:
            for i in range(3):
                step(*_batch(i))
                if i == 2:
                    os.kill(os.getpid(), signal.SIGTERM)
                mgr.on_step()   # i=1 enqueues step_2 (commit will fail)
    assert exc.value.step == 3
    assert latest_step(root) == 3     # final commit landed regardless


def test_manager_interval_saves_and_gc(tmp_path):
    root = str(tmp_path / "ckpts")
    step = _build_step()
    with CheckpointManager(step, root, interval_steps=2, keep_n=2,
                           asynchronous=False) as mgr:
        for i in range(8):
            step(*_batch(i))
            mgr.on_step()
    steps = dckpt.checkpoint_steps(root)
    # keep_n=2 newest valid survive; older interval saves GC'd
    assert steps == [6, 8]
    assert all(verify_checkpoint(os.path.join(root, f"step_{n}")) is None
               for n in steps)
    assert mgr.save_count == 4


def test_async_interval_save_commits_at_next_step_boundary(tmp_path):
    """An async interval save must become visible at the first step
    boundary after serialization finishes — not at the NEXT interval
    (which would double the worst-case SIGKILL loss)."""
    root = str(tmp_path / "ckpts")
    step = _build_step()
    with CheckpointManager(step, root, interval_steps=4, keep_n=2) as mgr:
        for i in range(4):
            step(*_batch(i))
            mgr.on_step()        # step 4 enqueues the async save
        # serialization of this tiny tree finishes almost immediately;
        # give it a bounded moment, then one more step boundary
        deadline = time.monotonic() + 30.0
        while (not dckpt.Checkpointer.instance().pending_ready()
               and time.monotonic() < deadline):
            time.sleep(0.01)
        step(*_batch(4))
        mgr.on_step()            # step 5: NOT an interval — commits here
        assert latest_step(root) == 4
        assert verify_checkpoint(os.path.join(root, "step_4")) is None


@pytest.mark.chaos
def test_resume_fallback_event_names_landing_step(tmp_path):
    """resume()'s checkpoint_fallback events carry the step actually
    resumed from (same semantics as latest_step)."""
    root = str(tmp_path / "ckpts")
    step = _build_step()
    mgr = CheckpointManager(step, root, interval_steps=1, keep_n=3,
                            asynchronous=False)
    try:
        step(*_batch(0))
        mgr.save()
        step(*_batch(1))
        chaos.configure("ckpt.write.torn@1")
        mgr.save()
        chaos.reset()
    finally:
        mgr.close()
    fresh = _build_step()
    mgr2 = CheckpointManager(fresh, root, interval_steps=1)
    try:
        with flag_scope("flight_recorder", True):
            info = mgr2.resume()
            events = flight.get_flight_recorder().events
    finally:
        mgr2.close()
    assert info["step"] == 1
    fb = [e for e in events if e["event"] == "checkpoint_fallback"]
    assert fb and fb[0]["step"] == 2 and fb[0]["fallback_to"] == 1


@pytest.mark.chaos
def test_chaos_hang_without_timeout_budget_is_rejected():
    import jax.numpy as jnp
    from paddle_tpu.distributed import collective as C
    g = C.new_group([0, 1])
    chaos.arm("collective.hang")
    with pytest.raises(RuntimeError, match="FLAGS_collective_timeout_s"):
        C.all_reduce(jnp.ones((2, 4), jnp.float32), group=g)
    chaos.reset()


def test_gc_never_deletes_last_valid(tmp_path):
    root = str(tmp_path / "ckpts")
    step = _build_step()
    step(*_batch(0))
    mgr = CheckpointManager(step, root, interval_steps=1, keep_n=1,
                            asynchronous=False)
    try:
        mgr.save()
        mgr.gc()
        assert dckpt.checkpoint_steps(root) == [1]
        # orphan staging dirs are GC'd
        os.makedirs(os.path.join(root, "step_9.tmp"))
        mgr.gc()
        assert not os.path.exists(os.path.join(root, "step_9.tmp"))
        assert dckpt.checkpoint_steps(root) == [1]
    finally:
        mgr.close()


@pytest.mark.chaos
def test_resume_falls_back_past_unrestorable_checkpoint(tmp_path):
    root = str(tmp_path / "ckpts")
    step = _build_step()
    mgr = CheckpointManager(step, root, interval_steps=1, keep_n=3,
                            asynchronous=False)
    try:
        step(*_batch(0))
        mgr.save()
        step(*_batch(1))
        chaos.configure("ckpt.write.torn@1")
        mgr.save()
        chaos.reset()
        fresh = _build_step()
        mgr2 = CheckpointManager(fresh, root, interval_steps=1)
        try:
            info = mgr2.resume()
        finally:
            mgr2.close()
        assert info["step"] == 1     # torn step_2 skipped
    finally:
        mgr.close()


@pytest.mark.chaos
def test_worker_die_site_raises_chaos_fault(tmp_path):
    step = _build_step()
    mgr = CheckpointManager(step, str(tmp_path), interval_steps=100)
    try:
        chaos.configure("worker.die@2")
        step(*_batch(0))
        mgr.on_step()                 # occurrence 1: survives
        step(*_batch(1))
        with pytest.raises(chaos.ChaosFault) as exc:
            mgr.on_step()             # occurrence 2: dies
        assert exc.value.site == "worker.die"
    finally:
        mgr.close()


# ---------------------------------------------------------------------------
# Collective timeout watchdog
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_hung_collective_raises_within_budget():
    """Acceptance: a chaos-hung eager collective raises
    CollectiveTimeoutError within FLAGS_collective_timeout_s (plus
    watchdog overhead) instead of hanging the suite."""
    import jax.numpy as jnp
    from paddle_tpu.distributed import collective as C

    g = C.new_group([0, 1])
    x = jnp.ones((2, 4), jnp.float32)
    with flag_scope("collective_timeout_s", 1.0):
        # watchdog pass-through: a healthy collective still works
        out = C.all_reduce(x, group=g)
        np.testing.assert_allclose(np.asarray(out)[0], 2.0)
        chaos.arm("collective.hang", at=1)
        with flag_scope("flight_recorder", True):
            t0 = time.monotonic()
            with pytest.raises(C.CollectiveTimeoutError) as exc:
                C.all_reduce(jnp.ones((2, 4), jnp.float32), group=g)
            elapsed = time.monotonic() - t0
            events = flight.get_flight_recorder().events
    assert 0.9 <= elapsed < 5.0, elapsed
    assert exc.value.op == "all_reduce"
    assert exc.value.timeout_s == 1.0
    names = [e["event"] for e in events]
    assert "collective_timeout" in names
    assert "chaos" in names           # the injected fault is on record
    chaos.reset()
    # the abandoned worker must not poison later dispatches
    out = C.all_reduce(jnp.ones((2, 4), jnp.float32), group=g)
    np.testing.assert_allclose(np.asarray(out)[0], 2.0)


def test_collective_timeout_off_by_default():
    import jax.numpy as jnp
    from paddle_tpu.distributed import collective as C
    g = C.new_group([0, 1])
    out = C.all_reduce(jnp.ones((2, 4), jnp.float32), group=g)
    np.testing.assert_allclose(np.asarray(out)[0], 2.0)


# ---------------------------------------------------------------------------
# skip_nonfinite_budget: graceful degradation
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_nonfinite_step_skipped_and_rolled_back():
    # constant batch: the rolled-back update is retried on the SAME data
    # next call, so the post-skip trajectory must realign with the
    # uninterrupted one exactly
    ref_step = _build_step()
    ref = [float(ref_step(*_batch(0))) for _ in range(4)]
    chaos.configure("grad.nonfinite@2")
    step = _build_step(skip_nonfinite_budget=2)
    losses = []
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with flag_scope("flight_recorder", True):
            for i in range(5):
                losses.append(float(step(*_batch(0))))
            events = flight.get_flight_recorder().events
    chaos.reset()
    assert np.isnan(losses[1])
    # the update was rolled back: the retried step reproduces the
    # uninterrupted trajectory bit-exactly
    assert losses[2] == ref[1] and losses[4] == ref[3]
    assert step.step_count == 4
    assert step.stats()["nonfinite_skips"] == 1
    skip_events = [e for e in events if e["event"] == "nonfinite_skip"]
    assert skip_events and skip_events[0]["budget"] == 2
    assert any("skipped and rolled back" in str(w.message) for w in caught)


@pytest.mark.chaos
def test_nonfinite_budget_exhaustion_raises():
    from paddle_tpu.monitor.numerics import NonFiniteError
    chaos.configure("grad.nonfinite")      # every step trips
    step = _build_step(skip_nonfinite_budget=2)
    done = 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(NonFiniteError, match="budget exhausted"):
            for i in range(5):
                step(*_batch(0))
                done += 1
    chaos.reset()
    assert done == 2                       # two skips, third trip raises
    assert step.stats()["nonfinite_skips"] == 2
    # exhaustion also rolls back: the state a supervisor checkpoints
    # after catching the error is the last-known-good one
    assert step.step_count == 0
    assert all(bool(np.isfinite(np.asarray(v)).all())
               for v in step.params.values())


@pytest.mark.chaos
def test_finite_step_resets_consecutive_counter():
    """budget=1: trip, finite, trip — the middle finite step resets the
    consecutive counter, so the second trip is a SKIP, not a raise."""
    ref = _ref_losses(1)
    step = _build_step(skip_nonfinite_budget=1)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        chaos.arm("grad.nonfinite")
        l0 = float(step(*_batch(0)))       # trip: skipped (1/1)
        chaos.reset()
        l1 = float(step(*_batch(0)))       # finite: counter resets
        chaos.arm("grad.nonfinite")
        l2 = float(step(*_batch(0)))       # trip again: skipped, no raise
        chaos.reset()
    assert np.isnan(l0) and l1 == ref[0] and np.isnan(l2)
    assert step.stats()["nonfinite_skips"] == 2


# ---------------------------------------------------------------------------
# fs/elastic store retries
# ---------------------------------------------------------------------------

def test_retry_with_backoff_exponential_jittered():
    from paddle_tpu.distributed.fleet.utils.fs import retry_with_backoff
    sleeps = []
    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 4:
            raise OSError("transient")
        return "ok"

    out = retry_with_backoff(flaky, retries=5, base_delay=0.1,
                             retry_on=(OSError,), sleep=sleeps.append)
    assert out == "ok" and attempts["n"] == 4
    assert len(sleeps) == 3
    # exponential base with jitter in [1, 1.5): delay_k in base*2^k*[1,1.5)
    for k, d in enumerate(sleeps):
        lo = 0.1 * (2 ** k)
        assert lo <= d < lo * 1.5, (k, d)


def test_retry_with_backoff_respects_permanent_failures():
    from paddle_tpu.distributed.fleet.utils.fs import retry_with_backoff
    calls = {"n": 0}

    def permanent():
        calls["n"] += 1
        e = OSError("no such CLI")
        e.retryable = False
        raise e

    with pytest.raises(OSError):
        retry_with_backoff(permanent, retries=5, retry_on=(OSError,),
                           sleep=lambda s: pytest.fail("slept on a "
                                                       "permanent error"))
    assert calls["n"] == 1


def test_retry_exhaustion_reraises():
    from paddle_tpu.distributed.fleet.utils.fs import retry_with_backoff
    with pytest.raises(OSError, match="still down"):
        retry_with_backoff(lambda: (_ for _ in ()).throw(
            OSError("still down")), retries=2, retry_on=(OSError,),
            sleep=lambda s: None)


def test_hdfs_missing_cli_fails_fast_no_retry():
    from paddle_tpu.distributed.fleet.utils.fs import ExecuteError, HDFSClient
    client = HDFSClient(hadoop_home="/nonexistent")
    t0 = time.monotonic()
    with pytest.raises(ExecuteError, match="not found"):
        client.upload("/tmp/x", "/remote/x")
    assert time.monotonic() - t0 < 1.0     # no backoff on permanent fail


def test_elastic_heartbeat_uses_store_retry(tmp_path, monkeypatch):
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.fleet.utils import fs as fs_mod

    seen = {}
    real = fs_mod.retry_with_backoff

    def spy(fn, **kw):
        seen.update(kw)
        return real(fn, **kw)

    monkeypatch.setattr(fs_mod, "retry_with_backoff", spy)
    mgr = ElasticManager(root=str(tmp_path), rank=0, np_=1, min_np=1,
                         max_np=1, timeout=60)
    mgr.beat()
    assert seen["retry_on"] == (OSError,)
    assert mgr.alive_workers() == [0]
    mgr.mark_completed()
    assert os.path.exists(os.path.join(mgr.root, "COMPLETED"))


# ---------------------------------------------------------------------------
# Chaos injector semantics + recovery-timeline rendering
# ---------------------------------------------------------------------------

def test_chaos_spec_parsing_and_determinism():
    chaos.configure("grad.nonfinite@2, collective.hang:0.5*3", seed=7)
    assert not chaos.probe("grad.nonfinite")      # occurrence 1
    assert chaos.probe("grad.nonfinite")          # occurrence 2 fires
    assert not chaos.probe("grad.nonfinite")      # @N is single-shot
    pattern1 = [chaos.probe("collective.hang") for _ in range(20)]
    assert sum(pattern1) == 3                     # *3 cap
    chaos.configure("collective.hang:0.5", seed=7)
    pattern2 = [chaos.probe("collective.hang") for _ in range(20)]
    # same (seed, site, occurrence) → same decisions (until the cap bit)
    assert pattern1[:pattern1.index(True) + 1] == \
        pattern2[:pattern1.index(True) + 1]
    chaos.reset()
    assert not chaos.active()
    assert not chaos.probe("collective.hang")


def test_chaos_unknown_site_rejected():
    with pytest.raises(ValueError, match="unknown site"):
        chaos.arm("ckpt.write.tron")
    assert not chaos.active()


def test_flight_report_renders_recovery_timeline(tmp_path):
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import monitor_report

    fr = flight.FlightRecorder(capacity=16, dump_dir=str(tmp_path))
    fr.record_event("checkpoint_commit", path="/ck/step_2", step=2,
                    files=9, bytes=1234)
    fr.record_event("compile", kind="step", step=1)   # not recovery
    fr.record_event("collective_timeout", op="all_reduce", group="dp",
                    nranks=4, timeout_s=5.0)
    fr.record_event("nonfinite_skip", step=7, offender="loss",
                    consecutive=1, budget=3)
    fr.record_event("checkpoint_fallback", step=8, reason="torn file",
                    fallback_to=2)
    path = fr.dump(reason="explicit")
    out = monitor_report.render_flight(flight.load_dump(path), last=10)
    assert "Recovery timeline (4 events)" in out
    assert "checkpoint_commit" in out and "checkpoint_fallback" in out
    assert "collective_timeout" in out and "nonfinite_skip" in out
    assert "op=all_reduce" in out
    # non-recovery events stay out of the timeline section
    timeline = out.split("== Events")[0]
    assert "compile" not in timeline


def test_manager_sidecar_is_committed_and_covered(tmp_path):
    """The dataloader-position sidecar is inside the manifest's file
    set: a torn sidecar invalidates the checkpoint like any data file."""
    root = str(tmp_path / "ckpts")
    step = _build_step()
    step(*_batch(0))
    mgr = CheckpointManager(step, root, interval_steps=1,
                            asynchronous=False)
    try:
        path = mgr.save(dataloader_state={"epoch": 1, "offset": 17})
    finally:
        mgr.close()
    m = dckpt.read_manifest(path)
    assert "manager_state.json" in m["files"]
    with open(os.path.join(path, "manager_state.json")) as f:
        sidecar = json.load(f)
    assert sidecar["dataloader"] == {"epoch": 1, "offset": 17}
    # truncating the sidecar breaks verification
    with open(os.path.join(path, "manager_state.json"), "w") as f:
        f.write("{")
    assert "torn file" in verify_checkpoint(path)
