"""viterbi_decode golden tests + incubate LookAhead/ModelAverage
(reference: text/viterbi_decode.py, incubate/optimizer/)."""

import numpy as np

import paddle_tpu as paddle


def _brute_force(emit, trans, length, include):
    """Enumerate all tag paths (golden reference)."""
    import itertools
    C = emit.shape[-1]
    best, best_path = -1e30, None
    for path in itertools.product(range(C), repeat=length):
        s = emit[0, path[0]]
        if include:
            s += trans[C - 1, path[0]]
        for t in range(1, length):
            s += trans[path[t - 1], path[t]] + emit[t, path[t]]
        if include:
            s += trans[C - 2, path[length - 1]]
        if s > best:
            best, best_path = s, path
    return best, list(best_path)


def test_viterbi_matches_brute_force():
    rng = np.random.default_rng(0)
    B, L, C = 3, 5, 4
    emit = rng.normal(size=(B, L, C)).astype(np.float32)
    trans = rng.normal(size=(C, C)).astype(np.float32)
    lengths = np.array([5, 3, 1], np.int64)
    for include in (False, True):
        scores, paths = paddle.text.viterbi_decode(
            paddle.to_tensor(emit), paddle.to_tensor(trans),
            paddle.to_tensor(lengths), include_bos_eos_tag=include)
        for b in range(B):
            bs, bp = _brute_force(emit[b], trans, int(lengths[b]), include)
            assert abs(float(scores.numpy()[b]) - bs) < 1e-4, (b, include)
            got = paths.numpy()[b, :int(lengths[b])].tolist()
            assert got == bp, (b, include, got, bp)
            assert (paths.numpy()[b, int(lengths[b]):] == 0).all()


def test_viterbi_decoder_layer():
    rng = np.random.default_rng(1)
    emit = paddle.to_tensor(rng.normal(size=(2, 4, 3)).astype(np.float32))
    trans = paddle.to_tensor(rng.normal(size=(3, 3)).astype(np.float32))
    dec = paddle.text.ViterbiDecoder(trans, include_bos_eos_tag=False)
    scores, paths = dec(emit, paddle.to_tensor(np.array([4, 4], np.int64)))
    assert scores.shape == [2] and paths.shape == [2, 4]


def test_lookahead_slow_fast_blend():
    from paddle_tpu import nn
    paddle.seed(0)
    lin = nn.Linear(4, 4)
    w0 = np.asarray(lin.weight._data).copy()
    inner = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=lin.parameters())
    opt = paddle.incubate.LookAhead(inner, alpha=0.5, k=2)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    for step in range(2):
        loss = lin(x).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    # after k=2 steps: fast took 2 sgd steps, then w = w0 + 0.5*(fast-w0)
    fast = w0 - 0.1 * np.ones((4, 4)) * 2 * 2   # dL/dw = sum over batch(2)
    expect = w0 + 0.5 * (fast - w0)
    np.testing.assert_allclose(np.asarray(lin.weight._data), expect,
                               atol=1e-5)


def test_model_average_apply_restore():
    from paddle_tpu import nn
    paddle.seed(1)
    lin = nn.Linear(3, 3)
    ma = paddle.incubate.ModelAverage(0.5, parameters=lin.parameters(),
                                      min_average_window=2,
                                      max_average_window=100)
    vals = []
    for v in (1.0, 2.0, 3.0):
        lin.weight._data = np.full((3, 3), v, np.float32) * 1.0
        import jax.numpy as jnp
        lin.weight._data = jnp.asarray(lin.weight._data)
        ma.step()
        vals.append(v)
    cur = np.asarray(lin.weight._data).copy()
    with ma.apply():
        avg = np.asarray(lin.weight._data)
        np.testing.assert_allclose(avg, np.mean(vals), atol=1e-6)
    np.testing.assert_allclose(np.asarray(lin.weight._data), cur)
