"""Test configuration.

Force an 8-device virtual CPU mesh BEFORE jax initialises, per SURVEY.md §4's
test strategy: multi-device distributed tests run on one host (the analogue
of the reference's multi-process localhost tests, test_dist_base.py:778).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# The TPU-tunnel site customization force-selects its platform via
# jax.config (ignoring the JAX_PLATFORMS env var), so re-select CPU
# explicitly — tests need the virtual 8-device CPU mesh.
jax.config.update("jax_platforms", "cpu")

# NOTE: this JAX build lowers f32 matmuls to bf16 passes by default
# (TPU-style). Do NOT globally raise jax_default_matmul_precision here — on
# this CPU backend non-default precision makes conv compiles ~10x slower.
# Numeric-gradient checks raise precision locally (see op_test.check_grad).

# Persistent compilation cache: XLA:CPU compiles dominate suite runtime;
# warm runs hit disk instead of recompiling.
jax.config.update("jax_compilation_cache_dir", "/tmp/jax_test_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "chaos: drives the paddle_tpu.testing.chaos fault injector "
        "(injector state is reset around every test by the autouse "
        "_chaos_isolation fixture)")
    config.addinivalue_line(
        "markers",
        "serve: exercises the paddle_tpu.serving engine (engine global "
        "state — live engines, request-id counter — is reset around "
        "every test by the autouse _serving_isolation fixture)")
    config.addinivalue_line(
        "markers",
        "multichip: exercises DP×TP×PP programs over the 8-device "
        "virtual CPU mesh this conftest forces via "
        "--xla_force_host_platform_device_count (pipeline schedule "
        "stats are reset around every test by the autouse "
        "_pipeline_isolation fixture)")
    config.addinivalue_line(
        "markers",
        "pallas: runs ops.pallas kernel BODIES on the CPU test backend "
        "via the Pallas interpreter (the autouse _pallas_interpret "
        "fixture forces FLAGS_pallas_interpret for marked tests, so "
        "kernel dispatch serves the real kernels instead of the XLA "
        "fallbacks; fallback stats are reset around every test)")
    config.addinivalue_line(
        "markers",
        "recsys: exercises the paddle_tpu.recsys giant-embedding "
        "subsystem (tier caches, the table registry, tmp SSD log "
        "files and RECSYS_STATS are reset around every test by the "
        "autouse _recsys_isolation fixture)")
    config.addinivalue_line(
        "markers",
        "slow: excluded from the tier-1 wall-clock budget "
        "(`-m 'not slow'`); full bench legs and other multi-minute "
        "drills carry it")


@pytest.fixture(autouse=True)
def _pallas_interpret(request):
    """``pallas``-marked tests run the real kernel bodies on CPU through
    the Pallas interpreter (FLAGS_pallas_interpret); every test starts
    with clean fallback stats so kill-switch tests can assert on exactly
    the fallbacks they caused."""
    import sys
    if "paddle_tpu.ops.pallas" in sys.modules:
        sys.modules["paddle_tpu.ops.pallas"].reset_pallas_stats()
    if request.node.get_closest_marker("pallas"):
        from paddle_tpu.core.flags import flag_scope
        with flag_scope("pallas_interpret", True):
            yield
    else:
        yield


@pytest.fixture(autouse=True)
def _pipeline_isolation():
    """Pipeline-schedule telemetry (PIPELINE_STATS, the fallback
    warn-once set) must not leak between tests, so multichip tests can
    pin exact program-build/fallback counts."""
    import sys
    mod = sys.modules.get(
        "paddle_tpu.distributed.meta_parallel.spmd_pipeline")
    if mod is not None:
        mod.reset_pipeline_stats()
    yield
    mod = sys.modules.get(
        "paddle_tpu.distributed.meta_parallel.spmd_pipeline")
    if mod is not None:
        mod.reset_pipeline_stats()


@pytest.fixture(autouse=True)
def _chaos_isolation():
    """Chaos plans must never leak between tests: the injector is fully
    disarmed (and any chaos-hung worker threads cancelled) before AND
    after every test, whether or not the test is marked ``chaos``."""
    from paddle_tpu.testing import chaos
    chaos.reset()
    yield
    chaos.reset()


@pytest.fixture(autouse=True)
def _serving_isolation():
    """Serving-engine global state (live engines, the request-id
    counter, the scan-fallback warn-once set) must not leak between
    tests. Only touches paddle_tpu.serving when a test imported it."""
    import sys
    yield
    if "paddle_tpu.serving" in sys.modules:
        import paddle_tpu.serving as serving
        serving.reset()


@pytest.fixture(autouse=True)
def _recsys_isolation():
    """Recsys global state (the table registry — whose reset also
    closes tables owning tmp SSD log files — RECSYS_STATS, live
    serving-engine queues, the request-id counter) must not leak
    between tests. Only touches paddle_tpu.recsys when a test
    imported it."""
    import sys
    yield
    if "paddle_tpu.recsys" in sys.modules:
        import paddle_tpu.recsys as recsys
        recsys.reset()


@pytest.fixture(autouse=True)
def _admin_server_isolation():
    """The embedded admin HTTP server (monitor/server.py) must not
    leak threads/sockets or provider registrations between tests.
    Only touches the module when a test imported it."""
    import sys
    yield
    mod = sys.modules.get("paddle_tpu.monitor.server")
    if mod is not None:
        mod.stop_server()


@pytest.fixture(autouse=True)
def _fleet_monitor_isolation():
    """The fleet federator (monitor/fleet.py) must not leak its scrape
    thread, admin socket or registry between tests. Only touches the
    module when a test imported it."""
    import sys
    yield
    mod = sys.modules.get("paddle_tpu.monitor.fleet")
    if mod is not None:
        mod.stop_federator()


@pytest.fixture(autouse=True)
def _trace_isolation():
    """Structured-tracer state (retained ring, live traces, allocation
    probe) must not leak between tests — the zero-overhead pin reads
    the probe from a clean 0."""
    from paddle_tpu.monitor import trace as trace_mod
    yield
    if trace_mod._tracer is not None:
        trace_mod._tracer.reset()
    trace_mod.reset_trace_stats()


@pytest.fixture(autouse=True)
def _goodput_isolation():
    """Goodput-ledger module state (the process ledger, GOODPUT_STATS
    allocation probe, last layer-health vector, dump-provider
    registrations) must not leak between tests — the zero-overhead pin
    reads the probe from a clean 0. Only touches the module when a test
    imported it."""
    import sys
    yield
    mod = sys.modules.get("paddle_tpu.monitor.goodput")
    if mod is not None:
        mod.reset()


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    # Tests that fleet.init() / set_mesh() must not leak the global mesh into
    # later tests (sharding constraints would bind to a stale 8-way mesh).
    # Snapshot/restore keeps module-scoped mesh fixtures working.
    from paddle_tpu.distributed import env as dist_env
    snap = dict(dist_env._global)
    yield
    dist_env._global.update(snap)


@pytest.fixture(autouse=True)
def _flight_recorder_isolation(tmp_path):
    """Watchdog-trip flight-recorder dumps must land in the test's tmp
    dir (not the repo cwd), and recorder ring state must not leak
    between tests."""
    from paddle_tpu.core.flags import flag_scope
    from paddle_tpu.monitor import flight_recorder as fr
    old = fr.set_flight_recorder(None)
    with flag_scope("flight_recorder_dir", str(tmp_path)):
        yield
    current = fr.set_flight_recorder(old)
    if current is not None:
        current.uninstall()


@pytest.fixture(autouse=True)
def _fleet_isolation():
    """fleet state must not leak between tests: whatever a test does to
    the fleet globals (init, strategy attach) is rolled back to the
    pre-test snapshot, so outcomes are order-independent while
    module-scoped mesh fixtures (test_mp_layers) keep working."""
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import topology
    state_snap = dict(fleet._fleet_state)
    hcg_snap = topology.get_hybrid_communicate_group()
    yield
    fleet._fleet_state.update(state_snap)
    topology.set_hybrid_communicate_group(hcg_snap)
