"""GPT generation with static KV cache (models/generation.py)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny


def _model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


def test_greedy_matches_full_forward_rollout():
    model = _model()
    model.eval()
    rng = np.random.default_rng(0)
    prompt = rng.integers(2, 250, (2, 8)).astype(np.int32)
    out = model.generate(prompt, max_new_tokens=6,
                         decode_strategy="greedy_search").numpy()
    assert out.shape == (2, 14)
    np.testing.assert_array_equal(out[:, :8], prompt)
    # golden: re-derive every generated token by full (uncached) forwards
    from paddle_tpu.core.tensor import no_grad
    ids = prompt.copy()
    for t in range(6):
        with no_grad():
            logits = model(paddle.to_tensor(ids)).numpy()
        nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
        np.testing.assert_array_equal(out[:, 8 + t], nxt,
                                      err_msg=f"step {t}")
        ids = np.concatenate([ids, nxt[:, None]], axis=1)


def test_sampling_reproducible_and_in_range():
    model = _model()
    prompt = np.full((3, 4), 7, np.int32)
    a = model.generate(prompt, max_new_tokens=5, decode_strategy="sampling",
                       top_k=20, temperature=0.8, seed=3).numpy()
    b = model.generate(prompt, max_new_tokens=5, decode_strategy="sampling",
                       top_k=20, temperature=0.8, seed=3).numpy()
    np.testing.assert_array_equal(a, b)          # same seed, same output
    c = model.generate(prompt, max_new_tokens=5, decode_strategy="sampling",
                       top_k=20, temperature=0.8, seed=4).numpy()
    assert not np.array_equal(a, c)              # different seed differs
    assert a.min() >= 0 and a.max() < 256


def test_eos_padding():
    model = _model()
    prompt = np.full((2, 3), 5, np.int32)
    greedy = model.generate(prompt, max_new_tokens=8,
                            decode_strategy="greedy_search").numpy()
    # force eos = the first greedily generated token: everything after
    # must be pad (0)
    eos = int(greedy[0, 3])
    out = model.generate(prompt, max_new_tokens=8,
                         decode_strategy="greedy_search",
                         eos_token_id=eos, pad_token_id=0).numpy()
    row = out[0, 3:]
    assert row[0] == eos
    assert (row[1:] == 0).all()


def test_top_p_sampling_runs():
    model = _model()
    prompt = np.full((1, 4), 9, np.int32)
    out = model.generate(prompt, max_new_tokens=4,
                         decode_strategy="sampling", top_p=0.9,
                         seed=0).numpy()
    assert out.shape == (1, 8)
