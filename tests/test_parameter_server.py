"""Parameter-server (host sparse table) tests.

reference analogues: test_dist_fleet_ps*.py / the DownpourWorker
pull/push cycle — sparse rows update on push, untouched rows stay put,
and a model with a PS embedding trains end to end.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.distributed.ps import DistributedEmbedding, SparseTable


def test_pull_push_sgd_semantics():
    t = SparseTable(10, 4, optimizer="sgd", lr=0.5, seed=0)
    before = t.data.copy()
    rows = t.pull([2, 7])
    np.testing.assert_allclose(rows, before[[2, 7]])
    g = np.ones((2, 4), np.float32)
    t.push([2, 7], g)
    np.testing.assert_allclose(t.data[[2, 7]], before[[2, 7]] - 0.5)
    # untouched rows unchanged
    mask = np.ones(10, bool)
    mask[[2, 7]] = False
    np.testing.assert_allclose(t.data[mask], before[mask])


def test_push_accumulates_duplicate_ids():
    t = SparseTable(4, 2, optimizer="sgd", lr=1.0, seed=1)
    before = t.data.copy()
    t.push([1, 1], np.ones((2, 2), np.float32))
    np.testing.assert_allclose(t.data[1], before[1] - 2.0)


def test_sharded_routing():
    t0 = SparseTable(8, 2, shard_id=0, num_shards=2)
    t1 = SparseTable(8, 2, shard_id=1, num_shards=2)
    t0.pull([0, 2, 4])                      # even ids -> shard 0
    t1.pull([1, 3, 5])
    with pytest.raises(ValueError, match="wrong shard"):
        t0.pull([1])


def test_table_checkpoint_roundtrip():
    t = SparseTable(6, 3, optimizer="adagrad", seed=2)
    t.push([0, 1], np.ones((2, 3), np.float32))
    state = t.state_dict()
    t2 = SparseTable(6, 3, optimizer="adagrad", seed=99)
    t2.load_state_dict(state)
    np.testing.assert_allclose(t2.data, t.data)
    t.push([0], np.ones((1, 3), np.float32))
    t2.push([0], np.ones((1, 3), np.float32))
    np.testing.assert_allclose(t2.data, t.data)   # adagrad state restored


def test_distributed_embedding_trains():
    paddle.seed(3)
    V, D = 50, 8
    emb = DistributedEmbedding(V, D, optimizer="adagrad", lr=0.1)
    head = nn.Linear(D, 2)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=head.parameters())
    rng = np.random.RandomState(4)
    ids = rng.randint(0, V, (16,)).astype(np.int64)
    labels = (ids % 2).astype(np.int64)     # learnable from embedding id

    losses = []
    for _ in range(40):
        vecs = emb(paddle.to_tensor(ids))
        loss = F.cross_entropy(head(vecs), paddle.to_tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    assert emb.table.push_count >= 40       # grads really stream host-side
    # the table is NOT a dense parameter
    assert all("table" not in k for k, _ in emb.named_parameters())
