"""Text dataset parser tests over synthetic archives.

Analogue of the reference's dataset tests (reference:
tests/unittests/test_datasets.py) — but the archives are generated
in-test (no egress), exercising the same formats the reference downloads.
"""

import gzip
import io
import os
import tarfile
import zipfile

import numpy as np
import pytest

from paddle_tpu.text.datasets import (Imdb, Imikolov, Movielens, UCIHousing,
                                      WMT14, WMT16)


def _add_bytes(tf, name, data: bytes):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


def test_uci_housing(tmp_path):
    rows = np.random.RandomState(0).rand(50, 14).astype(np.float32)
    path = tmp_path / "housing.data"
    np.savetxt(path, rows)
    train = UCIHousing(data_file=str(path), mode="train")
    test = UCIHousing(data_file=str(path), mode="test")
    assert len(train) == 40 and len(test) == 10
    x, y = train[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_uci_housing_missing_file_raises():
    with pytest.raises(RuntimeError, match="no network egress"):
        UCIHousing(data_file=None, mode="train")


def test_imdb(tmp_path):
    arc = tmp_path / "aclImdb_v1.tar.gz"
    docs = {
        "aclImdb/train/pos/0.txt": b"a great great movie, great acting!",
        "aclImdb/train/neg/0.txt": b"a bad movie; bad bad plot.",
        "aclImdb/test/pos/0.txt": b"great fun",
        "aclImdb/test/neg/0.txt": b"bad fun",
    }
    with tarfile.open(arc, "w:gz") as tf:
        for name, data in docs.items():
            _add_bytes(tf, name, data)
    ds = Imdb(data_file=str(arc), mode="train", cutoff=1)
    # 'great' x5 and 'bad' x5 pass cutoff 1; 'a'/'movie' x2, 'fun' x2 too
    assert "great" in ds.word_idx and "bad" in ds.word_idx
    assert len(ds) == 2
    doc, label = ds[0]
    assert label[0] == 0                      # first docs are positive
    assert doc.dtype.kind == "i"


def test_imikolov(tmp_path):
    arc = tmp_path / "simple-examples.tgz"
    train = b"the cat sat\nthe dog sat\n"
    valid = b"the cat ran\n"
    with tarfile.open(arc, "w:gz") as tf:
        _add_bytes(tf, "./simple-examples/data/ptb.train.txt", train)
        _add_bytes(tf, "./simple-examples/data/ptb.valid.txt", valid)
    ds = Imikolov(data_file=str(arc), data_type="NGRAM", window_size=2,
                  mode="train", min_word_freq=0)
    assert len(ds) > 0
    gram = ds[0]
    assert len(gram) == 2
    seq = Imikolov(data_file=str(arc), data_type="SEQ", mode="test",
                   min_word_freq=0)
    assert len(seq) == 1                      # valid split has one line


def test_movielens(tmp_path):
    arc = tmp_path / "ml-1m.zip"
    with zipfile.ZipFile(arc, "w") as z:
        z.writestr("ml-1m/movies.dat",
                   "1::Toy Story (1995)::Animation|Comedy\n"
                   "2::Jumanji (1995)::Adventure\n")
        z.writestr("ml-1m/users.dat",
                   "1::F::1::10::48067\n2::M::25::16::70072\n")
        z.writestr("ml-1m/ratings.dat",
                   "1::1::5::978300760\n2::2::3::978302109\n"
                   "1::2::4::978301968\n")
    ds = Movielens(data_file=str(arc), mode="train", test_ratio=0.0)
    assert len(ds) == 3
    sample = ds[0]
    assert len(sample) == 8                   # 4 user + 3 movie + rating
    assert sample[-1].shape == (1,)


def test_wmt14(tmp_path):
    arc = tmp_path / "wmt14.tgz"
    src_dict = b"<s>\n<e>\n<unk>\nhello\nworld\n"
    trg_dict = b"<s>\n<e>\n<unk>\nbonjour\nmonde\n"
    pairs = b"hello world\tbonjour monde\nhello\tbonjour\n"
    with tarfile.open(arc, "w:gz") as tf:
        _add_bytes(tf, "wmt14/src.dict", src_dict)
        _add_bytes(tf, "wmt14/trg.dict", trg_dict)
        _add_bytes(tf, "wmt14/train/train", pairs)
    ds = WMT14(data_file=str(arc), mode="train", dict_size=5)
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    assert src[0] == ds.src_dict["<s>"] and src[-1] == ds.src_dict["<e>"]
    assert trg[0] == ds.trg_dict["<s>"]
    assert trg_next[-1] == ds.trg_dict["<e>"]


def test_conll05(tmp_path):
    from paddle_tpu.text.datasets import Conll05st
    words = b"The\ncat\nsat\n\n"
    # verb column + one proposition column (B-V on 'sat', A0 on 'The cat')
    props = b"-\t(A0*\n-\t*)\nsit\t(V*)\n\n"
    arc = tmp_path / "conll05st-tests.tar.gz"
    wbuf, pbuf = io.BytesIO(), io.BytesIO()
    with gzip.GzipFile(fileobj=wbuf, mode="w") as g:
        g.write(words)
    with gzip.GzipFile(fileobj=pbuf, mode="w") as g:
        g.write(props)
    with tarfile.open(arc, "w:gz") as tf:
        _add_bytes(tf, "conll05st-release/test.wsj/words/test.wsj.words.gz",
                   wbuf.getvalue())
        _add_bytes(tf, "conll05st-release/test.wsj/props/test.wsj.props.gz",
                   pbuf.getvalue())
    wd = tmp_path / "wordDict.txt"
    wd.write_text("The\ncat\nsat\n")
    vd = tmp_path / "verbDict.txt"
    vd.write_text("sit\n")
    td = tmp_path / "targetDict.txt"
    td.write_text("B-A0\nI-A0\nB-V\nI-V\nO\n")
    ds = Conll05st(data_file=str(arc), word_dict_file=str(wd),
                   verb_dict_file=str(vd), target_dict_file=str(td))
    assert len(ds) == 1
    fields = ds[0]
    assert len(fields) == 9
    word_idx, *ctx, pred, mark, labels = fields
    assert list(word_idx) == [0, 1, 2]
    assert list(mark) == [1, 1, 1]            # all within +-2 of the verb
    lbl_names = {v: k for k, v in ds.label_dict.items()}
    assert lbl_names[labels[2]] == "B-V"
    assert lbl_names[labels[0]] == "B-A0"
    assert lbl_names[labels[1]] == "I-A0"


def test_wmt16(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_DATA_HOME", str(tmp_path / "home"))
    import importlib

    import paddle_tpu.text.datasets._base as base
    importlib.reload(base)
    import paddle_tpu.text.datasets.wmt16 as wmt16_mod
    importlib.reload(wmt16_mod)

    arc = tmp_path / "wmt16.tar.gz"
    pairs = (b"a cat\teine katze\nthe dog\tder hund\n")
    with tarfile.open(arc, "w:gz") as tf:
        _add_bytes(tf, "wmt16/train", pairs)
        _add_bytes(tf, "wmt16/val", pairs)
        _add_bytes(tf, "wmt16/test", pairs)
    ds = wmt16_mod.WMT16(data_file=str(arc), mode="train", src_dict_size=10,
                         trg_dict_size=10, lang="en")
    assert len(ds) == 2
    src, trg, trg_next = ds[0]
    assert src[0] == 0 and src[-1] == 1       # <s> ... <e>
    d = ds.get_dict("en")
    assert "<unk>" in d
