"""Pallas kernel-layer parity + kill-switch tests (ISSUE 7).

Three kernels behind one dispatch convention (ops/pallas/__init__.py):
fused chunked-CE, paged flash-decode, int8 quantized matmul. Each is
pinned three ways here:

- PARITY: the kernel body (run on CPU via the interpreter — the
  ``pallas`` marker flips FLAGS_pallas_interpret) matches the reference
  math to the module's documented tolerances;
- KILL SWITCH: with the kernel's flag off, dispatch serves the XLA
  fallback and the numbers are bit-identical to the pre-kernel
  implementation;
- OBSERVABILITY: fallbacks land in PALLAS_STATS and (monitor mode) the
  ``pallas_fallback_total{kernel,reason}`` counter; ``kernels()``
  enumerates the layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import chunked_ce as cce
from paddle_tpu.ops import pallas as pallas_ops


# ---------------------------------------------------------------------------
# dispatch convention / registry
# ---------------------------------------------------------------------------


def test_kernel_registry_enumerates_the_layer():
    rows = {r["kernel"]: r for r in pallas_ops.kernels()}
    assert set(rows) == {"flash_attention", "chunked_ce", "paged_decode",
                         "int8_matmul", "bgmv"}
    assert rows["chunked_ce"]["flag"] == "FLAGS_pallas_ce"
    assert rows["paged_decode"]["flag"] == "FLAGS_pallas_paged_decode"
    assert rows["int8_matmul"]["flag"] == "FLAGS_pallas_int8"
    assert rows["bgmv"]["flag"] == "FLAGS_pallas_bgmv"
    # CPU backend without the interpreter: nothing is live
    assert not any(r["live"] for r in rows.values())
    for r in rows.values():
        assert r["fallback"]            # every kernel names its fallback


@pytest.mark.pallas
def test_kernel_registry_live_under_interpret():
    rows = {r["kernel"]: r for r in pallas_ops.kernels()}
    assert rows["chunked_ce"]["live"]
    assert rows["paged_decode"]["live"]
    assert rows["int8_matmul"]["live"]
    with flag_scope("pallas_ce", False):
        rows = {r["kernel"]: r for r in pallas_ops.kernels()}
        assert not rows["chunked_ce"]["live"]
        assert rows["chunked_ce"]["flag_value"] is False


def test_fallbacks_counted_in_stats_and_registry():
    from paddle_tpu.monitor import scoped_registry
    with scoped_registry() as reg, flag_scope("monitor", True):
        assert not pallas_ops.kernel_enabled("chunked_ce")  # CPU backend
        with flag_scope("pallas_interpret", True), \
                flag_scope("pallas_int8", False):
            assert not pallas_ops.kernel_enabled("int8_matmul")
    assert pallas_ops.PALLAS_STATS[("chunked_ce", "cpu_backend")] == 1
    assert pallas_ops.PALLAS_STATS[("int8_matmul", "flag_off")] == 1
    c = reg.counter("pallas_fallback_total")
    assert c.value(kernel="chunked_ce", reason="cpu_backend") == 1
    assert c.value(kernel="int8_matmul", reason="flag_off") == 1
    # kernels() surfaces the observed fallbacks without inflating them
    rows = {r["kernel"]: r for r in pallas_ops.kernels()}
    assert rows["chunked_ce"]["fallbacks_seen"] == {"cpu_backend": 1}
    assert pallas_ops.PALLAS_STATS[("chunked_ce", "cpu_backend")] == 1


def test_monitor_report_kernels_mode(capsys):
    """tools/monitor_report.py --kernels renders the live inventory."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "..", "tools"))
    import monitor_report
    pallas_ops.note_fallback("chunked_ce", "cpu_backend")
    assert monitor_report.main(["--kernels"]) == 0
    out = capsys.readouterr().out
    assert "ops.pallas kernel layer" in out
    for name in ("flash_attention", "chunked_ce", "paged_decode",
                 "int8_matmul"):
        assert name in out
    assert "FLAGS_pallas_ce=on" in out
    assert "cpu_backend:1" in out


# ---------------------------------------------------------------------------
# fused chunked-CE
# ---------------------------------------------------------------------------


def _dense_nll(lg, lab):
    lg32 = lg.astype(jnp.float32)
    return (jax.nn.logsumexp(lg32, -1)
            - jnp.take_along_axis(lg32, lab[:, None], 1)[:, 0])


@pytest.mark.pallas
@pytest.mark.parametrize("N,V,chunk", [(8, 50, 16), (4, 5, 8),
                                       (24, 129, 64), (7, 256, 256)])
def test_ce_kernel_parity_fwd_bwd(N, V, chunk):
    rng = np.random.RandomState(0)
    lg = jnp.asarray((rng.randn(N, V) * 3).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, V, (N,)).astype(np.int32))
    assert pallas_ops.kernel_enabled("chunked_ce", note=False)
    got = cce.hard_nll(lg, lab, chunk=chunk)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_dense_nll(lg, lab)),
                               rtol=1e-6, atol=1e-6)
    g_ref = jax.grad(lambda l: _dense_nll(l, lab).sum())(lg)
    g_got = jax.grad(lambda l: cce.hard_nll(l, lab, chunk=chunk).sum())(lg)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.pallas
def test_ce_kernel_bf16_f32_accumulation():
    rng = np.random.RandomState(1)
    lg = jnp.asarray(rng.randn(6, 40).astype(np.float32)) \
        .astype(jnp.bfloat16)
    lab = jnp.asarray(rng.randint(0, 40, (6,)).astype(np.int32))
    got = jax.jit(lambda l: cce.hard_nll(l, lab, chunk=16))(lg)
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_dense_nll(lg, lab)),
                               rtol=2e-2, atol=1e-2)
    g = jax.grad(lambda l: cce.hard_nll(l, lab, chunk=16).sum())(lg)
    assert g.dtype == jnp.bfloat16


@pytest.mark.pallas
def test_ce_kernel_through_cross_entropy_epilogue():
    """F.cross_entropy keeps ignore_index / class weights / reduction in
    the epilogue OUTSIDE the kernel — parity vs the dense path."""
    rng = np.random.RandomState(2)
    logits_np = (rng.randn(8, 50) * 2).astype(np.float32)
    labels_np = rng.randint(0, 50, (8,)).astype(np.int64)
    labels_np[2] = -100
    w_np = rng.uniform(0.2, 2.0, (50,)).astype(np.float32)
    with flag_scope("chunked_ce_threshold", 8), \
            flag_scope("chunked_ce_chunk", 16):
        x1 = Tensor(logits_np)
        x1.stop_gradient = False
        l1 = F.cross_entropy(x1, Tensor(labels_np), weight=Tensor(w_np))
    with flag_scope("chunked_ce_threshold", 0):
        x2 = Tensor(logits_np)
        x2.stop_gradient = False
        l2 = F.cross_entropy(x2, Tensor(labels_np), weight=Tensor(w_np))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    l1.backward()
    l2.backward()
    np.testing.assert_allclose(np.asarray(x1.grad._data),
                               np.asarray(x2.grad._data),
                               rtol=1e-5, atol=1e-7)
    assert np.abs(np.asarray(x1.grad._data)[2]).max() == 0.0


@pytest.mark.pallas
def test_ce_kill_switch_is_bit_identical_to_pre_kernel_path():
    """FLAGS_pallas_ce off routes hard_nll to the XLA streaming op —
    the EXACT pre-kernel implementation (same function), so fallback
    outputs and gradients are bitwise equal to it."""
    rng = np.random.RandomState(3)
    lg = jnp.asarray(rng.randn(6, 50).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, 50, (6,)).astype(np.int32))
    with flag_scope("pallas_ce", False):
        off = cce.hard_nll(lg, lab, chunk=16)
        g_off = jax.grad(lambda l: cce.hard_nll(l, lab, chunk=16).sum())(lg)
    direct = cce._ce_hard(16, lg, lab)
    g_direct = jax.grad(lambda l: cce._ce_hard(16, l, lab).sum())(lg)
    np.testing.assert_array_equal(np.asarray(off), np.asarray(direct))
    np.testing.assert_array_equal(np.asarray(g_off), np.asarray(g_direct))
    assert ("chunked_ce", "flag_off") in pallas_ops.PALLAS_STATS
    # and the kernel path agrees with the fallback to streaming-CE tol
    on = cce.hard_nll(lg, lab, chunk=16)
    np.testing.assert_allclose(np.asarray(on), np.asarray(off),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.pallas
def test_ce_kill_switch_not_defeated_by_eager_op_cache():
    """The Pallas dispatch outcome rides F.cross_entropy's eager-jit
    cache token: flipping FLAGS_pallas_ce between same-signature calls
    must re-dispatch (serving the fallback), not replay the cached
    kernel trace."""
    rng = np.random.RandomState(7)
    logits_np = (rng.randn(8, 64) * 2).astype(np.float32)
    labels_np = rng.randint(0, 64, (8,)).astype(np.int64)
    with flag_scope("chunked_ce_threshold", 8), \
            flag_scope("chunked_ce_chunk", 16):
        x1 = Tensor(logits_np)
        x1.stop_gradient = False
        l_on = F.cross_entropy(x1, Tensor(labels_np))
        with flag_scope("pallas_ce", False):
            x2 = Tensor(logits_np)
            x2.stop_gradient = False
            l_off = F.cross_entropy(x2, Tensor(labels_np))
    # the off-call really took the fallback path (a stale cached kernel
    # trace would never note the flag_off fallback)
    assert ("chunked_ce", "flag_off") in pallas_ops.PALLAS_STATS
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-6)


@pytest.mark.pallas
def test_ce_block_env_override_validated():
    import os
    os.environ["PTPU_CE_BLOCK_N"] = "bogus"
    try:
        with pytest.raises(ValueError, match="PTPU_CE_BLOCK_N"):
            cce.hard_nll(jnp.zeros((4, 32)), jnp.zeros((4,), jnp.int32),
                         chunk=16)
    finally:
        del os.environ["PTPU_CE_BLOCK_N"]


# ---------------------------------------------------------------------------
# paged flash-decode
# ---------------------------------------------------------------------------


def _paged_state(rng, B=3, MB=4, bs=4, H=2, D=8, P=10):
    """Pools + tables + positions with slots at different fill levels,
    written through the production write_pages path."""
    from paddle_tpu.serving.kv_cache import write_pages
    kp = jnp.zeros((P, bs, H, D), jnp.float32)
    vp = jnp.zeros((P, bs, H, D), jnp.float32)
    tbl = np.zeros((B, MB), np.int32)
    tbl[0, :3] = [1, 2, 3]
    tbl[1, :1] = [4]
    tbl[2, :4] = [6, 7, 8, 9]
    tbl = jnp.asarray(tbl)
    pos = jnp.asarray(np.array([9, 2, 14], np.int32))
    for b in range(B):
        n = int(pos[b]) + 1
        kp = write_pages(kp, jnp.asarray(
            rng.randn(1, n, H, D).astype(np.float32)),
            tbl[b:b + 1], jnp.zeros((1,), jnp.int32))
        vp = write_pages(vp, jnp.asarray(
            rng.randn(1, n, H, D).astype(np.float32)),
            tbl[b:b + 1], jnp.zeros((1,), jnp.int32))
    return kp, vp, tbl, pos


def _dense_decode_ref(q, kp, vp, tbl, pos, scale):
    """The XLA fallback's math: gather_pages + masked softmax."""
    from paddle_tpu.serving.kv_cache import gather_pages
    gk, gv = gather_pages(kp, tbl), gather_pages(vp, tbl)
    cols = jnp.arange(gk.shape[1])
    mask = jnp.where(cols[None, :] <= pos[:, None], 0.0, -1e30)
    s = jnp.einsum("bhd,bkhd->bhk", q, gk) * scale + mask[:, None, :]
    pr = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhk,bkhd->bhd", pr, gv)


@pytest.mark.pallas
def test_paged_decode_kernel_parity():
    from paddle_tpu.ops.pallas.paged_decode import paged_decode_attention
    rng = np.random.RandomState(0)
    kp, vp, tbl, pos = _paged_state(rng)
    q = jnp.asarray(rng.randn(3, 2, 8).astype(np.float32))
    scale = 1.0 / np.sqrt(8)
    ref = _dense_decode_ref(q, kp, vp, tbl, pos, scale)
    got = paged_decode_attention(q, kp, vp, tbl, pos, scale=scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # under jit (the serving decode program wraps it)
    got_j = jax.jit(lambda *a: paged_decode_attention(
        *a, scale=scale))(q, kp, vp, tbl, pos)
    np.testing.assert_allclose(np.asarray(got_j), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def _gpt_paged_decode_logits(pallas_on, scan_on=True):
    """One prefill + one batched decode step through GPTModel over the
    paged cache; returns the decode-step hidden states."""
    from paddle_tpu.models.gpt import GPTModel, gpt_tiny
    from paddle_tpu.serving.kv_cache import PagedCacheView, PagedKVCache
    paddle.seed(11)
    cfg = gpt_tiny()
    m = GPTModel(cfg)
    m.eval()
    cache = PagedKVCache(cfg.num_layers, cfg.num_heads, cfg.head_dim,
                         num_pages=10, block_size=4, max_slots=2,
                         max_blocks_per_slot=4)
    assert cache.alloc_slot(0, 7) and cache.alloc_slot(1, 4)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, cfg.vocab_size, (1, n)).astype(np.int32)
               for n in (6, 3)]
    ctx = flag_scope("pallas_paged_decode", pallas_on)
    with ctx, flag_scope("scan_decode", scan_on), paddle.no_grad():
        for slot, ids in enumerate(prompts):
            view = PagedCacheView(cache.k, cache.v,
                                  cache.table_array([slot]))
            _, nc = m(paddle.to_tensor(ids), caches=view,
                      cache_pos=paddle.to_tensor(np.zeros(1, np.int32)))
            cache.update(nc.k._data, nc.v._data)
        dec = rng.randint(0, cfg.vocab_size, (2, 1)).astype(np.int32)
        view = PagedCacheView(cache.k, cache.v, cache.table_array([0, 1]))
        hd, _ = m(paddle.to_tensor(dec), caches=view,
                  cache_pos=paddle.to_tensor(np.array([6, 3], np.int32)))
    return np.asarray(hd._data)


@pytest.mark.pallas
@pytest.mark.serve
def test_paged_decode_token_exact_in_gpt_model():
    """Decode through the full GPT paged path (scan layout): kernel-on
    states match the dense fallback to float tolerance and the greedy
    token choice is EXACT."""
    h_off = _gpt_paged_decode_logits(pallas_on=False)
    h_on = _gpt_paged_decode_logits(pallas_on=True)
    np.testing.assert_allclose(h_on, h_off, rtol=1e-5, atol=1e-5)
    assert (h_on.argmax(-1) == h_off.argmax(-1)).all()


@pytest.mark.pallas
@pytest.mark.serve
def test_paged_decode_kill_switch_loop_layout():
    """Kill switch off + loop layout = the pre-kernel gather+SDPA path;
    kernel-on loop layout agrees with it."""
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)  # scan fallback
        h_off = _gpt_paged_decode_logits(pallas_on=False, scan_on=False)
        h_on = _gpt_paged_decode_logits(pallas_on=True, scan_on=False)
    assert ("paged_decode", "flag_off") in pallas_ops.PALLAS_STATS
    np.testing.assert_allclose(h_on, h_off, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# int8 quantized matmul
# ---------------------------------------------------------------------------


@pytest.mark.pallas
def test_int8_matmul_exact_vs_int_reference():
    """The kernel's integer arithmetic is EXACT: int8 x int8 -> int32
    matches the XLA int dot bit for bit; only the one f32 epilogue
    multiply separates it from the closed form."""
    from paddle_tpu.ops.pallas.quant_matmul import (
        int8_matmul, quantize_per_channel, quantize_per_tensor)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(20, 256).astype(np.float32))
    w = jnp.asarray((rng.randn(256, 128) * 0.05).astype(np.float32))
    w_q, w_s = quantize_per_channel(w)
    x_q, a_s = quantize_per_tensor(x)
    got = int8_matmul(x_q, w_q, w_s, a_s)
    ref = (jnp.matmul(x_q.astype(jnp.int32), w_q.astype(jnp.int32))
           .astype(jnp.float32) * (a_s * w_s)[None, :])
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.pallas
def test_int8_matmul_within_quantization_error_of_f32():
    from paddle_tpu.ops.pallas.quant_matmul import int8_linear
    from paddle_tpu.ops.pallas.quant_matmul import quantize_per_channel
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(2, 5, 256).astype(np.float32))
    w = jnp.asarray((rng.randn(256, 128) * 0.05).astype(np.float32))
    b = jnp.asarray(rng.randn(128).astype(np.float32))
    w_q, w_s = quantize_per_channel(w)
    y = int8_linear(x, w_q, w_s, bias=b)
    ref = jnp.matmul(x, w) + b
    rel = (np.abs(np.asarray(y) - np.asarray(ref)).max()
           / np.abs(np.asarray(ref)).max())
    assert rel < 0.06, rel


@pytest.mark.pallas
def test_quantized_linear_keeps_weights_int8_through_matmul():
    """slim.QuantizedLinear + FLAGS_pallas_int8: the gemm consumes the
    int8 weights directly (W8A8-dynamic), within quantization error of
    the f32 linear; the static-act mode matches the XLA int8 dot."""
    from paddle_tpu import slim
    from paddle_tpu.nn import Linear
    paddle.seed(0)
    lin = Linear(256, 128)
    x = paddle.to_tensor(
        np.random.RandomState(2).randn(4, 256).astype(np.float32))
    ref = lin(x).numpy()
    q = slim.QuantizedLinear.from_linear(lin)
    assert q.weight_q.numpy().dtype == np.int8
    out = q(x).numpy()
    rel = np.abs(out - ref).max() / np.abs(ref).max()
    assert rel < 0.05, rel
    # static calibrated act_scale: kernel == the XLA int8 dot fallback
    a_s = float(np.abs(x.numpy()).max() / 127.0)
    q2 = slim.QuantizedLinear.from_linear(lin, act_scale=a_s)
    out_k = q2(x).numpy()
    with flag_scope("pallas_int8", False):
        out_x = q2(x).numpy()
    np.testing.assert_allclose(out_k, out_x, rtol=1e-5, atol=1e-5)


def test_quantized_linear_kill_switch_bit_identical():
    """Flag off (or a CPU backend without the interpreter — the tier-1
    default) = the pre-kernel dequantize-to-float matmul, bit for bit."""
    from paddle_tpu import slim
    from paddle_tpu.nn import Linear
    paddle.seed(1)
    lin = Linear(64, 48)        # not 128-aligned: kernel-ineligible too
    x = paddle.to_tensor(
        np.random.RandomState(3).randn(4, 64).astype(np.float32))
    q = slim.QuantizedLinear.from_linear(lin)
    out = q(x).numpy()
    wq = q.weight_q.numpy()
    s = q.scale.numpy()
    pre_pr = (x.numpy() @ (wq.astype(np.float32) * s)
              + lin.bias.numpy())
    np.testing.assert_allclose(out, pre_pr, rtol=1e-6, atol=1e-6)


@pytest.mark.pallas
def test_int8_shape_fallback_counted():
    from paddle_tpu import slim
    from paddle_tpu.nn import Linear
    paddle.seed(2)
    lin = Linear(100, 48)       # K, N not 128-aligned
    x = paddle.to_tensor(np.ones((2, 100), np.float32))
    slim.QuantizedLinear.from_linear(lin)(x)
    assert ("int8_matmul", "shape") in pallas_ops.PALLAS_STATS


def test_observer_is_the_one_scale_rule():
    """nn.quant.PerChannelAbsMaxObserver == slim._channel_scales ==
    ops.pallas.quantize_per_channel: one quantization grid everywhere."""
    from paddle_tpu import slim
    from paddle_tpu.nn.quant import PerChannelAbsMaxObserver
    from paddle_tpu.ops.pallas.quant_matmul import quantize_per_channel
    rng = np.random.RandomState(4)
    w = (rng.randn(64, 32) * 0.1).astype(np.float32)
    obs = PerChannelAbsMaxObserver(quant_bits=8, quant_axis=1)
    s_obs = obs.observe(w)
    np.testing.assert_allclose(s_obs, slim._channel_scales(w), rtol=1e-7)
    q_k, s_k = quantize_per_channel(jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(s_k), s_obs, rtol=1e-6)
    q_obs, _ = obs.quantize(w)
    np.testing.assert_array_equal(np.asarray(q_k), q_obs)
    # running-absmax accumulation across observe() calls
    s2 = obs.observe(w * 0.5)
    np.testing.assert_allclose(s2, s_obs, rtol=1e-6)


@pytest.mark.pallas
def test_amp_int8_linear_flag_gated():
    """FLAGS_amp_int8_matmul routes eligible F.linear calls under
    autocast through the int8 kernel; the backward is the
    straight-through dense pair, so gradients equal the f32 linear's."""
    from paddle_tpu import amp
    from paddle_tpu.nn import Linear
    paddle.seed(3)
    lin = Linear(128, 128)
    x_np = np.random.RandomState(5).randn(4, 128).astype(np.float32)
    ref = F.linear(paddle.to_tensor(x_np), lin.weight, lin.bias).numpy()

    x1 = paddle.to_tensor(x_np)
    x1.stop_gradient = False
    with flag_scope("amp_int8_matmul", True), \
            amp.auto_cast(level="O1", dtype="float32"):
        y = F.linear(x1, lin.weight, lin.bias)
    rel = np.abs(y.numpy() - ref).max() / np.abs(ref).max()
    assert rel < 0.06, rel
    assert not np.allclose(y.numpy(), ref, atol=1e-7)   # int8 really ran
    y.sum().backward()
    x2 = paddle.to_tensor(x_np)
    x2.stop_gradient = False
    F.linear(x2, lin.weight, lin.bias).sum().backward()
    np.testing.assert_allclose(x1.grad.numpy(), x2.grad.numpy(),
                               rtol=1e-4, atol=1e-5)
    # without the flag: the plain matmul path, bit-identical to ref
    with amp.auto_cast(level="O1", dtype="float32"):
        y_off = F.linear(paddle.to_tensor(x_np), lin.weight, lin.bias)
    np.testing.assert_array_equal(y_off.numpy(), ref)


# ---------------------------------------------------------------------------
# bench record gating
# ---------------------------------------------------------------------------


def test_bench_kernels_metrics_are_gated_by_check_bench():
    """kernel_*_ms lines gate as lower-is-better, kernel_*_gbps as
    higher-is-better — the BENCH_kernels.json self-gate contract."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                    "..", "tools"))
    import check_bench  # noqa: E402
    old = [
        {"metric": "kernel_ce_fused_ms", "value": 10.0, "unit": "ms"},
        {"metric": "kernel_ce_fused_gbps", "value": 50.0, "unit": "GB/s"},
        {"metric": "kernel_paged_decode_ms", "value": 5.0, "unit": "ms"},
    ]
    new_ok = [
        {"metric": "kernel_ce_fused_ms", "value": 10.5, "unit": "ms"},
        {"metric": "kernel_ce_fused_gbps", "value": 48.0, "unit": "GB/s"},
        {"metric": "kernel_paged_decode_ms", "value": 5.1, "unit": "ms"},
    ]
    assert check_bench.compare_common(old, new_ok) == []
    new_bad = [
        {"metric": "kernel_ce_fused_ms", "value": 14.0, "unit": "ms"},
        {"metric": "kernel_ce_fused_gbps", "value": 30.0, "unit": "GB/s"},
    ]
    problems = check_bench.compare_common(old, new_bad)
    assert len(problems) == 2
    assert any("kernel_ce_fused_ms" in p for p in problems)
    assert any("kernel_ce_fused_gbps" in p for p in problems)
