"""MoE tests: gating invariants, layer training, expert-parallel routing.

reference analogue: test_collective_global_scatter/gather.py exercise the
primitives; the MoELayer (GShard dispatch/combine) goes beyond the
reference's op-only surface, so its gold standard is internal invariants
+ convergence.
"""

import jax
from paddle_tpu.distributed.env import shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.incubate.moe import (MoELayer, global_gather, global_scatter,
                                     top2_gating)


def test_top2_gating_invariants():
    rng = np.random.RandomState(0)
    S, E, C = 32, 4, 16
    logits = jnp.asarray(rng.randn(S, E).astype(np.float32))
    combine, dispatch, aux = top2_gating(logits, C)
    assert combine.shape == (S, E, C) and dispatch.shape == (S, E, C)
    # each token sends weight to at most 2 (expert, slot) pairs, weights
    # normalized to <= 1
    per_token = np.asarray((dispatch.sum(axis=(1, 2))))
    assert (per_token <= 2).all() and (per_token >= 1).all()
    w = np.asarray(combine.sum(axis=(1, 2)))
    np.testing.assert_allclose(w[per_token > 0], 1.0, rtol=1e-5)
    # capacity respected: each (expert, slot) receives at most one token
    slot_load = np.asarray(dispatch.sum(axis=0))
    assert (slot_load <= 1.0 + 1e-6).all()
    assert float(aux) > 0


def test_capacity_overflow_drops_tokens():
    S, E, C = 16, 2, 2                      # tiny capacity: must overflow
    logits = jnp.zeros((S, E), jnp.float32).at[:, 0].set(5.0)
    combine, dispatch, aux = top2_gating(logits, C)
    # expert 0 can hold only C tokens in slot dim
    assert float(dispatch[:, 0].sum()) <= C + 1e-6


def test_moe_layer_trains():
    paddle.seed(0)
    D, E = 16, 4
    experts = [nn.Sequential(nn.Linear(D, 32), nn.ReLU(), nn.Linear(32, D))
               for _ in range(E)]
    moe = MoELayer(D, experts, capacity_factor=2.0)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=moe.parameters())
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(2, 8, D).astype(np.float32))
    target = paddle.to_tensor((rng.randn(2, 8, D) * 0.1).astype(np.float32))
    losses = []
    for _ in range(30):
        out = moe(x)
        loss = F.mse_loss(out, target) + 0.01 * moe.aux_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # gate receives gradients (routing is learned): one more backward
    # without clear_grad
    loss = F.mse_loss(moe(x), target) + 0.01 * moe.aux_loss
    loss.backward()
    assert moe.gate.weight.grad is not None
    assert float(np.abs(np.asarray(moe.gate.weight.grad._data)).sum()) > 0


def test_moe_under_jit_trainstep():
    from paddle_tpu.jit.to_static import TrainStep

    paddle.seed(2)
    D, E = 8, 2
    experts = [nn.Linear(D, D) for _ in range(E)]
    moe = MoELayer(D, experts)

    def loss_fn(layer, x, y):
        out = layer(x)
        return F.mse_loss(out, y) + 0.01 * layer.aux_loss

    step = TrainStep(moe, loss_fn,
                     paddle.optimizer.Adam(learning_rate=1e-2,
                                           parameters=moe.parameters()))
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, D).astype(np.float32)
    y = (rng.randn(2, 4, D) * 0.1).astype(np.float32)
    losses = [float(step(x, y)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_stacked_experts_shard_over_ep_axis():
    from jax.sharding import NamedSharding
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.distributed.spmd import make_mesh
    from paddle_tpu.incubate.moe import ExpertFFN
    from paddle_tpu.jit.to_static import TrainStep

    paddle.seed(5)
    D, E, Hd = 8, 4, 16
    moe = MoELayer(D, num_experts=E, d_hidden=Hd)
    mesh = make_mesh({"dp": 2, "ep": 4})
    dist_env.set_mesh(mesh)

    def loss_fn(layer, x, y):
        return F.mse_loss(layer(x), y) + 0.01 * layer.aux_loss

    step = TrainStep(moe, loss_fn,
                     paddle.optimizer.Adam(learning_rate=1e-2,
                                           parameters=moe.parameters()),
                     mesh=mesh, data_spec=P("dp"))
    # expert weights really sharded one-expert-per-ep-slice
    w1 = step.params["experts.w1"]
    assert {s.data.shape for s in w1.addressable_shards} == {(1, D, Hd)}
    rng = np.random.RandomState(6)
    x = rng.randn(4, 8, D).astype(np.float32)
    y = (rng.randn(4, 8, D) * 0.1).astype(np.float32)
    losses = [float(step(x, y)) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_global_scatter_gather_roundtrip():
    # explicit expert-parallel routing over the ep axis (8 ranks)
    N = 8
    mesh = Mesh(np.array(jax.devices()[:N]), ("ep",))
    rows = 16                                 # per-rank rows, N | rows
    x = jnp.arange(N * rows * 4, dtype=jnp.float32) \
        .reshape(N * rows, 4)
    spec = P("ep")

    def body(xs):
        sent = global_scatter(xs, None, None)
        back = global_gather(sent, None, None)
        return back

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                                out_specs=spec))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


# ---------------------------------------------------------------------------
# ISSUE 10: sort-based dispatch, router, expert parallelism
# ---------------------------------------------------------------------------

from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import no_grad
from paddle_tpu.distributed import collective as C, env as dist_env
from paddle_tpu.distributed.spmd import make_mesh
from paddle_tpu.incubate.moe import (MOE_STATS, Routing, einsum_combine,
                                     einsum_dispatch, moe_capacity,
                                     reset_moe_stats, sort_combine,
                                     sort_dispatch, topk_routing)
from paddle_tpu.testing import chaos


@pytest.fixture(autouse=True)
def _moe_isolation():
    reset_moe_stats()
    yield
    reset_moe_stats()
    dist_env.reset()


def _train_once(mode, top_k, cf, dtype_bf16=False, seed=0):
    """One fwd+bwd of an 8-expert MoELayer under the given dispatch mode;
    returns (out, gate_grad, w1_grad, stats)."""
    paddle.seed(seed)
    D, E = 16, 8
    moe = MoELayer(D, num_experts=E, d_hidden=32, top_k=top_k,
                   capacity_factor=cf)
    rng = np.random.RandomState(1)
    x = rng.randn(2, 16, D).astype(np.float32)
    t = paddle.to_tensor(x)
    t.stop_gradient = False
    with flag_scope("moe_dispatch", mode):
        if dtype_bf16:
            with paddle.amp.auto_cast(level="O1"):
                out = moe(t)
        else:
            out = moe(t)
        loss = (F.mse_loss(out.astype("float32"),
                           paddle.to_tensor(np.zeros_like(x)))
                + 0.01 * moe.aux_loss + 1e-3 * moe.z_loss)
        loss.backward()
    return (np.asarray(out._data, dtype=np.float32),
            np.asarray(moe.gate.weight.grad._data),
            np.asarray(moe.experts.w1.grad._data),
            np.asarray(moe.router_stats._data))


@pytest.mark.parametrize("top_k", [1, 2])
@pytest.mark.parametrize("cf", [0.5, 2.0])
def test_sort_einsum_parity_fwd_and_grads(top_k, cf):
    """The parity sweep (ISSUE 10 acceptance): sort-vs-einsum dispatch
    agree on forward outputs AND gradients across top_k and capacity
    factors including the overflow-drop regime (cf=0.5 drops ~half the
    assignments — both paths share one router, so drop decisions are
    identical and stats match exactly)."""
    o_e, g_e, w_e, s_e = _train_once("einsum", top_k, cf)
    o_s, g_s, w_s, s_s = _train_once("sort", top_k, cf)
    np.testing.assert_allclose(o_s, o_e, rtol=0, atol=1e-7)
    np.testing.assert_allclose(g_s, g_e, rtol=0, atol=1e-8)
    np.testing.assert_allclose(w_s, w_e, rtol=0, atol=1e-9)
    np.testing.assert_array_equal(s_s, s_e)     # same drops, same loads
    if cf == 0.5:
        assert s_e[0] > 0.1                      # overflow really dropped


def test_bf16_stream_keeps_f32_router_and_parity():
    """bf16 activation stream (AMP O1): the router runs in f32 (logits
    dtype pinned) and the two dispatch paths still agree within bf16
    rounding."""
    paddle.seed(3)
    D, E = 16, 4
    moe = MoELayer(D, num_experts=E, d_hidden=32)
    x = paddle.to_tensor(np.random.RandomState(0)
                         .randn(2, 8, D).astype(np.float32))
    with paddle.amp.auto_cast(level="O1"):
        moe(x)
    logits = moe._router_logits(
        paddle.to_tensor(np.random.RandomState(0)
                         .randn(16, D).astype(np.float32)))
    assert str(logits._data.dtype) == "float32"
    o_e, g_e, _, _ = _train_once("einsum", 2, 1.0, dtype_bf16=True)
    o_s, g_s, _, _ = _train_once("sort", 2, 1.0, dtype_bf16=True)
    np.testing.assert_allclose(o_s, o_e, rtol=1e-2, atol=1e-2)
    np.testing.assert_allclose(g_s, g_e, rtol=1e-2, atol=1e-3)


def test_dispatch_kill_switch_restores_einsum_bit_for_bit():
    """FLAGS_moe_dispatch=einsum must route through the einsum oracle
    exactly: the layer's output equals a hand-built einsum
    dispatch->expert->combine over the same routing, bitwise."""
    paddle.seed(5)
    D, E, k = 8, 4, 2
    moe = MoELayer(D, num_experts=E, d_hidden=16, top_k=k)
    rng = np.random.RandomState(2)
    x = rng.randn(1, 8, D).astype(np.float32)
    T = 8
    C = moe_capacity(T, moe.capacity_factor, E)
    with flag_scope("moe_dispatch", "einsum"), no_grad():
        out = np.asarray(moe(paddle.to_tensor(x))._data)
    # oracle recomputation over raw arrays
    import jax.numpy as jnp
    flat = jnp.asarray(x.reshape(T, D))
    logits = flat @ jnp.asarray(moe.gate.weight._data)
    r = topk_routing(logits, k, C)
    ein = einsum_dispatch(flat, r, E, C)
    from paddle_tpu.incubate.moe import expert_ffn_apply
    eo = expert_ffn_apply(ein, moe.experts.w1._data, moe.experts.b1._data,
                          moe.experts.w2._data, moe.experts.b2._data)
    ref = np.asarray(einsum_combine(eo, r, C)).reshape(1, 8, D)
    np.testing.assert_array_equal(out, ref)
    assert MOE_STATS["einsum_dispatches"] >= 1


def test_router_z_loss_and_stats_vector():
    paddle.seed(7)
    moe = MoELayer(8, num_experts=4, d_hidden=16)
    x = paddle.to_tensor(np.random.RandomState(3)
                         .randn(2, 8, 8).astype(np.float32))
    with no_grad():
        moe(x)
    assert float(moe.z_loss) > 0
    s = np.asarray(moe.router_stats._data)
    E = 4
    assert s.shape == (3 + E,)
    assert 0.0 <= s[0] <= 1.0                      # drop fraction
    assert s[1] > 0                                # entropy
    assert 0.0 <= s[2] <= 1.0 + 1e-6               # balance
    np.testing.assert_allclose(s[3:].sum(), 1.0, atol=1e-5)
    v = np.asarray(moe.moe_vec._data)
    assert v.shape == (5 + E,)
    np.testing.assert_allclose(v[0], float(moe.aux_loss), rtol=1e-6)
    np.testing.assert_allclose(v[1], float(moe.z_loss), rtol=1e-6)
    np.testing.assert_array_equal(v[2:], s)


@pytest.mark.multichip
def test_expert_parallel_matches_auto_path():
    """ep8 mesh, ample capacity (no drops): the explicit shard_map +
    all_to_all program computes the SAME outputs as the meshless auto
    path (kept-token math is identical; only aux is per-shard). Grads
    through the data loss must match too."""
    D, E = 16, 8

    def run(mesh):
        if mesh is None:
            dist_env.reset()
        else:
            dist_env.set_mesh(mesh)
        paddle.seed(11)
        moe = MoELayer(D, num_experts=E, d_hidden=32,
                       capacity_factor=float(E))
        x = paddle.to_tensor(np.random.RandomState(4)
                             .randn(8, 8, D).astype(np.float32))
        x.stop_gradient = False
        out = moe(x)
        # data loss only: the aux term is per-shard under ep (GShard
        # local-batch semantics), so it is excluded from grad parity
        loss = F.mse_loss(out, paddle.to_tensor(
            np.zeros((8, 8, D), np.float32)))
        loss.backward()
        return (np.asarray(out._data),
                np.asarray(moe.gate.weight.grad._data),
                np.asarray(moe.experts.w1.grad._data))

    o_ref, g_ref, w_ref = run(None)
    reset_moe_stats()
    o_ep, g_ep, w_ep = run(make_mesh({"ep": 8}))
    assert MOE_STATS["ep_dispatches"] >= 1
    assert MOE_STATS["fallbacks"] == 0
    np.testing.assert_allclose(o_ep, o_ref, rtol=0, atol=1e-6)
    np.testing.assert_allclose(g_ep, g_ref, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(w_ep, w_ref, rtol=1e-5, atol=1e-7)


@pytest.mark.multichip
def test_expert_parallel_fallback_counted_on_mixed_mesh():
    """XLA:CPU cannot compile the manual-ep program when another mesh
    axis is nontrivial — the layer must degrade to the GSPMD auto path
    with ONE counted fallback + a one-time warning, not crash."""
    dist_env.set_mesh(make_mesh({"dp": 2, "ep": 4}))
    paddle.seed(13)
    moe = MoELayer(8, num_experts=4, d_hidden=16)
    x = paddle.to_tensor(np.random.RandomState(5)
                         .randn(4, 8, 8).astype(np.float32))
    with pytest.warns(RuntimeWarning, match="GSPMD auto path"), no_grad():
        out = moe(x)
    assert np.all(np.isfinite(np.asarray(out._data)))
    assert MOE_STATS["fallbacks"] == 1
    assert MOE_STATS["ep_dispatches"] == 0


@pytest.mark.multichip
@pytest.mark.chaos
def test_chaos_hang_on_expert_all_to_all_raises_structured():
    """The chaos ``collective.hang`` drill on the expert all_to_all
    (ISSUE 10 satellite): a hung eager expert exchange raises
    CollectiveTimeoutError naming the MoE program within the watchdog
    budget. (Autograd-recorded eager calls jit the whole op — the eager
    watchdog path is the no_grad one, as for the pipeline.)"""
    dist_env.set_mesh(make_mesh({"ep": 8}))
    paddle.seed(17)
    moe = MoELayer(16, num_experts=8, d_hidden=32)
    x = paddle.to_tensor(np.random.RandomState(6)
                         .randn(8, 4, 16).astype(np.float32))
    with no_grad():
        out = moe(x)                      # compile OUTSIDE the budget
        assert np.all(np.isfinite(np.asarray(out._data)))
        assert MOE_STATS["ep_dispatches"] >= 1
        with flag_scope("collective_timeout_s", 1.0):
            out = moe(x + 1.0)            # healthy warm guarded dispatch
            assert np.all(np.isfinite(np.asarray(out._data)))
            chaos.arm("collective.hang", at=1)
            with pytest.raises(C.CollectiveTimeoutError) as exc:
                moe(x + 2.0)
    assert exc.value.op == "moe.all_to_all"
    assert exc.value.group_axis == "ep"
    assert exc.value.timeout_s == 1.0


@pytest.mark.multichip
def test_heterogeneous_experts_fallback_counted_on_ep_mesh():
    """Hetero (list-of-Layer) experts cannot run the explicit ep program;
    on an ep>1 mesh that degradation must be counted + warned like every
    other ineligibility cause, not silent."""
    from paddle_tpu import nn
    dist_env.set_mesh(make_mesh({"ep": 8}))
    paddle.seed(19)
    experts = [nn.Linear(8, 8) for _ in range(4)]
    moe = MoELayer(8, experts)
    x = paddle.to_tensor(np.random.RandomState(7)
                         .randn(8, 4, 8).astype(np.float32))
    with pytest.warns(RuntimeWarning, match="GSPMD auto path"), no_grad():
        out = moe(x)
    assert np.all(np.isfinite(np.asarray(out._data)))
    assert MOE_STATS["fallbacks"] >= 1
    assert MOE_STATS["ep_dispatches"] == 0
