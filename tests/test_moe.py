"""MoE tests: gating invariants, layer training, expert-parallel routing.

reference analogue: test_collective_global_scatter/gather.py exercise the
primitives; the MoELayer (GShard dispatch/combine) goes beyond the
reference's op-only surface, so its gold standard is internal invariants
+ convergence.
"""

import jax
from paddle_tpu.distributed.env import shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.incubate.moe import (MoELayer, global_gather, global_scatter,
                                     top2_gating)


def test_top2_gating_invariants():
    rng = np.random.RandomState(0)
    S, E, C = 32, 4, 16
    logits = jnp.asarray(rng.randn(S, E).astype(np.float32))
    combine, dispatch, aux = top2_gating(logits, C)
    assert combine.shape == (S, E, C) and dispatch.shape == (S, E, C)
    # each token sends weight to at most 2 (expert, slot) pairs, weights
    # normalized to <= 1
    per_token = np.asarray((dispatch.sum(axis=(1, 2))))
    assert (per_token <= 2).all() and (per_token >= 1).all()
    w = np.asarray(combine.sum(axis=(1, 2)))
    np.testing.assert_allclose(w[per_token > 0], 1.0, rtol=1e-5)
    # capacity respected: each (expert, slot) receives at most one token
    slot_load = np.asarray(dispatch.sum(axis=0))
    assert (slot_load <= 1.0 + 1e-6).all()
    assert float(aux) > 0


def test_capacity_overflow_drops_tokens():
    S, E, C = 16, 2, 2                      # tiny capacity: must overflow
    logits = jnp.zeros((S, E), jnp.float32).at[:, 0].set(5.0)
    combine, dispatch, aux = top2_gating(logits, C)
    # expert 0 can hold only C tokens in slot dim
    assert float(dispatch[:, 0].sum()) <= C + 1e-6


def test_moe_layer_trains():
    paddle.seed(0)
    D, E = 16, 4
    experts = [nn.Sequential(nn.Linear(D, 32), nn.ReLU(), nn.Linear(32, D))
               for _ in range(E)]
    moe = MoELayer(D, experts, capacity_factor=2.0)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=moe.parameters())
    rng = np.random.RandomState(1)
    x = paddle.to_tensor(rng.randn(2, 8, D).astype(np.float32))
    target = paddle.to_tensor((rng.randn(2, 8, D) * 0.1).astype(np.float32))
    losses = []
    for _ in range(30):
        out = moe(x)
        loss = F.mse_loss(out, target) + 0.01 * moe.aux_loss
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # gate receives gradients (routing is learned): one more backward
    # without clear_grad
    loss = F.mse_loss(moe(x), target) + 0.01 * moe.aux_loss
    loss.backward()
    assert moe.gate.weight.grad is not None
    assert float(np.abs(np.asarray(moe.gate.weight.grad._data)).sum()) > 0


def test_moe_under_jit_trainstep():
    from paddle_tpu.jit.to_static import TrainStep

    paddle.seed(2)
    D, E = 8, 2
    experts = [nn.Linear(D, D) for _ in range(E)]
    moe = MoELayer(D, experts)

    def loss_fn(layer, x, y):
        out = layer(x)
        return F.mse_loss(out, y) + 0.01 * layer.aux_loss

    step = TrainStep(moe, loss_fn,
                     paddle.optimizer.Adam(learning_rate=1e-2,
                                           parameters=moe.parameters()))
    rng = np.random.RandomState(3)
    x = rng.randn(2, 4, D).astype(np.float32)
    y = (rng.randn(2, 4, D) * 0.1).astype(np.float32)
    losses = [float(step(x, y)) for _ in range(10)]
    assert losses[-1] < losses[0]


def test_stacked_experts_shard_over_ep_axis():
    from jax.sharding import NamedSharding
    from paddle_tpu.distributed import env as dist_env
    from paddle_tpu.distributed.spmd import make_mesh
    from paddle_tpu.incubate.moe import ExpertFFN
    from paddle_tpu.jit.to_static import TrainStep

    paddle.seed(5)
    D, E, Hd = 8, 4, 16
    moe = MoELayer(D, num_experts=E, d_hidden=Hd)
    mesh = make_mesh({"dp": 2, "ep": 4})
    dist_env.set_mesh(mesh)

    def loss_fn(layer, x, y):
        return F.mse_loss(layer(x), y) + 0.01 * layer.aux_loss

    step = TrainStep(moe, loss_fn,
                     paddle.optimizer.Adam(learning_rate=1e-2,
                                           parameters=moe.parameters()),
                     mesh=mesh, data_spec=P("dp"))
    # expert weights really sharded one-expert-per-ep-slice
    w1 = step.params["experts.w1"]
    assert {s.data.shape for s in w1.addressable_shards} == {(1, D, Hd)}
    rng = np.random.RandomState(6)
    x = rng.randn(4, 8, D).astype(np.float32)
    y = (rng.randn(4, 8, D) * 0.1).astype(np.float32)
    losses = [float(step(x, y)) for _ in range(5)]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]


def test_global_scatter_gather_roundtrip():
    # explicit expert-parallel routing over the ep axis (8 ranks)
    N = 8
    mesh = Mesh(np.array(jax.devices()[:N]), ("ep",))
    rows = 16                                 # per-rank rows, N | rows
    x = jnp.arange(N * rows * 4, dtype=jnp.float32) \
        .reshape(N * rows, 4)
    spec = P("ep")

    def body(xs):
        sent = global_scatter(xs, None, None)
        back = global_gather(sent, None, None)
        return back

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                                out_specs=spec))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))
