"""Golden tests for the detection-head op tail.

Brute-force reference loops transcribed from the C++ kernel semantics
(prior_box_op.h, anchor_generator_op.h, box_coder_op.h,
multiclass_nms_op.cc) — each op must match element-for-element.
"""

import math

import numpy as np

from paddle_tpu.vision.ops import (anchor_generator, box_coder,
                                   multiclass_nms, prior_box)


def _ref_prior_box(fh, fw, ih, iw, min_sizes, max_sizes, ars_in, flip,
                   clip, offset, mm_order):
    ars = [1.0]
    for ar in ars_in:
        if any(abs(ar - v) < 1e-6 for v in ars):
            continue
        ars.append(ar)
        if flip:
            ars.append(1.0 / ar)
    sw, sh = iw / fw, ih / fh
    num = len(ars) * len(min_sizes) + len(max_sizes or [])
    out = np.zeros((fh, fw, num, 4), np.float32)
    for h in range(fh):
        for w in range(fw):
            cx, cy = (w + offset) * sw, (h + offset) * sh
            k = 0

            def put(bw, bh):
                nonlocal k
                out[h, w, k] = [(cx - bw) / iw, (cy - bh) / ih,
                                (cx + bw) / iw, (cy + bh) / ih]
                k += 1
            for s, mn in enumerate(min_sizes):
                if mm_order:
                    put(mn / 2, mn / 2)
                    if max_sizes:
                        m = math.sqrt(mn * max_sizes[s]) / 2
                        put(m, m)
                    for ar in ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        put(mn * math.sqrt(ar) / 2, mn / math.sqrt(ar) / 2)
                else:
                    for ar in ars:
                        put(mn * math.sqrt(ar) / 2, mn / math.sqrt(ar) / 2)
                    if max_sizes:
                        m = math.sqrt(mn * max_sizes[s]) / 2
                        put(m, m)
    if clip:
        out = np.clip(out, 0, 1)
    return out


def test_prior_box_matches_reference_math():
    feat = np.zeros((1, 3, 6, 9), np.float32)
    img = np.zeros((1, 3, 90, 135), np.float32)
    for mm_order in (False, True):
        for flip in (False, True):
            boxes, var = prior_box(
                feat, img, min_sizes=[20.0, 40.0], max_sizes=[30.0, 60.0],
                aspect_ratios=[2.0, 0.5] if not flip else [2.0],
                flip=flip, clip=True,
                min_max_aspect_ratios_order=mm_order)
            ref = _ref_prior_box(
                6, 9, 90, 135, [20.0, 40.0], [30.0, 60.0],
                [2.0, 0.5] if not flip else [2.0], flip, True, 0.5,
                mm_order)
            got = np.asarray(boxes._data)
            assert got.shape == ref.shape, (got.shape, ref.shape)
            np.testing.assert_allclose(got, ref, atol=1e-5)
            v = np.asarray(var._data)
            assert v.shape == ref.shape
            np.testing.assert_allclose(v[0, 0, 0], [0.1, 0.1, 0.2, 0.2])


def test_anchor_generator_matches_reference_math():
    feat = np.zeros((1, 8, 5, 7), np.float32)
    sizes, ratios, stride = [32.0, 64.0], [0.5, 1.0, 2.0], (16.0, 16.0)
    anchors, var = anchor_generator(feat, sizes, ratios, stride=stride)
    got = np.asarray(anchors._data)
    assert got.shape == (5, 7, 6, 4)
    for h in (0, 4):
        for w in (0, 6):
            idx = 0
            for ar in ratios:
                for size in sizes:
                    area = stride[0] * stride[1]
                    bw = round(math.sqrt(area / ar))
                    bh = round(bw * ar)
                    aw = size / stride[0] * bw
                    ah = size / stride[1] * bh
                    xc = w * stride[0] + 0.5 * (stride[0] - 1)
                    yc = h * stride[1] + 0.5 * (stride[1] - 1)
                    ref = [xc - 0.5 * (aw - 1), yc - 0.5 * (ah - 1),
                           xc + 0.5 * (aw - 1), yc + 0.5 * (ah - 1)]
                    np.testing.assert_allclose(got[h, w, idx], ref,
                                               atol=1e-4)
                    idx += 1


def test_box_coder_encode_matches_reference_math():
    rng = np.random.default_rng(0)
    prior = np.abs(rng.standard_normal((5, 4))).astype(np.float32)
    prior[:, 2:] += prior[:, :2] + 0.5
    pvar = np.abs(rng.standard_normal((5, 4))).astype(np.float32) + 0.1
    target = np.abs(rng.standard_normal((3, 4))).astype(np.float32)
    target[:, 2:] += target[:, :2] + 0.5

    out = np.asarray(box_coder(prior, pvar, target,
                               code_type="encode_center_size")._data)
    assert out.shape == (3, 5, 4)
    for i in range(3):
        for j in range(5):
            pw = prior[j, 2] - prior[j, 0]
            ph = prior[j, 3] - prior[j, 1]
            pcx, pcy = prior[j, 0] + pw / 2, prior[j, 1] + ph / 2
            tw = target[i, 2] - target[i, 0]
            th = target[i, 3] - target[i, 1]
            tcx = (target[i, 0] + target[i, 2]) / 2
            tcy = (target[i, 1] + target[i, 3]) / 2
            ref = np.array([(tcx - pcx) / pw, (tcy - pcy) / ph,
                            math.log(abs(tw / pw)),
                            math.log(abs(th / ph))]) / pvar[j]
            np.testing.assert_allclose(out[i, j], ref, rtol=1e-4,
                                       atol=1e-5)


def test_box_coder_decode_round_trips_encode():
    rng = np.random.default_rng(1)
    prior = np.abs(rng.standard_normal((4, 4))).astype(np.float32)
    prior[:, 2:] += prior[:, :2] + 0.5
    target = np.abs(rng.standard_normal((4, 4))).astype(np.float32)
    target[:, 2:] += target[:, :2] + 0.5

    enc = box_coder(prior, [0.1, 0.1, 0.2, 0.2], target,
                    code_type="encode_center_size")
    # decode each target against ITS prior: take the diagonal; axis=1
    # indexes the prior per ROW
    enc_diag = np.asarray(enc._data)[np.arange(4), np.arange(4)][:, None, :]
    dec = np.asarray(box_coder(prior, [0.1, 0.1, 0.2, 0.2], enc_diag,
                               code_type="decode_center_size",
                               axis=1)._data)
    np.testing.assert_allclose(dec[:, 0, :], target, rtol=1e-4, atol=1e-4)


def test_multiclass_nms_suppression_and_topk():
    # two classes (+background 0), overlapping boxes
    boxes = np.array([[
        [0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],   # heavy overlap pair
        [20, 20, 30, 30], [100, 100, 110, 110],
    ]], np.float32)
    scores = np.zeros((1, 3, 4), np.float32)
    scores[0, 1] = [0.9, 0.85, 0.6, 0.05]   # class 1: pair + 1 + below-thr
    scores[0, 2] = [0.0, 0.0, 0.7, 0.95]    # class 2
    out, counts = multiclass_nms(boxes, scores, score_threshold=0.1,
                                 nms_top_k=10, keep_top_k=5,
                                 nms_threshold=0.5)
    o = np.asarray(out._data)[0]
    n = int(np.asarray(counts._data)[0])
    # class1: box0 kept, box1 suppressed, box2 kept; class2: box3, box2
    assert n == 4
    # sorted by score desc: (2,0.95,box3), (1,0.9,box0), (2,0.7,box2), (1,0.6,box2)
    np.testing.assert_allclose(o[0, :2], [2, 0.95], atol=1e-6)
    np.testing.assert_allclose(o[1, :2], [1, 0.9], atol=1e-6)
    np.testing.assert_allclose(o[2, :2], [2, 0.7], atol=1e-6)
    np.testing.assert_allclose(o[3, :2], [1, 0.6], atol=1e-6)
    assert (o[4] == -1).all()               # padding
    # keep_top_k bound
    out2, counts2 = multiclass_nms(boxes, scores, score_threshold=0.1,
                                   nms_top_k=10, keep_top_k=2,
                                   nms_threshold=0.5)
    assert int(np.asarray(counts2._data)[0]) == 2
