"""NHWC-native vision fast path (nn.layout planner + fused conv/BN).

Covers the internal-layout contract of docs/PARITY.md: inside a
channels-last scope, NCHW conv/BN/pool chains run physically NHWC with
one entry and one exit transpose, and every public-facing numeric result
matches the plain NCHW path to fp32 tolerance — fwd AND bwd.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import layout


def _rand(shape, seed=0):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def test_conv_bn_pool_chain_parity_fwd_bwd():
    """conv2d -> batch_norm -> relu -> max_pool2d chain: channels-last
    scope matches NCHW numerics and gradients."""
    x_np = _rand((2, 3, 16, 16))
    w_np = _rand((8, 3, 3, 3), 1) * 0.2
    rm = Tensor(np.zeros(8, np.float32))
    rv = Tensor(np.ones(8, np.float32))
    g = Tensor(np.full(8, 1.5, np.float32))
    b = Tensor(np.full(8, 0.25, np.float32))

    def run(channels_last):
        x = paddle.to_tensor(x_np, stop_gradient=False)
        w = paddle.to_tensor(w_np, stop_gradient=False)
        rm_ = Tensor(rm._data)
        rv_ = Tensor(rv._data)
        with layout.channels_last_scope(channels_last):
            y = F.conv2d(x, w, stride=1, padding=1)
            y = F.batch_norm(y, rm_, rv_, g, b, training=True)
            y = F.relu(y)
            y = F.max_pool2d(y, 2, 2)
            loss = y.astype("float32").sum()
        loss.backward()
        return (float(loss), x.grad.numpy(), w.grad.numpy(),
                np.asarray(rm_._data))

    l_ref, gx_ref, gw_ref, rm_ref = run(False)
    l_cl, gx_cl, gw_cl, rm_cl = run(True)
    np.testing.assert_allclose(l_cl, l_ref, rtol=1e-5)
    np.testing.assert_allclose(gx_cl, gx_ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(gw_cl, gw_ref, atol=1e-5, rtol=1e-5)
    # running-stat EMA must update identically (layout-invariant stats)
    np.testing.assert_allclose(rm_cl, rm_ref, atol=1e-6)


def test_scope_tags_and_single_exit():
    """The planner inserts ONE entry transpose, keeps the tag through
    transparent ops, and exits exactly at the first layout-unaware op."""
    x = paddle.to_tensor(_rand((2, 3, 8, 8)))
    w = paddle.to_tensor(_rand((4, 3, 3, 3), 1))
    with layout.channels_last_scope():
        y = F.conv2d(x, w, padding=1)
        assert y._layout == "NHWC" and y.shape == [2, 8, 8, 4]
        z = F.relu(y) * 2.0
        assert z._layout == "NHWC"          # transparent ops keep the tag
        p = F.avg_pool2d(z, 2, 2)
        assert p._layout == "NHWC" and p.shape == [2, 4, 4, 4]
        from paddle_tpu.tensor.manipulation import flatten
        f = flatten(p, 1)                   # unaware -> exit transpose
        assert f.shape == [2, 64]
    # outside any scope nothing is tagged
    y2 = F.conv2d(x, w, padding=1)
    assert y2._layout is None and y2.shape == [2, 4, 8, 8]


def test_adaptive_pool_and_global_head_parity():
    """ResNet-style tail: adaptive pool to (1,1) then flatten+linear gives
    identical logits across layouts (the exit restores NCHW order)."""
    x_np = _rand((2, 6, 8, 8))
    w_np = _rand((6 * 1 * 1, 5), 3)

    def run(cl):
        x = paddle.to_tensor(x_np)
        lw = paddle.to_tensor(w_np)
        with layout.channels_last_scope(cl):
            if cl:   # force a tagged tensor through an identity conv-free path
                x2 = layout.to_channels_last(x)
            else:
                x2 = x
            p = F.adaptive_avg_pool2d(x2, (1, 1))
            from paddle_tpu.tensor.manipulation import flatten
            return F.linear(flatten(p, 1), lw).numpy()

    np.testing.assert_allclose(run(True), run(False), atol=1e-6)


def test_fused_conv_bn_matches_unfused_train_and_eval():
    """fused_conv_bn == conv2d + batch_norm + relu, including the EMA
    buffer updates, in train and eval mode."""
    x = paddle.to_tensor(_rand((2, 3, 12, 12)), stop_gradient=False)
    w = paddle.to_tensor(_rand((8, 3, 3, 3), 1) * 0.2, stop_gradient=False)
    g = Tensor(np.full(8, 1.25, np.float32))
    b = Tensor(np.full(8, -0.1, np.float32))

    for training in (True, False):
        rm_f = Tensor(np.zeros(8, np.float32))
        rv_f = Tensor(np.ones(8, np.float32))
        rm_u = Tensor(np.zeros(8, np.float32))
        rv_u = Tensor(np.ones(8, np.float32))
        fused = F.fused_conv_bn(x, w, None, rm_f, rv_f, g, b, stride=1,
                                padding=1, training=training,
                                activation="relu")
        ref = F.relu(F.batch_norm(F.conv2d(x, w, padding=1), rm_u, rv_u,
                                  g, b, training=training))
        np.testing.assert_allclose(fused.numpy(), ref.numpy(), atol=1e-5,
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(rm_f._data),
                                   np.asarray(rm_u._data), atol=1e-6)
        np.testing.assert_allclose(np.asarray(rv_f._data),
                                   np.asarray(rv_u._data), atol=1e-6)

    # gradients flow through the fused op
    loss = F.fused_conv_bn(x, w, None, Tensor(np.zeros(8, np.float32)),
                           Tensor(np.ones(8, np.float32)), g, b, padding=1,
                           training=True, activation="relu").sum()
    loss.backward()
    assert x.grad is not None and w.grad is not None


def test_fused_conv_bn_rejects_unknown_activation():
    x = paddle.to_tensor(_rand((1, 3, 8, 8)))
    w = paddle.to_tensor(_rand((4, 3, 3, 3)))
    with pytest.raises(ValueError, match="relu"):
        F.fused_conv_bn(x, w, None, Tensor(np.zeros(4, np.float32)),
                        Tensor(np.ones(4, np.float32)), None, None,
                        activation="gelu")


def test_bf16_conv_explicit_f32_accumulation_grads():
    """The bf16 conv stream (preferred_element_type=f32 fwd) must be
    differentiable — the raw form breaks jax's conv transpose rule; the
    custom VJP restores it. Output and grads stay bf16."""
    import jax.numpy as jnp
    x = Tensor(np.ones((2, 3, 8, 8), np.float32))
    x = Tensor(x._data.astype(jnp.bfloat16))
    x.stop_gradient = False
    w = Tensor(_rand((4, 3, 3, 3), 2).astype(np.float32))
    w = Tensor(w._data.astype(jnp.bfloat16))
    w.stop_gradient = False

    y = F.conv2d(x, w, padding=1)
    assert y.dtype == jnp.bfloat16
    y.astype("float32").sum().backward()
    assert w.grad is not None and w.grad.dtype == jnp.bfloat16

    # transpose conv: previously broke under grad with bf16 inputs
    wt = Tensor(_rand((3, 4, 3, 3), 3).astype(np.float32))
    wt = Tensor(wt._data.astype(jnp.bfloat16))
    wt.stop_gradient = False
    yt = F.conv2d_transpose(x, wt, stride=2, padding=1)
    assert yt.dtype == jnp.bfloat16
    yt.astype("float32").sum().backward()
    assert wt.grad is not None


def test_amp_o1_conv_bn_chain_under_scope():
    """AMP O1 + channels-last scope: conv runs bf16 with f32 accumulation,
    batch_norm keeps its f32 EMA buffers (keep-dtype op)."""
    import jax.numpy as jnp
    x = paddle.to_tensor(_rand((2, 3, 8, 8)), stop_gradient=False)
    w = paddle.to_tensor(_rand((4, 3, 3, 3), 1) * 0.2, stop_gradient=False)
    rm = Tensor(np.zeros(4, np.float32))
    rv = Tensor(np.ones(4, np.float32))
    with paddle.amp.auto_cast(level="O1"), layout.channels_last_scope():
        y = F.conv2d(x, w, padding=1)
        assert y.dtype == jnp.bfloat16 and y._layout == "NHWC"
        z = F.batch_norm(y, rm, rv, training=True)
        assert z.dtype == jnp.bfloat16
    assert rm._data.dtype == jnp.float32      # EMA buffers never degrade
    assert rv._data.dtype == jnp.float32
    z.astype("float32").sum().backward()
    assert w.grad is not None


def test_mixed_layout_elementwise_falls_back_to_nchw():
    """A transparent elementwise op combining a tagged-NHWC tensor with an
    untagged NCHW-world tensor must NOT mix physical layouts: the planner
    exits to NCHW for that op, so results match the plain path exactly
    (code-review regression: x + conv(x) with square dims was silently
    wrong; channel-broadcast scales crashed)."""
    x_np = _rand((2, 8, 8, 8))                 # square dims: the silent case
    w_np = _rand((8, 8, 3, 3), 1) * 0.2
    s_np = _rand((1, 8, 1, 1), 2)              # NCHW channel-broadcast scale

    x = paddle.to_tensor(x_np)
    w = paddle.to_tensor(w_np)
    s = paddle.to_tensor(s_np)
    ref_res = (x + F.conv2d(x, w, padding=1)).numpy()
    ref_scaled = (F.conv2d(x, w, padding=1) * s).numpy()

    with layout.channels_last_scope():
        out_res = x + F.conv2d(x, w, padding=1)       # untagged + tagged
        out_scaled = F.conv2d(x, w, padding=1) * s    # tagged * NCHW scale
    np.testing.assert_allclose(out_res.numpy(), ref_res, atol=1e-5,
                               rtol=1e-5)
    np.testing.assert_allclose(out_scaled.numpy(), ref_scaled, atol=1e-5,
                               rtol=1e-5)

    # tagged + tagged (the residual fast path) still stays channels-last
    with layout.channels_last_scope():
        a = F.conv2d(x, w, padding=1)
        b = F.conv2d(x, w, padding=1)
        c = a + b
        assert c._layout == "NHWC"
