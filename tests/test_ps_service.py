"""Parameter-server process model: C++ server + python client + async
communicator (reference: paddle/fluid/distributed/service/brpc_ps_server.h,
brpc_ps_client.h, communicator.cc)."""

import os

import numpy as np
import pytest

from paddle_tpu.distributed.ps import service as svc

pytestmark = pytest.mark.skipif(
    not svc.native_available(), reason="no C++ toolchain for ps_server")


@pytest.fixture()
def cluster():
    """Two PS processes + a connected client (real process model)."""
    servers = [svc.PSServerHandle(), svc.PSServerHandle()]
    client = svc.PSClient([s.endpoint for s in servers])
    yield servers, client
    client.stop_servers()
    for s in servers:
        assert s.wait(timeout=10) == 0    # clean shutdown on STOP


def test_dense_pull_push_sgd(cluster):
    _, client = cluster
    client.ping()
    client.create_table(0, kind="dense", dim=8, rows=4, optimizer="sgd",
                        lr=0.5, seed=3)
    w0 = client.pull_dense(0, 4, 8)
    assert w0.shape == (4, 8) and np.abs(w0).max() <= 0.01
    g = np.ones((4, 8), np.float32)
    client.push_dense(0, g, grad=True)
    w1 = client.pull_dense(0, 4, 8)
    np.testing.assert_allclose(w1, w0 - 0.5 * g, atol=1e-6)
    # set semantics
    client.push_dense(0, np.full((4, 8), 7.0, np.float32), grad=False)
    np.testing.assert_allclose(client.pull_dense(0, 4, 8), 7.0)


def test_sparse_lazy_init_deterministic_and_sharded(cluster):
    servers, client = cluster
    client.create_table(1, kind="sparse", dim=16, optimizer="sgd", lr=1.0,
                        seed=9, init_scale=0.05)
    keys = np.arange(100, dtype=np.uint64)
    rows = client.pull_sparse(1, keys, 16)
    assert rows.shape == (100, 16) and np.abs(rows).max() <= 0.05
    # deterministic: same keys -> identical rows, any order
    again = client.pull_sparse(1, keys[::-1].copy(), 16)
    np.testing.assert_array_equal(again, rows[::-1])
    # rows really live on BOTH server processes (client-side sharding)
    per_server = [client.num_rows(1)]
    solo = svc.PSClient([servers[0].endpoint])
    n0 = solo.num_rows(1)
    solo.close()
    assert per_server[0] == 100 and 0 < n0 < 100


def test_sparse_grad_apply_and_duplicate_keys(cluster):
    _, client = cluster
    client.create_table(2, kind="sparse", dim=4, optimizer="sgd", lr=0.1,
                        seed=1, init_scale=0.0)   # zero init: exact math
    keys = np.array([5, 9], dtype=np.uint64)
    w0 = client.pull_sparse(2, keys, 4)
    np.testing.assert_allclose(w0, 0.0)
    g = np.stack([np.full(4, 1.0), np.full(4, 2.0)]).astype(np.float32)
    client.push_sparse(2, keys, g, grad=True)
    w1 = client.pull_sparse(2, keys, 4)
    np.testing.assert_allclose(w1, -0.1 * g, atol=1e-6)


def test_save_load_roundtrip(cluster, tmp_path):
    _, client = cluster
    client.create_table(3, kind="sparse", dim=8, optimizer="adagrad",
                        lr=0.1, seed=4)
    keys = np.arange(50, dtype=np.uint64)
    client.push_sparse(3, keys, np.ones((50, 8), np.float32), grad=True)
    trained = client.pull_sparse(3, keys, 8)
    client.save(3, str(tmp_path / "ckpt"))
    # clobber, then restore
    client.push_sparse(3, keys, np.zeros((50, 8), np.float32), grad=False)
    client.load(3, str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(client.pull_sparse(3, keys, 8), trained)
    files = os.listdir(tmp_path / "ckpt")
    assert len(files) == 2                      # one shard file per server


def test_async_communicator_merges_and_flushes(cluster):
    _, client = cluster
    client.create_table(4, kind="sparse", dim=4, optimizer="sgd", lr=1.0,
                        seed=0, init_scale=0.0)
    comm = svc.AsyncCommunicator(client, send_every=0.002)
    # duplicate keys across pushes must SUM before the apply
    for _ in range(10):
        comm.push_sparse_grad(4, np.array([7], np.uint64),
                              np.full((1, 4), 0.5, np.float32))
    comm.flush()
    comm.stop()
    w = client.pull_sparse(4, np.array([7], np.uint64), 4)
    np.testing.assert_allclose(w, -5.0, atol=1e-5)


def test_distributed_embedding_over_service(cluster):
    """End-to-end: DistributedEmbedding trains against the PS processes."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.ps import DistributedEmbedding

    _, client = cluster
    client.create_table(5, kind="sparse", dim=8, optimizer="sgd", lr=1.0,
                        seed=2, init_scale=0.01)
    emb = DistributedEmbedding(1000, 8, client=client, table_id=5)
    ids = paddle.to_tensor(np.array([[1, 2], [3, 1]], np.int64))
    target = np.ones((2, 2, 8), np.float32)

    losses = []
    for _ in range(60):
        out = emb(ids)
        loss = ((out - paddle.to_tensor(target)) ** 2).mean()
        loss.backward()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, losses[::10]
    # the trained rows live on the servers, not in the layer
    rows = client.pull_sparse(5, np.array([1, 2, 3], np.uint64), 8)
    assert np.abs(rows - 1.0).mean() < 0.3


def test_role_env_protocol(monkeypatch):
    monkeypatch.setenv("TRAINING_ROLE", "PSERVER")
    monkeypatch.setenv("PADDLE_PSERVERS_IP_PORT_LIST",
                       "127.0.0.1:1234,127.0.0.1:1235")
    assert svc.role_from_env() == "PSERVER"
    assert svc.server_endpoints_from_env() == ["127.0.0.1:1234",
                                               "127.0.0.1:1235"]


def test_fresh_client_discovers_table_kind(cluster, tmp_path):
    """A second client process (no local kind registry) can checkpoint a
    dense table: the kind is discovered from the servers."""
    servers, client = cluster
    client.create_table(6, kind="dense", dim=4, rows=2, optimizer="sgd",
                        lr=0.1, seed=0)
    fresh = svc.PSClient([s.endpoint for s in servers])
    assert fresh.table_kind(6) == "dense"
    fresh.save(6, str(tmp_path / "dense_ckpt"))
    assert len(os.listdir(tmp_path / "dense_ckpt")) == 1   # owner only
    assert fresh.table_kind(99) == "absent"
    fresh.close()


def test_geo_sgd_two_workers_converge(cluster):
    """Geo mode: two 'workers' train local rows, push deltas; both see the
    combined result after sync (reference: GeoCommunicator)."""
    _, client = cluster
    client.create_table(7, kind="sparse", dim=4, optimizer="sgd", lr=1.0,
                        seed=0, init_scale=0.0)
    w1 = svc.GeoCommunicator(client, 7, 4, trigger_steps=2)
    w2 = svc.GeoCommunicator(client, 7, 4, trigger_steps=2)
    keys = np.array([3], np.uint64)

    # worker 1 trains its local row by +1 per step; worker 2 by +10
    for step in range(2):
        r1 = w1.pull(keys)
        w1.update(keys, r1 + 1.0)
        w1.maybe_sync()
        r2 = w2.pull(keys)
        w2.update(keys, r2 + 10.0)
        w2.maybe_sync()
    # after both synced: server row = sum of both workers' deltas
    server_row = client.pull_sparse(7, keys, 4)
    np.testing.assert_allclose(server_row, 22.0, atol=1e-5)
    # a fresh sync refreshes worker 1's base to the combined value
    w1.pull(keys)
    for _ in range(2):
        r1 = w1.pull(keys)
        w1.update(keys, r1)      # no local change
        w1.maybe_sync()
    np.testing.assert_allclose(w1.pull(keys), 22.0, atol=1e-5)
