"""MoE-GPT integration tests (ISSUE 10 tentpole 3).

Pins: GPTConfig(moe_experts, moe_every) wiring, the homogeneous-MoE
scan-over-layers compile discipline (ONE body trace / zero warm
retraces via CompileCounter), the kill-switch-through-cache contract
(flipping FLAGS_moe_dispatch retraces into the other path), mixed-stack
loop collection, state_dict stability, CheckpointManager bit-exact
resume, decode-path compatibility, and the monitor_report --moe render.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import Tensor, no_grad
from paddle_tpu.incubate.moe import MOE_STATS, reset_moe_stats
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.models.gpt import (GPTForPretraining, GPTMoEDecoderLayer,
                                   GPTPretrainingCriterion, gpt_tiny)
from paddle_tpu.nn.scan import SCAN_STATS, reset_scan_stats
from paddle_tpu.optimizer import AdamW
from paddle_tpu.utils import CompileCounter


@pytest.fixture(autouse=True)
def _moe_isolation():
    reset_moe_stats()
    reset_scan_stats()
    yield
    reset_moe_stats()
    from paddle_tpu.distributed import env as dist_env
    dist_env.reset()


def _batch(cfg, B=4, S=32, seed=0):
    rng = np.random.default_rng(seed)
    ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
    return ids, labels


def _build_step(cfg, seed=0, lr=1e-3):
    paddle.seed(seed)
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, ids, labels):
        return crit(layer(ids), labels) + layer.moe_loss()

    step = TrainStep(model, loss_fn,
                     AdamW(learning_rate=lr,
                           parameters=model.parameters()))
    return model, step


def test_moe_layer_indices():
    assert gpt_tiny(num_layers=4).moe_layer_indices() == []
    assert gpt_tiny(num_layers=4, moe_experts=4).moe_layer_indices() \
        == [0, 1, 2, 3]
    assert gpt_tiny(num_layers=6, moe_experts=4,
                    moe_every=2).moe_layer_indices() == [1, 3, 5]
    assert gpt_tiny(num_layers=6, moe_experts=4,
                    moe_every=3).moe_layer_indices() == [2, 5]


def test_homogeneous_moe_stack_scans_one_trace_and_trains():
    """Acceptance: a homogeneous MoE stack under scan-over-layers pins
    ONE body trace on the cold step and ZERO retraces/compiles warm,
    and the train loss decreases with the router losses in the mix."""
    cfg = gpt_tiny(num_layers=4, moe_experts=8)
    model, step = _build_step(cfg)
    ids, labels = _batch(cfg)
    reset_scan_stats()
    l0 = float(step(ids, labels))
    assert SCAN_STATS["body_traces"] == 1      # one trace, not O(L)
    assert SCAN_STATS["fallbacks"] == 0
    with CompileCounter() as c:
        losses = [float(step(ids, labels)) for _ in range(5)]
    assert c.backend_compiles == 0 and c.jaxpr_traces == 0
    assert losses[-1] < l0
    assert all(np.isfinite(v) for v in [l0] + losses)


def test_dispatch_kill_switch_retraces_through_scan_cache():
    """The dispatch mode rides the scan's eager-cache token: flipping
    FLAGS_moe_dispatch must RETRACE into the other path (a cached trace
    must never replay a stale dispatch), pinned via the MOE_STATS
    dispatch counters which only move at trace time."""
    cfg = gpt_tiny(num_layers=2, moe_experts=4)
    paddle.seed(0)
    model = GPTForPretraining(cfg)
    ids, _ = _batch(cfg, B=2, S=16)
    # grad-enabled forwards: the eager jit cache only serves recorded
    # ops (a no_grad forward re-runs the python body every call)
    with flag_scope("moe_dispatch", "sort"):
        model(paddle.to_tensor(ids))
        n_sort = MOE_STATS["sort_dispatches"]
        assert n_sort >= 1
        model(paddle.to_tensor(ids))           # warm: no new body trace
        assert MOE_STATS["sort_dispatches"] == n_sort
    with flag_scope("moe_dispatch", "einsum"):
        model(paddle.to_tensor(ids))
        assert MOE_STATS["einsum_dispatches"] >= 1


def test_mixed_stack_loop_collects_stats_and_loss():
    """moe_every=2 (heterogeneous stack): the python loop collects
    per-MoE-layer vectors, moe_loss() is finite and differentiable, and
    publish_moe_telemetry lands per-layer gauges."""
    from paddle_tpu.monitor import scoped_registry

    cfg = gpt_tiny(num_layers=4, moe_experts=4, moe_every=2)
    paddle.seed(1)
    model = GPTForPretraining(cfg)
    ids, labels = _batch(cfg, B=2, S=16)
    out = model(paddle.to_tensor(ids))
    assert tuple(out.shape) == (2, 16, cfg.vocab_size)
    stats = model.gpt.moe_layer_stats()
    assert tuple(stats.shape) == (2, 5 + 4)          # layers 1, 3
    assert float(model.moe_loss()) > 0
    with scoped_registry() as reg:
        assert model.gpt.publish_moe_telemetry() == 2
        g = reg.get("moe_router_balance_pct")
        layers = {dict(lbl)["layer"] for lbl, _ in g.samples()}
        assert layers == {"layer1", "layer3"}

    # trains end to end through TrainStep (loop path in the trace)
    model2, step = _build_step(cfg, seed=1)
    losses = [float(step(ids, labels)) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_state_dict_names_and_bit_exact_roundtrip():
    """Dense-layer state_dict names are UNCHANGED by the MoE wiring;
    MoE layers add layers.<i>.moe.* leaves; a save->load roundtrip into
    a fresh model reproduces the forward bit-for-bit."""
    cfg = gpt_tiny(num_layers=4, moe_experts=4, moe_every=2)
    paddle.seed(2)
    model = GPTForPretraining(cfg)
    names = set(model.state_dict().keys())
    # dense layers (0, 2) keep the classic mlp names
    assert "gpt.layers.0.mlp.w_in" in names
    assert "gpt.layers.2.mlp.w_out" in names
    # MoE layers (1, 3) carry the expert stack + gate
    assert "gpt.layers.1.moe.experts.w1" in names
    assert "gpt.layers.3.moe.gate.weight" in names
    assert "gpt.layers.1.mlp.w_in" not in names

    ids, _ = _batch(cfg, B=2, S=16)
    with no_grad():
        ref = np.asarray(model(paddle.to_tensor(ids))._data)
    paddle.seed(99)                                  # different init
    fresh = GPTForPretraining(cfg)
    fresh.set_state_dict(model.state_dict())
    with no_grad():
        got = np.asarray(fresh(paddle.to_tensor(ids))._data)
    np.testing.assert_array_equal(got, ref)


def test_checkpoint_manager_resume_bit_exact(tmp_path):
    """Acceptance: CheckpointManager resume of an MoE GPT is bit-exact —
    the interrupted run's remaining loss trajectory equals the
    uninterrupted reference exactly."""
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    cfg = gpt_tiny(num_layers=2, moe_experts=4)
    root = str(tmp_path / "ckpts")
    ids, labels = _batch(cfg, B=2, S=16)

    _, step_ref = _build_step(cfg, seed=5)
    ref = [float(step_ref(ids, labels)) for _ in range(6)]

    _, step_a = _build_step(cfg, seed=5)
    with CheckpointManager(step_a, root, interval_steps=2,
                           keep_n=2) as mgr:
        got_a = []
        for i in range(4):
            got_a.append(float(step_a(ids, labels)))
            mgr.on_step(dataloader_state={"offset": i + 1})
    assert got_a == ref[:4]

    _, step_b = _build_step(cfg, seed=5)
    with CheckpointManager(step_b, root, interval_steps=2,
                           keep_n=2) as mgr:
        info = mgr.resume()
        assert info["step"] == 4
        got_b = [float(step_b(ids, labels)) for _ in range(2)]
    assert got_b == ref[4:]


def test_moe_gpt_static_cache_decode_matches_full_forward():
    """Greedy decode through the static-KV cache path (MoE layers return
    (x, cache) there, stats suppressed) matches argmax over the full
    forward recomputation token for token. Capacity is ample (cf=E) so
    no assignment drops: MoE routing is capacity-coupled across the
    tokens routed together, so drop decisions legitimately differ
    between a whole-sequence forward and one-token decode chunks —
    dropless is the regime where the two must agree exactly."""
    cfg = gpt_tiny(num_layers=2, moe_experts=4,
                   moe_capacity_factor=4.0)
    paddle.seed(6)
    model = GPTForPretraining(cfg)
    model.eval()
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, cfg.vocab_size, (1, 8)).astype(np.int32)
    with no_grad():
        out = model.generate(paddle.to_tensor(prompt), max_new_tokens=6,
                             decode_strategy="greedy_search")
        got = np.asarray(out._data if hasattr(out, "_data") else out)
        # reference: greedy over full recomputation
        cur = prompt.copy()
        for _ in range(6):
            logits = model(paddle.to_tensor(cur))
            nxt = int(np.argmax(np.asarray(logits._data)[0, -1]))
            cur = np.concatenate(
                [cur, np.array([[nxt]], np.int32)], axis=1)
    np.testing.assert_array_equal(got[:, :cur.shape[1]], cur)


def test_monitor_report_moe_renders_per_layer_table(tmp_path):
    """tools/monitor_report.py --moe renders the router-health table
    from a registry dump."""
    import importlib.util
    import os
    import sys

    from paddle_tpu.monitor import scoped_registry

    cfg = gpt_tiny(num_layers=2, moe_experts=4)
    paddle.seed(7)
    model = GPTForPretraining(cfg)
    ids, _ = _batch(cfg, B=2, S=16)
    with no_grad():
        model(paddle.to_tensor(ids))
    with scoped_registry() as reg:
        assert model.gpt.publish_moe_telemetry() == 2
        path = str(tmp_path / "mon.jsonl")
        reg.dump_jsonl(path)

    spec = importlib.util.spec_from_file_location(
        "monitor_report", os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "monitor_report.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from paddle_tpu.monitor import load_jsonl
    text = mod.render(load_jsonl(path), moe=True)
    assert "MoE router health" in text
    assert "layer0" in text and "layer1" in text
    assert "balance%" in text and "drop%" in text


@pytest.mark.multichip
@pytest.mark.chaos
def test_trainstep_moe_ep_watchdog_raises_structured():
    """TrainStep applies the collective watchdog to its whole step
    program when the model carries expert-parallel MoE layers over an
    ep>1 mesh: a chaos hang at the step dispatch raises structured."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed import collective as C, env as dist_env
    from paddle_tpu.distributed.spmd import make_mesh
    from paddle_tpu.testing import chaos

    mesh = make_mesh({"ep": 8})
    dist_env.set_mesh(mesh)
    cfg = gpt_tiny(num_layers=2, moe_experts=8)
    paddle.seed(8)
    model = GPTForPretraining(cfg)
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, ids, labels):
        return crit(layer(ids), labels) + layer.moe_loss()

    step = TrainStep(model, loss_fn, AdamW(learning_rate=1e-3),
                     mesh=mesh, data_spec=P("ep"))
    assert step._ep_degree == 8
    ids, labels = _batch(cfg, B=8, S=16)
    # compile AND the step-2 sharding-drift re-lower (the PR 4 AOT
    # self-heal recompiles once when XLA re-shards updated params)
    # happen OUTSIDE the watchdog budget
    float(step(ids, labels))
    float(step(ids, labels))
    with flag_scope("collective_timeout_s", 10.0):
        float(step(ids, labels))               # healthy guarded dispatch
        chaos.arm("collective.hang", at=1)
        with pytest.raises(C.CollectiveTimeoutError) as exc:
            step(ids, labels)
    assert exc.value.op == "moe_step"
    assert exc.value.group_axis == "ep"
    assert exc.value.timeout_s == 10.0
