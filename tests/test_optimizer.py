"""Optimizer tests (reference pattern: test_adam_op.py, test_sgd_op.py …)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _quadratic_problem(opt_factory, steps=60):
    """Minimise ||Wx - y||^2 on an exactly-solvable system; returns final loss."""
    paddle.seed(0)
    net = nn.Linear(4, 4)
    opt = opt_factory(net.parameters())
    x = paddle.randn([4, 4])
    target = paddle.randn([4, 4])
    loss_val = None
    for _ in range(steps):
        loss = ((net(x) - target) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        loss_val = float(loss)
    return loss_val


def test_sgd_converges():
    final = _quadratic_problem(
        lambda ps: paddle.optimizer.SGD(learning_rate=0.1, parameters=ps))
    assert final < 0.2


def test_momentum_converges():
    final = _quadratic_problem(
        lambda ps: paddle.optimizer.Momentum(learning_rate=0.02, momentum=0.9,
                                             parameters=ps))
    assert final < 0.2


def test_adam_converges():
    final = _quadratic_problem(
        lambda ps: paddle.optimizer.Adam(learning_rate=0.1, parameters=ps))
    assert final < 0.2


def test_adamw_decay():
    # with pure decay and zero grads, weights shrink
    p = paddle.nn.Linear(2, 2)
    opt = paddle.optimizer.AdamW(learning_rate=0.1, weight_decay=0.5,
                                 parameters=p.parameters())
    w0 = np.abs(p.weight.numpy()).sum()
    x = paddle.zeros([1, 2])
    (p(x).sum() * 0.0).backward()
    opt.step()
    assert np.abs(p.weight.numpy()).sum() < w0


def test_adam_matches_reference_formula():
    w = paddle.core.Parameter(np.array([1.0, 2.0], np.float32))
    opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
    w.grad = paddle.to_tensor(np.array([0.5, -0.5], np.float32))
    opt.step()
    # manual adam step 1
    g = np.array([0.5, -0.5])
    m = 0.1 * g
    v = 0.001 * g * g
    lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expected = np.array([1.0, 2.0]) - lr_t * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(w.numpy(), expected, rtol=1e-5)


def test_lamb_and_rmsprop_run():
    for factory in [
        lambda ps: paddle.optimizer.Lamb(learning_rate=0.01, parameters=ps),
        lambda ps: paddle.optimizer.RMSProp(learning_rate=0.01, parameters=ps),
        lambda ps: paddle.optimizer.Adagrad(learning_rate=0.1, parameters=ps),
        lambda ps: paddle.optimizer.Adadelta(learning_rate=1.0, parameters=ps),
        lambda ps: paddle.optimizer.Adamax(learning_rate=0.05, parameters=ps),
    ]:
        final = _quadratic_problem(factory, steps=80)
        assert np.isfinite(final)


def test_grad_clip_global_norm():
    from paddle_tpu.optimizer import ClipGradByGlobalNorm
    w = paddle.core.Parameter(np.zeros(4, np.float32))
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=[w],
                               grad_clip=ClipGradByGlobalNorm(1.0))
    w.grad = paddle.to_tensor(np.array([10.0, 0, 0, 0], np.float32))
    opt.step()
    np.testing.assert_allclose(np.abs(w.numpy()).sum(), 1.0, rtol=1e-5)


def test_lr_schedulers():
    from paddle_tpu.optimizer.lr import (CosineAnnealingDecay, LinearWarmup,
                                         MultiStepDecay, NoamDecay, StepDecay)
    s = StepDecay(0.1, step_size=2, gamma=0.5)
    lrs = []
    for _ in range(5):
        lrs.append(s())
        s.step()
    np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    w = LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    vals = []
    for _ in range(5):
        vals.append(w())
        w.step()
    np.testing.assert_allclose(vals[:4], [0.0, 0.025, 0.05, 0.075])

    c = CosineAnnealingDecay(1.0, T_max=10)
    assert abs(c() - 1.0) < 1e-6

    opt = paddle.optimizer.SGD(learning_rate=s)
    assert opt.get_lr() == s()


def test_optimizer_state_dict_roundtrip():
    net = nn.Linear(3, 3)
    opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    x = paddle.randn([4, 3])
    (net(x).sum()).backward()
    opt.step()
    sd = opt.state_dict()
    assert sd["_step_count"] == 1

    opt2 = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
    opt2.set_state_dict(sd)
    assert opt2._step_count == 1


def test_multi_tensor_adamw_matches_per_param():
    """use_multi_tensor=True (stacked group update, reference:
    merged_adam multi-tensor kernels) is numerically identical to the
    per-param path."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.nn import functional as F
    from paddle_tpu.optimizer import AdamW

    def build(mt):
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 16),
                          nn.ReLU(), nn.Linear(16, 4))
        opt = AdamW(learning_rate=0.01, parameters=m.parameters(),
                    weight_decay=0.01, use_multi_tensor=mt)
        step = TrainStep(
            m, lambda layer, x, y: F.cross_entropy(layer(x), y), opt)
        return step

    s_ref = build(False)
    s_mt = build(True)
    rng = np.random.default_rng(0)
    for _ in range(5):
        x = rng.normal(size=(16, 8)).astype(np.float32)
        y = rng.integers(0, 4, (16,)).astype(np.int64)
        l_ref = float(s_ref(x, y))
        l_mt = float(s_mt(x, y))
        np.testing.assert_allclose(l_mt, l_ref, rtol=1e-6, atol=1e-7)
    for k in s_ref.params:
        np.testing.assert_allclose(np.asarray(s_mt.params[k]),
                                   np.asarray(s_ref.params[k]),
                                   rtol=1e-5, atol=1e-7)
    # the stacked state round-trips through TrainStep checkpointing
    sd = s_mt.state_dict()
    s_mt2 = build(True)
    s_mt2.set_state_dict(sd)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = rng.integers(0, 4, (16,)).astype(np.int64)
    np.testing.assert_allclose(float(s_mt2(x, y)), float(s_mt(x, y)),
                               rtol=1e-6)


def test_multi_tensor_missing_grad_raises():
    import numpy as np
    import pytest

    import paddle_tpu as paddle
    from paddle_tpu.optimizer import Adam

    paddle.seed(0)
    opt = Adam(learning_rate=0.01, use_multi_tensor=True)
    import jax.numpy as jnp
    params = {"a": jnp.ones((4,)), "b": jnp.ones((4,))}
    state = opt.init_state(params)
    with pytest.raises(ValueError, match="use_multi_tensor"):
        opt.apply_gradients(params, {"a": jnp.ones((4,))}, state)


def test_adam_bf16_state_dtype_loss_parity():
    """state_dtype="bfloat16" halves optimizer-state HBM traffic; the
    update computes in f32, so the loss curve tracks the f32-state run
    (reference analogue: adam_op.cu multi-precision fused variants)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.nn import functional as F
    from paddle_tpu.optimizer import AdamW

    def run(state_dtype):
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))

        def loss_fn(layer, x, y):
            return F.cross_entropy(layer(x), y)

        step = TrainStep(m, loss_fn,
                         AdamW(learning_rate=1e-2,
                               state_dtype=state_dtype))
        rng = np.random.default_rng(0)
        losses = []
        for _ in range(30):
            x = rng.normal(size=(32, 8)).astype(np.float32)
            y = (x.sum(1) > 0).astype(np.int64)
            losses.append(float(step(x, y)))
        slots = jax.tree_util.tree_leaves(step.opt_state)
        return losses, slots

    import jax
    l32, s32 = run("float32")
    l16, s16 = run("bfloat16")
    assert all(s.dtype == jax.numpy.bfloat16 for s in s16
               if s.ndim > 0)
    assert l16[-1] < l16[0] * 0.5            # both learn
    assert abs(l32[-1] - l16[-1]) < 0.05 + 0.1 * l32[-1], (l32[-1], l16[-1])
