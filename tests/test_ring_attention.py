"""Sequence-parallel attention tests on the 8-device CPU mesh.

No reference analogue exists (SURVEY §2.3: the reference has no SP) —
gold standard is single-device full attention; the sharded ring/Ulysses
runs must match it.
"""

import jax
from paddle_tpu.distributed.env import shard_map
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.ops.attention import _sdpa_xla
from paddle_tpu.ops.ring_attention import (block_attention, ring_attention,
                                           ulysses_attention)

N = 8
B, S, H, D = 2, 64, 8, 16      # S sharded 8 ways -> 8 tokens per device


def _mesh():
    return Mesh(np.array(jax.devices()[:N]), ("sp",))


def _qkv(seed):
    rng = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(rng.randn(B, S, H, D)  # noqa: E731
                             .astype(np.float32) * 0.5)
    return mk(), mk(), mk()


def _gold(q, k, v, causal):
    with jax.default_matmul_precision("highest"):
        return _sdpa_xla(q, k, v, None, 0.0, causal, None)


def test_block_attention_matches_sdpa():
    q, k, v = _qkv(0)
    o, lse = block_attention(q, k, v, causal=True)
    ref = _gold(q, k, v, True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
    assert lse.shape == (B, S, H)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    q, k, v = _qkv(1)
    mesh = _mesh()
    spec = P(None, "sp", None, None)

    ring = jax.jit(shard_map(
        lambda a, b, c: ring_attention(a, b, c, "sp", causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    out = ring(q, k, v)
    ref = _gold(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


def test_ring_attention_grads_match_full():
    q, k, v = _qkv(2)
    mesh = _mesh()
    spec = P(None, "sp", None, None)

    def ring_loss(q, k, v):
        # check_vma/check_rep off: legacy jax's replication inference cannot
        # type the causal lax.switch branches through the grad transpose
        # (the framework's own shard_map call sites disable it the same way)
        out = shard_map(
            lambda a, b, c: ring_attention(a, b, c, "sp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)(q, k, v)
        return jnp.sum(out ** 2)

    def full_loss(q, k, v):
        return jnp.sum(_gold(q, k, v, True) ** 2)

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_full = jax.grad(full_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_full, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-4, rtol=5e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(causal):
    q, k, v = _qkv(3)
    mesh = _mesh()
    spec = P(None, "sp", None, None)

    uly = jax.jit(shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, "sp", causal=causal,
                                          use_flash=False),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))
    out = uly(q, k, v)
    ref = _gold(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


def test_ring_long_sequence_memory_shape():
    # 8x the single-shard length: each device only ever holds S/8 keys
    q, k, v = _qkv(4)
    mesh = _mesh()
    spec = P(None, "sp", None, None)
    out = jax.jit(shard_map(
        lambda a, b, c: ring_attention(a, b, c, "sp"),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec))(q, k, v)
    assert out.shape == (B, S, H, D)
    # sharding preserved on the sequence axis (trailing Nones normalized)
    assert out.sharding.spec[1] == "sp"
