"""Top-level API tail (reference: python/paddle/__init__.py __all__)."""

import numpy as np

import paddle_tpu as paddle


def test_addmm_broadcast_conj_diagonal():
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    y = paddle.to_tensor(np.ones((3, 2), np.float32))
    inp = paddle.to_tensor(np.full((2, 2), 2.0, np.float32))
    out = paddle.addmm(inp, x, y, beta=0.5, alpha=2.0)
    np.testing.assert_allclose(out.numpy(), 0.5 * 2.0 + 2.0 * 3.0)

    a, b = paddle.broadcast_tensors([
        paddle.to_tensor(np.ones((1, 4), np.float32)),
        paddle.to_tensor(np.ones((3, 1), np.float32))])
    assert a.shape == [3, 4] and b.shape == [3, 4]

    z = paddle.to_tensor(np.array([1 + 2j, 3 - 4j], np.complex64))
    np.testing.assert_allclose(paddle.conj(z).numpy(),
                               np.array([1 - 2j, 3 + 4j], np.complex64))

    m = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
    np.testing.assert_allclose(paddle.diagonal(m).numpy(), [0, 4, 8])


def test_inplace_variants_mutate_and_autograd():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    r = paddle.reshape_(x, (3, 2))
    assert r is x and x.shape == [3, 2]
    paddle.unsqueeze_(x, 0)
    assert x.shape == [1, 3, 2]
    paddle.squeeze_(x, 0)
    assert x.shape == [3, 2]
    t = paddle.to_tensor(np.zeros((2,), np.float32))
    paddle.tanh_(t)
    np.testing.assert_allclose(t.numpy(), 0.0)


def test_rank_shape_reverse_floor_mod():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert int(paddle.rank(x)) == 2
    assert paddle.shape(x).numpy().tolist() == [2, 3]
    np.testing.assert_allclose(paddle.reverse(x, 1).numpy()[:, 0],
                               [2.0, 5.0])
    np.testing.assert_allclose(
        paddle.floor_mod(paddle.to_tensor(np.array([7.0], np.float32)),
                         paddle.to_tensor(np.array([3.0], np.float32)))
        .numpy(), [1.0])


def test_create_parameter_and_batch_reader():
    p = paddle.create_parameter((4, 4), dtype="float32")
    assert tuple(p.shape) == (4, 4) and not p.stop_gradient

    def reader():
        yield from range(7)

    batches = list(paddle.batch(reader, 3)())
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(paddle.batch(reader, 3, drop_last=True)()) == \
        [[0, 1, 2], [3, 4, 5]]


def test_flops_counts_matmuls():
    import paddle_tpu.nn as nn

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(32, 64)

        def forward(self, x):
            return self.fc(x)

    n = paddle.flops(M(), (8, 32))
    assert n >= 2 * 8 * 32 * 64      # at least the gemm


def test_places_and_dtype_exports():
    assert paddle.CUDAPinnedPlace is paddle.CPUPlace
    assert paddle.NPUPlace is paddle.TPUPlace
    assert paddle.dtype("float32") == np.float32
    assert paddle.bool == np.bool_
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)
    paddle.set_printoptions(precision=4)
    paddle.disable_signal_handler()
    paddle.check_shape((2, -1, 3))
