"""Top-level API tail (reference: python/paddle/__init__.py __all__)."""

import numpy as np

import paddle_tpu as paddle


def test_addmm_broadcast_conj_diagonal():
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    y = paddle.to_tensor(np.ones((3, 2), np.float32))
    inp = paddle.to_tensor(np.full((2, 2), 2.0, np.float32))
    out = paddle.addmm(inp, x, y, beta=0.5, alpha=2.0)
    np.testing.assert_allclose(out.numpy(), 0.5 * 2.0 + 2.0 * 3.0)

    a, b = paddle.broadcast_tensors([
        paddle.to_tensor(np.ones((1, 4), np.float32)),
        paddle.to_tensor(np.ones((3, 1), np.float32))])
    assert a.shape == [3, 4] and b.shape == [3, 4]

    z = paddle.to_tensor(np.array([1 + 2j, 3 - 4j], np.complex64))
    np.testing.assert_allclose(paddle.conj(z).numpy(),
                               np.array([1 - 2j, 3 + 4j], np.complex64))

    m = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
    np.testing.assert_allclose(paddle.diagonal(m).numpy(), [0, 4, 8])


def test_inplace_variants_mutate_and_autograd():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    r = paddle.reshape_(x, (3, 2))
    assert r is x and x.shape == [3, 2]
    paddle.unsqueeze_(x, 0)
    assert x.shape == [1, 3, 2]
    paddle.squeeze_(x, 0)
    assert x.shape == [3, 2]
    t = paddle.to_tensor(np.zeros((2,), np.float32))
    paddle.tanh_(t)
    np.testing.assert_allclose(t.numpy(), 0.0)


def test_rank_shape_reverse_floor_mod():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert int(paddle.rank(x)) == 2
    assert paddle.shape(x).numpy().tolist() == [2, 3]
    np.testing.assert_allclose(paddle.reverse(x, 1).numpy()[:, 0],
                               [2.0, 5.0])
    np.testing.assert_allclose(
        paddle.floor_mod(paddle.to_tensor(np.array([7.0], np.float32)),
                         paddle.to_tensor(np.array([3.0], np.float32)))
        .numpy(), [1.0])


def test_create_parameter_and_batch_reader():
    p = paddle.create_parameter((4, 4), dtype="float32")
    assert tuple(p.shape) == (4, 4) and not p.stop_gradient

    def reader():
        yield from range(7)

    batches = list(paddle.batch(reader, 3)())
    assert batches == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(paddle.batch(reader, 3, drop_last=True)()) == \
        [[0, 1, 2], [3, 4, 5]]


def test_flops_counts_matmuls():
    import paddle_tpu.nn as nn

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(32, 64)

        def forward(self, x):
            return self.fc(x)

    n = paddle.flops(M(), (8, 32))
    assert n >= 2 * 8 * 32 * 64      # at least the gemm


def test_places_and_dtype_exports():
    assert paddle.CUDAPinnedPlace is paddle.CPUPlace
    assert paddle.NPUPlace is paddle.TPUPlace
    assert paddle.dtype("float32") == np.float32
    assert paddle.bool == np.bool_
    st = paddle.get_cuda_rng_state()
    paddle.set_cuda_rng_state(st)
    paddle.set_printoptions(precision=4)
    paddle.disable_signal_handler()
    paddle.check_shape((2, -1, 3))


def test_diag_embed_fill_diagonal_clip_edit_distance():
    v = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    m = paddle.diag_embed(v)
    assert m.shape == [2, 2, 2]
    np.testing.assert_allclose(m.numpy()[0], [[1, 0], [0, 2]])
    mo = paddle.diag_embed(v, offset=1)
    assert mo.shape == [2, 3, 3]
    np.testing.assert_allclose(mo.numpy()[1],
                               [[0, 3, 0], [0, 0, 4], [0, 0, 0]])

    x = paddle.to_tensor(np.zeros((3, 3), np.float32))
    paddle.fill_diagonal_(x, 5.0)
    np.testing.assert_allclose(x.numpy(), np.eye(3) * 5.0)

    big = paddle.to_tensor(np.full((4,), 10.0, np.float32))
    clipped = paddle.clip_by_norm(big, 5.0)
    np.testing.assert_allclose(np.linalg.norm(clipped.numpy()), 5.0,
                               rtol=1e-5)
    small = paddle.to_tensor(np.full((4,), 0.1, np.float32))
    np.testing.assert_allclose(paddle.clip_by_norm(small, 5.0).numpy(),
                               small.numpy())

    hyp = paddle.to_tensor(np.array([[1, 2, 3, 0]], np.int64))
    ref = paddle.to_tensor(np.array([[1, 3, 3, 0]], np.int64))
    d, n = paddle.edit_distance(hyp, ref, normalized=False,
                                input_length=np.array([3]),
                                label_length=np.array([3]))
    assert float(d.numpy()[0, 0]) == 1.0 and int(n.numpy()[0]) == 1
    dn, _ = paddle.edit_distance(hyp, ref, normalized=True,
                                 input_length=np.array([3]),
                                 label_length=np.array([3]))
    np.testing.assert_allclose(float(dn.numpy()[0, 0]), 1 / 3, rtol=1e-5)


def test_fill_diagonal_rectangular_offsets():
    x = paddle.to_tensor(np.zeros((5, 3), np.float32))
    paddle.fill_diagonal_(x, 7.0, offset=-2)
    got = x.numpy()
    expect = np.zeros((5, 3), np.float32)
    expect[2, 0] = expect[3, 1] = expect[4, 2] = 7.0
    np.testing.assert_allclose(got, expect)
