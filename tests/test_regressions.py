"""Regression tests for round-1 advisor findings (ADVICE.md)."""

import hashlib

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def test_to_static_backward_uses_current_rng_key():
    """The cached compiled backward must rematerialize the forward with the
    CURRENT call's RNG key — dropout grads must match the mask actually
    sampled in that step's forward, not step 1's."""

    class M(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = paddle.nn.Linear(4, 4)

        def forward(self, x):
            # keep fc in the graph so the training path is taken, but make
            # the output depend on x only through the dropout mask
            return F.dropout(x, p=0.5) + 0.0 * self.fc(x).sum()

    model = M()
    model = paddle.jit.to_static(model)

    for step in range(3):
        x = paddle.to_tensor(np.full((16, 4), 2.0, np.float32),
                             stop_gradient=False)
        y = model(x)
        mask_scale = y.numpy() / 2.0  # 0 or 1/(1-p) per element
        y.backward(paddle.to_tensor(np.ones((16, 4), np.float32)))
        np.testing.assert_allclose(x.grad.numpy(), mask_scale, rtol=1e-5,
                                   err_msg=f"step {step}: backward used a "
                                           "stale dropout mask")


def test_grad_scaler_no_double_unscale():
    model = paddle.nn.Linear(3, 1)
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)

    x = paddle.to_tensor(np.ones((4, 3), np.float32))
    loss = model(x).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    g = model.weight.grad.numpy().copy()
    # documented pattern: unscale_ -> clip -> step -> update must not
    # re-divide (reference: grad_scaler.py:159 docstring pattern)
    scaler.step(opt)
    np.testing.assert_allclose(g, model.weight.grad.numpy(), rtol=1e-6)
    scaler.update()

    # explicit double unscale_ raises (reference parity)
    loss = model(x).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    with pytest.raises(RuntimeError):
        scaler.unscale_(opt)

    # step without an intervening update also raises (reference parity)
    scaler.step(opt)
    with pytest.raises(RuntimeError):
        scaler.step(opt)


def test_weighted_cross_entropy_mean_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(0)
    logits = rng.randn(8, 5).astype(np.float32)
    labels = rng.randint(0, 5, (8,)).astype(np.int64)
    weight = rng.rand(5).astype(np.float32) + 0.1

    ours = F.cross_entropy(paddle.to_tensor(logits), paddle.to_tensor(labels),
                           weight=paddle.to_tensor(weight), reduction="mean")
    ref = torch.nn.functional.cross_entropy(
        torch.tensor(logits), torch.tensor(labels), weight=torch.tensor(weight))
    np.testing.assert_allclose(ours.numpy(), ref.numpy(), rtol=1e-5)


def test_rng_stream_id_deterministic():
    # use a name no other test registers an explicit offset for
    from paddle_tpu.core.random import _stream_id
    expected = (int.from_bytes(
        hashlib.sha256(b"regr_stream_check").digest()[:4], "little") & 0x7FFFFFFF)
    assert _stream_id("regr_stream_check") == (expected or 1)


def test_state_dict_filters_sublayer_non_persistable_buffers():
    class Sub(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.register_buffer("scratch", paddle.to_tensor([1.0]),
                                 persistable=False)
            self.register_buffer("kept", paddle.to_tensor([2.0]))

    class Root(paddle.nn.Layer):
        def __init__(self):
            super().__init__()
            self.sub = Sub()

    sd = Root().state_dict()
    assert "sub.kept" in sd
    assert "sub.scratch" not in sd


def test_linear_matmul_precision_flag():
    """f32 linear runs at full precision by default (tpu_matmul_precision)."""
    rng = np.random.RandomState(0)
    x = rng.randn(32, 64).astype(np.float32)
    w = rng.randn(64, 16).astype(np.float32)
    out = F.linear(paddle.to_tensor(x), paddle.to_tensor(w))
    np.testing.assert_allclose(out.numpy(), x @ w, rtol=1e-5, atol=1e-5)


def test_eager_jit_cache_correct_and_hit():
    """FLAGS_eager_jit_ops: tape-path ops run through a cached jitted
    fwd + remat-bwd pair — identical values AND grads to the uncached
    path, and repeated calls reuse one cache entry."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core import tensor as T

    rng = np.random.default_rng(0)
    xv = rng.standard_normal((8, 8)).astype(np.float32)
    yv = rng.standard_normal((8, 8)).astype(np.float32)

    def run():
        x = paddle.to_tensor(xv); x.stop_gradient = False
        y = paddle.to_tensor(yv); y.stop_gradient = False
        z = (x * y + x).sum()
        z.backward()
        return float(z), np.asarray(x.grad._data), np.asarray(y.grad._data)

    paddle.set_flags({"eager_jit_ops": False})
    try:
        z0, gx0, gy0 = run()
    finally:
        paddle.set_flags({"eager_jit_ops": True})
    T._EAGER_FN_CACHE.clear()
    z1, gx1, gy1 = run()
    assert z0 == z1
    np.testing.assert_allclose(gx0, gx1, rtol=1e-6)
    np.testing.assert_allclose(gy0, gy1, rtol=1e-6)

    n_after_first = len(T._EAGER_FN_CACHE)
    assert n_after_first > 0
    for _ in range(5):
        run()
    assert len(T._EAGER_FN_CACHE) == n_after_first   # all hits, no growth


def test_eager_jit_cache_skips_closures():
    """Closure-capturing fns (dropout's key, scalar binops) must NOT be
    cached — captured values are invisible to the cache key."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import _eager_cacheable

    import jax.numpy as jnp

    two = 2.0

    def with_closure(a):
        return a * two

    def local_no_closure(a):
        return a * 2

    assert not _eager_cacheable(with_closure, {})
    # local defs/lambdas have per-call-site identity -> not cacheable
    assert not _eager_cacheable(local_no_closure, {})
    # stable module-level callables are
    assert _eager_cacheable(jnp.add, {})

    # dropout behaves stochastically per call (key captured in closure):
    # two eager dropout calls differ -> proves it was not served from a
    # stale cached program
    paddle.seed(0)
    x = paddle.to_tensor(np.ones((64,), np.float32))
    a = np.asarray(paddle.nn.functional.dropout(x, 0.5)._data)
    b = np.asarray(paddle.nn.functional.dropout(x, 0.5)._data)
    assert not np.array_equal(a, b)


def test_amp_eager_backward_across_listed_boundaries():
    """The AMP cast lives INSIDE the taped function: eager backward must
    work across white/black-listed op boundaries (conv -> bn), and a
    backward issued OUTSIDE the autocast context must replay the
    forward's policy in the deferred cached trace."""
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(0)
    conv = nn.Conv2D(3, 8, 3, padding=1)
    bn = nn.BatchNorm2D(8)
    x = paddle.to_tensor(np.random.default_rng(0)
                         .normal(size=(2, 3, 8, 8)).astype(np.float32))
    x.stop_gradient = False
    with paddle.amp.auto_cast(level="O1"):
        y = bn(conv(x))
    y.sum().backward()                 # outside the context
    assert x.grad is not None
    assert np.isfinite(np.asarray(x.grad._data, dtype=np.float32)).all()

    # deferred cached backward of a black-listed cacheable op
    z = paddle.to_tensor(np.ones((64, 64), np.float32))
    z._data = z._data.astype(jnp.bfloat16)
    z.stop_gradient = False
    with paddle.amp.auto_cast(level="O1"):
        e = paddle.exp(z)              # black-listed: f32 compute
    assert str(e.dtype) == "float32"
    e.sum().backward()
    assert str(z.grad.dtype) == "bfloat16"
    np.testing.assert_allclose(np.asarray(z.grad._data, np.float32),
                               np.e, rtol=2e-2)


def test_bn_ema_buffers_stay_f32_under_amp():
    """batch_norm is dtype-preserving under AMP: the f32 running-stat
    buffers must never round through bf16 — at O1 (no cast) NOR at O2
    (where a blanket cast would hit every float input). Round-5 review
    finding; round-3 invariant."""
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    for level in ("O1", "O2"):
        paddle.seed(0)
        bn = nn.BatchNorm2D(8)
        bn.train()
        x = paddle.to_tensor(
            np.random.default_rng(0).normal(size=(4, 8, 5, 5))
            .astype(np.float32))
        with paddle.amp.auto_cast(level=level):
            y = bn(x.astype("bfloat16") if level == "O1" else x)
        assert bn._mean._data.dtype == jnp.float32, (level,
                                                     bn._mean._data.dtype)
        assert bn._variance._data.dtype == jnp.float32, level
        # and the op preserves its input dtype (bf16 stream stays bf16)
        if level == "O1":
            assert y._data.dtype == jnp.bfloat16, y._data.dtype


def test_eager_dispatch_cache_covers_vision_hot_loop():
    """Eager-dispatch recovery (the LeNet-eager perf leg): every op in a
    warm LeNet train step must dispatch through the token-keyed eager jit
    cache — zero misses on the steady-state loop, so the 100 us/op vjp
    re-trace never runs hot."""
    from paddle_tpu.core import tensor as ct
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = LeNet()
    opt = Momentum(learning_rate=0.01, parameters=model.parameters())
    x = paddle.to_tensor(np.random.default_rng(0)
                         .normal(size=(8, 1, 28, 28)).astype(np.float32))
    y = paddle.to_tensor(np.zeros((8,), np.int64))

    def one():
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    one()                                   # warm the caches
    ct._EAGER_CACHE_STATS.update(hits=0, misses=0)
    before = len(ct._EAGER_FN_CACHE)
    one()
    assert ct._EAGER_CACHE_STATS["misses"] == 0, \
        "steady-state LeNet step re-traced an op (cache miss)"
    # conv/pool/linear/flatten/cross_entropy all ride the cache: the fwd
    # has >= 10 cached dispatches
    assert ct._EAGER_CACHE_STATS["hits"] >= 10
    assert len(ct._EAGER_FN_CACHE) == before


def test_cache_token_distinguishes_op_configs():
    """Two calls of the same op with different closure config (stride) must
    NOT share a cache entry — the token keys them apart."""
    w = paddle.to_tensor(np.random.default_rng(1)
                         .normal(size=(4, 3, 3, 3)).astype(np.float32))
    x = paddle.to_tensor(np.random.default_rng(2)
                         .normal(size=(1, 3, 8, 8)).astype(np.float32),
                         stop_gradient=False)
    y1 = F.conv2d(x, w, stride=1, padding=1)
    y2 = F.conv2d(x, w, stride=2, padding=1)
    assert y1.shape == [1, 4, 8, 8]
    assert y2.shape == [1, 4, 4, 4]        # a shared entry would be wrong
