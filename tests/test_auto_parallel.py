"""Auto-parallel annotation tests on the 8-device mesh.

reference analogue: test_auto_parallel_api.py (shard_tensor/shard_op
annotations recorded with correct dims_mapping); here annotation IS
placement, so the assertions check real shard layouts.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import (ProcessMesh, shard_op,
                                                  shard_tensor)


def test_process_mesh_topology():
    pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    assert pm.shape == [2, 4]
    assert pm.dim_names == ["x", "y"]
    assert pm.process_ids == list(range(8))
    assert tuple(pm.mesh.axis_names) == ("x", "y")


def test_shard_tensor_places_shards():
    pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    t = paddle.to_tensor(np.arange(8 * 12, dtype=np.float32)
                         .reshape(8, 12))
    out = shard_tensor(t, dist_attr={"process_mesh": pm,
                                     "dims_mapping": [0, 1]})
    shards = {s.data.shape for s in out._data.addressable_shards}
    assert shards == {(4, 3)}           # 8/2 x 12/4

    # -1 keeps a dim replicated
    t2 = paddle.to_tensor(np.zeros((8, 12), np.float32))
    out2 = shard_tensor(t2, process_mesh=pm, dims_mapping=[0, -1])
    assert {s.data.shape for s in out2._data.addressable_shards} == {(4, 12)}


def test_shard_tensor_reference_dict_form():
    out = shard_tensor(
        paddle.to_tensor(np.ones((4, 6), np.float32)),
        dist_attr={"process_mesh": [[0, 1], [2, 3]],
                   "dims_mapping": [0, -1]})
    assert {s.data.shape for s in out._data.addressable_shards} == {(2, 6)}


def test_shard_op_places_inputs():
    pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
    x = paddle.to_tensor(np.ones((4, 6), np.float32))
    y = paddle.to_tensor(np.zeros((4, 6), np.float32))
    dist_add = shard_op(paddle.add, dist_attr={
        "process_mesh": pm,
        x: {"dims_mapping": [0, -1]},
        y: {"dims_mapping": [0, -1]},
    })
    out = dist_add(x, y)
    np.testing.assert_allclose(out.numpy(), np.ones((4, 6)))
    assert {s.data.shape for s in x._data.addressable_shards} == {(2, 6)}


def test_annotations_compose_with_jit():
    import jax
    pm = ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["dp"])
    x = shard_tensor(paddle.to_tensor(np.ones((8, 4), np.float32)),
                     process_mesh=pm, dims_mapping=[0])
    f = jax.jit(lambda a: a * 2)
    out = f(x._data)
    # layout preserved through jit
    assert {s.data.shape for s in out.addressable_shards} == {(1, 4)}


def test_reshard_between_different_meshes():
    """Runtime reshard moves a tensor between ARBITRARY meshes (reference:
    auto_parallel/reshard.py Resharder): different axis names, shapes and
    device orders — values bitwise identical, layout matches the target."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import (ProcessMesh,
                                                      reshard,
                                                      shard_tensor)

    if len(jax.devices()) < 8:
        import pytest
        pytest.skip("needs 8 virtual devices")

    mesh_a = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]],
                         dim_names=["x", "y"])
    mesh_b = ProcessMesh([[7, 6], [5, 4], [3, 2], [1, 0]],
                         dim_names=["p", "q"])

    data = np.arange(8 * 16, dtype=np.float32).reshape(8, 16)
    t = shard_tensor(paddle.to_tensor(data), process_mesh=mesh_a,
                     dims_mapping=[0, -1])          # rows over x
    assert t._data.sharding.spec == P("x", None)

    out = reshard(t, process_mesh=mesh_b, dims_mapping=[1, 0])
    np.testing.assert_array_equal(np.asarray(out._data), data)
    s = out._data.sharding
    assert isinstance(s, NamedSharding)
    assert s.mesh.axis_names == ("p", "q")
    assert s.spec == P("q", "p")
    # each shard holds rows/2 x cols/4
    shapes = {sh.data.shape for sh in out._data.addressable_shards}
    assert shapes == {(8 // 2, 16 // 4)}

    # replicate-on-target shorthand (dims_mapping omitted)
    rep = reshard(out, process_mesh=mesh_a)
    np.testing.assert_array_equal(np.asarray(rep._data), data)
    assert {sh.data.shape for sh in rep._data.addressable_shards} \
        == {(8, 16)}


def test_reshard_rejects_traced_values():
    import jax
    import numpy as np
    import pytest

    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed.auto_parallel import ProcessMesh, reshard

    if len(jax.devices()) < 2:
        pytest.skip("needs 2 devices")
    mesh = ProcessMesh([0, 1], dim_names=["d"])

    def f(a):
        with pytest.raises(ValueError, match="traced"):
            reshard(Tensor(a), process_mesh=mesh, dims_mapping=[0])
        return a

    jax.jit(f)(np.ones((4,), np.float32))
