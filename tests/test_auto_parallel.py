"""Auto-parallel annotation tests on the 8-device mesh.

reference analogue: test_auto_parallel_api.py (shard_tensor/shard_op
annotations recorded with correct dims_mapping); here annotation IS
placement, so the assertions check real shard layouts.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.auto_parallel import (ProcessMesh, shard_op,
                                                  shard_tensor)


def test_process_mesh_topology():
    pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    assert pm.shape == [2, 4]
    assert pm.dim_names == ["x", "y"]
    assert pm.process_ids == list(range(8))
    assert tuple(pm.mesh.axis_names) == ("x", "y")


def test_shard_tensor_places_shards():
    pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["x", "y"])
    t = paddle.to_tensor(np.arange(8 * 12, dtype=np.float32)
                         .reshape(8, 12))
    out = shard_tensor(t, dist_attr={"process_mesh": pm,
                                     "dims_mapping": [0, 1]})
    shards = {s.data.shape for s in out._data.addressable_shards}
    assert shards == {(4, 3)}           # 8/2 x 12/4

    # -1 keeps a dim replicated
    t2 = paddle.to_tensor(np.zeros((8, 12), np.float32))
    out2 = shard_tensor(t2, process_mesh=pm, dims_mapping=[0, -1])
    assert {s.data.shape for s in out2._data.addressable_shards} == {(4, 12)}


def test_shard_tensor_reference_dict_form():
    out = shard_tensor(
        paddle.to_tensor(np.ones((4, 6), np.float32)),
        dist_attr={"process_mesh": [[0, 1], [2, 3]],
                   "dims_mapping": [0, -1]})
    assert {s.data.shape for s in out._data.addressable_shards} == {(2, 6)}


def test_shard_op_places_inputs():
    pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["x", "y"])
    x = paddle.to_tensor(np.ones((4, 6), np.float32))
    y = paddle.to_tensor(np.zeros((4, 6), np.float32))
    dist_add = shard_op(paddle.add, dist_attr={
        "process_mesh": pm,
        x: {"dims_mapping": [0, -1]},
        y: {"dims_mapping": [0, -1]},
    })
    out = dist_add(x, y)
    np.testing.assert_allclose(out.numpy(), np.ones((4, 6)))
    assert {s.data.shape for s in x._data.addressable_shards} == {(2, 6)}


def test_annotations_compose_with_jit():
    import jax
    pm = ProcessMesh([0, 1, 2, 3, 4, 5, 6, 7], dim_names=["dp"])
    x = shard_tensor(paddle.to_tensor(np.ones((8, 4), np.float32)),
                     process_mesh=pm, dims_mapping=[0])
    f = jax.jit(lambda a: a * 2)
    out = f(x._data)
    # layout preserved through jit
    assert {s.data.shape for s in out.addressable_shards} == {(1, 4)}
