"""Checkpoint/resume + export round-trip tests.

Analogue of the reference's save/load + inference-model tests
(reference: test_jit_save_load.py, test_static_save_load.py — resume
training from a checkpoint matches uninterrupted training; a loaded
inference model reproduces outputs).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.optimizer import AdamW


def _model_and_step():
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    def loss_fn(layer, x, y):
        return F.cross_entropy(layer(x), y)

    opt = AdamW(learning_rate=1e-2, parameters=model.parameters())
    return model, TrainStep(model, loss_fn, opt)


def _data(seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randint(0, 4, (16,)).astype(np.int64)
    return x, y


def test_trainstep_resume_bit_exact(tmp_path):
    x, y = _data()
    path = str(tmp_path / "ckpt.pkl")

    # uninterrupted: 6 steps
    paddle.seed(42)
    _, step_a = _model_and_step()
    for _ in range(3):
        step_a(x, y)
    # interrupted: 3 steps, checkpoint, fresh process-state, restore, 3 more
    state = step_a.state_dict()
    step_a.save(path)
    ref_losses = [float(step_a(x, y)) for _ in range(3)]

    paddle.seed(999)                       # clobber RNG to prove restore
    _, step_b = _model_and_step()          # fresh params/opt
    step_b.load(path)
    assert step_b.step_count == 3
    res_losses = [float(step_b(x, y)) for _ in range(3)]
    np.testing.assert_allclose(ref_losses, res_losses, rtol=0, atol=0)

    # the saved state is host-side numpy (safe to pickle/ship)
    assert isinstance(next(iter(state["params"].values())), np.ndarray)


def test_state_dict_includes_all_components():
    paddle.seed(0)
    _, step = _model_and_step()
    x, y = _data()
    step(x, y)
    sd = step.state_dict()
    assert set(sd) >= {"params", "frozen", "buffers", "opt_state",
                       "step_count", "rng_state"}
    assert sd["step_count"] == 1


def test_jit_save_load_runnable(tmp_path):
    from paddle_tpu.static import InputSpec

    paddle.seed(1)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model.eval()
    path = str(tmp_path / "inference/model")
    paddle.jit.save(model, path,
                    input_spec=[InputSpec((2, 8), "float32")])

    x = np.random.RandomState(2).randn(2, 8).astype(np.float32)
    with paddle.no_grad():
        ref = model(paddle.to_tensor(x)).numpy()

    loaded = paddle.jit.load(path)
    out = loaded(x)
    np.testing.assert_allclose(ref, out.numpy(), rtol=1e-6)
    # weights surface for inspection
    assert any("weight" in k for k in loaded.state_dict())


def test_jit_load_params_only(tmp_path):
    paddle.seed(3)
    model = nn.Linear(4, 4)
    path = str(tmp_path / "weights/model")
    paddle.jit.save(model, path)           # no input_spec -> params only
    got = paddle.jit.load(path)
    assert isinstance(got, dict)
    assert any("weight" in k for k in got)
