"""End-to-end inference.Config/create_predictor coverage over the
conv+BN weight-folding pass (inference/passes.py) with the bf16 and int8
weight passes — live-Layer and jit.save round trips, parity vs eager
(ISSUE 6 satellite: passes.py previously had no e2e predictor test)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference
from paddle_tpu.jit.input_spec import InputSpec
from paddle_tpu.nn import BatchNorm2D, Conv2D, Linear
from paddle_tpu.nn import functional as F


class ConvBNNet(paddle.nn.Layer):
    """Conv→BN→ReLU ×2 + classifier head: the exact chain fold_conv_bn
    rewrites (it folds BN stats into the conv weights/bias)."""

    def __init__(self):
        super().__init__()
        self.conv1 = Conv2D(3, 8, 3, padding=1)
        self.bn1 = BatchNorm2D(8)
        self.conv2 = Conv2D(8, 8, 3, padding=1)
        self.bn2 = BatchNorm2D(8)
        self.fc = Linear(8 * 8 * 8, 10)

    def forward(self, x):
        x = F.relu(self.bn1(self.conv1(x)))
        x = F.relu(self.bn2(self.conv2(x)))
        return self.fc(x.reshape((x.shape[0], -1)))


def _net():
    paddle.seed(7)
    m = ConvBNNet()
    # non-trivial BN running stats so folding actually changes weights
    m.train()
    rng = np.random.default_rng(0)
    for _ in range(3):
        m(paddle.to_tensor(
            rng.normal(size=(4, 3, 8, 8)).astype(np.float32) * 2 + 0.5))
    m.eval()
    return m


def _x(seed=1):
    return np.random.default_rng(seed).normal(
        size=(4, 3, 8, 8)).astype(np.float32)


def test_live_layer_fold_parity():
    m = _net()
    x = _x()
    ref = m(paddle.to_tensor(x)).numpy()
    cfg = inference.Config.from_layer(m, [InputSpec((4, 3, 8, 8),
                                                    "float32")])
    pred = inference.create_predictor(cfg)
    out = pred.run([x])[0]
    # fold_conv_bn rewrites parameter values: same function, float
    # reassociation only
    np.testing.assert_allclose(out, ref, atol=2e-4, rtol=2e-4)


def test_live_layer_bf16_pass_parity():
    m = _net()
    x = _x(2)
    ref = m(paddle.to_tensor(x)).numpy()
    cfg = inference.Config.from_layer(m, [InputSpec((4, 3, 8, 8),
                                                    "float32")])
    cfg.enable_tpu_bf16()
    pred = inference.create_predictor(cfg)
    out = pred.run([x])[0]
    assert out.shape == ref.shape
    # bf16 weights: ~3 significant decimal digits
    np.testing.assert_allclose(out, ref, atol=0.15, rtol=0.15)
    # the pass applies to the predictor's copy, not the live layer
    assert m.conv1.weight.numpy().dtype == np.float32


def test_live_layer_int8_pass_parity():
    m = _net()
    x = _x(3)
    ref = m(paddle.to_tensor(x)).numpy()
    cfg = inference.Config.from_layer(m, [InputSpec((4, 3, 8, 8),
                                                    "float32")])
    cfg.enable_int8()
    pred = inference.create_predictor(cfg)
    out = pred.run([x])[0]
    # weight-only int8 (per-channel): agreement to a few percent and the
    # ranking of logits should survive quantization
    np.testing.assert_allclose(out, ref, atol=0.3, rtol=0.3)
    assert (out.argmax(-1) == ref.argmax(-1)).mean() >= 0.75


class MLPNet(paddle.nn.Layer):
    """Kernel-eligible head (128-aligned in/out): the int8 weight pass
    keeps these weights int8 THROUGH the matmul (ops.pallas.quant_matmul)
    instead of dequantizing to f32 at load."""

    def __init__(self):
        super().__init__()
        self.fc1 = Linear(256, 128)
        self.fc2 = Linear(128, 128)

    def forward(self, x):
        return self.fc2(F.relu(self.fc1(x)))


@pytest.mark.pallas
def test_int8_pass_serves_the_kernel_with_parity():
    """ISSUE 7 satellite pin: enable_int8 + FLAGS_pallas_int8 runs the
    predictor's linears int8-end-to-end (W8A8-dynamic through the Pallas
    kernel) with output parity to the f32 layer within quantization
    error; the kill switch restores the pre-PR dequantize-to-float pass
    bit for bit."""
    from paddle_tpu.core.flags import flag_scope
    from paddle_tpu.ops import pallas as pallas_ops
    paddle.seed(9)
    m = MLPNet()
    m.eval()
    x = np.random.default_rng(6).normal(size=(4, 256)).astype(np.float32)
    ref = m(paddle.to_tensor(x)).numpy()

    def _int8_out():
        cfg = inference.Config.from_layer(
            MLPNet(), [InputSpec((4, 256), "float32")])
        # fresh layer each build: quantize_weights rewrites in place
        cfg.layer.set_state_dict(m.state_dict())
        cfg.layer.eval()
        cfg.enable_int8()
        return inference.create_predictor(cfg).run([x])[0]

    out_kernel = _int8_out()
    assert not any(k[0] == "int8_matmul" and k[1] == "shape"
                   for k in pallas_ops.PALLAS_STATS), \
        "the 128-aligned MLP must serve the kernel, not the shape fallback"
    rel = np.abs(out_kernel - ref).max() / np.abs(ref).max()
    assert rel < 0.08, rel
    with flag_scope("pallas_int8", False):
        out_off = _int8_out()
    # kill switch = the pre-PR weight-only pass: dequantize into the
    # f32 gemm — and the kernel path really is a different computation
    assert not np.array_equal(out_kernel, out_off)
    rel = np.abs(out_off - ref).max() / np.abs(ref).max()
    assert rel < 0.08, rel


def test_jit_save_roundtrip_through_predictor(tmp_path):
    m = _net()
    x = _x(4)
    ref = m(paddle.to_tensor(x)).numpy()
    from paddle_tpu.jit.to_static import save as jsave
    jsave(m, str(tmp_path / "convbn"),
          input_spec=[InputSpec((4, 3, 8, 8), "float32")])
    pred = inference.create_predictor(
        inference.Config(str(tmp_path / "convbn")))
    # zero-copy handle surface
    h = pred.get_input_handle(pred.get_input_names()[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_save_optimized_model_reload_parity(tmp_path):
    m = _net()
    x = _x(5)
    cfg = inference.Config.from_layer(m, [InputSpec((4, 3, 8, 8),
                                                    "float32")])
    pred = inference.create_predictor(cfg)
    first = pred.run([x])[0]
    pred.save_optimized_model(str(tmp_path / "opt"))
    pred2 = inference.create_predictor(
        inference.Config(str(tmp_path / "opt")))
    second = pred2.run([x])[0]
    # the re-exported optimized bundle replays the optimized predictor
    np.testing.assert_allclose(second, first, atol=1e-5, rtol=1e-5)


def test_precision_warning_on_frozen_export(tmp_path):
    m = _net()
    from paddle_tpu.jit.to_static import save as jsave
    jsave(m, str(tmp_path / "m"),
          input_spec=[InputSpec((4, 3, 8, 8), "float32")])
    cfg = inference.Config(str(tmp_path / "m"))
    cfg.enable_tpu_bf16()
    with pytest.warns(UserWarning, match="already compiled"):
        inference.create_predictor(cfg)
