"""SSD disk-backed sparse table (reference:
fluid/distributed/table/ssd_sparse_table.h:21 — cold rows on local disk
behind a hot cache, same pull/push protocol)."""

import numpy as np
import pytest

from paddle_tpu.distributed.ps.ssd_table import SSDSparseTable


def test_grows_past_memory_cap_and_spills(tmp_path):
    t = SSDSparseTable(num_rows=10_000, dim=8, cache_rows=16,
                       path=str(tmp_path / "t.log"), seed=3)
    ids = np.arange(200)
    first = t.pull(ids).copy()                 # touch 200 rows, cap 16
    assert t.resident_rows <= 16
    assert t.evict_count > 0
    assert t.spilled_rows >= 200 - 16
    assert t.log_bytes() > 0
    # spilled rows read back EXACTLY (round-trip through the log)
    again = t.pull(ids)
    np.testing.assert_array_equal(first, again)
    # deterministic lazy init: a fresh table over the same seed agrees
    t2 = SSDSparseTable(num_rows=10_000, dim=8, cache_rows=300,
                        path=str(tmp_path / "t2.log"), seed=3)
    np.testing.assert_array_equal(first, t2.pull(ids))
    t.close()
    t2.close()


def test_push_updates_survive_eviction(tmp_path):
    t = SSDSparseTable(num_rows=1000, dim=4, cache_rows=8, lr=0.5,
                       optimizer="sgd", path=str(tmp_path / "t.log"))
    ids = np.asarray([3, 7, 3])                # duplicate id accumulates
    before = t.pull(np.asarray([3, 7])).copy()
    g = np.ones((3, 4), np.float32)
    t.push(ids, g)
    after = t.pull(np.asarray([3, 7]))
    np.testing.assert_allclose(after[0], before[0] - 0.5 * 2.0, rtol=1e-6)
    np.testing.assert_allclose(after[1], before[1] - 0.5 * 1.0, rtol=1e-6)
    # force both rows out of cache, then read back the UPDATED values
    t.pull(np.arange(100, 140))
    assert 3 not in t._cache and 7 not in t._cache
    np.testing.assert_allclose(t.pull(np.asarray([3, 7])), after,
                               rtol=1e-6)
    t.close()


def test_adagrad_matches_in_memory_table(tmp_path):
    """Optimizer semantics match SparseTable exactly on the same grads."""
    from paddle_tpu.distributed.ps import SparseTable
    mem = SparseTable(64, 4, optimizer="adagrad", lr=0.1, seed=0)
    ssd = SSDSparseTable(64, 4, cache_rows=4, optimizer="adagrad", lr=0.1,
                         path=str(tmp_path / "t.log"))
    ids = np.asarray([1, 5, 9, 1])
    # align starting rows (initializers differ by design: lazy vs eager)
    ssd_start = ssd.pull(np.unique(ids))
    mem.data[np.unique(ids)] = ssd_start
    rng = np.random.default_rng(0)
    for _ in range(5):
        g = rng.normal(size=(4, 4)).astype(np.float32)
        mem.push(ids, g)
        ssd.push(ids, g)
    np.testing.assert_allclose(mem.pull(np.unique(ids)),
                               ssd.pull(np.unique(ids)), rtol=1e-5,
                               atol=1e-6)
    ssd.close()


def test_save_load_roundtrip(tmp_path):
    t = SSDSparseTable(500, 8, cache_rows=8, path=str(tmp_path / "a.log"),
                       seed=11)
    ids = np.arange(40)
    t.push(ids, np.ones((40, 8), np.float32))
    want = t.pull(ids).copy()
    t.save(str(tmp_path / "ckpt"))

    t2 = SSDSparseTable(500, 8, cache_rows=8,
                        path=str(tmp_path / "b.log"), seed=11)
    t2.load(str(tmp_path / "ckpt"))
    np.testing.assert_array_equal(t2.pull(ids), want)
    # adagrad slots restored too: identical next update
    g = np.full((40, 8), 0.5, np.float32)
    t.push(ids, g)
    t2.push(ids, g)
    np.testing.assert_allclose(t.pull(ids), t2.pull(ids), rtol=1e-6)
    t.close()
    t2.close()


def test_compact_reclaims_log(tmp_path):
    t = SSDSparseTable(1000, 8, cache_rows=4,
                       path=str(tmp_path / "t.log"))
    ids = np.arange(64)
    for _ in range(4):                          # rewrite rows repeatedly
        t.push(ids, np.ones((64, 8), np.float32))
        t.pull(np.arange(200, 232))             # churn the cache
    want = t.pull(ids).copy()
    before = t.log_bytes()
    t.compact()
    assert t.log_bytes() < before
    np.testing.assert_array_equal(t.pull(ids), want)
    t.close()


def test_compact_state_dict_roundtrip(tmp_path):
    """compact() must be invisible to checkpointing: the state_dict
    before and after a compaction is identical (ids, rows, adagrad
    slots), and a table restored from the post-compaction state serves
    the same rows — the log-structured file's live-set contract."""
    t = SSDSparseTable(2000, 4, cache_rows=8,
                       path=str(tmp_path / "c.log"), seed=2)
    rng = np.random.default_rng(1)
    for _ in range(5):
        ids = rng.integers(0, 100, size=32)
        t.push(ids, rng.normal(size=(32, 4)).astype(np.float32))
        t.pull(rng.integers(300, 400, size=16))    # churn + spill
    before = t.state_dict()
    t.compact()
    after = t.state_dict()
    np.testing.assert_array_equal(before["row_ids"], after["row_ids"])
    np.testing.assert_array_equal(before["data"], after["data"])
    np.testing.assert_array_equal(before["g2"], after["g2"])
    t2 = SSDSparseTable(2000, 4, cache_rows=8,
                        path=str(tmp_path / "c2.log"), seed=2)
    t2.load_state_dict(after)
    np.testing.assert_array_equal(t2.pull(before["row_ids"]),
                                  t.pull(before["row_ids"]))
    t.close()
    t2.close()


@pytest.mark.chaos
def test_ssd_snapshot_torn_commit_falls_back(tmp_path):
    """The log-structured table's torn-append drill (ISSUE 12): an SSD
    table snapshotted through the recsys manifest commit survives a
    chaos ``ckpt.write.torn`` fire — the torn snapshot never reads as
    valid and restore falls back to the previous committed one."""
    from paddle_tpu.recsys import load_tables, save_tables
    from paddle_tpu.testing import chaos

    t = SSDSparseTable(3000, 8, cache_rows=8,
                       path=str(tmp_path / "s.log"), seed=5)
    ids = np.arange(50)
    t.push(ids, np.ones((50, 8), np.float32))
    t.compact()                                 # snapshot a compacted log
    want = t.pull(ids).copy()
    save_tables(str(tmp_path / "snap"), {"ssd": t})
    t.push(ids, np.ones((50, 8), np.float32))
    with chaos.chaos_scope("ckpt.write.torn@1"):
        save_tables(str(tmp_path / "snap"), {"ssd": t})
    t2 = SSDSparseTable(3000, 8, cache_rows=8,
                        path=str(tmp_path / "s2.log"), seed=5)
    path = load_tables(str(tmp_path / "snap"), {"ssd": t2})
    assert path is not None and path.endswith("tables_1")
    np.testing.assert_allclose(t2.pull(ids), want, rtol=1e-6, atol=1e-7)
    t.close()
    t2.close()


def test_raw_row_access_skips_optimizer_and_cache(tmp_path):
    """read_rows/write_rows (the tier manager's promotion/demotion
    surface): verbatim values, no gradient math, no cache promotion."""
    t = SSDSparseTable(1000, 4, cache_rows=4,
                       path=str(tmp_path / "r.log"), seed=0)
    t.pull(np.arange(20))                       # spill most rows
    resident = set(t._cache)
    cold = [r for r in range(20) if r not in resident][:3]
    vecs, g2 = t.read_rows(cold)
    assert set(t._cache) == resident            # no promotion
    new = np.full((len(cold), 4), 7.0, np.float32)
    t.write_rows(cold, new, np.full(len(cold), 2.0, np.float32))
    np.testing.assert_array_equal(t.pull(cold), new)
    v2, g22 = t.read_rows(cold)
    np.testing.assert_array_equal(g22, np.full(len(cold), 2.0))
    t.close()


def test_distributed_embedding_over_ssd_table(tmp_path):
    """DistributedEmbedding trains over the SSD backend unchanged
    (protocol compatibility)."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.ps import DistributedEmbedding

    t = SSDSparseTable(1000, 16, cache_rows=32,
                       path=str(tmp_path / "e.log"))
    emb = DistributedEmbedding(1000, 16, table=t)
    ids = paddle.to_tensor(np.asarray([[1, 2], [3, 900]], np.int64))
    out = emb(ids)
    assert tuple(out.shape) == (2, 2, 16)
    loss = (out ** 2).sum()
    loss.backward()
    assert t.push_count == 1                   # grads streamed to disk tier
    t.close()
