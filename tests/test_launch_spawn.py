"""Launcher/spawn env-protocol tests.

Analogue of the reference's launch tests
(reference: test_launch_coverage.py, test_fleet_launch.sh — workers get
the right PADDLE_* env, failures propagate, logs land in log_dir).
JAX's multi-process handshake itself is not exercised here (single-host
CI); init_parallel_env consumes the same env vars these set.
"""

import os
import sys
import textwrap

import pytest

from paddle_tpu.distributed.launch import launch, main


def _write(tmp_path, body):
    p = tmp_path / "worker.py"
    p.write_text(textwrap.dedent(body))
    return str(p)


def test_launch_sets_env_protocol(tmp_path):
    out = tmp_path / "out"
    out.mkdir()
    script = _write(tmp_path, f"""
        import os
        rank = os.environ["PADDLE_TRAINER_ID"]
        with open(r"{out}" + "/rank_" + rank, "w") as f:
            f.write(",".join([
                os.environ["PADDLE_TRAINERS_NUM"],
                os.environ["PADDLE_MASTER"],
                os.environ["MASTER_PORT"],
                os.environ["PADDLE_LOCAL_RANK"],
            ]))
    """)
    rc = launch(script, [], nproc_per_node=2, port=23456)
    assert rc == 0
    got = sorted(os.listdir(out))
    assert got == ["rank_0", "rank_1"]
    body = (out / "rank_1").read_text().split(",")
    assert body == ["2", "127.0.0.1", "23456", "1"]


def test_launch_propagates_failure_and_stops_peers(tmp_path):
    script = _write(tmp_path, """
        import os, sys, time
        if os.environ["PADDLE_TRAINER_ID"] == "1":
            sys.exit(7)
        time.sleep(30)          # would hang; must be terminated
    """)
    import time
    t0 = time.time()
    rc = launch(script, [], nproc_per_node=2)
    assert rc == 7
    assert time.time() - t0 < 20, "peers not terminated on failure"


def test_launch_log_dir(tmp_path):
    script = _write(tmp_path, """
        import os
        print("hello from", os.environ["PADDLE_TRAINER_ID"])
    """)
    rc = launch(script, [], nproc_per_node=2, log_dir=str(tmp_path / "logs"))
    assert rc == 0
    logs = sorted(os.listdir(tmp_path / "logs"))
    assert logs == ["workerlog.0", "workerlog.1"]
    assert "hello from 0" in (tmp_path / "logs" / "workerlog.0").read_text()


def test_main_cli_args(tmp_path):
    script = _write(tmp_path, "pass")
    rc = main(["--nproc_per_node", "1", script])
    assert rc == 0


def _spawn_target(path):
    import os
    with open(os.path.join(
            path, f"spawned_{os.environ['PADDLE_TRAINER_ID']}"), "w") as f:
        f.write(os.environ["PADDLE_TRAINERS_NUM"])


def test_spawn_runs_workers(tmp_path):
    from paddle_tpu.distributed.spawn_mod import spawn
    ctx = spawn(_spawn_target, args=(str(tmp_path),), nprocs=2)
    assert sorted(os.listdir(tmp_path)) == ["spawned_0", "spawned_1"]
    assert (tmp_path / "spawned_0").read_text() == "2"


def _failing_target():
    sys.exit(3)


def test_spawn_raises_on_failure():
    from paddle_tpu.distributed.spawn_mod import spawn
    with pytest.raises(RuntimeError, match="failed"):
        spawn(_failing_target, nprocs=2)
