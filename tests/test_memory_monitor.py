"""HBM memory accounting + per-program cost attribution (ISSUE 4):
memory_analysis plumbing through TrainStep.stats(), the OOM pre-flight
check on both sides of the threshold, live-buffer census attribution,
leak-growth detection, the shared cost_analysis normalization, and the
device.cuda memory shims."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.cost_model import (CostModel, device_peak_flops,
                                   normalize_cost_analysis)
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.monitor import memory as M
from paddle_tpu.monitor import scoped_registry
from paddle_tpu.optimizer import SGD, AdamW


def _mse(layer, x, y):
    return ((layer(x) - y) ** 2).mean()


def _linear_step(optimizer=None, **kw):
    paddle.seed(7)
    m = nn.Linear(4, 2)
    opt = optimizer or SGD(learning_rate=0.1, parameters=m.parameters())
    return TrainStep(m, _mse, opt, **kw)


def _batch(seed=0):
    rng = np.random.RandomState(seed)
    return (rng.rand(8, 4).astype(np.float32),
            rng.rand(8, 2).astype(np.float32))


# ---------------------------------------------------------------------------
# per-program attribution through TrainStep.stats()
# ---------------------------------------------------------------------------

def test_train_step_program_attribution():
    step = _linear_step()
    x, y = _batch()
    step(x, y)
    prog = step.stats()["programs"]
    assert "step" in prog
    p = prog["step"]
    assert p["flops"] > 0
    assert p["bytes_accessed"] > 0
    assert p["arithmetic_intensity"] > 0
    assert p["peak_hbm_bytes"] > 0
    assert p["argument_bytes"] > 0
    # the peak estimate decomposes into the memory_analysis parts
    assert p["peak_hbm_bytes"] <= (p["argument_bytes"] + p["output_bytes"]
                                   + p["temp_bytes"]
                                   + p["generated_code_bytes"])
    # CPU test backend: no known peak FLOP/s, so no MFU fiction
    assert p["mfu"] is None


def test_grad_accum_programs_attributed_separately():
    paddle.seed(7)
    m = nn.Linear(4, 2)
    step = TrainStep(m, _mse, SGD(learning_rate=0.1,
                                  parameters=m.parameters()),
                     grad_accum_steps=2)
    x, y = _batch()
    step(x, y)
    step(x, y)
    prog = step.stats()["programs"]
    assert {"accum", "apply"} <= set(prog)
    assert prog["accum"]["flops"] > 0
    # the apply program folds the optimizer update in: strictly more work
    assert prog["apply"]["flops"] > prog["accum"]["flops"]


def test_scan_gpt_attribution_with_monitor_off_zero_writes():
    """Acceptance pin: the scan-GPT fixture reports non-zero flops and a
    peak-HBM estimate for the train program kind while FLAGS_monitor off
    costs ZERO registry writes (same contract as the PR 3 stats)."""
    from paddle_tpu.models.gpt import (GPTForPretraining,
                                       GPTPretrainingCriterion, gpt_tiny)
    paddle.seed(3)
    model = GPTForPretraining(gpt_tiny(num_layers=3, scan_layers=True))
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, ids, labels):
        return crit(layer(ids), labels)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, 256, (2, 16)).astype(np.int32)
    labels = rng.randint(0, 256, (2, 16)).astype(np.int32)
    with scoped_registry() as reg:
        step = TrainStep(model, loss_fn,
                         AdamW(learning_rate=1e-3,
                               parameters=model.parameters()))
        before = reg.write_count
        for _ in range(3):
            loss = step(ids, labels)
        assert np.isfinite(float(loss))
        assert reg.write_count == before
        assert reg.names() == []
    prog = step.stats()["programs"]["step"]
    assert prog["flops"] > 0
    assert prog["peak_hbm_bytes"] > 0


def test_monitor_on_publishes_attribution_gauges():
    x, y = _batch()
    with scoped_registry() as reg:
        with flag_scope("monitor", True):
            step = _linear_step()
            step(x, y)
        g = reg.gauge("train_step_program_flops")
        assert g.value(kind="step") > 0
        assert reg.gauge("train_step_program_peak_hbm_bytes"
                         ).value(kind="step") > 0


# ---------------------------------------------------------------------------
# cost_analysis normalization + CostModel (satellite)
# ---------------------------------------------------------------------------

def test_normalize_cost_analysis_shapes():
    assert normalize_cost_analysis(None) == {}
    d = normalize_cost_analysis({"flops": 4.0, "bytes accessed": 2.0})
    assert d == {"flops": 4.0, "bytes accessed": 2.0}
    # list-of-dicts (older jax): numeric keys summed across computations
    merged = normalize_cost_analysis(
        [{"flops": 3.0, "bytes accessed": 1.0}, {"flops": 2.0},
         None, {"utilization": "n/a"}])
    assert merged["flops"] == 5.0
    assert merged["bytes accessed"] == 1.0
    assert "utilization" not in merged


def test_cost_model_profile_measure_and_attribute():
    import jax.numpy as jnp
    cm = CostModel()

    def f(a, b):
        return a @ b

    a = jnp.ones((32, 32), jnp.float32)
    r = cm.profile_measure(f, (a, a), iters=3, warmup=1)
    assert r["flops"] > 0
    assert r["bytes_accessed"] > 0
    assert r["wall_ms"] > 0
    assert r["achieved_tflops"] > 0
    import jax
    lowered = jax.jit(f).lower(a, a)
    attr = cm.attribute(lowered)
    assert attr["flops"] == r["flops"]
    assert attr["arithmetic_intensity"] > 0


def test_device_peak_flops_unknown_chip():
    # CPU test backend: unknown chip -> None (or the caller's default)
    assert device_peak_flops() is None
    assert device_peak_flops(default=1e12) == 1e12
    cm = CostModel()
    assert cm.mfu(1e9, 0.01) is None
    assert cm.mfu(1e9, 0.01, peak_flops=1e12) == pytest.approx(1e-1)


# ---------------------------------------------------------------------------
# ProgramMemory + pre-flight
# ---------------------------------------------------------------------------

def test_program_memory_peak_arithmetic():
    pm = M.ProgramMemory("step", argument_bytes=100, output_bytes=50,
                         temp_bytes=30, alias_bytes=40,
                         generated_code_bytes=10)
    assert pm.peak_bytes == 100 + 50 + 30 + 10 - 40
    assert pm.as_dict()["peak_bytes"] == pm.peak_bytes
    # aliasing can exceed the sum on degenerate stats; clamp at zero
    assert M.ProgramMemory("x", alias_bytes=999).peak_bytes == 0


def test_preflight_off_by_default():
    pm = M.ProgramMemory("step", argument_bytes=1 << 40)
    # no action flag set -> no check, regardless of how big the program is
    assert M.preflight_check(pm, limit_bytes=1) is None


def test_preflight_warn_and_raise_both_sides():
    pm = M.ProgramMemory("step", argument_bytes=1 << 20)   # 1 MiB
    # fits: below the limit -> result, no warning
    import warnings as W
    with W.catch_warnings():
        W.simplefilter("error")
        r = M.preflight_check(pm, limit_bytes=2 << 20, action="warn")
    assert r == {"estimate_bytes": 1 << 20, "limit_bytes": 2 << 20,
                 "fits": True, "kind": "step"}
    # over the limit: warn mode warns and still returns the numbers
    with scoped_registry() as reg:
        with pytest.warns(RuntimeWarning, match="expected to OOM"):
            r = M.preflight_check(pm, limit_bytes=1 << 19, action="warn")
        assert r["fits"] is False
        assert reg.counter("memory_preflight_failures_total"
                           ).value(kind="step") == 1
    # raise mode raises with the numbers attached
    with pytest.raises(M.MemoryBudgetError) as ei:
        M.preflight_check(pm, limit_bytes=1 << 19, action="raise")
    assert ei.value.estimate_bytes == 1 << 20
    assert ei.value.limit_bytes == 1 << 19


def test_preflight_flag_gated_through_train_step():
    x, y = _batch()
    # tiny explicit budget + raise -> compiling the step program trips
    with flag_scope("memory_preflight", "raise"), \
            flag_scope("memory_preflight_limit_mb", 1):
        step = _linear_step()
        with pytest.raises(M.MemoryBudgetError):
            # Linear(4,2) won't exceed 1 MiB of args/temps... make it
            big = np.zeros((1 << 17, 4), np.float32)        # 2 MiB batch
            step(big, np.zeros((1 << 17, 2), np.float32))
    # generous budget: the same config sails through
    with flag_scope("memory_preflight", "raise"), \
            flag_scope("memory_preflight_limit_mb", 1 << 14):
        step = _linear_step()
        step(x, y)


def test_unknown_preflight_action_rejected():
    pm = M.ProgramMemory("step", argument_bytes=1)
    with pytest.raises(ValueError, match="memory_preflight"):
        M.preflight_check(pm, limit_bytes=1, action="explode")


# ---------------------------------------------------------------------------
# live-buffer census + leak detection
# ---------------------------------------------------------------------------

def test_census_attributes_params_optimizer_buffers():
    paddle.seed(0)
    m = nn.Sequential(nn.Linear(16, 8), nn.BatchNorm1D(8))
    opt = AdamW(learning_rate=1e-3, parameters=m.parameters())
    step = TrainStep(m, _mse, opt)
    rng = np.random.RandomState(0)
    step(rng.rand(4, 16).astype(np.float32),
         rng.rand(4, 8).astype(np.float32))
    census = M.live_buffer_census(step)
    param_bytes = sum(int(v.nbytes) for v in step.params.values())
    assert census["params"]["bytes"] == param_bytes
    assert census["params"]["count"] == len(step.params)
    assert census["optimizer"]["bytes"] > 0        # AdamW m/v slots
    assert census["buffers"]["bytes"] > 0          # BN running stats
    assert census["total"]["bytes"] >= (census["params"]["bytes"]
                                        + census["optimizer"]["bytes"]
                                        + census["buffers"]["bytes"])
    # without a train step everything floats is 'activations'
    anon = M.live_buffer_census()
    assert anon["params"]["bytes"] == 0
    assert anon["total"]["bytes"] == census["total"]["bytes"]


def test_leak_monitor_flags_monotonic_growth_only():
    leak = M.LeakMonitor(window=3, tolerance_bytes=100)
    base = 10_000
    # flat: never suspicious
    for _ in range(6):
        assert leak.observe(base) is False
    # monotonic growth above tolerance: trips (warn + counter)
    with scoped_registry() as reg:
        with pytest.warns(RuntimeWarning, match="leak suspected"):
            tripped = [leak.observe(base + i * 200) for i in range(1, 5)]
        assert tripped[-1] is True
        assert leak.suspected >= 1
        assert reg.counter("memory_leak_suspected_total").value() >= 1
    # growth below tolerance: quiet
    quiet = M.LeakMonitor(window=3, tolerance_bytes=10_000)
    assert not any(quiet.observe(base + i * 10) for i in range(1, 6))
    # non-monotonic (sawtooth): quiet
    saw = M.LeakMonitor(window=3, tolerance_bytes=0)
    vals = [base, base + 500, base - 500, base + 1000, base - 1000]
    assert not any(saw.observe(v) for v in vals)
    with pytest.raises(ValueError):
        M.LeakMonitor(window=1)


def test_memory_summary_renders():
    step = _linear_step()
    x, y = _batch()
    step(x, y)
    text = M.memory_summary(step)
    assert "memory summary" in text
    assert "compiled programs" in text
    assert "step" in text
    assert "live buffers" in text
    assert "params" in text
    # also renders without a train step (process-global program table)
    assert "live buffers" in M.memory_summary()


def test_publish_census_gauges():
    with scoped_registry() as reg:
        census = M.publish_census()
        g = reg.gauge("live_buffer_bytes")
        assert g.value(category="total") == census["total"]["bytes"]
        assert reg.gauge("live_buffer_count").value(category="total") \
            == census["total"]["count"]


def test_monitor_report_memory_section(tmp_path):
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "monitor_report", os.path.join(os.path.dirname(__file__), "..",
                                       "tools", "monitor_report.py"))
    report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report)
    from paddle_tpu.monitor import MetricsRegistry, load_jsonl
    reg = MetricsRegistry()
    reg.gauge("train_step_program_peak_hbm_bytes").set(1 << 30,
                                                       kind="step")
    reg.gauge("train_step_program_flops").set(1e12, kind="step")
    reg.gauge("train_step_program_bytes_accessed").set(1e9, kind="step")
    reg.gauge("live_buffer_bytes").set(12345, category="params")
    reg.gauge("live_buffer_count").set(7, category="params")
    path = str(tmp_path / "m.jsonl")
    reg.dump_jsonl(path)
    out = report.render(load_jsonl(path), memory=True)
    assert "Program HBM budgets" in out
    assert "1.0 GiB" in out
    assert "Live-buffer census" in out
    assert "params" in out
    # without --memory the gauges still show up (in 'Other metrics')
    out2 = report.render(load_jsonl(path))
    assert "Program HBM budgets" not in out2
    assert "train_step_program_flops" in out2


# ---------------------------------------------------------------------------
# device.cuda memory shims (satellite)
# ---------------------------------------------------------------------------

def test_device_cuda_memory_shims_graceful_on_cpu():
    from paddle_tpu.device import cuda
    # CPU backend publishes no memory_stats: every shim degrades to 0
    # instead of raising (reference CPU behavior)
    assert cuda.memory_allocated() == 0
    assert cuda.max_memory_allocated() == 0
    assert cuda.memory_reserved() == 0
    assert cuda.max_memory_reserved() == 0
    assert cuda.reset_max_memory_allocated() is None
    assert cuda.max_memory_allocated() == 0
    assert M.device_memory_stats() is None
    assert M.device_hbm_bytes() is None
