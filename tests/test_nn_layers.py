"""nn layer tests (reference pattern: test_layers.py, test_transformer_api.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def test_linear_shapes_and_values():
    lin = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    out = lin(x)
    assert out.shape == [2, 3]
    expected = x.numpy() @ lin.weight.numpy() + lin.bias.numpy()
    np.testing.assert_allclose(out.numpy(), expected, rtol=1e-5)


def test_conv2d_against_manual():
    conv = nn.Conv2D(2, 4, 3, padding=1)
    x = paddle.randn([1, 2, 8, 8])
    out = conv(x)
    assert out.shape == [1, 4, 8, 8]
    # stride/padding variants
    out2 = nn.Conv2D(2, 4, 3, stride=2)(x)
    assert out2.shape == [1, 4, 3, 3]


def test_conv2d_groups_depthwise():
    conv = nn.Conv2D(4, 4, 3, padding=1, groups=4)
    x = paddle.randn([2, 4, 6, 6])
    assert conv(x).shape == [2, 4, 6, 6]


def test_conv_transpose():
    convt = nn.Conv2DTranspose(3, 2, 4, stride=2, padding=1)
    x = paddle.randn([1, 3, 8, 8])
    assert convt(x).shape == [1, 2, 16, 16]


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.randn([4, 3, 5, 5]) * 3 + 1
    bn.train()
    out = bn(x)
    m = out.numpy().mean(axis=(0, 2, 3))
    np.testing.assert_allclose(m, np.zeros(3), atol=1e-4)
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), np.zeros(3))
    bn.eval()
    out2 = bn(x)
    assert out2.shape == [4, 3, 5, 5]


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 4, 8]) * 5 + 2
    out = ln(x)
    np.testing.assert_allclose(out.numpy().mean(-1), np.zeros((2, 4)), atol=1e-4)
    np.testing.assert_allclose(out.numpy().std(-1), np.ones((2, 4)), atol=1e-2)


def test_groupnorm_instance_norm():
    gn = nn.GroupNorm(2, 4)
    x = paddle.randn([2, 4, 5, 5])
    assert gn(x).shape == [2, 4, 5, 5]
    inorm = nn.InstanceNorm2D(4)
    assert inorm(x).shape == [2, 4, 5, 5]


def test_embedding():
    emb = nn.Embedding(10, 6)
    ids = paddle.to_tensor(np.array([[1, 2], [3, 4]]), dtype="int64")
    out = emb(ids)
    assert out.shape == [2, 2, 6]
    np.testing.assert_allclose(out.numpy()[0, 0], emb.weight.numpy()[1])


def test_embedding_padding_idx():
    emb = nn.Embedding(10, 4, padding_idx=0)
    ids = paddle.to_tensor(np.array([0, 1]), dtype="int64")
    out = emb(ids)
    np.testing.assert_allclose(out.numpy()[0], np.zeros(4))


def test_dropout_train_eval():
    do = nn.Dropout(0.5)
    x = paddle.ones([100, 100])
    do.train()
    out = do(x)
    frac_zero = (out.numpy() == 0).mean()
    assert 0.3 < frac_zero < 0.7
    # upscale keeps expectation
    np.testing.assert_allclose(out.numpy().mean(), 1.0, atol=0.1)
    do.eval()
    np.testing.assert_array_equal(do(x).numpy(), x.numpy())


def test_pooling():
    x = paddle.randn([1, 2, 8, 8])
    assert nn.MaxPool2D(2, 2)(x).shape == [1, 2, 4, 4]
    assert nn.AvgPool2D(2, 2)(x).shape == [1, 2, 4, 4]
    assert nn.AdaptiveAvgPool2D((1, 1))(x).shape == [1, 2, 1, 1]
    np.testing.assert_allclose(
        nn.AdaptiveAvgPool2D((1, 1))(x).numpy().reshape(2),
        x.numpy().mean(axis=(0, 2, 3)), rtol=1e-5)


def test_activations():
    x = paddle.to_tensor([-2.0, -0.5, 0.0, 0.5, 2.0])
    np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 0, 0.5, 2])
    assert nn.GELU()(x).shape == [5]
    assert nn.Sigmoid()(x).numpy()[2] == 0.5
    np.testing.assert_allclose(nn.LeakyReLU(0.1)(x).numpy(),
                               [-0.2, -0.05, 0, 0.5, 2], rtol=1e-6)
    sm = F.softmax(paddle.randn([3, 5]))
    np.testing.assert_allclose(sm.numpy().sum(-1), np.ones(3), rtol=1e-5)


def test_losses():
    logits = paddle.randn([4, 10], dtype="float32")
    labels = paddle.to_tensor(np.array([1, 2, 3, 4]), dtype="int64")
    loss = nn.CrossEntropyLoss()(logits, labels)
    # numpy reference
    x = logits.numpy().astype(np.float64)
    p = np.exp(x - x.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    expected = -np.log(p[np.arange(4), [1, 2, 3, 4]]).mean()
    np.testing.assert_allclose(float(loss), expected, rtol=1e-4)

    a, b = paddle.randn([3, 4]), paddle.randn([3, 4])
    np.testing.assert_allclose(float(nn.MSELoss()(a, b)),
                               ((a.numpy() - b.numpy()) ** 2).mean(), rtol=1e-5)
    np.testing.assert_allclose(float(nn.L1Loss()(a, b)),
                               np.abs(a.numpy() - b.numpy()).mean(), rtol=1e-5)


def test_cross_entropy_ignore_index_and_smoothing():
    logits = paddle.randn([4, 6])
    labels = paddle.to_tensor(np.array([1, -100, 3, -100]), dtype="int64")
    loss = F.cross_entropy(logits, labels, ignore_index=-100)
    x = logits.numpy().astype(np.float64)
    p = np.exp(x - x.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    expected = -np.log(p[[0, 2], [1, 3]]).mean()
    np.testing.assert_allclose(float(loss), expected, rtol=1e-4)
    sm = F.cross_entropy(logits, paddle.to_tensor(np.array([1, 2, 3, 0]),
                                                  dtype="int64"),
                         label_smoothing=0.1)
    assert np.isfinite(float(sm))


def test_sequential_and_layerlist():
    seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    assert seq(x).shape == [3, 2]
    assert len(seq) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    assert len(list(ll)) == 3


def test_state_dict_roundtrip():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = net.state_dict()
    assert len(sd) == 4  # 2 weights + 2 biases
    net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net2.set_state_dict({k: v.numpy() for k, v in sd.items()})
    for (k1, v1), (k2, v2) in zip(net.state_dict().items(),
                                  net2.state_dict().items()):
        np.testing.assert_array_equal(v1.numpy(), v2.numpy())


def test_named_parameters_unique():
    net = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4))
    names = [n for n, _ in net.named_parameters()]
    assert len(names) == len(set(names)) == 4


def test_multi_head_attention():
    mha = nn.MultiHeadAttention(16, 4)
    q = paddle.randn([2, 5, 16])
    out = mha(q, q, q)
    assert out.shape == [2, 5, 16]
    # with mask
    mask = paddle.ones([2, 4, 5, 5], dtype="float32") * 0.0
    out2 = mha(q, q, q, attn_mask=mask)
    assert out2.shape == [2, 5, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(d_model=16, nhead=4, dim_feedforward=32)
    enc = nn.TransformerEncoder(layer, 2)
    src = paddle.randn([2, 6, 16])
    assert enc(src).shape == [2, 6, 16]
    # parameters are independent across stacked layers
    p = list(enc.named_parameters())
    assert len(p) == 2 * len(list(layer.named_parameters()))


def test_transformer_full():
    model = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=2,
                           num_decoder_layers=2, dim_feedforward=32)
    src = paddle.randn([2, 5, 16])
    tgt = paddle.randn([2, 4, 16])
    out = model(src, tgt)
    assert out.shape == [2, 4, 16]


def test_rnn_lstm_gru():
    x = paddle.randn([2, 7, 5])
    lstm = nn.LSTM(5, 8)
    out, (h, c) = lstm(x)
    assert out.shape == [2, 7, 8]
    assert h.shape == [1, 2, 8] and c.shape == [1, 2, 8]
    gru = nn.GRU(5, 8, direction="bidirect")
    out2, h2 = gru(x)
    assert out2.shape == [2, 7, 16]
    rnn = nn.SimpleRNN(5, 8, num_layers=2)
    out3, h3 = rnn(x)
    assert out3.shape == [2, 7, 8]


def test_rnn_grad_flows():
    lstm = nn.LSTM(4, 6)
    x = paddle.randn([2, 5, 4])
    out, _ = lstm(x)
    out.sum().backward()
    for p in lstm.parameters():
        assert p.grad is not None


def test_layer_hooks():
    lin = nn.Linear(2, 2)
    calls = []
    h = lin.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    lin(paddle.randn([1, 2]))
    assert calls == [1]
    h.remove()
    lin(paddle.randn([1, 2]))
    assert calls == [1]


def test_pad_and_interpolate():
    x = paddle.randn([1, 2, 4, 4])
    assert F.pad(x, [1, 1, 2, 2]).shape == [1, 2, 8, 6]
    assert F.interpolate(x, size=[8, 8], mode="nearest").shape == [1, 2, 8, 8]
    assert F.interpolate(x, scale_factor=2, mode="bilinear").shape == [1, 2, 8, 8]


def test_one_hot_and_sequence_mask():
    ids = paddle.to_tensor(np.array([0, 2]), dtype="int64")
    oh = F.one_hot(ids, 4)
    np.testing.assert_array_equal(oh.numpy(), [[1, 0, 0, 0], [0, 0, 1, 0]])
    lens = paddle.to_tensor(np.array([1, 3]), dtype="int64")
    m = F.sequence_mask(lens, maxlen=4)
    np.testing.assert_array_equal(m.numpy(), [[1, 0, 0, 0], [1, 1, 1, 0]])


def test_functional_tail_bilinear_margin_ce_inplace():
    """reference: nn/functional bilinear, margin_cross_entropy (ArcFace),
    inplace activation variants."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(0)
    x1 = paddle.to_tensor(rng.normal(size=(2, 3)).astype(np.float32))
    x2 = paddle.to_tensor(rng.normal(size=(2, 4)).astype(np.float32))
    w = paddle.to_tensor(rng.normal(size=(5, 3, 4)).astype(np.float32))
    b = paddle.to_tensor(rng.normal(size=(5,)).astype(np.float32))
    out = F.bilinear(x1, x2, w, b)
    ref = np.einsum("bi,oij,bj->bo", x1.numpy(), w.numpy(), x2.numpy()) \
        + b.numpy()
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-5, rtol=1e-5)

    # margin CE with zero margins/scale-1 reduces to plain softmax CE
    # (logits must be cosines in [-1, 1] — the ArcFace input contract)
    lg = paddle.to_tensor(np.clip(rng.normal(size=(4, 8)), -0.95, 0.95)
                          .astype(np.float32))
    lab = paddle.to_tensor(np.array([0, 1, 2, 3], np.int64))
    m = F.margin_cross_entropy(lg, lab, margin1=1.0, margin2=0.0,
                               margin3=0.0, scale=1.0)
    plain = F.cross_entropy(lg, lab)
    np.testing.assert_allclose(float(m), float(plain), rtol=1e-4)

    t = paddle.to_tensor(np.array([-2.0, 3.0], np.float32))
    r = F.relu_(t)
    assert r is t
    np.testing.assert_allclose(t.numpy(), [0.0, 3.0])
    np.testing.assert_allclose(
        F.thresholded_relu(paddle.to_tensor(
            np.array([0.5, 1.5], np.float32)), 1.0).numpy(), [0.0, 1.5])
