"""Distribution breadth tests (Bernoulli/Multinomial/Beta/Dirichlet +
kl_divergence) — golden values via scipy.

The reference ships exactly Uniform/Normal/Categorical
(python/paddle/distribution.py); these surpass per SURVEY §7.9.
"""

import numpy as np
import pytest

import paddle_tpu.distribution as D

scipy_stats = pytest.importorskip("scipy.stats")


def _f(t):
    return float(np.asarray(t._data))


def test_bernoulli_scipy_parity():
    b = D.Bernoulli(0.7)
    assert abs(_f(b.log_prob(1.0)) - np.log(0.7)) < 1e-6
    assert abs(_f(b.log_prob(0.0)) - np.log(0.3)) < 1e-6
    assert abs(_f(b.entropy()) - scipy_stats.bernoulli.entropy(0.7)) < 1e-6
    s = np.asarray(b.sample((2000,), seed=1)._data)
    assert set(np.unique(s)) <= {0.0, 1.0}
    assert abs(s.mean() - 0.7) < 0.05
    kl = _f(D.kl_divergence(D.Bernoulli(0.7), D.Bernoulli(0.4)))
    ref = 0.7 * np.log(0.7 / 0.4) + 0.3 * np.log(0.3 / 0.6)
    assert abs(kl - ref) < 1e-6


def test_beta_scipy_parity():
    b = D.Beta(2.0, 3.0)
    assert abs(_f(b.log_prob(0.3))
               - scipy_stats.beta.logpdf(0.3, 2, 3)) < 1e-5
    assert abs(_f(b.entropy()) - scipy_stats.beta.entropy(2, 3)) < 1e-5
    assert abs(_f(b.mean()) - 0.4) < 1e-6
    s = np.asarray(b.sample((3000,), seed=2)._data)
    assert ((s > 0) & (s < 1)).all()
    assert abs(s.mean() - 0.4) < 0.03


def test_dirichlet_scipy_parity():
    c = np.array([1.5, 2.5, 3.0], np.float32)
    d = D.Dirichlet(c)
    x = np.array([0.2, 0.3, 0.5], np.float32)
    assert abs(_f(d.log_prob(x))
               - scipy_stats.dirichlet.logpdf(x, c)) < 1e-4
    assert abs(_f(d.entropy())
               - scipy_stats.dirichlet.entropy(c)) < 1e-4
    s = np.asarray(d.sample((500,), seed=3)._data)
    np.testing.assert_allclose(s.sum(-1), 1.0, rtol=1e-5)


def test_multinomial_scipy_parity():
    p = np.array([0.2, 0.3, 0.5], np.float32)
    m = D.Multinomial(5, p)
    cnt = np.array([1.0, 2.0, 2.0], np.float32)
    assert abs(_f(m.log_prob(cnt))
               - scipy_stats.multinomial.logpmf(cnt, 5, p)) < 1e-4
    s = np.asarray(m.sample((100,), seed=4)._data)
    assert s.shape == (100, 3)
    np.testing.assert_array_equal(s.sum(-1), 5.0)


def test_kl_divergence_dispatch():
    kl = _f(D.kl_divergence(D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)))
    ref = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
    assert abs(kl - ref) < 1e-6
    with pytest.raises(NotImplementedError):
        D.kl_divergence(D.Normal(0.0, 1.0), D.Beta(1.0, 1.0))
