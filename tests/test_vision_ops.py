"""Detection-op tests (reference analogues: test_nms_op.py,
test_roi_align_op.py, test_yolo_box_op.py, test_iou_similarity_op.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops


def test_box_iou_matches_numpy():
    rng = np.random.RandomState(0)
    a = np.sort(rng.rand(6, 4).astype(np.float32) * 100, axis=-1)
    b = np.sort(rng.rand(4, 4).astype(np.float32) * 100, axis=-1)
    got = ops.box_iou(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()

    def iou(x, y):
        ax = max(0, min(x[2], y[2]) - max(x[0], y[0]))
        ay = max(0, min(x[3], y[3]) - max(x[1], y[1]))
        inter = ax * ay
        ua = ((x[2] - x[0]) * (x[3] - x[1])
              + (y[2] - y[0]) * (y[3] - y[1]) - inter)
        return inter / (ua + 1e-10)

    ref = np.array([[iou(x, y) for y in b] for x in a], np.float32)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_nms_greedy_reference():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30],
                      [21, 21, 31, 31], [50, 50, 60, 60]], np.float32)
    scores = np.array([0.9, 0.8, 0.7, 0.95, 0.5], np.float32)
    idx = np.asarray(ops.nms(paddle.to_tensor(boxes), iou_threshold=0.5,
                             scores=paddle.to_tensor(scores)).data)
    # highest scorer of each overlapping cluster survives, sorted by score
    assert idx.tolist() == [3, 0, 4]


def test_nms_category_aware():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
    scores = np.array([0.9, 0.8], np.float32)
    cats = np.array([0, 1], np.int32)           # different categories:
    idx = np.asarray(ops.nms(paddle.to_tensor(boxes), 0.5,
                             paddle.to_tensor(scores),
                             category_idxs=paddle.to_tensor(cats),
                             categories=[0, 1]).data)
    assert sorted(idx.tolist()) == [0, 1]       # no cross-category suppress


def test_roi_align_uniform_feature():
    # constant feature map -> every bin averages to the constant
    feat = np.full((1, 3, 16, 16), 7.0, np.float32)
    rois = np.array([[2, 2, 10, 10], [0, 0, 15, 15]], np.float32)
    out = ops.roi_align(paddle.to_tensor(feat), paddle.to_tensor(rois),
                        np.array([2]), output_size=4).numpy()
    assert out.shape == (2, 3, 4, 4)
    np.testing.assert_allclose(out, 7.0, rtol=1e-5)


def test_roi_align_gradient_flows():
    feat = paddle.to_tensor(np.random.RandomState(0)
                            .randn(1, 2, 8, 8).astype(np.float32))
    feat.stop_gradient = False
    rois = paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32))
    out = ops.roi_align(feat, rois, np.array([1]), output_size=2)
    out.sum().backward()
    g = np.asarray(feat.grad._data)
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_roi_pool_max_semantics():
    feat = np.zeros((1, 1, 8, 8), np.float32)
    feat[0, 0, 1, 1] = 5.0
    feat[0, 0, 6, 6] = 9.0
    rois = np.array([[0, 0, 7, 7]], np.float32)
    out = ops.roi_pool(paddle.to_tensor(feat), paddle.to_tensor(rois),
                       np.array([1]), output_size=2).numpy()
    assert out.max() == 9.0 and out[0, 0, 0, 0] == 5.0


def test_yolo_box_shapes_and_range():
    N, A, cls, H, W = 2, 3, 4, 5, 5
    x = np.random.RandomState(0).randn(N, A * (5 + cls), H, W) \
        .astype(np.float32)
    img = np.tile(np.array([[320, 320]], np.int32), (N, 1))
    boxes, scores = ops.yolo_box(
        paddle.to_tensor(x), paddle.to_tensor(img),
        anchors=[10, 13, 16, 30, 33, 23], class_num=cls,
        conf_thresh=0.0, downsample_ratio=32)
    assert tuple(boxes.shape) == (N, A * H * W, 4)
    assert tuple(scores.shape) == (N, A * H * W, cls)
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 320).all()   # clipped to image
    s = scores.numpy()
    assert (s >= 0).all() and (s <= 1).all()


def test_deform_conv2d_zero_offset_equals_conv2d():
    # with zero offsets (and no mask) deformable conv == standard conv
    rng = np.random.RandomState(10)
    N, C, H, W, OC, K = 2, 4, 8, 8, 6, 3
    x = rng.randn(N, C, H, W).astype(np.float32)
    w = rng.randn(OC, C, K, K).astype(np.float32)
    b = rng.randn(OC).astype(np.float32)
    oH = oW = H  # padding 1, stride 1
    offset = np.zeros((N, 2 * K * K, oH, oW), np.float32)

    got = ops.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(offset),
                            paddle.to_tensor(w), bias=paddle.to_tensor(b),
                            stride=1, padding=1).numpy()
    import paddle_tpu.nn.functional as F
    ref = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w),
                   paddle.to_tensor(b), stride=1, padding=1).numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-3)


def test_deform_conv2d_mask_modulates():
    rng = np.random.RandomState(11)
    N, C, H, W, OC, K = 1, 2, 6, 6, 3, 3
    x = rng.randn(N, C, H, W).astype(np.float32)
    w = rng.randn(OC, C, K, K).astype(np.float32)
    offset = np.zeros((N, 2 * K * K, H, W), np.float32)
    mask0 = np.zeros((N, K * K, H, W), np.float32)     # all taps off
    out = ops.deform_conv2d(paddle.to_tensor(x), paddle.to_tensor(offset),
                            paddle.to_tensor(w), stride=1, padding=1,
                            mask=paddle.to_tensor(mask0)).numpy()
    np.testing.assert_allclose(out, 0.0, atol=1e-6)


def test_deform_conv2d_gradients_flow():
    rng = np.random.RandomState(12)
    x = paddle.to_tensor(rng.randn(1, 2, 6, 6).astype(np.float32))
    x.stop_gradient = False
    off = paddle.to_tensor(
        (rng.randn(1, 18, 6, 6) * 0.1).astype(np.float32))
    off.stop_gradient = False
    w = paddle.to_tensor(rng.randn(3, 2, 3, 3).astype(np.float32))
    w.stop_gradient = False
    out = ops.deform_conv2d(x, off, w, stride=1, padding=1)
    out.sum().backward()
    for t, name in ((x, "x"), (off, "offset"), (w, "weight")):
        g = np.asarray(t.grad._data)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0, name


def test_psroi_pool_constant_feature():
    ph = pw = 2
    out_c = 3
    C = out_c * ph * pw
    feat = np.full((1, C, 8, 8), 0.0, np.float32)
    for c in range(C):
        feat[0, c] = c                  # channel-identifying values
    rois = np.array([[0, 0, 8, 8]], np.float32)
    out = ops.psroi_pool(paddle.to_tensor(feat), paddle.to_tensor(rois),
                         np.array([1]), output_size=ph).numpy()
    assert out.shape == (1, out_c, ph, pw)
    # bin (i, j) of output channel k reads input channel k*ph*pw + i*pw + j
    for k in range(out_c):
        for i in range(ph):
            for j in range(pw):
                assert out[0, k, i, j] == k * ph * pw + i * pw + j


def test_read_file_decode_jpeg_roundtrip(tmp_path):
    from PIL import Image
    # smooth gradient image (random noise is JPEG's worst case)
    yy, xx = np.mgrid[0:16, 0:20]
    img = np.stack([yy * 8, xx * 6, (yy + xx) * 4], axis=-1) \
        .astype(np.uint8)
    p = tmp_path / "img.jpg"
    Image.fromarray(img).save(p, quality=95)
    data = ops.read_file(str(p))
    assert data.dtype.name == "uint8"
    decoded = ops.decode_jpeg(data, mode="rgb")
    assert tuple(decoded.shape) == (3, 16, 20)
    # lossy codec: just check it is recognisably the same image
    err = np.abs(decoded.numpy().transpose(1, 2, 0).astype(np.int32)
                 - img.astype(np.int32)).mean()
    assert err < 20
