"""Sharded/async/reshard-on-load checkpoint tests (distributed.checkpoint).

Analogue of the reference's fleet.save_persistables tests
(test_fleet_base.py save/load paths) plus the SURVEY §7.9 surpass
criteria: save on one mesh factorization, resume on another, loss curve
continues bit-close.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.distributed import fleet
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.models import (GPTForPretraining, GPTPretrainingCriterion,
                               gpt_tiny)
from paddle_tpu.optimizer import AdamW


def _make_step(dp, mp):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "mp_degree": mp}
    fleet.init(is_collective=True, strategy=strategy)
    mesh = fleet.get_hybrid_communicate_group().mesh
    cfg = gpt_tiny()
    model = GPTForPretraining(cfg)
    model = fleet.distributed_model(model)
    crit = GPTPretrainingCriterion()
    opt = AdamW(learning_rate=1e-3, weight_decay=0.01)

    def loss_fn(layer, ids, labels, mask):
        return crit(layer(ids), labels, mask)

    step = TrainStep(model, loss_fn, opt, mesh=mesh, data_spec=P("dp"),
                     zero_axis="dp")
    return step, cfg


def _batch(cfg, i):
    rng = np.random.default_rng(100 + i)
    ids = rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    labels = rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    mask = np.ones((8, 32), np.float32)
    return Tensor(ids), Tensor(labels), Tensor(mask)


def test_async_save_and_plain_restore(tmp_path):
    """Async save returns before files are durable; wait() makes them so;
    a template-free load round-trips values."""
    paddle.seed(0)
    step, cfg = _make_step(dp=4, mp=2)
    float(np.asarray(step(*_batch(cfg, 0))._data))
    path = str(tmp_path / "ckpt_async")
    step.save_sharded(path, asynchronous=True)
    dckpt.wait()
    assert os.path.isdir(path)
    state = dckpt.load(path)
    assert int(state["step_count"]) == 1
    k = next(iter(step.params))
    np.testing.assert_allclose(np.asarray(state["params"][k]),
                               np.asarray(step.params[k]), rtol=1e-6)


def test_save_shards_not_replicas(tmp_path):
    """Array data on disk is written once per logical array (sharded
    writers), not once per device replica: total checkpoint bytes stay
    within a small factor of the logical state size."""
    paddle.seed(1)
    step, cfg = _make_step(dp=4, mp=2)
    path = str(tmp_path / "ckpt_size")
    step.save_sharded(path, asynchronous=False)

    logical = 0
    for tree in (step.params, step.frozen, step.buffers, step.opt_state):
        for leaf in jax.tree_util.tree_leaves(tree):
            if hasattr(leaf, "nbytes"):
                logical += leaf.nbytes
    on_disk = sum(os.path.getsize(os.path.join(r, f))
                  for r, _, fs in os.walk(path) for f in fs)
    assert on_disk < logical * 1.5 + 1e6, (on_disk, logical)


def test_reshard_on_load_continues_loss_curve(tmp_path):
    """Save on dp4×mp2 after 3 steps; restore into a FRESH TrainStep on a
    dp2×mp4 mesh; the next 3 losses match a continuous 6-step run
    bit-close (SURVEY §7.9 'resume on a different factorization')."""
    path = str(tmp_path / "ckpt_reshard")

    # continuous reference run
    paddle.seed(7)
    step, cfg = _make_step(dp=4, mp=2)
    ref_losses = [float(np.asarray(step(*_batch(cfg, i))._data))
                  for i in range(6)]

    # run A: 3 steps on dp4xmp2, sharded save
    paddle.seed(7)
    step_a, cfg = _make_step(dp=4, mp=2)
    for i in range(3):
        step_a(*_batch(cfg, i))
    step_a.save_sharded(path, asynchronous=False)

    # run B: fresh everything on the TRANSPOSED factorization
    paddle.seed(999)    # deliberately different init — must be overwritten
    step_b, cfg = _make_step(dp=2, mp=4)
    step_b.load_sharded(path)
    assert step_b.step_count == 3
    # params landed in the NEW mesh layout
    k = next(iter(step_b.params))
    assert step_b.params[k].sharding.mesh.shape["dp"] == 2
    cont_losses = [float(np.asarray(step_b(*_batch(cfg, 3 + i))._data))
                   for i in range(3)]
    np.testing.assert_allclose(cont_losses, ref_losses[3:], rtol=2e-4)


def test_reshard_to_single_device(tmp_path):
    """A mesh checkpoint restores into a mesh-free TrainStep (single-chip
    inference/fine-tune resume)."""
    path = str(tmp_path / "ckpt_single")
    paddle.seed(3)
    step, cfg = _make_step(dp=4, mp=2)
    l0 = float(np.asarray(step(*_batch(cfg, 0))._data))
    step.save_sharded(path, asynchronous=False)

    from paddle_tpu.distributed import env as dist_env
    dist_env.set_mesh(None)
    paddle.seed(555)
    cfg2 = gpt_tiny()
    model = GPTForPretraining(cfg2)
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, ids, labels, mask):
        return crit(layer(ids), labels, mask)

    step2 = TrainStep(model, loss_fn, AdamW(learning_rate=1e-3))
    assert step2.mesh is None
    step2.load_sharded(path)
    k = next(iter(step.params))
    np.testing.assert_allclose(np.asarray(step2.params[k]),
                               np.asarray(step.params[k]), rtol=1e-6)


def test_fleet_save_load_persistables(tmp_path):
    """fleet.save_persistables / load_persistables parity surface
    (reference: fleet_base.py:779) over the sharded checkpoint."""
    path = str(tmp_path / "persistables")
    paddle.seed(11)
    step, cfg = _make_step(dp=4, mp=2)
    fleet.save_persistables(step, path, asynchronous=False)

    paddle.seed(222)
    step2, _ = _make_step(dp=4, mp=2)
    fleet.load_persistables(step2, path)
    k = next(iter(step.params))
    np.testing.assert_allclose(np.asarray(step2.params[k]),
                               np.asarray(step.params[k]), rtol=1e-6)

    # Layer variant: params + buffers only
    from paddle_tpu import nn
    lin = nn.Linear(4, 4)
    path2 = str(tmp_path / "layer_persistables")
    fleet.save_persistables(lin, path2, asynchronous=False)
    lin2 = nn.Linear(4, 4)
    fleet.load_persistables(lin2, path2)
    np.testing.assert_allclose(np.asarray(lin2.weight._data),
                               np.asarray(lin.weight._data), rtol=1e-6)
