"""Model-file encryption (reference:
paddle/fluid/framework/io/crypto/cipher.h:24 AES model crypto; here an
authenticated PRF-CTR scheme, framework/crypto.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.framework import crypto


def test_bytes_roundtrip_and_tamper_detection():
    key = crypto.generate_key()
    msg = b"sparse rows " * 1000 + b"tail"
    blob = crypto.encrypt_bytes(msg, key)
    assert blob != msg and crypto.is_encrypted(blob)
    assert crypto.decrypt_bytes(blob, key) == msg
    # wrong key
    with pytest.raises(crypto.DecryptionError, match="authentication"):
        crypto.decrypt_bytes(blob, crypto.generate_key())
    # bit flip in ciphertext
    bad = bytearray(blob)
    bad[len(blob) // 2] ^= 1
    with pytest.raises(crypto.DecryptionError, match="authentication"):
        crypto.decrypt_bytes(bytes(bad), key)
    # distinct nonces: same plaintext encrypts differently
    assert crypto.encrypt_bytes(msg, key) != blob


def test_cipher_factory_file_roundtrip(tmp_path):
    cipher = crypto.CipherFactory.create_cipher()
    key = crypto.generate_key(16)
    p = str(tmp_path / "enc.bin")
    cipher.encrypt_to_file(b"model bytes", key, p)
    assert cipher.decrypt_from_file(key, p) == b"model bytes"


def test_paddle_save_load_encrypted(tmp_path):
    paddle.seed(0)
    net = paddle.nn.Linear(4, 3)
    key = crypto.generate_key()
    p = str(tmp_path / "model.pdparams")
    paddle.save(net.state_dict(), p, encryption_key=key)

    # loading without the key fails loudly, not with a pickle error
    with pytest.raises(ValueError, match="encrypted"):
        paddle.load(p)

    state = paddle.load(p, encryption_key=key)
    net2 = paddle.nn.Linear(4, 3)
    net2.set_state_dict(state)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-6)


def test_plain_save_still_loads(tmp_path):
    p = str(tmp_path / "plain.pdparams")
    paddle.save({"a": paddle.to_tensor(np.ones(3, np.float32))}, p)
    out = paddle.load(p)
    np.testing.assert_array_equal(out["a"], np.ones(3, np.float32))
