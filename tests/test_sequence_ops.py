"""Sequence-op tests over the (padded, lengths) TPU-native contract.

reference: operators/sequence_ops/* defined over LoD tensors; semantics
checked against hand-computed ragged results.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.tensor.sequence import (sequence_concat,
                                        sequence_enumerate,
                                        sequence_expand_as, sequence_pad,
                                        sequence_pool, sequence_reverse,
                                        sequence_softmax, sequence_unpad)

RAGGED = [np.array([[1., 2.], [3., 4.], [5., 6.]], np.float32),
          np.array([[7., 8.]], np.float32),
          np.array([[9., 10.], [11., 12.]], np.float32)]


def test_pad_unpad_round_trip():
    padded, lens = sequence_pad(RAGGED, pad_value=-1.0)
    p = np.asarray(padded._data)
    assert p.shape == (3, 3, 2)
    np.testing.assert_array_equal(np.asarray(lens._data), [3, 1, 2])
    assert (p[1, 1:] == -1).all()
    back = sequence_unpad(padded, lens)
    for a, b in zip(RAGGED, back):
        np.testing.assert_array_equal(a, np.asarray(b._data))


def test_pool_modes_match_ragged():
    padded, lens = sequence_pad(RAGGED)
    for mode, ref_fn in [
        ("sum", lambda a: a.sum(0)),
        ("average", lambda a: a.mean(0)),
        ("sqrt", lambda a: a.sum(0) / np.sqrt(a.shape[0])),
        ("max", lambda a: a.max(0)),
        ("first", lambda a: a[0]),
        ("last", lambda a: a[-1]),
    ]:
        out = np.asarray(sequence_pool(padded, lens, mode)._data)
        for i, a in enumerate(RAGGED):
            np.testing.assert_allclose(out[i], ref_fn(a), rtol=1e-6,
                                       err_msg=mode)


def test_pool_empty_sequence_is_zero():
    padded, lens = sequence_pad(RAGGED)
    lens = paddle.to_tensor(np.array([3, 0, 2], np.int32))
    for mode in ("sum", "average", "max", "first", "last"):
        out = np.asarray(sequence_pool(padded, lens, mode)._data)
        assert (out[1] == 0).all(), mode


def test_reverse_keeps_padding_in_place():
    padded, lens = sequence_pad(RAGGED, pad_value=-1.0)
    out = np.asarray(sequence_reverse(padded, lens)._data)
    np.testing.assert_array_equal(out[0], np.asarray(RAGGED[0])[::-1])
    np.testing.assert_array_equal(out[2, :2], np.asarray(RAGGED[2])[::-1])
    assert (out[1, 1:] == -1).all()         # padding untouched


def test_softmax_masks_padding():
    x = np.array([[1., 2., 3.], [4., 5., 6.]], np.float32)
    lens = np.array([2, 3], np.int32)
    out = np.asarray(sequence_softmax(x, lens)._data)
    ref0 = np.exp(x[0, :2] - x[0, :2].max())
    ref0 = ref0 / ref0.sum()
    np.testing.assert_allclose(out[0, :2], ref0, rtol=1e-5)
    assert out[0, 2] == 0.0
    np.testing.assert_allclose(out[1].sum(), 1.0, rtol=1e-5)


def test_expand_as_and_enumerate():
    row = np.array([[1., 2.], [3., 4.]], np.float32)
    lens = np.array([3, 1], np.int32)
    out = np.asarray(sequence_expand_as(row, lens)._data)
    assert out.shape == (2, 3, 2)
    np.testing.assert_array_equal(out[0], np.tile(row[0], (3, 1)))
    np.testing.assert_array_equal(out[1, 0], row[1])
    assert (out[1, 1:] == 0).all()

    ids = np.array([[1, 2, 3, 0], [4, 5, 0, 0]], np.int32)
    lens = np.array([3, 2], np.int32)
    win = np.asarray(sequence_enumerate(ids, lens, win_size=2,
                                        pad_value=-1)._data)
    np.testing.assert_array_equal(win[0, 0], [1, 2])
    np.testing.assert_array_equal(win[0, 2], [3, -1])   # overhang padded
    np.testing.assert_array_equal(win[1, 1], [5, -1])
    assert (win[0, 3] == -1).all()                      # past end


def test_concat_repacks_lengths():
    a, la = sequence_pad([np.array([[1.], [2.]], np.float32),
                          np.array([[3.]], np.float32)])
    b, lb = sequence_pad([np.array([[4.]], np.float32),
                          np.array([[5.], [6.], [7.]], np.float32)])
    out, lens = sequence_concat([(a, la), (b, lb)])
    o = np.asarray(out._data)
    np.testing.assert_array_equal(np.asarray(lens._data), [3, 4])
    np.testing.assert_array_equal(o[0, :3, 0], [1, 2, 4])
    np.testing.assert_array_equal(o[1, :4, 0], [3, 5, 6, 7])


def test_sequence_ops_jit_compatible():
    """The device-side ops (pool/reverse/softmax/enumerate) trace under
    jit with static shapes."""
    import jax
    import jax.numpy as jnp
    padded, lens = sequence_pad(RAGGED)

    def f(p, ln):
        from paddle_tpu.core.tensor import Tensor
        s = sequence_pool(Tensor(p), Tensor(ln), "average")
        r = sequence_reverse(Tensor(p), Tensor(ln))
        return s._data, r._data

    s, r = jax.jit(f)(padded._data, lens._data)
    assert s.shape == (3, 2) and r.shape == (3, 3, 2)
