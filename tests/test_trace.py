"""Structured tracing + SLO burn rate (paddle_tpu.monitor.trace / .slo,
ISSUE 11): span trees, tail-based anomaly sampling, Perfetto export,
exemplars, trace-context survival across preemption and drain/resume,
and the zero-overhead contract."""

import json

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.models.gpt import GPTForPretraining, gpt_tiny
from paddle_tpu.monitor import scoped_registry
from paddle_tpu.monitor import trace as trace_mod
from paddle_tpu.monitor.slo import SLOTracker
from paddle_tpu.optimizer import SGD
from paddle_tpu.serving import (Request, ServingConfig, ServingEngine,
                                load_drain_snapshot,
                                requests_from_snapshot)
from paddle_tpu.testing import chaos


@pytest.fixture(scope="module")
def tiny_model():
    paddle.seed(0)
    return GPTForPretraining(gpt_tiny())


def _engine(model, **kw):
    cfg = dict(max_batch_slots=3, block_size=4, max_context_len=64,
               prefill_buckets=(8, 16), batch_buckets=(1, 2))
    cfg.update(kw)
    return ServingEngine(model, ServingConfig(**cfg))


def _spans(tr):
    return [(s.name, s.parent_id) for s in tr.spans]


def _span_names(tdoc_or_trace):
    spans = (tdoc_or_trace.get("spans")
             if isinstance(tdoc_or_trace, dict)
             else [s.to_dict() for s in tdoc_or_trace.spans])
    return [s["name"] for s in spans]


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------


def test_span_tree_ids_parents_and_durations():
    t = trace_mod.Tracer(capacity=8, seed=0)
    with flag_scope("trace_sample", 1.0):
        tr = t.start_trace("unit", foo="bar")
    assert tr.root.parent_id is None and tr.root.span_id == 0
    a = tr.start_span("a")
    b = tr.start_span("b", parent=a)
    assert a.parent_id == 0 and b.parent_id == a.span_id
    tr.end_span(b)
    tr.end_span(a)
    assert b.duration is not None and b.duration >= 0
    ev = tr.event("marker", outcome="x")
    assert ev.duration == 0.0
    assert t.finish_trace(tr) is True
    d = tr.to_dict()
    assert d["trace_id"] == tr.trace_id
    assert [s["name"] for s in d["spans"]] == ["unit", "a", "b",
                                               "marker"]
    assert d["spans"][0]["attrs"]["foo"] == "bar"
    # idempotent finish
    assert t.finish_trace(tr) is True
    assert len(t.retained()) == 1


def test_head_and_tail_sampling_decisions():
    t = trace_mod.Tracer(capacity=32, seed=0)
    with flag_scope("trace_sample", 0.0):
        healthy = t.start_trace("h")
        assert t.finish_trace(healthy) is False        # dropped
        weird = t.start_trace("w")
        weird.mark_anomaly("chaos", site="x")
        assert weird.anomaly == "chaos"
        weird.mark_anomaly("failed")                   # first wins
        assert weird.anomaly == "chaos"
        assert t.finish_trace(weird) is True           # tail-kept
    with flag_scope("trace_sample", 1.0):
        head = t.start_trace("s")
        assert t.finish_trace(head) is True
    assert {tr.name for tr in t.retained()} == {"w", "s"}
    assert trace_mod.TRACE_STATS["tail_retained"] == 1
    assert trace_mod.TRACE_STATS["traces_dropped"] == 1


def test_retained_ring_is_bounded():
    t = trace_mod.Tracer(capacity=3)
    with flag_scope("trace_sample", 1.0):
        traces = [t.start_trace(f"t{i}") for i in range(5)]
        for tr in traces:
            t.finish_trace(tr)
    kept = t.retained()
    assert len(kept) == 3
    assert [tr.name for tr in kept] == ["t2", "t3", "t4"]


def test_trace_off_allocates_nothing():
    assert trace_mod.start_trace("x") is None
    with trace_mod.maybe_span("y"):
        pass
    assert trace_mod.TRACE_STATS["spans_allocated"] == 0
    assert trace_mod.TRACE_STATS["traces_started"] == 0


def test_activate_and_maybe_span_attach():
    t = trace_mod.Tracer(capacity=4)
    with flag_scope("trace_sample", 1.0):
        tr = t.start_trace("step")
    assert trace_mod.current_trace() is None
    with trace_mod.activate(tr):
        assert trace_mod.current_trace() is tr
        with trace_mod.maybe_span("inner", k=1) as sp:
            assert sp is not None and sp.trace_id == tr.trace_id
    assert trace_mod.current_trace() is None
    assert "inner" in _span_names(tr)


def test_perfetto_export_valid_json_monotonic_tracks(tmp_path):
    t = trace_mod.Tracer(capacity=8)
    with flag_scope("trace_sample", 1.0):
        for i in range(2):
            tr = t.start_trace(f"r{i}")
            with tr.span("a"):
                with tr.span("b"):
                    pass
            t.finish_trace(tr)
    path = str(tmp_path / "perfetto.json")
    trace_mod.export_perfetto(path, traces=t.snapshot())
    with open(path) as f:
        doc = json.load(f)                      # valid JSON, the pin
    events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert events, "no duration events exported"
    per_track = {}
    for e in events:
        per_track.setdefault((e["pid"], e["tid"]), []).append(e["ts"])
    for ts_list in per_track.values():
        assert ts_list == sorted(ts_list)       # monotonic per track
    names = {e["name"] for e in events}
    assert {"r0", "r1", "a", "b"} <= names
    # metadata names the tracks
    assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
               for e in doc["traceEvents"])


def test_flight_recorder_dump_carries_traces(tmp_path):
    from paddle_tpu.monitor import flight_recorder as fr
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        tr = trace_mod.start_trace("inflight", request_id=9)
        assert tr is not None                   # provider registered
        rec = fr.FlightRecorder(capacity=8, dump_dir=str(tmp_path))
        path = rec.dump(reason="explicit")
    with open(path) as f:
        doc = json.load(f)
    ids = [t["trace_id"] for t in doc.get("traces", [])]
    assert tr.trace_id in ids                   # live trace attached
    trace_mod.get_tracer().finish_trace(tr)


# ---------------------------------------------------------------------------
# exemplars
# ---------------------------------------------------------------------------


def test_histogram_exemplar_round_trip(tmp_path):
    from paddle_tpu.monitor import load_jsonl
    with scoped_registry() as reg:
        h = reg.histogram("ex_seconds", "t", buckets=(0.1, 1.0, 10.0))
        h.observe(0.05, exemplar="tid-1")
        h.observe(0.5)                          # no exemplar: kept old
        h.observe(0.7, exemplar="tid-2")
        h.observe(100.0, exemplar="tid-inf")    # past the last bucket
        ex = h.exemplars()
        assert ex["0.1"]["trace_id"] == "tid-1"
        assert ex["1.0"]["trace_id"] == "tid-2"
        assert ex["+Inf"]["trace_id"] == "tid-inf"
        p = str(tmp_path / "m.jsonl")
        reg.dump_jsonl(p)
    rows = [r for r in load_jsonl(p) if r["name"] == "ex_seconds"]
    assert rows and rows[0]["exemplars"]["1.0"]["trace_id"] == "tid-2"
    assert rows[0]["count"] == 4                # histogram itself intact


# ---------------------------------------------------------------------------
# SLO burn rate
# ---------------------------------------------------------------------------


def _clocked_tracker(**kw):
    now = [0.0]
    t = SLOTracker("t", kw.pop("objective", 0.99),
                   windows=kw.pop("windows", (60.0, 600.0)),
                   clock=lambda: now[0], **kw)
    return t, now


def test_burn_rate_arithmetic():
    t, now = _clocked_tracker(objective=0.99)    # budget = 1%
    for i in range(99):
        now[0] = float(i)
        t.record(good=1)
    now[0] = 99.0
    t.record(bad=1)
    # window 600s covers everything: error ratio 1% -> burn exactly 1.0
    assert t.error_ratio(600.0) == pytest.approx(0.01)
    assert t.burn_rate(600.0) == pytest.approx(1.0)
    # 60s window sees the tail: 59 good (t>=40..98) + 1 bad
    r60 = t.error_ratio(60.0)
    assert t.burn_rate(60.0) == pytest.approx(r60 / 0.01)
    assert t.burn_rate(60.0) > 1.0
    # budget: 1 bad / 100 total on a 1% budget = fully consumed
    assert t.budget_remaining() == pytest.approx(0.0)
    # no-traffic window burns nothing
    now[0] = 10_000.0
    assert t.burn_rate(60.0) == 0.0


def test_burn_alert_needs_both_windows():
    t, now = _clocked_tracker(objective=0.999, windows=(60.0, 3600.0))
    # old burst (bad), then a long quiet good period: the long window
    # still shows burn but the short one has recovered -> no alert
    now[0] = 0.0
    t.record(bad=50)
    for i in range(1, 120):
        now[0] = float(i * 25)
        t.record(good=10)
    pairs = ((3600.0, 60.0, 10.0),)
    assert t.burn_rate(3600.0) > 10.0
    assert t.burn_rate(60.0) < 10.0
    assert t.should_alert(pairs) == []
    # fresh burst: both windows fire
    t.record(bad=50)
    firing = t.should_alert(pairs)
    assert len(firing) == 1 and firing[0]["threshold"] == 10.0


def test_slo_validation_and_publish():
    with pytest.raises(ValueError):
        SLOTracker("x", 1.5)
    with pytest.raises(ValueError):
        SLOTracker("x", 0.99, windows=())
    t, now = _clocked_tracker(objective=0.9, windows=(60.0,))
    now[0] = 1.0
    t.record(good=8, bad=2)
    with scoped_registry() as reg:
        t.publish(registry=reg)
        burn = reg.get("slo_burn_rate")
        assert burn.value(slo="t", window="60s") == pytest.approx(2.0)
        assert reg.get("slo_error_budget_remaining").value(
            slo="t") == pytest.approx(-1.0)
        assert reg.get("slo_objective").value(slo="t") == \
            pytest.approx(0.9)
    snap = t.snapshot()
    assert snap["burn_60s"] == pytest.approx(2.0)


# ---------------------------------------------------------------------------
# serving lifecycle traces
# ---------------------------------------------------------------------------


@pytest.mark.serve
def test_serving_request_lifecycle_trace(tiny_model):
    with scoped_registry() as reg, flag_scope("trace", True), \
            flag_scope("trace_sample", 1.0):
        eng = _engine(tiny_model)
        eng.generate([[1, 2, 3], [4, 5, 6, 7]], max_new_tokens=3)
        kept = trace_mod.get_tracer().retained()
        assert len(kept) == 2
        ids = {tr.trace_id for tr in kept}
        assert len(ids) == 2                    # one trace per request
        for tr in kept:
            assert tr.anomaly is None and tr.finished
            names = _span_names(tr)
            assert names[0] == "serve.request"
            for expected in ("queued", "admitted", "prefill",
                             "decode[1]", "decode[2]", "terminal"):
                assert expected in names, (expected, names)
            term = [s for s in tr.spans if s.name == "terminal"][0]
            assert term.attrs["outcome"] == "completed"
            assert tr.root.attrs["outcome"] == "completed"
            # decode spans nest under admitted, which nests under root
            adm = [s for s in tr.spans if s.name == "admitted"][0]
            dec = [s for s in tr.spans if s.name.startswith("decode")]
            assert all(d.parent_id == adm.span_id for d in dec)
        # exemplars link the latency histograms to these traces
        ex = reg.get("serve_ttft_seconds").exemplars()
        assert any(v["trace_id"] in ids for v in ex.values())


@pytest.mark.serve
def test_zero_overhead_with_flags_off(tiny_model):
    """Both flags off ⇒ zero span allocations, zero trace/slo registry
    series over a 50-request serve run (the acceptance probe)."""
    with scoped_registry() as reg:
        eng = _engine(tiny_model)
        for i in range(50):
            eng.submit(Request([1 + (i % 7), 2, 3], max_new_tokens=2))
        eng.run()
        assert eng.scheduler.stats["completed"] == 50
    assert trace_mod.TRACE_STATS["spans_allocated"] == 0
    assert trace_mod.TRACE_STATS["traces_started"] == 0
    assert trace_mod._tracer is None or not \
        trace_mod._tracer.retained()
    assert not [n for n in reg.names()
                if n.startswith(("trace_", "slo_"))]


@pytest.mark.serve
@pytest.mark.chaos
def test_chaos_drill_tail_keeps_only_anomalies(tiny_model):
    """Head sample 0.0 + serve.request.poison: the poisoned request
    retains a COMPLETE span tree with its failure reason; healthy
    requests retain zero traces (the acceptance drill)."""
    chaos.configure("serve.request.poison@2", seed=0)
    with flag_scope("trace", True), flag_scope("trace_sample", 0.0):
        eng = _engine(tiny_model)
        for i in range(4):
            eng.submit(Request([1, 2, 3, 4], max_new_tokens=2))
        eng.run()
        assert eng.scheduler.stats["failed"] == 1
        assert eng.scheduler.stats["completed"] == 3
        kept = trace_mod.get_tracer().retained()
        assert len(kept) == 1                   # ONLY the anomaly
        tr = kept[0]
        assert tr.anomaly == "chaos"
        names = _span_names(tr)
        assert {"queued", "admitted", "terminal"} <= set(names)
        term = [s for s in tr.spans if s.name == "terminal"][0]
        assert term.attrs["outcome"] == "failed"
        assert "non-finite" in term.attrs["reason"]
        assert trace_mod.TRACE_STATS["tail_retained"] == 1


@pytest.mark.serve
@pytest.mark.chaos
def test_watchdog_trip_tail_keeps_inflight_traces(tiny_model):
    from paddle_tpu.serving import DecodeWatchdogError
    chaos.configure("serve.decode.hang@1", seed=0)
    with flag_scope("trace", True), flag_scope("trace_sample", 0.0), \
            flag_scope("serve_watchdog_s", 2.0):
        eng = _engine(tiny_model)
        eng.submit(Request([1, 2, 3], max_new_tokens=2))
        with pytest.raises(DecodeWatchdogError):
            eng.run()
        chaos.cancel_hangs()
        eng.run()                               # post-trip retry
        assert eng.scheduler.stats["completed"] == 1
        kept = trace_mod.get_tracer().retained()
        assert len(kept) == 1
        assert kept[0].anomaly == "watchdog"
        assert kept[0].root.attrs["outcome"] == "completed"


@pytest.mark.serve
@pytest.mark.chaos
def test_trace_survives_recompute_preemption(tiny_model):
    # probe #1 (admission) passes, probe #2 (decode capacity) forces a
    # recompute-preemption of the newest-admitted request
    chaos.configure("serve.pages.exhaust@2", seed=0)
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        eng = _engine(tiny_model)
        a = eng.submit(Request([1, 2, 3], max_new_tokens=3))
        b = eng.submit(Request([4, 5, 6], max_new_tokens=3))
        eng.run()
        assert eng.scheduler.stats["preemptions"] == 1
        assert eng.scheduler.stats["completed"] == 2
        victim = b if b.preemptions else a
        assert victim.preemptions == 1
        kept = {t.trace_id: t for t in trace_mod.get_tracer().retained()}
        tr = kept[victim.trace.trace_id]        # same trace object/id
        names = _span_names(tr)
        assert names.count("queued") == 2       # both residencies
        assert names.count("admitted") == 2
        requeued = [s for s in tr.spans
                    if s.name == "queued" and s.attrs.get("reason")]
        assert requeued and requeued[0].attrs["reason"] == "preemption"
        assert tr.root.attrs["outcome"] == "completed"


@pytest.mark.serve
def test_trace_id_survives_drain_resume(tiny_model, tmp_path):
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        eng = _engine(tiny_model, drain_dir=str(tmp_path))
        st1 = eng.submit(Request([1, 2, 3], max_new_tokens=8))
        st2 = eng.submit(Request([4, 5, 6], max_new_tokens=8))
        eng.step()                              # admit + first tokens
        report = eng.drain(budget_s=0.0)        # snapshot, don't finish
        assert report.snapshotted == 2
        orig_ids = {st.request.request_id: st.trace.trace_id
                    for st in (st1, st2)}
        path, specs = load_drain_snapshot(str(tmp_path))
        assert path is not None and len(specs) == 2
        by_req = {s["request_id"]: s for s in specs}
        for rid, tid in orig_ids.items():
            assert by_req[rid]["trace_id"] == tid
        # successor engine resumes the SAME trace ids — and a resumed
        # identity is kept even when the head coin would drop it (the
        # first half may already be retained; a re-flip must not orphan
        # the continuation)
        with flag_scope("trace_sample", 0.0):
            eng2 = _engine(tiny_model)
            states = [eng2.submit(r)
                      for r in requests_from_snapshot(specs)]
            eng2.run()
        resumed_ids = {st.trace.trace_id for st in states}
        assert resumed_ids == set(orig_ids.values())
        kept_ids = {t.trace_id
                    for t in trace_mod.get_tracer().retained()}
        assert resumed_ids <= kept_ids
        for st in states:
            assert st.trace.root.attrs["resumed"] is True
            assert st.trace.root.attrs["outcome"] == "completed"


@pytest.mark.serve
def test_serving_slo_trackers(tiny_model):
    with scoped_registry() as reg:
        eng = _engine(tiny_model, slo_availability=0.99,
                      slo_deadline=0.95, slo_windows=(60.0, 600.0))
        eng.generate([[1, 2, 3]], max_new_tokens=2)
        assert eng._slo_avail.total_good == 1
        assert eng._slo_avail.total_bad == 0
        assert reg.get("slo_burn_rate").value(
            slo="serve_availability", window="60s") == 0.0
        assert reg.get("slo_error_budget_remaining").value(
            slo="serve_availability") == pytest.approx(1.0)
        # a queued expiry spends availability AND deadline budget
        eng2 = _engine(tiny_model, slo_availability=0.99,
                       slo_deadline=0.95)
        st = eng2.submit(Request([1, 2], max_new_tokens=2,
                                 deadline_s=1e-6))
        import time as _time
        _time.sleep(0.01)
        eng2.scheduler.expire_queued()
        assert st.outcome == "expired"
        assert eng2._slo_avail.total_bad == 1
        assert eng2._slo_deadline.total_bad == 1


@pytest.mark.serve
def test_spans_follow_injected_engine_clock(tiny_model):
    """Every span of a serving trace lives in the ENGINE clock domain
    (injectable), never the tracer's wall clock — one time base per
    trace."""
    fake = [1000.0]

    def clock():
        fake[0] += 0.25
        return fake[0]

    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        eng = ServingEngine(tiny_model, ServingConfig(
            max_batch_slots=2, block_size=4, max_context_len=64,
            prefill_buckets=(8,), batch_buckets=(1, 2)),
            clock=clock)
        eng.generate([[1, 2, 3]], max_new_tokens=2)
        tr = trace_mod.get_tracer().retained()[0]
    for s in tr.spans:
        assert 1000.0 <= s.t0 <= fake[0], (s.name, s.t0)
        assert s.t1 is not None and s.t1 <= fake[0], s.name
        assert s.t1 >= s.t0, (s.name, s.t0, s.t1)


@pytest.mark.serve
def test_requeue_closes_open_queued_span(tiny_model):
    """A watchdog rollback of a never-prefilled state must close its
    ORIGINAL queued span before opening the new one (no open-span
    leak)."""
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        eng = _engine(tiny_model)
        st = eng.submit(Request([1, 2, 3], max_new_tokens=2))
        first_q = st.trace_spans["queued"]
        assert first_q.t1 is None
        eng._trace_requeue(st, "watchdog_rollback")
        assert first_q.t1 is not None               # closed, not leaked
        assert first_q.attrs["requeued"] == "watchdog_rollback"
        second_q = st.trace_spans["queued"]
        assert second_q is not first_q and second_q.t1 is None
        eng.run()
        assert all(s.t1 is not None
                   for s in st.trace.spans), "open span leaked"


def test_flight_dump_survives_nonfinite_span_attrs(tmp_path):
    from paddle_tpu.monitor import flight_recorder as fr
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        tr = trace_mod.start_trace("weird")
        tr.mark_anomaly("nonfinite", loss=float("nan"))
        rec = fr.FlightRecorder(capacity=4, dump_dir=str(tmp_path))
        path = rec.dump(reason="explicit")   # allow_nan=False must hold
    doc = json.load(open(path))
    root = doc["traces"][0]["spans"][0]
    assert root["attrs"]["loss"] == "nan"
    trace_mod.get_tracer().finish_trace(tr)


# ---------------------------------------------------------------------------
# training-step traces
# ---------------------------------------------------------------------------


def _train_step():
    paddle.seed(7)
    m = nn.Linear(8, 4)
    opt = SGD(learning_rate=0.1, parameters=m.parameters())
    return m, TrainStep(m, lambda layer, x, y: F.mse_loss(layer(x), y),
                        opt)


def test_train_step_trace_and_zero_overhead():
    _, step = _train_step()
    x = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    y = paddle.to_tensor(np.random.randn(4, 4).astype("float32"))
    with scoped_registry() as reg:
        w0 = reg.write_count
        for _ in range(3):
            step(x, y)
        # monitor AND trace off: zero registry writes, zero spans
        assert reg.write_count == w0
    assert trace_mod.TRACE_STATS["spans_allocated"] == 0
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
        step(x, y)
    kept = trace_mod.get_tracer().retained()
    assert len(kept) == 1
    tr = kept[0]
    assert tr.name == "train.step"
    assert "dispatch" in _span_names(tr)
    assert tr.anomaly is None


def test_train_step_nonfinite_tail_retains():
    _, step = _train_step()
    step._check_numerics = "warn"
    x = paddle.to_tensor(
        np.full((4, 8), np.nan, dtype="float32"))
    y = paddle.to_tensor(np.zeros((4, 4), dtype="float32"))
    with flag_scope("trace", True), flag_scope("trace_sample", 0.0):
        with pytest.warns(RuntimeWarning):
            step(x, y)
    kept = trace_mod.get_tracer().retained()
    assert len(kept) == 1 and kept[0].anomaly == "nonfinite"


def test_checkpoint_commit_span_attaches(tmp_path):
    from paddle_tpu.serving.resilience import save_drain_snapshot
    t = trace_mod.Tracer(capacity=4)
    old = trace_mod.set_tracer(t)
    try:
        with flag_scope("trace", True), flag_scope("trace_sample", 1.0):
            tr = trace_mod.start_trace("train.step")
            with trace_mod.activate(tr):
                save_drain_snapshot(str(tmp_path / "d"), [])
            t.finish_trace(tr)
        assert "checkpoint.commit" in _span_names(tr)
    finally:
        trace_mod.set_tracer(old)


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------


def test_monitor_report_trace_render(tmp_path):
    import tools.monitor_report as report
    t = trace_mod.Tracer(capacity=8)
    with flag_scope("trace_sample", 0.0):
        tr = t.start_trace("serve.request", request_id=1)
        with tr.span("queued"):
            pass
        adm = tr.start_span("admitted")
        with tr.span("prefill", parent=adm):
            pass
        with tr.span("decode[1]", parent=adm):
            pass
        tr.end_span(adm)
        tr.event("terminal", outcome="failed", reason="boom")
        tr.mark_anomaly("failed")
        t.finish_trace(tr)
    path = t.dump(str(tmp_path / "traces.json"))
    out = report.render_traces(trace_mod.load_trace_dump(path))
    assert "ANOMALY: failed" in out
    assert "[tail-kept]" in out
    assert "decode[1]" in out and "terminal" in out
    assert "Exclusive time by span" in out
    assert "*" in out                           # critical path marked
    # the CLI path parses the same file
    assert report.main(["--trace", path]) == 0


def test_monitor_report_fallbacks_render():
    import tools.monitor_report as report
    rows = [
        {"name": "scan_fallback_total", "type": "counter",
         "labels": {"reason": "kv_cache"}, "value": 2},
        {"name": "pallas_fallback_total", "type": "counter",
         "labels": {"kernel": "chunked_ce", "reason": "cpu_backend"},
         "value": 5},
        {"name": "pipeline_fallback_total", "type": "counter",
         "labels": {"reason": "tp_mesh"}, "value": 1},
        {"name": "moe_fallback_total", "type": "counter",
         "labels": {"reason": "mixed_mesh"}, "value": 3},
    ]
    out = report.render(rows, fallbacks=True)
    assert "Fallbacks / degradations (11 total)" in out
    for sub in ("scan", "pallas", "pipeline", "moe"):
        assert sub in out
    assert "reason=kv_cache" in out
    # counters claimed by the section do not re-render below
    assert "Other metrics" not in out
    empty = report.render([], fallbacks=True)
    assert "no *_fallback_total counters" in empty


def test_recovery_events_single_source():
    """Satellite pin: the tool imports the canonical RECOVERY_EVENTS;
    its standalone fallback copy can never drift."""
    import tools.monitor_report as report
    from paddle_tpu.monitor.flight_recorder import RECOVERY_EVENTS
    assert report._recovery_events() is RECOVERY_EVENTS
    assert report._RECOVERY_EVENTS_FALLBACK == RECOVERY_EVENTS


def test_check_bench_overhead_unit():
    from tools.check_bench import compare
    old = [{"metric": "serve_trace_overhead_pct", "value": 1.0,
            "unit": "overhead%"}]
    grown = [{"metric": "serve_trace_overhead_pct", "value": 25.0,
              "unit": "overhead%"}]
    assert compare(old, grown, tolerance=0.10)      # +24 points trips
    ok = [{"metric": "serve_trace_overhead_pct", "value": 6.0,
           "unit": "overhead%"}]
    assert compare(old, ok, tolerance=0.10) == []   # +5 points passes
