"""PS graph table + service (reference:
fluid/distributed/table/common_graph_table.h:1,
service/graph_brpc_server.cc)."""

import numpy as np

from paddle_tpu.distributed.ps.graph import (GraphClient, GraphService,
                                             GraphTable)


def _toy_table():
    t = GraphTable(seed=0)
    t.add_graph_node("user", [0, 1, 2, 3])
    # star around 0 plus a chain
    t.add_edges("follows", src=[0, 0, 0, 1, 2], dst=[1, 2, 3, 2, 3])
    t.build()
    return t


def test_sample_neighbors_and_degree():
    t = _toy_table()
    flat, counts = t.sample_neighbors("follows", [0, 1, 9], sample_size=2)
    assert counts.tolist()[1] == 1 and counts[2] == 0
    assert counts[0] == 2                      # capped at sample_size
    assert set(flat[:2]).issubset({1, 2, 3})
    assert flat[2] == 2                        # node 1's only neighbor
    np.testing.assert_array_equal(t.degree("follows", [0, 1, 2, 9]),
                                  [3, 1, 1, 0])


def test_sample_with_replacement_and_incremental_edges():
    t = _toy_table()
    flat, counts = t.sample_neighbors("follows", [1], sample_size=4,
                                      replace=True)
    assert counts[0] == 4 and set(flat) == {2}
    t.add_edges("follows", src=[1], dst=[3])   # invalidates + rebuilds
    np.testing.assert_array_equal(t.degree("follows", [1]), [2])


def test_node_feats_roundtrip_and_random_nodes():
    t = _toy_table()
    t.set_node_feat("emb", [1, 3], np.asarray([[1., 2.], [3., 4.]],
                                              np.float32))
    out = t.get_node_feat("emb", [3, 1, 7])
    np.testing.assert_allclose(out, [[3., 4.], [1., 2.], [0., 0.]])
    t.set_node_feat("emb", [1], np.asarray([[9., 9.]], np.float32))
    np.testing.assert_allclose(t.get_node_feat("emb", [1]), [[9., 9.]])
    ids = t.random_sample_nodes("user", 3)
    assert len(ids) == 3 and set(ids).issubset({0, 1, 2, 3})
    assert len(t.random_sample_nodes("user", 99)) == 4


def test_save_load_roundtrip(tmp_path):
    t = _toy_table()
    t.set_node_feat("emb", [0], np.ones((1, 4), np.float32))
    t.save(str(tmp_path))
    t2 = GraphTable()
    t2.load(str(tmp_path))
    np.testing.assert_array_equal(t2.degree("follows", [0]), [3])
    np.testing.assert_allclose(t2.get_node_feat("emb", [0]),
                               np.ones((1, 4), np.float32))


def test_graph_service_over_tcp():
    svc = GraphService(GraphTable(seed=1))
    try:
        c = GraphClient(svc.endpoint)
        c.add_graph_node("item", [10, 11, 12])
        c.add_edges("clicks", src=[10, 10, 11], dst=[11, 12, 12])
        c.build()
        flat, counts = c.sample_neighbors("clicks", [10, 11],
                                          sample_size=5)
        assert counts.tolist() == [2, 1]
        assert set(flat[:2]) == {11, 12} and flat[2] == 12
        c.set_node_feat("f", [10], np.full((1, 3), 7.0, np.float32))
        np.testing.assert_allclose(c.get_node_feat("f", [10]),
                                   [[7., 7., 7.]])
        # errors propagate without killing the connection
        import pytest
        with pytest.raises(RuntimeError, match="graph service error"):
            c.sample_neighbors("nope", [1], sample_size=1)
        assert c.degree("clicks", [10]).tolist() == [2]
        c.close()
    finally:
        svc.stop()


def test_add_edges_after_load_keeps_loaded_edges(tmp_path):
    """Regression (ADVICE r5): add_edges() on an edge type restored by
    load() must ACCUMULATE — the loaded CSR is decomposed back into a
    pending chunk, not silently dropped by the rebuild."""
    t = _toy_table()
    t.save(str(tmp_path))
    t2 = GraphTable(seed=0)
    t2.load(str(tmp_path))
    np.testing.assert_array_equal(t2.degree("follows", [0, 1]), [3, 1])

    t2.add_edges("follows", src=[1, 0], dst=[0, 9])
    t2.build()
    # loaded edges survive AND the new ones land
    np.testing.assert_array_equal(t2.degree("follows", [0, 1, 2]),
                                  [4, 2, 1])
    flat, counts = t2.sample_neighbors("follows", [1], sample_size=8)
    assert counts[0] == 2 and set(flat.tolist()) == {0, 2}


def test_wire_codec_roundtrip_and_dtype_allowlist():
    """The typed struct+numpy wire framing (no pickle): values round-trip
    exactly; object-dtype buffers are refused in both directions."""
    import pytest

    from paddle_tpu.distributed.ps.graph import (_pack_fields,
                                                 _pack_value,
                                                 _unpack_fields)

    fields = {"op": "sample_neighbors", "edge_type": "follows",
              "ids": np.asarray([1, 2, 3], np.int64), "sample_size": 5,
              "replace": False, "none_v": None, "f": 2.5,
              "lst": [1, 2.0, "x"]}
    out = _unpack_fields(_pack_fields(fields))
    assert out["op"] == "sample_neighbors" and out["sample_size"] == 5
    assert out["replace"] is False and out["none_v"] is None
    assert out["f"] == 2.5 and out["lst"] == [1, 2.0, "x"]
    np.testing.assert_array_equal(out["ids"], [1, 2, 3])

    with pytest.raises(TypeError, match="dtype"):
        _pack_value(np.asarray([object()], dtype=object))
