"""Detection/ranking op tail (reference: fluid/layers/detection.py
bipartite_match/box_clip/density_prior_box/FPN ops; loss.py
bpr_loss/center_loss; cvm_op.cc; nn.py add_position_encoding)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def test_bipartite_match_greedy():
    # classic example: greedy max matching, no duplicate rows
    d = paddle.to_tensor(np.array([[0.1, 0.9, 0.3],
                                   [0.8, 0.2, 0.4]], np.float32))
    idx, dist = V.bipartite_match(d)
    idx, dist = idx.numpy()[0], dist.numpy()[0]
    # col1 -> row0 (0.9 best overall), col0 -> row1 (0.8), col2 unmatched
    assert idx.tolist() == [1, 0, -1]
    np.testing.assert_allclose(dist[:2], [0.8, 0.9], atol=1e-6)
    # per_prediction fills unmatched cols above threshold
    idx2, _ = V.bipartite_match(d, match_type="per_prediction",
                                dist_threshold=0.25)
    assert idx2.numpy()[0].tolist() == [1, 0, 1]    # col2 argmax row=1 (0.4)


def test_box_clip():
    boxes = paddle.to_tensor(np.array([[[-5.0, -5.0, 120.0, 80.0]]],
                                      np.float32))
    im_info = paddle.to_tensor(np.array([[60.0, 100.0, 1.0]], np.float32))
    out = V.box_clip(boxes, im_info).numpy()[0, 0]
    np.testing.assert_allclose(out, [0.0, 0.0, 99.0, 59.0])


def test_density_prior_box_shapes_and_reference_spacing():
    feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
    img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
    boxes, var = V.density_prior_box(feat, img, densities=[2],
                                     fixed_sizes=[16.0],
                                     fixed_ratios=[1.0])
    assert boxes.shape == [2, 2, 4, 4]      # 2x2 cells, 1*1*2*2 boxes
    assert var.shape == boxes.shape
    # reference spacing: step 32 -> sub-centers at cx -/+ step_avg/4 = 8
    b = boxes.numpy()[0, 0] * 64.0          # cell center (16, 16)
    centers_x = np.sort((b[:, 0] + b[:, 2]) / 2.0)
    np.testing.assert_allclose(centers_x, [8.0, 8.0, 24.0, 24.0],
                               atol=1e-4)


def test_fpn_distribute_and_collect():
    rois = paddle.to_tensor(np.array(
        [[0, 0, 16, 16],        # scale 16 -> low level
         [0, 0, 224, 224],      # scale 224 -> refer level
         [0, 0, 500, 500]], np.float32))
    multi, restore, counts = V.distribute_fpn_proposals(
        rois, min_level=2, max_level=5, refer_level=4, refer_scale=224)
    assert len(multi) == 4
    c = counts.numpy()
    assert c.sum() == 3 and c[0] == 1       # the 16x16 roi at min level
    # collect: top-k by score across levels; PAD rows (beyond each
    # level's count) must never outrank real proposals
    scores = [paddle.to_tensor(np.full((3, 1), s, np.float32))
              for s in (0.9, 0.8, 0.7, 0.6)]
    out_rois, out_scores = V.collect_fpn_proposals(
        multi, scores, 2, 5, post_nms_top_n=3,
        rois_num_per_level=counts)
    got = out_scores.numpy()[:, 0]
    np.testing.assert_allclose(got, [0.9, 0.7, 0.6], atol=1e-6)
    assert not (out_rois.numpy() == 0).all(axis=1).any()


def test_bpr_and_center_loss_and_cvm():
    x = paddle.to_tensor(np.array([[5.0, 0.0, 0.0]], np.float32))
    y = paddle.to_tensor(np.array([[0]], np.int64))
    loss = V.bpr_loss(x, y)
    assert float(loss.numpy()) < 0.1        # label logit dominates

    feats = paddle.to_tensor(np.ones((4, 8), np.float32))
    labels = paddle.to_tensor(np.zeros((4,), np.int64))
    l1, centers = V.center_loss(feats, labels, num_classes=3, alpha=0.5)
    assert l1.shape == [4, 1]
    # centers moved toward the features
    assert float(np.abs(centers.numpy()[0]).sum()) > 0
    l2, _ = V.center_loss(feats, labels, 3, 0.5, centers=centers)
    assert float(l2.numpy().sum()) < float(l1.numpy().sum())

    emb = paddle.to_tensor(np.ones((2, 5), np.float32))
    sc = paddle.to_tensor(np.array([[9.0, 3.0], [1.0, 0.0]], np.float32))
    out = V.cvm(emb, sc, use_cvm=True)
    assert out.shape == [2, 5]
    np.testing.assert_allclose(out.numpy()[0, 0], np.log(10.0), rtol=1e-5)
    out2 = V.cvm(emb, sc, use_cvm=False)
    assert out2.shape == [2, 3]


def test_add_position_encoding_and_crf_decoding():
    x = paddle.to_tensor(np.zeros((1, 4, 6), np.float32))
    out = V.add_position_encoding(x, alpha=1.0, beta=1.0).numpy()
    # PE at position 0: sin(0)=0 for first half, cos(0)=1 for second
    np.testing.assert_allclose(out[0, 0, :3], 0.0, atol=1e-6)
    np.testing.assert_allclose(out[0, 0, 3:], 1.0, atol=1e-6)

    rng = np.random.default_rng(0)
    emis = paddle.to_tensor(rng.normal(size=(2, 5, 3)).astype(np.float32))
    trans = paddle.to_tensor(rng.normal(size=(3, 3)).astype(np.float32))
    path = V.crf_decoding(emis, trans)
    assert path.shape == [2, 5]
    mask = V.crf_decoding(emis, trans, label=path)
    assert (mask.numpy() == 1).all()        # path agrees with itself
