"""Training goodput ledger + per-layer model health (ISSUE 19,
docs/OBSERVABILITY.md "Training goodput & model health").

Pins the three contracts the feature ships on:

- **exhaustiveness**: every second of trainer wall-clock lands in
  exactly ONE exclusive bucket — sum(buckets) == elapsed by
  construction (``host_other`` is the derived residual), including
  across chaos faults and a SIGTERM → resume restart;
- **zero overhead off**: with ``FLAGS_train_goodput`` unset no ledger
  is ever allocated (``GOODPUT_STATS['ledgers_allocated']`` stays 0),
  no registry series appear, and the compiled step program — and
  therefore the loss trajectory — is bit-identical;
- **attribution**: each chaos drill's wall-clock shows up in its
  designated bucket (``ckpt.write.torn`` → checkpoint_stall,
  ``grad.nonfinite`` → nonfinite_rollback, ``collective.hang`` →
  host_other), and ``train_goodput_pct`` reconstructs bit-consistently
  across preemption via the CheckpointManager sidecar.
"""

import json
import os
import signal
import sys
import time
import urllib.request
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.distributed.checkpoint import (CheckpointManager,
                                               PreemptionSignal)
from paddle_tpu.distributed.checkpoint.manager import MANAGER_STATE_NAME
from paddle_tpu.jit.to_static import TrainStep, _layer_key
from paddle_tpu.monitor import flight_recorder as flight
from paddle_tpu.monitor import goodput, scoped_registry
from paddle_tpu.monitor import trace as trace_mod
from paddle_tpu.monitor.goodput import (BADPUT_BUCKETS, BUCKETS,
                                        GOODPUT_STATS, GoodputLedger,
                                        LayerHealthMonitor)
from paddle_tpu.monitor.metrics import MetricsRegistry
from paddle_tpu.monitor.server import AdminServer
from paddle_tpu.testing import chaos

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


def _build_step(**kwargs):
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    return TrainStep(model, lambda l, a, b: F.cross_entropy(l(a), b),
                     paddle.optimizer.Adam(learning_rate=1e-2,
                                           parameters=model.parameters()),
                     **kwargs)


def _batch(i):
    rng = np.random.default_rng(50 + i)
    return (rng.standard_normal((8, 8)).astype(np.float32),
            rng.integers(0, 4, (8,)).astype(np.int64))


def _ref_losses(n):
    step = _build_step()
    return [float(step(*_batch(i))) for i in range(n)]


# ---------------------------------------------------------------------------
# GoodputLedger unit behaviour
# ---------------------------------------------------------------------------

def test_bucket_taxonomy():
    assert BUCKETS[0] == "productive_dispatch"
    assert set(BADPUT_BUCKETS) == set(BUCKETS) - {"productive_dispatch"}
    for b in ("compile", "data_wait", "checkpoint_stall",
              "nonfinite_rollback", "restart_gap", "host_other"):
        assert b in BADPUT_BUCKETS
    led = GoodputLedger()
    with pytest.raises(ValueError, match="unknown goodput bucket"):
        with led.measure("coffee_break"):
            pass


def test_bucket_sum_equals_elapsed():
    """The exhaustiveness invariant: measured buckets plus the derived
    host_other residual account for ALL elapsed wall-clock."""
    led = GoodputLedger()
    with led.measure("compile"):
        time.sleep(0.02)
    with led.measure("productive_dispatch"):
        time.sleep(0.03)
    time.sleep(0.01)            # unmeasured host time -> residual
    snap = led.snapshot()
    total = sum(snap["buckets"].values())
    assert total == pytest.approx(snap["elapsed_s"], rel=1e-6)
    # the acceptance band (1%) is therefore trivially met
    assert abs(total - snap["elapsed_s"]) <= 0.01 * snap["elapsed_s"]
    assert snap["buckets"]["compile"] >= 0.015
    assert snap["buckets"]["productive_dispatch"] >= 0.025
    assert snap["buckets"]["host_other"] >= 0.005
    assert 0.0 < snap["goodput_pct"] < 100.0


def test_nested_measures_never_double_count():
    """The exclusivity cursor clips overlap: an inner interval already
    accounted is never charged again to the outer bucket."""
    led = GoodputLedger()
    t_begin = time.perf_counter()
    with led.measure("host_other"):
        with led.measure("compile"):
            time.sleep(0.02)
        time.sleep(0.01)
    wall = time.perf_counter() - t_begin
    assert led._seconds["compile"] >= 0.015
    # outer gets only its own tail, inner only its own body; together
    # they can never exceed the real wall-clock of the nest
    assert (led._seconds["compile"] + led._seconds["host_other"]
            <= wall + 1e-6)


def test_measure_on_error_attributes_and_reraises():
    led = GoodputLedger()
    with pytest.raises(RuntimeError, match="boom"):
        with led.measure("productive_dispatch", on_error="host_other"):
            time.sleep(0.01)
            raise RuntimeError("boom")
    assert led._seconds["host_other"] >= 0.005
    assert led._seconds["productive_dispatch"] == 0.0


def test_reattribute_last_moves_seconds_once():
    led = GoodputLedger()
    assert led.reattribute_last("nonfinite_rollback") == 0.0
    with led.measure("productive_dispatch"):
        time.sleep(0.01)
    moved = led.reattribute_last("nonfinite_rollback")
    assert moved >= 0.005
    assert led._seconds["productive_dispatch"] == pytest.approx(0.0,
                                                                abs=1e-12)
    assert led._seconds["nonfinite_rollback"] == pytest.approx(moved)
    assert GOODPUT_STATS["reattributions"] == 1
    # idempotent when the interval already lives in the target bucket
    assert led.reattribute_last("nonfinite_rollback") == \
        pytest.approx(moved)
    assert GOODPUT_STATS["reattributions"] == 1


def test_restore_is_bit_consistent_and_names_the_gap():
    a = GoodputLedger()
    with a.measure("productive_dispatch"):
        time.sleep(0.02)
    with a.measure("compile"):
        time.sleep(0.01)
    saved = a.state()
    assert saved["version"] == 1 and saved["wall"] > 0
    saved = json.loads(json.dumps(saved))     # the sidecar round-trip
    time.sleep(0.05)                          # the restart dead time
    b = GoodputLedger()
    gap = b.restore(saved)
    assert gap > 0.0
    assert GOODPUT_STATS["restores"] == 1
    for bucket in BUCKETS:
        if bucket != "restart_gap":
            assert b._carry[bucket] == saved["buckets"][bucket]
    assert b._carry["restart_gap"] == \
        saved["buckets"]["restart_gap"] + gap
    assert b._restarts == saved["restarts"] + 1
    snap = b.snapshot()
    assert snap["restarts"] == 1
    # productive seconds carried bit-exactly, invariant intact
    assert snap["buckets"]["productive_dispatch"] == \
        saved["buckets"]["productive_dispatch"]
    assert sum(snap["buckets"].values()) == \
        pytest.approx(snap["elapsed_s"], rel=1e-6)


def test_restore_without_wall_stamp_adds_no_gap():
    b = GoodputLedger()
    gap = b.restore({"buckets": {"compile": 1.0}, "elapsed_s": 2.0,
                     "restarts": 0})
    assert gap == 0.0
    assert b._carry["compile"] == 1.0
    assert b._carry["restart_gap"] == 0.0


def test_publish_emits_monotonic_counter_deltas():
    led = GoodputLedger()
    reg = MetricsRegistry()
    with led.measure("compile"):
        time.sleep(0.01)
    led.publish(reg)
    ctr = reg.get("train_badput_seconds_total")
    first = ctr.value(bucket="compile")
    assert first >= 0.005
    with led.measure("compile"):
        time.sleep(0.01)
    led.publish(reg)
    assert ctr.value(bucket="compile") > first   # delta, not re-set
    assert reg.get("train_goodput_pct") is not None


# ---------------------------------------------------------------------------
# LayerHealthMonitor + layer grouping
# ---------------------------------------------------------------------------

def test_layer_key_groups_by_first_numeric_component():
    assert _layer_key("layers.0.attn.qkv_weight") == "layers.0"
    assert _layer_key("layers.11.mlp.w2") == "layers.11"
    assert _layer_key("embed.weight") == "embed"
    assert _layer_key("0.weight") == "0"
    assert _layer_key("bias") == "bias"


def test_health_monitor_spikes_after_warmup_then_rearms():
    mon = LayerHealthMonitor(alpha=0.3, factor=10.0, warmup=3)
    for _ in range(4):
        assert mon.observe({"fc": {"grad_norm": 1.0}}) == []
    assert mon.observe({"fc": {"grad_norm": 50.0}}) == ["fc"]
    # the EWMA keeps tracking: a genuine regime change stops alerting
    for _ in range(12):
        mon.observe({"fc": {"grad_norm": 50.0}})
    assert mon.observe({"fc": {"grad_norm": 50.0}}) == []


def test_health_monitor_nonfinite_always_spikes():
    mon = LayerHealthMonitor()
    assert mon.observe({"a": {"grad_norm": float("nan")}}) == ["a"]


# ---------------------------------------------------------------------------
# Zero-overhead pin (flags off — the default)
# ---------------------------------------------------------------------------

def test_zero_overhead_when_flags_off():
    """FLAGS_train_goodput unset: no ledger allocation, no accounting,
    no registry series, no stats section, no statusz section."""
    step = _build_step()
    with scoped_registry() as reg:
        for i in range(2):
            step(*_batch(i))
    with goodput.measure("compile"):       # the seam form: a no-op
        pass
    assert GOODPUT_STATS["ledgers_allocated"] == 0
    assert GOODPUT_STATS["intervals_accounted"] == 0
    assert goodput.get_ledger() is None
    assert goodput.active_ledger() is None
    assert goodput.statusz_section() is None
    assert "goodput" not in step.stats()
    assert reg.write_count == 0
    assert reg.get("train_goodput_pct") is None
    assert reg.get("train_badput_seconds_total") is None


def test_flag_on_keeps_loss_trajectory_bit_identical():
    """The ledger only brackets host seams: dispatch args and the
    compiled program are untouched, so losses match bit-for-bit."""
    ref = _ref_losses(3)
    with flag_scope("train_goodput", True):
        step = _build_step()
        got = [float(step(*_batch(i))) for i in range(3)]
    assert got == ref
    assert GOODPUT_STATS["ledgers_allocated"] == 1


# ---------------------------------------------------------------------------
# TrainStep integration
# ---------------------------------------------------------------------------

def test_trainstep_stats_carry_goodput_snapshot():
    with flag_scope("train_goodput", True):
        step = _build_step()
        for i in range(3):
            step(*_batch(i))
        snap = step.stats()["goodput"]
        assert snap["buckets"]["compile"] > 0.0
        assert snap["buckets"]["productive_dispatch"] > 0.0
        assert 0.0 < snap["goodput_pct"] < 100.0
        assert sum(snap["buckets"].values()) == \
            pytest.approx(snap["elapsed_s"], rel=0.01)
    # flag off again: the section disappears (ledger object survives)
    assert "goodput" not in step.stats()
    assert goodput.get_ledger() is not None


def test_monitor_mode_publishes_goodput_series():
    with flag_scope("train_goodput", True), flag_scope("monitor", True):
        with scoped_registry() as reg:
            step = _build_step()
            for i in range(2):
                step(*_batch(i))
    assert reg.get("train_goodput_pct") is not None
    prom = reg.to_prometheus()
    assert "train_goodput_pct" in prom
    assert "train_badput_seconds_total" in prom
    assert 'bucket="compile"' in prom


def test_statusz_renders_goodput_section():
    with flag_scope("train_goodput", True):
        led = goodput.active_ledger()
        with led.measure("compile"):
            time.sleep(0.005)
        srv = AdminServer(port=0).start()
        try:
            srv.register_status("goodput", goodput.statusz_section)
            with urllib.request.urlopen(srv.url + "/statusz",
                                        timeout=10) as r:
                doc = json.loads(r.read())
        finally:
            srv.close()
    sec = doc["sections"]["goodput"]
    assert sec["buckets"]["compile"] > 0
    assert "goodput_pct" in sec and "elapsed_s" in sec


def test_data_wait_span_attaches_to_step_trace():
    """The wait for a step's batch closes before its trace exists; the
    ledger parks the interval and TrainStep attaches it retroactively
    as an explicit-timestamp span on the same perf_counter timeline."""
    from paddle_tpu.io import DataLoader, Dataset

    class _DS(Dataset):
        def __len__(self):
            return 16

        def __getitem__(self, i):
            rng = np.random.default_rng(i)
            return (rng.standard_normal(8).astype(np.float32),
                    np.int64(i % 4))

    with flag_scope("train_goodput", True), flag_scope("trace", True), \
            flag_scope("trace_sample", 1.0):
        step = _build_step()
        loader = DataLoader(_DS(), batch_size=8, drop_last=True)
        xb, yb = next(iter(loader))
        step(xb, yb)
        kept = [t for t in trace_mod.get_tracer().retained()
                if t.name == "train.step"]
    assert kept
    names = [s.name for s in kept[-1].spans]
    assert "data_wait" in names and "dispatch" in names
    dw = [s for s in kept[-1].spans if s.name == "data_wait"][0]
    assert dw.t1 is not None and dw.t1 >= dw.t0
    # consumed on attach: nothing pending for the next step
    assert goodput.get_ledger().pop_pending_data_wait() is None
    assert goodput.get_ledger().snapshot()["buckets"]["data_wait"] > 0


# ---------------------------------------------------------------------------
# Per-layer model health in the compiled step
# ---------------------------------------------------------------------------

def test_health_gauges_and_last_vector():
    with flag_scope("train_goodput", True), \
            flag_scope("train_health_every", 1), \
            flag_scope("monitor", True):
        with scoped_registry() as reg:
            step = _build_step()
            for i in range(2):
                step(*_batch(i))
    lh = goodput.last_layer_health()
    assert lh is not None and lh["step"] == 2
    # nn.Sequential param names are index-rooted: layers "0" and "2"
    assert set(lh["layers"]) == {"0", "2"}
    for vals in lh["layers"].values():
        assert set(vals) == {"grad_norm", "param_norm", "update_ratio"}
        assert all(np.isfinite(v) and v >= 0 for v in vals.values())
    prom = reg.to_prometheus()
    assert "train_layer_grad_norm" in prom and 'layer="0"' in prom
    assert "train_layer_param_norm" in prom
    assert "train_layer_update_ratio" in prom


def test_health_program_preserves_trajectory():
    """Health side-outputs only ADD f32 scalars to the step program —
    params/opt-state math is byte-for-byte the same computation."""
    ref = _ref_losses(3)
    with flag_scope("train_health_every", 1):
        step = _build_step()
        got = [float(step(*_batch(i))) for i in range(3)]
    np.testing.assert_allclose(got, ref, rtol=1e-5)
    assert step.stats()["compiles"] == 1     # one program, health fused


def test_health_publish_respects_cadence():
    with flag_scope("train_health_every", 2):
        step = _build_step()
        step(*_batch(0))
        assert goodput.last_layer_health() is None     # step 1: skipped
        step(*_batch(1))
        lh = goodput.last_layer_health()
        assert lh is not None and lh["step"] == 2


def test_health_spike_marks_trace_and_flight():
    assert "health_spike" in trace_mod.ANOMALY_REASONS
    assert "health_spike" in flight.RECOVERY_EVENTS
    step = _build_step()
    mon = LayerHealthMonitor(warmup=0)
    for _ in range(2):
        mon.observe({"0": {"grad_norm": 1.0}})
    step._health_mon = mon
    hvec = {"0": {"grad_norm": np.float32(1e6),
                  "param_norm": np.float32(1.0),
                  "update_ratio": np.float32(1e-3)}}
    with flag_scope("trace", True), flag_scope("trace_sample", 1.0), \
            flag_scope("flight_recorder", True):
        tr = trace_mod.get_tracer().start_trace("train.step")
        with trace_mod.activate(tr):
            step._publish_health(hvec, False)
        trace_mod.get_tracer().finish_trace(tr)
        events = flight.get_flight_recorder().events
    assert tr.anomaly == "health_spike"
    assert step.stats()["health_spikes"] == 1
    spikes = [e for e in events if e["event"] == "health_spike"]
    assert spikes and spikes[0]["layers"] == ["0"]


def test_flight_dump_attaches_goodput_and_layer_health():
    """Satellite: every flight-recorder dump carries the goodput
    snapshot and the last layer-health vector; --flight renders them."""
    import monitor_report
    with flag_scope("train_goodput", True):
        led = goodput.active_ledger()
        with led.measure("compile"):
            time.sleep(0.005)
        goodput.note_layer_health(
            {"0": {"grad_norm": 1.5, "param_norm": 2.0,
                   "update_ratio": 3e-4}}, step=7)
        doc = flight.get_flight_recorder().doc(reason="test")
    assert doc["goodput"]["buckets"]["compile"] > 0
    assert doc["layer_health"]["step"] == 7
    assert doc["layer_health"]["layers"]["0"]["param_norm"] == 2.0
    out = monitor_report.render_flight(doc)
    assert "goodput:" in out
    assert "Goodput buckets at dump (seconds)" in out
    assert "Last layer-health vector (step 7)" in out


# ---------------------------------------------------------------------------
# Windowed rendering (monitor_report --goodput, monitor_top pane)
# ---------------------------------------------------------------------------

def test_monitor_report_goodput_section(tmp_path):
    import monitor_report
    from paddle_tpu.monitor import load_jsonl
    reg = MetricsRegistry()
    led = GoodputLedger()
    with led.measure("data_wait"):
        time.sleep(0.01)
    with led.measure("productive_dispatch"):
        time.sleep(0.01)
    led.publish(reg)
    reg.gauge("train_layer_grad_norm", "h").set(3.5, layer="layers.0")
    reg.gauge("train_layer_update_ratio", "h").set(2e-3, layer="layers.0")
    reg.counter("train_health_spikes_total", "h").inc(layer="layers.0")
    p = str(tmp_path / "m.jsonl")
    reg.dump_jsonl(p)
    out = monitor_report.render(load_jsonl(p), goodput=True)
    assert "Training goodput (FLAGS_train_goodput)" in out
    assert "Badput by bucket" in out and "data_wait" in out
    assert "Per-layer model health" in out and "layers.0" in out
    # empty dump: a hint, not a crash
    assert "no goodput series" in monitor_report.render([], goodput=True)


def test_monitor_top_goodput_pane():
    import monitor_top
    from paddle_tpu.monitor.timeseries import (TimeseriesRing,
                                               parse_prometheus)

    class _Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clock = _Clock()
    ring = TimeseriesRing(clock=clock)
    reg = MetricsRegistry()
    reg.gauge("train_goodput_pct", "h").set(87.5)
    reg.counter("train_badput_seconds_total", "h").inc(
        1.0, bucket="data_wait")
    reg.gauge("train_layer_grad_norm", "h").set(4.0, layer="0")
    reg.gauge("train_layer_update_ratio", "h").set(1e-3, layer="0")
    ring.ingest_rows(parse_prometheus(reg.to_prometheus()))
    clock.t += 2.0
    reg.counter("train_badput_seconds_total", "h").inc(
        0.5, bucket="data_wait")
    ring.ingest_rows(parse_prometheus(reg.to_prometheus()))
    frame = monitor_top.render_frame(ring, "http://h/metrics")
    assert "goodput" in frame and "87.5% productive" in frame
    assert "badput/s" in frame and "data_wait" in frame
    assert "layers" in frame and "|g|=" in frame


# ---------------------------------------------------------------------------
# Chaos drills: every fault's wall-clock lands in its designated bucket
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_chaos_nonfinite_lands_in_rollback_bucket():
    """A chaos-NaN step trips the watchdog: its dispatch seconds are
    re-attributed from productive_dispatch to nonfinite_rollback (a
    rolled-back update made no progress) and the trip handling itself
    is accounted there too."""
    with flag_scope("train_goodput", True):
        chaos.configure("grad.nonfinite@2")
        step = _build_step(skip_nonfinite_budget=2)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for i in range(3):
                step(*_batch(i))
        chaos.reset()
        snap = goodput.get_ledger().snapshot()
    assert step.stats()["nonfinite_skips"] == 1
    assert snap["buckets"]["nonfinite_rollback"] > 0.0
    assert GOODPUT_STATS["reattributions"] >= 1
    assert sum(snap["buckets"].values()) == \
        pytest.approx(snap["elapsed_s"], rel=0.01)


@pytest.mark.chaos
def test_chaos_torn_checkpoint_write_lands_in_stall_bucket(tmp_path):
    """A torn write corrupts silently (save() does not raise) — its
    wall-clock still shows up as checkpoint_stall, never vanishing."""
    with flag_scope("train_goodput", True):
        step = _build_step()
        step(*_batch(0))
        before = goodput.get_ledger().snapshot()["buckets"][
            "checkpoint_stall"]
        mgr = CheckpointManager(step, str(tmp_path / "ck"),
                                interval_steps=1, asynchronous=False)
        try:
            chaos.configure("ckpt.write.torn@1")
            mgr.save()
            chaos.reset()
        finally:
            mgr.close()
        after = goodput.get_ledger().snapshot()["buckets"][
            "checkpoint_stall"]
    assert after > before


@pytest.mark.chaos
def test_chaos_hung_collective_is_host_other_badput():
    """The dispatch seam measures with on_error='host_other': a
    chaos-hung collective that dies as CollectiveTimeoutError inside
    the dispatch window is named badput, never productive time."""
    import jax.numpy as jnp

    from paddle_tpu.distributed import collective as C
    with flag_scope("train_goodput", True), \
            flag_scope("collective_timeout_s", 1.0):
        g = C.new_group([0, 1])
        chaos.arm("collective.hang", at=1)
        with pytest.raises(C.CollectiveTimeoutError):
            with goodput.measure("productive_dispatch",
                                 on_error="host_other"):
                C.all_reduce(jnp.ones((2, 4), jnp.float32), group=g)
        chaos.reset()
        snap = goodput.get_ledger().snapshot()
    assert snap["buckets"]["host_other"] >= 0.9     # ~the 1s timeout
    assert snap["buckets"]["productive_dispatch"] == 0.0


# ---------------------------------------------------------------------------
# SIGTERM → resume: goodput reconstructs across the restart
# ---------------------------------------------------------------------------

def test_goodput_survives_sigterm_resume(tmp_path):
    """Acceptance: the ledger rides the CheckpointManager sidecar
    through a preemption — bucket totals restore bit-exactly, the dead
    time between the final commit and the new process is attributed to
    restart_gap, and published counters stay monotonic."""
    root = str(tmp_path / "ckpts")
    with flag_scope("train_goodput", True):
        step_a = _build_step()
        with pytest.raises(PreemptionSignal) as exc:
            with CheckpointManager(step_a, root, interval_steps=2,
                                   keep_n=2) as mgr:
                for i in range(4):
                    step_a(*_batch(i))
                    if i == 2:
                        os.kill(os.getpid(), signal.SIGTERM)
                    mgr.on_step(dataloader_state={"offset": i + 1})
        assert exc.value.step == 3
        with open(os.path.join(exc.value.path, MANAGER_STATE_NAME)) as f:
            saved = json.load(f)["goodput"]
        assert saved["wall"] > 0 and saved["restarts"] == 0
        assert saved["buckets"]["productive_dispatch"] > 0
        assert sum(saved["buckets"].values()) == \
            pytest.approx(saved["elapsed_s"], rel=0.01)

        # "new process": module state dropped, then resume restores the
        # sidecar into a freshly allocated ledger
        goodput.reset()
        time.sleep(0.05)
        step_b = _build_step()
        with CheckpointManager(step_b, root, interval_steps=2,
                               keep_n=2) as mgr2:
            info = mgr2.resume()
        assert info["step"] == 3
        led = goodput.get_ledger()
        assert led is not None and GOODPUT_STATS["restores"] == 1
        for b in BUCKETS:
            if b != "restart_gap":
                assert led._carry[b] == saved["buckets"][b]
        gap = led._carry["restart_gap"] - saved["buckets"]["restart_gap"]
        assert gap > 0.0
        snap = led.snapshot()
        assert snap["restarts"] == 1
        # bit-consistent reconstruction: the productive numerator is
        # exactly the saved one, and the invariant still holds with the
        # restart gap folded in
        assert snap["buckets"]["productive_dispatch"] == \
            saved["buckets"]["productive_dispatch"]
        assert snap["buckets"]["restart_gap"] >= gap
        assert sum(snap["buckets"].values()) == \
            pytest.approx(snap["elapsed_s"], rel=0.01)
        # a restarted process publishes to a fresh registry: its first
        # publish carries the restored totals forward, so the fleet
        # aggregate never drops below what the dead process durably
        # exported in the sidecar
        reg_b = MetricsRegistry()
        led.publish(reg_b)
        ctr = reg_b.get("train_badput_seconds_total")
        for b in BADPUT_BUCKETS:
            assert ctr.value(bucket=b) >= saved["buckets"][b] - 1e-9
