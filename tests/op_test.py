"""OpTest-style golden test base.

Analogue of the reference's op test backbone
(reference: python/paddle/fluid/tests/unittests/op_test.py:277 —
check_output against numpy reference on every place, check_grad by
numeric-vs-analytic comparison).

Here: forward checked against a numpy reference fn; gradients checked by
comparing the eager tape's analytic grad to central-difference numerics.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


def check_output(op_fn: Callable, np_fn: Callable, inputs: Sequence[np.ndarray],
                 rtol=1e-5, atol=1e-6, **kwargs):
    tensors = [paddle.to_tensor(i) for i in inputs]
    out = op_fn(*tensors, **kwargs)
    expected = np_fn(*inputs, **kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    exps = expected if isinstance(expected, (tuple, list)) else [expected]
    for o, e in zip(outs, exps):
        np.testing.assert_allclose(np.asarray(o.data), e, rtol=rtol, atol=atol)
    return outs


def check_grad(op_fn: Callable, inputs: Sequence[np.ndarray], input_idx=0,
               delta=1e-3, rtol=1e-2, atol=1e-3, reduce_fn=None, **kwargs):
    """Central-difference numeric gradient vs tape analytic gradient.

    Runs under full-f32 matmul precision (this build's default lowers f32
    matmuls to bf16, which swallows the perturbation)."""
    import jax
    with jax.default_matmul_precision("highest"):
        return _check_grad_impl(op_fn, inputs, input_idx, delta, rtol, atol,
                                reduce_fn, **kwargs)


def _check_grad_impl(op_fn, inputs, input_idx, delta, rtol, atol,
                     reduce_fn, **kwargs):
    inputs = [np.asarray(i, np.float64).astype(np.float32) for i in inputs]

    def scalar_out(*arrs):
        tensors = [paddle.to_tensor(a) for a in arrs]
        out = op_fn(*tensors, **kwargs)
        if reduce_fn is not None:
            return reduce_fn(out)
        return out.sum() if out.size > 1 else out

    # analytic
    tensors = [paddle.to_tensor(a, stop_gradient=(i != input_idx))
               for i, a in enumerate(inputs)]
    out = op_fn(*tensors, **kwargs)
    s = reduce_fn(out) if reduce_fn is not None else (
        out.sum() if out.size > 1 else out)
    s.backward()
    analytic = np.asarray(tensors[input_idx].grad.data, np.float64)

    # numeric
    target = inputs[input_idx]
    numeric = np.zeros_like(target, np.float64)
    flat = target.reshape(-1)
    num_flat = numeric.reshape(-1)
    for j in range(flat.size):
        orig = flat[j]
        flat[j] = orig + delta
        plus = float(scalar_out(*inputs).item())
        flat[j] = orig - delta
        minus = float(scalar_out(*inputs).item())
        flat[j] = orig
        num_flat[j] = (plus - minus) / (2 * delta)

    np.testing.assert_allclose(analytic, numeric, rtol=rtol, atol=atol)
