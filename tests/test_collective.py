"""Multi-device collective tests on the virtual 8-device CPU mesh.

Analogue of the reference's localhost multi-process collective tests
(reference: python/paddle/fluid/tests/unittests/test_collective_base.py:32 —
2 procs run one collective op, parent compares numpy results). Here the
per-rank tensors are the stacked leading axis and the op runs the real XLA
collective lowering via shard_map.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist

N = 8  # conftest forces 8 virtual CPU devices


@pytest.fixture(scope="module")
def per_rank():
    rng = np.random.RandomState(0)
    return rng.randn(N, 4, 3).astype(np.float32)


def test_all_reduce_sum(per_rank):
    out = dist.all_reduce(jnp.asarray(per_rank))
    expected = np.broadcast_to(per_rank.sum(0), per_rank.shape)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_all_reduce_max_min(per_rank):
    out = dist.all_reduce(jnp.asarray(per_rank), op=dist.ReduceOp.MAX)
    np.testing.assert_allclose(np.asarray(out),
                               np.broadcast_to(per_rank.max(0), per_rank.shape),
                               rtol=1e-6)
    out = dist.all_reduce(jnp.asarray(per_rank), op=dist.ReduceOp.MIN)
    np.testing.assert_allclose(np.asarray(out),
                               np.broadcast_to(per_rank.min(0), per_rank.shape),
                               rtol=1e-6)


def test_all_reduce_avg_prod(per_rank):
    out = dist.all_reduce(jnp.asarray(per_rank), op=dist.ReduceOp.AVG)
    np.testing.assert_allclose(np.asarray(out),
                               np.broadcast_to(per_rank.mean(0), per_rank.shape),
                               rtol=1e-5)
    x = np.abs(per_rank) + 0.5
    out = dist.all_reduce(jnp.asarray(x), op=dist.ReduceOp.PROD)
    np.testing.assert_allclose(np.asarray(out),
                               np.broadcast_to(x.prod(0), x.shape), rtol=1e-4)


def test_all_reduce_tensor_in_place(per_rank):
    t = paddle.to_tensor(per_rank)
    ret = dist.all_reduce(t)
    assert ret is t
    np.testing.assert_allclose(t.numpy(),
                               np.broadcast_to(per_rank.sum(0), per_rank.shape),
                               rtol=1e-5)


def test_all_gather(per_rank):
    out = np.asarray(dist.all_gather(jnp.asarray(per_rank)))
    assert out.shape == (N, N, 4, 3)
    for slot in range(N):
        np.testing.assert_allclose(out[slot], per_rank, rtol=1e-6)


def test_broadcast(per_rank):
    out = np.asarray(dist.broadcast(jnp.asarray(per_rank), src=3))
    np.testing.assert_allclose(
        out, np.broadcast_to(per_rank[3], per_rank.shape), rtol=1e-6)


def test_reduce_to_dst(per_rank):
    out = np.asarray(dist.reduce(jnp.asarray(per_rank), dst=2))
    np.testing.assert_allclose(out[2], per_rank.sum(0), rtol=1e-5)
    for r in range(N):
        if r != 2:
            np.testing.assert_allclose(out[r], per_rank[r], rtol=1e-6)


def test_alltoall():
    rng = np.random.RandomState(1)
    blocks = rng.randn(N, N, 2).astype(np.float32)  # [src, dst, ...]
    out = np.asarray(dist.alltoall(jnp.asarray(blocks)))
    np.testing.assert_allclose(out, blocks.swapaxes(0, 1), rtol=1e-6)


def test_ppermute_shift(per_rank):
    out = np.asarray(dist.ppermute_shift(jnp.asarray(per_rank), shift=1))
    np.testing.assert_allclose(out, np.roll(per_rank, 1, axis=0), rtol=1e-6)
    out = np.asarray(dist.ppermute_shift(jnp.asarray(per_rank), shift=-1))
    np.testing.assert_allclose(out, np.roll(per_rank, -1, axis=0), rtol=1e-6)


def test_new_group_subset():
    g = dist.new_group(ranks=[0, 2, 4, 6])
    assert g.nranks == 4
    x = np.arange(4 * 3, dtype=np.float32).reshape(4, 3)
    out = np.asarray(dist.all_reduce(jnp.asarray(x), group=g))
    np.testing.assert_allclose(out, np.broadcast_to(x.sum(0), x.shape), rtol=1e-5)


def test_barrier_and_wait(per_rank):
    dist.barrier()
    t = paddle.to_tensor(per_rank)
    assert dist.wait(t) is t


def test_traced_collectives_inside_shard_map(per_rank):
    """Collectives called from inside a jitted shard_map lower to lax ops."""
    from jax.sharding import PartitionSpec as P
    g = dist.get_group(0)
    mesh = g.mesh

    def body(x):
        s = dist.all_reduce(x, group=g)           # psum
        m = dist.all_reduce(x, op=dist.ReduceOp.MAX, group=g)  # pmax
        return s + 0.0 * m

    f = jax.jit(dist.shard_map(body, mesh, in_specs=P("world"),
                               out_specs=P("world")))
    out = np.asarray(f(jnp.asarray(per_rank)))
    np.testing.assert_allclose(
        out, np.broadcast_to(per_rank.sum(0), per_rank.shape), rtol=1e-5)


def test_traced_broadcast_and_gather(per_rank):
    from jax.sharding import PartitionSpec as P
    g = dist.get_group(0)

    def body(x):
        local = x[0]                       # [4, 3] this-rank block
        got = dist.all_gather(local, group=g)   # [N, 4, 3]
        b = dist.broadcast(local, src=5, group=g)
        return (got.sum(0) + b)[None]

    f = jax.jit(dist.shard_map(body, g.mesh, in_specs=P("world"),
                               out_specs=P("world")))
    out = np.asarray(f(jnp.asarray(per_rank)))
    expected = per_rank.sum(0) + per_rank[5]
    for r in range(N):
        # atol: the 16-term f32 reduction's summation order differs
        # between XLA's gathered-block sum and numpy's pairwise sum; a
        # near-cancellation element (|sum| ~1e-3 from O(1) terms) can be
        # 1 ULP off absolutely, which rtol alone cannot absorb
        np.testing.assert_allclose(out[r], expected, rtol=1e-5, atol=1e-6)


def test_communicate_topology():
    from paddle_tpu.distributed.fleet import CommunicateTopology
    topo = CommunicateTopology(("dp", "pp", "sharding", "sp", "mp"),
                               (2, 2, 1, 1, 2))
    assert topo.world_size() == 8
    assert topo.get_rank(dp=1, pp=0, sharding=0, sp=0, mp=1) == 5
    assert topo.get_coord(5) == (1, 0, 0, 0, 1)
    # mp groups: consecutive pairs (mp is the innermost axis)
    assert topo.get_comm_list("mp")[0] == [0, 1]
    # dp groups stride over everything else
    assert [0, 4] in topo.get_comm_list("dp")


def test_hybrid_communicate_group():
    from paddle_tpu.distributed.fleet import HybridCommunicateGroup
    hcg = HybridCommunicateGroup(dp_degree=2, mp_degree=2, pp_degree=2)
    assert hcg.nranks == 8
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_model_parallel_group().axis_name == "mp"
    assert hcg.get_data_parallel_group().nranks == 2
    assert hcg.get_parallel_mode() == "pipeline"
    assert tuple(hcg.mesh.axis_names) == ("dp", "pp", "sharding", "sp", "mp")
    assert hcg.mesh.devices.size == 8


def test_fleet_init_and_data_parallel_model():
    import paddle_tpu.distributed.fleet as fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    assert fleet.init_is_called()
    hcg = fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 8
    model = paddle.nn.Linear(4, 2)
    wrapped = fleet.distributed_model(model)
    out = wrapped(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert out.shape == [2, 2]


def test_traced_reduce_prod(per_rank):
    from jax.sharding import PartitionSpec as P
    g = dist.get_group(0)
    x = np.abs(per_rank) + 0.5

    def body(v):
        return dist.reduce(v, dst=2, op=dist.ReduceOp.PROD, group=g)

    f = jax.jit(dist.shard_map(body, g.mesh, in_specs=P("world"),
                               out_specs=P("world")))
    out = np.asarray(f(jnp.asarray(x)))
    np.testing.assert_allclose(out[2], x.prod(0), rtol=1e-4)


def test_traced_all_gather_multi_axis_global_order():
    """Gather over a 2-axis mesh must return global-rank (row-major) order."""
    from jax.sharding import PartitionSpec as P
    mesh = dist.make_mesh({"a": 2, "b": 4})
    g = dist.get_group(0)  # default group → every bound axis
    vals = np.arange(8, dtype=np.float32).reshape(8, 1)

    def body(x):
        return dist.all_gather(x[0], group=g)[None]

    f = jax.jit(dist.shard_map(body, mesh, in_specs=P(("a", "b")),
                               out_specs=P(("a", "b"))))
    out = np.asarray(f(jnp.asarray(vals)))
    np.testing.assert_array_equal(out.reshape(8, 8)[0], np.arange(8))


def test_send_recv_pairing():
    t = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    buf = paddle.to_tensor(np.zeros(3, np.float32))
    dist.send(t, dst=0)  # self-loop: only deliverable pairing in one process
    out = dist.recv(buf, src=0)
    np.testing.assert_array_equal(out.numpy(), [1.0, 2.0, 3.0])
    with pytest.raises(RuntimeError):
        dist.recv(buf, src=5)  # nothing pending from rank 5


def test_destroy_process_group_keeps_world_default():
    g_sub = dist.new_group([0, 1])
    dist.destroy_process_group()
    g_new = dist.new_group([0, 1])
    assert g_new.id != 0  # gid 0 stays reserved for the world group
    assert dist.get_group(0).nranks == N  # default group is the full world


def test_distributed_module_attrs_no_recursion():
    """Round-1 bug: d.fleet raised RecursionError; missing names must raise
    AttributeError, present ones must resolve."""
    assert dist.fleet is not None
    assert dist.meta_parallel is not None
    assert callable(dist.all_reduce)
    with pytest.raises(AttributeError):
        dist.definitely_not_a_thing
