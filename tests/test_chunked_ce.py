"""Chunked (streamed-vocab) cross-entropy parity tests (ISSUE 2).

nn/chunked_ce.py streams softmax CE over vocab chunks with an online f32
logsumexp and a custom-VJP backward. Parity pinned here against the dense
reference composition across ignore_index, soft_label, class weights,
reductions, non-multiple-of-chunk vocab sizes, and the wired entry points
(F.cross_entropy, ParallelCrossEntropy, the BERT MLM head).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.nn import chunked_ce as cce


from paddle_tpu.core.flags import flag_scope


@pytest.fixture
def ce_flags():
    """Force the chunked path on for small test vocabs; restore after."""
    with flag_scope("chunked_ce_threshold", 8), \
            flag_scope("chunked_ce_chunk", 16):
        yield


def _dense_ce(*args, **kw):
    """Reference: the dense path, selected by disabling the chunked one."""
    with flag_scope("chunked_ce_threshold", 0):
        return F.cross_entropy(*args, **kw)


# vocab 50 with chunk 16: three full chunks + masked tail (non-multiple)
@pytest.mark.parametrize("reduction", ["mean", "sum", "none"])
def test_hard_label_parity_with_ignore_index(ce_flags, reduction):
    rng = np.random.RandomState(0)
    logits_np = (rng.randn(8, 50) * 2).astype(np.float32)
    labels_np = rng.randint(0, 50, (8,)).astype(np.int64)
    labels_np[2] = -100
    labels_np[5] = -100

    x1 = Tensor(logits_np)
    x1.stop_gradient = False
    out1 = F.cross_entropy(x1, Tensor(labels_np), reduction=reduction)
    x2 = Tensor(logits_np)
    x2.stop_gradient = False
    out2 = _dense_ce(x2, Tensor(labels_np), reduction=reduction)
    np.testing.assert_allclose(np.asarray(out1._data), np.asarray(out2._data),
                               rtol=1e-6, atol=1e-7)
    (out1.sum() if reduction == "none" else out1).backward()
    (out2.sum() if reduction == "none" else out2).backward()
    np.testing.assert_allclose(np.asarray(x1.grad._data),
                               np.asarray(x2.grad._data),
                               rtol=1e-5, atol=1e-7)
    # ignored rows contribute no gradient
    assert np.abs(np.asarray(x1.grad._data)[2]).max() == 0.0


def test_class_weights_parity(ce_flags):
    rng = np.random.RandomState(1)
    logits_np = rng.randn(6, 33).astype(np.float32)
    labels_np = rng.randint(0, 33, (6,)).astype(np.int64)
    labels_np[0] = -100
    w_np = rng.uniform(0.2, 2.0, (33,)).astype(np.float32)

    x1 = Tensor(logits_np)
    x1.stop_gradient = False
    l1 = F.cross_entropy(x1, Tensor(labels_np), weight=Tensor(w_np))
    x2 = Tensor(logits_np)
    x2.stop_gradient = False
    l2 = _dense_ce(x2, Tensor(labels_np), weight=Tensor(w_np))
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    l1.backward()
    l2.backward()
    np.testing.assert_allclose(np.asarray(x1.grad._data),
                               np.asarray(x2.grad._data),
                               rtol=1e-5, atol=1e-7)


def test_soft_label_parity(ce_flags):
    rng = np.random.RandomState(2)
    logits_np = rng.randn(5, 21).astype(np.float32)
    t = rng.uniform(size=(5, 21)).astype(np.float32)
    t /= t.sum(axis=1, keepdims=True)

    x1 = Tensor(logits_np)
    x1.stop_gradient = False
    l1 = F.cross_entropy(x1, Tensor(t), soft_label=True)
    x2 = Tensor(logits_np)
    x2.stop_gradient = False
    l2 = _dense_ce(x2, Tensor(t), soft_label=True)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    l1.backward()
    l2.backward()
    np.testing.assert_allclose(np.asarray(x1.grad._data),
                               np.asarray(x2.grad._data),
                               rtol=1e-5, atol=1e-7)


def test_keepdim_labels_and_3d_logits(ce_flags):
    """[B, S, V] logits with [B, S, 1] labels (the GPT criterion shape)."""
    rng = np.random.RandomState(3)
    logits_np = rng.randn(2, 7, 40).astype(np.float32)
    labels_np = rng.randint(0, 40, (2, 7, 1)).astype(np.int64)
    l1 = F.cross_entropy(Tensor(logits_np), Tensor(labels_np),
                         reduction="none")
    l2 = _dense_ce(Tensor(logits_np), Tensor(labels_np), reduction="none")
    np.testing.assert_allclose(np.asarray(l1._data), np.asarray(l2._data),
                               rtol=1e-6, atol=1e-7)


def test_label_smoothing_falls_back_to_dense(ce_flags):
    """label_smoothing is served by the dense path (same numbers)."""
    rng = np.random.RandomState(4)
    logits_np = rng.randn(4, 24).astype(np.float32)
    labels_np = rng.randint(0, 24, (4,)).astype(np.int64)
    l1 = F.cross_entropy(Tensor(logits_np), Tensor(labels_np),
                         label_smoothing=0.1)
    l2 = _dense_ce(Tensor(logits_np), Tensor(labels_np),
                   label_smoothing=0.1)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)


def test_parallel_cross_entropy_chunked_matches_dense(ce_flags):
    from paddle_tpu.distributed.meta_parallel.parallel_layers.mp_layers \
        import ParallelCrossEntropy

    rng = np.random.RandomState(5)
    logits_np = rng.randn(2, 9, 50).astype(np.float32)
    labels_np = rng.randint(0, 50, (2, 9)).astype(np.int64)

    ce = ParallelCrossEntropy()
    x1 = Tensor(logits_np)
    x1.stop_gradient = False
    out1 = ce(x1, Tensor(labels_np))          # no mesh + V>=8: chunked
    assert tuple(out1.shape) == (2, 9, 1)
    x2 = Tensor(logits_np)
    x2.stop_gradient = False
    with flag_scope("chunked_ce_threshold", 0):
        out2 = ce(x2, Tensor(labels_np))
    np.testing.assert_allclose(np.asarray(out1._data),
                               np.asarray(out2._data), rtol=1e-6, atol=1e-7)
    out1.sum().backward()
    out2.sum().backward()
    np.testing.assert_allclose(np.asarray(x1.grad._data),
                               np.asarray(x2.grad._data),
                               rtol=1e-5, atol=1e-7)


def test_bert_mlm_loss_chunked_matches_dense(ce_flags):
    from paddle_tpu.models.bert import BertForMaskedLM, bert_tiny

    paddle.seed(6)
    m = BertForMaskedLM(bert_tiny(num_layers=2))   # vocab 256 >= 8
    rng = np.random.RandomState(6)
    ids = Tensor(rng.randint(5, 250, (2, 16)).astype(np.int32))
    pos = Tensor(np.stack([rng.choice(16, 4, replace=False)
                           for _ in range(2)]).astype(np.int32))
    labels = Tensor(rng.randint(0, 256, (2, 4)).astype(np.int32))
    weights = Tensor(rng.uniform(0.5, 1.0, (2, 4)).astype(np.float32))
    with paddle.no_grad():
        scores = m(ids, masked_positions=pos)
    l_chunked = m.loss(scores, labels, weights)
    with flag_scope("chunked_ce_threshold", 0):
        l_dense = m.loss(scores, labels, weights)
    np.testing.assert_allclose(float(l_chunked), float(l_dense), rtol=1e-6)


def test_bf16_logits_and_jit(ce_flags):
    """bf16 logits: f32 accumulation inside, bf16 gradient out, same
    numbers under jit."""
    rng = np.random.RandomState(7)
    lg = jnp.asarray(rng.randn(6, 40).astype(np.float32)).astype(jnp.bfloat16)
    lab = jnp.asarray(rng.randint(0, 40, (6,)).astype(np.int32))

    ref = (jax.nn.logsumexp(lg.astype(jnp.float32), -1)
           - jnp.take_along_axis(lg.astype(jnp.float32),
                                 lab[:, None], 1)[:, 0])
    got = jax.jit(lambda l: cce.hard_nll(l, lab, chunk=16))(lg)
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-2, atol=1e-2)
    g = jax.grad(lambda l: cce.hard_nll(l, lab, chunk=16).sum())(lg)
    assert g.dtype == jnp.bfloat16


@pytest.mark.parametrize("V,chunk", [(5, 8), (16, 16), (50, 7), (129, 64)])
def test_kernel_chunk_geometry(V, chunk):
    """Exactness across chunk/tail geometries incl. chunk > vocab."""
    rng = np.random.RandomState(8)
    lg = jnp.asarray((rng.randn(4, V) * 3).astype(np.float32))
    lab = jnp.asarray(rng.randint(0, V, (4,)).astype(np.int32))
    ref = (jax.nn.logsumexp(lg, -1)
           - jnp.take_along_axis(lg, lab[:, None], 1)[:, 0])
    np.testing.assert_allclose(np.asarray(cce.hard_nll(lg, lab, chunk=chunk)),
                               np.asarray(ref), rtol=1e-6, atol=1e-6)
    g_ref = jax.grad(lambda l: (jax.nn.logsumexp(l, -1) - jnp.take_along_axis(
        l, lab[:, None], 1)[:, 0]).sum())(lg)
    g_got = jax.grad(lambda l: cce.hard_nll(l, lab, chunk=chunk).sum())(lg)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)
