"""1F1B pipeline-schedule tests: parity, compile counts, fault paths.

The schedule contract (docs/PARALLELISM.md): 1F1B and fill-drain are
SCHEDULES over one stacked-parameter layout — they may only reorder which
device computes a microbatch, so loss trajectories must agree with each
other and with single-device execution to float-reassociation tolerance
(1e-6, ISSUE 9 acceptance), state_dicts stay bit-exact across schedule
choice, each (schedule, mesh-shape) compiles exactly one program, a hung
stage handoff raises structured under the PR 5 collective watchdog, and
elastic restart resumes bit-exact from the PR 5 CheckpointManager.

Everything runs on the 8-device virtual CPU mesh the conftest forces;
1F1B itself requires a pp-only mesh on XLA:CPU (manual_collectives_ok) —
mixed dp/mp meshes pin the counted fallback instead.
"""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import env as dist_env, fleet
from paddle_tpu.distributed.meta_parallel import spmd_pipeline as sp
from paddle_tpu.distributed.meta_parallel.spmd_pipeline import (
    PipelineStageStack, bubble_fraction, pipeline_comm_model,
    resolve_schedule, schedule_slots, schedule_timetable)
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.optimizer import AdamW
from paddle_tpu.testing import chaos

H = 16


class Block(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(H, H)

    def forward(self, x):
        return x + paddle.nn.functional.tanh(self.fc(x))


class PipeNet(nn.Layer):
    """Toy pipelined net: stacked residual blocks + a linear regression
    head driven through ``PipelineStageStack.train_loss`` (the
    schedule-aware path TrainStep differentiates through)."""

    def __init__(self, num_layers=4, num_microbatches=4, schedule=None):
        super().__init__()
        self.blocks = PipelineStageStack(
            Block, num_layers, num_microbatches=num_microbatches,
            schedule=schedule)
        self.head = nn.Linear(H, 1)

    def loss(self, x, tgt):
        leaves = [p for _, p in self.head.named_parameters()]

        def head_apply(hl, y, t):
            w, b = hl[0], hl[1]
            pred = y @ w + b
            d = (pred - t).astype(jnp.float32)
            return jnp.sum(d * d), jnp.float32(d.size)

        return self.blocks.train_loss(
            x, head_apply, leaves, [tgt], head_token=("toy", id(self)))


def _pp_mesh(dp=1, pp=2, mp=1):
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": dp, "pp_degree": pp,
                               "mp_degree": mp}
    fleet.init(is_collective=True, strategy=strategy)
    return fleet.get_hybrid_communicate_group().mesh


def _toy_batch(B=8):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((B, H)).astype(np.float32)
    tgt = rng.standard_normal((B, 1)).astype(np.float32)
    return x, tgt


def _run_toy(schedule, steps=3, use_mesh=True):
    """3 AdamW steps of PipeNet under one schedule; returns the loss
    trajectory. use_mesh=False = the single-device reference."""
    fleet.reset()
    dist_env.reset()
    mesh = _pp_mesh(pp=2) if use_mesh else None
    paddle.seed(21)
    model = PipeNet(schedule=schedule)
    opt = AdamW(learning_rate=1e-2, weight_decay=0.01)

    def loss_fn(layer, x, tgt):
        return layer.loss(x, tgt)

    kw = dict(mesh=mesh) if use_mesh else {}
    step = TrainStep(model, loss_fn, opt, **kw)
    x, tgt = _toy_batch()
    return [float(np.asarray(step(Tensor(x), Tensor(tgt))._data))
            for _ in range(steps)]


# -- schedule math ----------------------------------------------------------

def test_schedule_slots_and_bubble():
    assert schedule_slots("fill_drain", 4, 8) == 11
    assert schedule_slots("1f1b", 4, 8) == 22
    assert schedule_slots("1f1b", 1, 8) == 8       # no pipeline, no bubble
    assert bubble_fraction("1f1b", 4, 8) == pytest.approx(3 / 11)
    assert bubble_fraction("fill_drain", 1, 8) == 0.0
    m = pipeline_comm_model("1f1b", 4, 8, boundary_bytes=1024)
    assert m["slots"] == 22 and m["bytes"] == m["ops"] * 1024


def test_timetable_matches_canonical_and_is_causal():
    """The measured (implemented-predicate) timetable reproduces the
    canonical bubble EXACTLY and respects dataflow causality: stage s+1's
    forward of microbatch m runs after stage s's, backward starts after
    the last stage's forward, and cotangents flow S-1 -> 0."""
    for S, M in [(2, 4), (4, 8), (2, 2), (8, 8)]:
        for sched in ("fill_drain", "1f1b"):
            tt = schedule_timetable(sched, S, M)
            assert tt["bubble_fraction"] == pytest.approx(
                bubble_fraction(sched, S, M)), (sched, S, M)
        tt = schedule_timetable("1f1b", S, M)
        fwd_slot = {}
        bwd_slot = {}
        for s in range(S):
            f_slots = np.flatnonzero(tt["fwd"][s])
            b_slots = np.flatnonzero(tt["bwd"][s])
            assert len(f_slots) == M and len(b_slots) == M
            for m, t in enumerate(f_slots):
                fwd_slot[(s, m)] = t
            for m, t in enumerate(b_slots):
                bwd_slot[(s, m)] = t
        for m in range(M):
            for s in range(S - 1):
                assert fwd_slot[(s, m)] < fwd_slot[(s + 1, m)]
                assert bwd_slot[(s + 1, m)] < bwd_slot[(s, m)]
            assert bwd_slot[(S - 1, m)] > fwd_slot[(S - 1, m)]
        # steady state is strictly one-forward-one-backward: no stage is
        # ever asked to do both in one slot
        assert not np.any(tt["fwd"] & tt["bwd"])


def test_schedule_resolution_precedence():
    # default comes from the fleet strategy's pipeline_configs (1F1B)
    assert resolve_schedule(None) == "1f1b"
    # explicit arg (reference spellings normalize) beats the strategy
    assert resolve_schedule("F-then-B") == "fill_drain"
    assert resolve_schedule("gpipe") == "fill_drain"
    assert resolve_schedule("1F1B") == "1f1b"
    # the global flag is the kill switch: beats the explicit arg
    with flag_scope("pipeline_schedule", "fill_drain"):
        assert resolve_schedule("1f1b") == "fill_drain"
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        resolve_schedule("zb-h1")
    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        PipelineStageStack(Block, 2, schedule="nope")


# -- numerics parity (the acceptance pin) -----------------------------------

@pytest.mark.multichip
def test_toy_1f1b_vs_fill_drain_vs_single_device():
    """ISSUE 9 acceptance: loss Δ ≤ 1e-6 between 1F1B, fill-drain and the
    single-device loop across 3 optimizer steps (fwd+bwd+AdamW through
    TrainStep — schedules only reorder which device computes what)."""
    l_1f1b = _run_toy("1f1b")
    l_fd = _run_toy("fill_drain")
    l_seq = _run_toy(None, use_mesh=False)
    assert all(np.isfinite(l_1f1b)), l_1f1b
    for a, b in zip(l_1f1b, l_fd):
        assert abs(a - b) <= 1e-6, (l_1f1b, l_fd)
    for a, b in zip(l_1f1b, l_seq):
        assert abs(a - b) <= 1e-6, (l_1f1b, l_seq)


@pytest.mark.multichip
def test_gpt_1f1b_three_step_parity():
    """GPT end-to-end acceptance pin: GPTForPretrainingPipe.pretraining_loss
    under 1F1B on a pp-only 8-device virtual mesh matches fill-drain AND
    single-device execution (Δ ≤ 1e-6) over 3 optimizer steps."""
    from paddle_tpu.models.gpt import GPTForPretrainingPipe, gpt_tiny

    def run(schedule, use_mesh=True):
        fleet.reset()
        dist_env.reset()
        mesh = _pp_mesh(pp=2) if use_mesh else None
        paddle.seed(1234)
        cfg = gpt_tiny()
        model = GPTForPretrainingPipe(cfg, num_microbatches=2,
                                      schedule=schedule)
        if use_mesh:
            model = fleet.distributed_model(model)
        opt = AdamW(learning_rate=1e-3, weight_decay=0.01)

        def loss_fn(layer, ids, labels, mask):
            base = layer._layers if hasattr(layer, "_layers") else layer
            return base.pretraining_loss(ids, labels, mask)

        kw = (dict(mesh=mesh, data_spec=P("dp")) if use_mesh else {})
        step = TrainStep(model, loss_fn, opt, **kw)
        rng = np.random.default_rng(0)
        B, S = 4, 32
        ids = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
        labels = rng.integers(0, cfg.vocab_size, (B, S)).astype(np.int32)
        mask = np.ones((B, S), np.float32)
        return [float(np.asarray(
            step(Tensor(ids), Tensor(labels), Tensor(mask))._data))
            for _ in range(3)]

    l_1f1b = run("1f1b")
    l_fd = run("fill_drain")
    l_seq = run(None, use_mesh=False)
    assert all(np.isfinite(l_1f1b)), l_1f1b
    for a, b in zip(l_1f1b, l_fd):
        assert abs(a - b) <= 1e-6, (l_1f1b, l_fd)
    for a, b in zip(l_1f1b, l_seq):
        assert abs(a - b) <= 1e-6, (l_1f1b, l_seq)


@pytest.mark.multichip
def test_schedule_parity_holds_with_dropout():
    """Kill-switch contract for STOCHASTIC models: both schedules derive
    stage RNG from the same (microbatch, stage) fold, so dropout masks —
    and therefore loss trajectories — are schedule-invariant (Δ ≤ 1e-6
    over 2 optimizer steps with dropout 0.1 everywhere)."""
    from paddle_tpu.models.gpt import GPTForPretrainingPipe, gpt_tiny

    def run(schedule):
        fleet.reset()
        dist_env.reset()
        mesh = _pp_mesh(pp=2)
        paddle.seed(77)
        cfg = gpt_tiny(hidden_dropout_prob=0.1,
                       attention_dropout_prob=0.1)
        model = fleet.distributed_model(
            GPTForPretrainingPipe(cfg, num_microbatches=2,
                                  schedule=schedule))
        opt = AdamW(learning_rate=1e-3)

        def loss_fn(layer, ids, labels, mask):
            base = layer._layers if hasattr(layer, "_layers") else layer
            return base.pretraining_loss(ids, labels, mask)

        step = TrainStep(model, loss_fn, opt, mesh=mesh,
                         data_spec=P("dp"))
        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, (4, 16)).astype(np.int32)
        labels = rng.integers(0, cfg.vocab_size, (4, 16)).astype(np.int32)
        mask = np.ones((4, 16), np.float32)
        return [float(np.asarray(
            step(Tensor(ids), Tensor(labels), Tensor(mask))._data))
            for _ in range(2)]

    l_1f1b = run("1f1b")
    l_fd = run("fill_drain")
    assert all(np.isfinite(l_1f1b)), l_1f1b
    for a, b in zip(l_1f1b, l_fd):
        assert abs(a - b) <= 1e-6, (l_1f1b, l_fd)


@pytest.mark.multichip
def test_1f1b_eval_mode_uses_fill_drain():
    """Forward-only consumers (eval) never see the combined fwd+bwd
    program: train_loss in eval mode equals the plain forward + head."""
    _pp_mesh(pp=2)
    paddle.seed(5)
    model = PipeNet(schedule="1f1b")
    model.eval()
    x, tgt = _toy_batch()
    built0 = sp.PIPELINE_STATS["programs_built"]
    loss = model.loss(Tensor(x), Tensor(tgt))
    # the fill-drain forward program was built, not the 1f1b one
    out = model.blocks(Tensor(x))
    pred = out._data @ model.head.weight._data + model.head.bias._data
    want = float(np.mean((np.asarray(pred) - tgt) ** 2))
    assert float(np.asarray(loss._data)) == pytest.approx(want, rel=1e-5)
    assert sp.PIPELINE_STATS["programs_built"] == built0 + 1


# -- state_dict + compile-count pins ----------------------------------------

@pytest.mark.multichip
def test_state_dict_bit_exact_roundtrip_across_schedules():
    """state_dict names/values are schedule-independent and roundtrip
    bit-exact: a 1F1B-trained model's state loads into a fill-drain model
    and the next loss is IDENTICAL (the checkpoint-manifest compatibility
    claim of docs/PARALLELISM.md)."""
    _pp_mesh(pp=2)

    def build(schedule):
        paddle.seed(33)
        return PipeNet(schedule=schedule)

    model_a = build("1f1b")
    opt = AdamW(learning_rate=1e-2)
    step = TrainStep(model_a, lambda l, x, t: l.loss(x, t), opt)
    x, tgt = _toy_batch()
    step(Tensor(x), Tensor(tgt))

    sd = model_a.state_dict()
    # per-layer views keep template names (state_dict manifest contract)
    per_layer = model_a.blocks.layer_state_dict(0)
    assert set(per_layer) == {"fc.weight", "fc.bias"}

    model_b = build("fill_drain")
    model_b.set_state_dict({k: Tensor(jnp.asarray(np.asarray(v._data)))
                            for k, v in sd.items()})
    for (k, pa), (_, pb) in zip(model_a.named_parameters(),
                                model_b.named_parameters()):
        np.testing.assert_array_equal(np.asarray(pa._data),
                                      np.asarray(pb._data), err_msg=k)
    la = float(np.asarray(model_a.loss(Tensor(x), Tensor(tgt))._data))
    lb = float(np.asarray(model_b.loss(Tensor(x), Tensor(tgt))._data))
    # same stacked values through two schedules: ≤ reassociation noise
    assert abs(la - lb) <= 1e-6


@pytest.mark.multichip
def test_one_program_per_schedule_and_mesh_shape():
    """Compile-count pin: M microbatches run in ONE pipelined program per
    (schedule, mesh shape) — program builds don't scale with M, and
    repeat calls with fresh data trace nothing new."""
    from paddle_tpu.utils import CompileCounter

    _pp_mesh(pp=2)
    paddle.seed(3)
    model = PipeNet(num_microbatches=4, schedule="1f1b")
    x, tgt = _toy_batch()
    assert sp.PIPELINE_STATS["programs_built"] == 0
    float(np.asarray(model.loss(Tensor(x), Tensor(tgt))._data))
    assert sp.PIPELINE_STATS["programs_built"] == 1   # one, not one per M
    with CompileCounter() as c:
        x2 = x + 1.0
        float(np.asarray(model.loss(Tensor(x2), Tensor(tgt))._data))
    assert sp.PIPELINE_STATS["programs_built"] == 1
    assert c.jaxpr_traces == 0, "warm 1f1b call re-traced"
    # switching schedule builds exactly one more program
    model.blocks.schedule = "fill_drain"
    float(np.asarray(model.loss(Tensor(x), Tensor(tgt))._data))
    assert sp.PIPELINE_STATS["programs_built"] == 2


# -- fallbacks + ZeRO interaction -------------------------------------------

@pytest.mark.multichip
def test_1f1b_counted_fallback_on_tp_mesh_and_zero_parity():
    """On XLA:CPU a nontrivial mp axis cannot run the manual-pp program:
    train_loss degrades to fill-drain with a one-time RuntimeWarning and
    a counted fallback — and the ZeRO-sharded TrainStep over that mesh
    still matches single-device numerics (the ZeRO re-shard interaction
    pin; on TPU the same config runs the real 1F1B program)."""

    def run(use_mesh):
        fleet.reset()
        dist_env.reset()
        mesh = _pp_mesh(dp=2, pp=2, mp=1) if use_mesh else None
        paddle.seed(11)
        model = PipeNet(schedule="1f1b")
        opt = AdamW(learning_rate=1e-2)
        kw = (dict(mesh=mesh, data_spec=P("dp"), zero_axis="dp")
              if use_mesh else {})
        step = TrainStep(model, lambda l, a, b: l.loss(a, b), opt, **kw)
        x, tgt = _toy_batch()
        return [float(np.asarray(step(Tensor(x), Tensor(tgt))._data))
                for _ in range(3)]

    sp.reset_pipeline_stats()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        l_mesh = run(True)
    assert sp.PIPELINE_STATS["fallbacks"] >= 1
    assert any("degraded to sequential" in str(x.message) for x in w)
    l_seq = run(False)
    for a, b in zip(l_mesh, l_seq):
        assert abs(a - b) <= 5e-6, (l_mesh, l_seq)

    # exactly ONE count per degraded dispatch: the 1f1b schedule pick in
    # train_loss probes WITHOUT counting, forward()'s own check records
    # the fallback (one trace = one degraded dispatch = one count)
    fleet.reset()
    dist_env.reset()
    _pp_mesh(dp=2, pp=2, mp=1)
    paddle.seed(2)
    m2 = PipeNet(schedule="1f1b")
    x, tgt = _toy_batch()
    sp.reset_pipeline_stats()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        float(np.asarray(m2.loss(Tensor(x), Tensor(tgt))._data))
    assert sp.PIPELINE_STATS["fallbacks"] == 1, sp.PIPELINE_STATS


# -- fault tolerance through the pipeline dispatch path ---------------------

@pytest.mark.multichip
@pytest.mark.chaos
def test_chaos_hang_in_pipeline_dispatch_raises_structured():
    """A chaos-hung stage handoff in the EAGER pipeline dispatch raises
    CollectiveTimeoutError naming the pipeline program, within the
    FLAGS_collective_timeout_s budget. (Autograd-recorded eager calls jit
    the whole op — there the guard sits on TrainStep's step dispatch
    instead — so the eager watchdog path is the no_grad one.)"""
    from paddle_tpu.core.tensor import no_grad
    from paddle_tpu.distributed import collective as C

    _pp_mesh(pp=2)
    paddle.seed(7)
    stack = PipelineStageStack(Block, num_layers=4, num_microbatches=2)
    x, _ = _toy_batch(B=4)
    with no_grad():
        out = stack(Tensor(x))           # compile OUTSIDE the budget
        assert np.all(np.isfinite(np.asarray(out._data)))
        assert sp.PIPELINE_STATS["dispatches"] >= 1
        with flag_scope("collective_timeout_s", 1.0):
            out = stack(Tensor(x + 1.0))  # healthy warm guarded dispatch
            assert np.all(np.isfinite(np.asarray(out._data)))
            chaos.arm("collective.hang", at=1)
            with pytest.raises(C.CollectiveTimeoutError) as exc:
                stack(Tensor(x + 2.0))
    assert exc.value.op == "pipeline.fill_drain"
    assert exc.value.timeout_s == 1.0


@pytest.mark.multichip
@pytest.mark.chaos
def test_chaos_hang_in_trainstep_pipeline_step_raises():
    """TrainStep applies the same watchdog to its whole step program when
    the model carries a pipeline: a hang at the step dispatch raises
    structured instead of stalling the controller."""
    from paddle_tpu.distributed import collective as C

    _pp_mesh(pp=2)
    paddle.seed(7)
    model = PipeNet(schedule="1f1b")
    step = TrainStep(model, lambda l, a, b: l.loss(a, b),
                     AdamW(learning_rate=1e-2))
    assert step._pp_degree == 2
    x, tgt = _toy_batch()
    # compile + first dispatch outside the watchdog budget
    float(np.asarray(step(Tensor(x), Tensor(tgt))._data))
    with flag_scope("collective_timeout_s", 1.0):
        float(np.asarray(step(Tensor(x), Tensor(tgt))._data))  # healthy
        chaos.arm("collective.hang", at=1)
        with pytest.raises(C.CollectiveTimeoutError) as exc:
            step(Tensor(x), Tensor(tgt))
    assert exc.value.op == "pipeline_step"


@pytest.mark.multichip
def test_checkpoint_resume_1f1b_bit_exact(tmp_path):
    """Elastic-restart acceptance: a 1F1B training run killed after an
    interval save resumes from the PR 5 CheckpointManager and continues
    BIT-EXACT vs the uninterrupted run."""
    from paddle_tpu.distributed.checkpoint import CheckpointManager

    root = str(tmp_path / "ckpt")
    x, tgt = _toy_batch()

    def build_step():
        fleet.reset()
        dist_env.reset()
        mesh = _pp_mesh(pp=2)
        paddle.seed(99)
        model = PipeNet(schedule="1f1b")
        return TrainStep(model, lambda l, a, b: l.loss(a, b),
                         AdamW(learning_rate=1e-2), mesh=mesh)

    # uninterrupted reference: 4 steps
    step = build_step()
    ref = [float(np.asarray(step(Tensor(x), Tensor(tgt))._data))
           for _ in range(4)]

    # run A: 2 steps, synchronous interval save at step 2
    step_a = build_step()
    with CheckpointManager(step_a, root, interval_steps=2,
                           asynchronous=False) as mgr:
        for i in range(2):
            step_a(Tensor(x), Tensor(tgt))
            mgr.on_step(dataloader_state={"offset": i + 1})
    # run B: fresh process-equivalent, resume + 2 more steps
    step_b = build_step()
    with CheckpointManager(step_b, root, interval_steps=2,
                           asynchronous=False) as mgr:
        info = mgr.resume()
        assert info and info["dataloader"]["offset"] == 2
        cont = [float(np.asarray(step_b(Tensor(x), Tensor(tgt))._data))
                for _ in range(2)]
    assert cont == ref[2:], (cont, ref)


# -- topology validation (satellite) ----------------------------------------

def test_topology_validation_named_errors():
    from paddle_tpu.distributed.fleet import (HybridCommunicateGroup,
                                              MeshTopologyError,
                                              validate_topology)

    n = len(jax.devices())
    assert n == 8
    # legal: exact factor and sub-mesh prefix
    assert validate_topology({"dp": 2, "pp": 2, "mp": 2}, 8) == 8
    assert validate_topology({"pp": 4}, 8) == 4
    with pytest.raises(MeshTopologyError, match="needs 16 devices"):
        validate_topology({"dp": 8, "mp": 2}, 8)
    with pytest.raises(MeshTopologyError, match="does not factor"):
        validate_topology({"dp": 3, "mp": 2}, 8)
    with pytest.raises(MeshTopologyError, match=">= 1"):
        validate_topology({"dp": 0, "mp": 2}, 8)
    # the named error surfaces from the user-facing constructor too —
    # not a shape error deep inside make_mesh
    with pytest.raises(MeshTopologyError, match="does not factor"):
        HybridCommunicateGroup(dp_degree=3, mp_degree=2)
    with pytest.raises(MeshTopologyError, match="needs"):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 16}
        fleet.init(is_collective=True, strategy=strategy)


# -- tooling ----------------------------------------------------------------

def test_monitor_report_comms_render():
    """tools/monitor_report.py --comms renders the overlapped-vs-exposed
    table from comm_overlap_ms gauges plus the schedule comm model."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import monitor_report

    def g(phase, v):
        return {"name": "comm_overlap_ms", "type": "gauge", "value": v,
                "labels": {"op": "ppermute", "mesh": "pp2_1f1b",
                           "schedule": "1f1b", "phase": phase}}

    rows = [g("serial", 10.0), g("exposed", 4.0), g("overlapped", 6.0),
            {"name": "pipeline_bubble_fraction", "type": "gauge",
             "value": 0.2, "labels": {"op": "ppermute", "schedule": "1f1b",
                                      "pp": 2, "microbatches": 4}}]
    out = monitor_report.render(rows, comms=True)
    assert "Comm/compute overlap" in out
    assert "60%" in out                       # 6 of 10 ms hidden
    assert "pipeline_bubble_fraction" in out
    # without --comms the gauges land in the generic table instead
    out2 = monitor_report.render(rows, comms=False)
    assert "Comm/compute overlap" not in out2
