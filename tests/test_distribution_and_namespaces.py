"""Distribution / linalg / regularizer / hub namespace tests.

reference analogues: test_distribution.py (sample stats, log_prob vs
scipy-style closed forms, KL), test_regularizer.py, test_hub.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import Categorical, Normal, Uniform


def test_uniform_sample_and_density():
    paddle.seed(0)
    u = Uniform(low=2.0, high=6.0)
    s = u.sample((5000,)).numpy()
    assert (s >= 2.0).all() and (s < 6.0).all()
    assert abs(s.mean() - 4.0) < 0.1
    np.testing.assert_allclose(
        u.probs(paddle.to_tensor(np.array([3.0], np.float32))).numpy(),
        [0.25], rtol=1e-6)
    assert np.isneginf(
        u.log_prob(paddle.to_tensor(np.array([7.0], np.float32))).numpy())
    np.testing.assert_allclose(u.entropy().numpy(), np.log(4.0), rtol=1e-6)


def test_normal_density_entropy_kl():
    n = Normal(loc=1.0, scale=2.0)
    x = np.array([0.0, 1.0, 3.0], np.float32)
    got = n.log_prob(paddle.to_tensor(x)).numpy()
    expect = (-((x - 1.0) ** 2) / 8.0 - np.log(2.0)
              - 0.5 * np.log(2 * np.pi))
    np.testing.assert_allclose(got, expect, rtol=1e-5)
    np.testing.assert_allclose(
        n.entropy().numpy(), 0.5 + 0.5 * np.log(2 * np.pi) + np.log(2.0),
        rtol=1e-6)
    # KL(N0||N1) closed form
    m = Normal(loc=0.0, scale=1.0)
    kl = n.kl_divergence(m).numpy()
    expect_kl = 0.5 * (4.0 + 1.0 - 1.0 - np.log(4.0))
    np.testing.assert_allclose(kl, expect_kl, rtol=1e-5)
    paddle.seed(1)
    s = n.sample((8000,)).numpy()
    assert abs(s.mean() - 1.0) < 0.1 and abs(s.std() - 2.0) < 0.1


def test_categorical():
    logits = np.log(np.array([0.1, 0.2, 0.7], np.float32))
    c = Categorical(logits)
    np.testing.assert_allclose(
        c.probs(paddle.to_tensor(np.array([2], np.int64))).numpy(), [0.7],
        rtol=1e-5)
    np.testing.assert_allclose(
        c.log_prob(paddle.to_tensor(np.array([0], np.int64))).numpy(),
        [np.log(0.1)], rtol=1e-5)
    ent = -np.sum([0.1, 0.2, 0.7] * np.log([0.1, 0.2, 0.7]))
    np.testing.assert_allclose(c.entropy().numpy(), ent, rtol=1e-5)
    other = Categorical(np.zeros(3, np.float32))       # uniform
    kl = float(c.kl_divergence(other).numpy())
    assert kl > 0
    paddle.seed(2)
    s = c.sample((4000,)).numpy()
    assert abs((s == 2).mean() - 0.7) < 0.05


def test_distribution_param_gradients():
    # policy-gradient style: grads must reach loc/scale/logits
    loc = paddle.to_tensor(np.array([1.0], np.float32))
    loc.stop_gradient = False
    scale = paddle.to_tensor(np.array([2.0], np.float32))
    scale.stop_gradient = False
    n = Normal(loc, scale)
    x = paddle.to_tensor(np.array([0.5], np.float32))
    n.log_prob(x).sum().backward()
    assert loc.grad is not None and scale.grad is not None
    # d/dmu log N = (x-mu)/sig^2 = (0.5-1)/4
    np.testing.assert_allclose(np.asarray(loc.grad._data), [-0.125],
                               rtol=1e-5)
    # reparameterized sampling also differentiates
    loc.clear_gradient()
    paddle.seed(5)
    n.sample((16,)).sum().backward()
    np.testing.assert_allclose(np.asarray(loc.grad._data), [16.0], rtol=1e-5)

    logits = paddle.to_tensor(np.zeros(3, np.float32))
    logits.stop_gradient = False
    c = Categorical(logits)
    c.log_prob(paddle.to_tensor(np.array([1], np.int64))).sum().backward()
    assert logits.grad is not None
    np.testing.assert_allclose(np.asarray(logits.grad._data),
                               [-1 / 3, 2 / 3, -1 / 3], rtol=1e-5)


def test_max_pool_mask_with_padding_negative_values():
    import paddle_tpu.nn.functional as F
    # all-negative input + padding: zeros must not leak in, indices stay
    # in-bounds
    x = -np.ones((1, 1, 4, 4), np.float32)
    out, mask = F.max_pool2d(paddle.to_tensor(x), kernel_size=2, stride=2,
                             padding=1, return_mask=True)
    assert (out.numpy() == -1).all()     # zero padding must not leak in
    m = mask.numpy()
    assert (m >= 0).all() and (m < 16).all()


def test_linalg_namespace():
    a = np.random.RandomState(0).randn(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    L = paddle.linalg.cholesky(paddle.to_tensor(spd)).numpy()
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    x = paddle.linalg.solve(paddle.to_tensor(spd),
                            paddle.to_tensor(a[:, :1])).numpy()
    np.testing.assert_allclose(spd @ x, a[:, :1], rtol=1e-3, atol=1e-3)


def test_regularizer_objects_accepted_by_optimizer():
    from paddle_tpu import nn
    from paddle_tpu.regularizer import L1Decay, L2Decay

    m = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters(),
                               weight_decay=L2Decay(0.5))
    x = paddle.to_tensor(np.zeros((2, 4), np.float32))  # zero input:
    loss = m(x).sum()                                   # data grad = 0
    loss.backward()
    w_before = np.asarray(m.weight._data).copy()
    opt.step()
    # pure decay: w -= lr * coeff * w
    np.testing.assert_allclose(np.asarray(m.weight._data),
                               w_before * (1 - 0.1 * 0.5), rtol=1e-5)
    assert isinstance(L1Decay(0.1).coeff, float)


def test_hub_local_roundtrip(tmp_path):
    (tmp_path / "hubconf.py").write_text(
        "def tiny_mlp(width=8):\n"
        "    '''A tiny MLP entrypoint.'''\n"
        "    import paddle_tpu as paddle\n"
        "    return paddle.nn.Linear(width, width)\n")
    names = paddle.hub.list(str(tmp_path))
    assert "tiny_mlp" in names
    assert "tiny MLP" in paddle.hub.help(str(tmp_path), "tiny_mlp")
    model = paddle.hub.load(str(tmp_path), "tiny_mlp", width=6)
    assert tuple(model.weight.shape) == (6, 6)
    with pytest.raises(RuntimeError, match="network"):
        paddle.hub.load("some/repo", "x", source="github")
