"""End-to-end model tests (reference pattern: tests/book/test_recognize_digits.py —
small models trained a few iterations asserting loss decreases)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.io import DataLoader
from paddle_tpu.jit import TrainStep
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet, resnet18


def test_lenet_mnist_eager_converges():
    """Eager dygraph loop over the DataLoader; fixed batch size keeps the
    per-op XLA compile cache warm after the first iteration."""
    paddle.seed(0)
    net = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=5e-3, parameters=net.parameters())
    loss_fn = nn.CrossEntropyLoss()
    ds = MNIST(mode="train", num_synthetic=96)
    loader = DataLoader(ds, batch_size=32, shuffle=True, drop_last=True)
    losses = []
    for epoch in range(4):
        for x, y in loader:
            out = net(x)
            loss = loss_fn(out, y.squeeze(-1))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.mean(losses[-3:]) < np.mean(losses[:3])


def test_lenet_jitted_trainstep_converges():
    paddle.seed(0)
    net = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=net.parameters())
    loss_layer = nn.CrossEntropyLoss()

    def loss_fn(model, x, y):
        return loss_layer(model(x), y)

    step = TrainStep(net, loss_fn, opt)
    x = paddle.randn([16, 1, 28, 28])
    y = paddle.to_tensor(np.random.randint(0, 10, 16), dtype="int64")
    losses = [float(step(x, y)) for _ in range(25)]
    assert losses[-1] < losses[0] * 0.5


def test_eager_and_jit_agree():
    """Same init, same data: one eager step ≈ one jitted step."""
    def build():
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(8, 16), nn.Tanh(), nn.Linear(16, 4))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        return net, opt

    x_np = np.random.randn(4, 8).astype(np.float32)
    y_np = np.random.randint(0, 4, 4)
    loss_layer = nn.CrossEntropyLoss()

    net1, opt1 = build()
    x, y = paddle.to_tensor(x_np), paddle.to_tensor(y_np, dtype="int64")
    l1 = loss_layer(net1(x), y)
    l1.backward()
    opt1.step()

    net2, opt2 = build()
    step = TrainStep(net2, lambda m, a, b: loss_layer(m(a), b), opt2)
    l2 = step(x, y)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    step.sync_to_layer()
    for (k1, p1), (k2, p2) in zip(net1.named_parameters(),
                                  net2.named_parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), rtol=1e-4, atol=1e-5)


def test_resnet18_jitted_train_step():
    """ResNet-18 trains via the compiled TrainStep (one XLA program — the
    'static graph' path from SURVEY §7 step 5); grads reach every param."""
    net = resnet18(num_classes=10)
    loss_layer = nn.CrossEntropyLoss()
    opt = paddle.optimizer.Momentum(learning_rate=0.01,
                                    parameters=net.parameters())
    step = TrainStep(net, lambda m, a, b: loss_layer(m(a), b), opt)
    x = paddle.randn([2, 3, 32, 32])
    y = paddle.to_tensor(np.array([1, 7]), dtype="int64")
    l0 = float(step(x, y))
    l1 = float(step(x, y))
    assert np.isfinite(l0) and np.isfinite(l1)
    # every trainable param received an update by step 2
    step.sync_to_layer()
    assert len(step.params) == len([p for p in net.parameters()
                                    if not p.stop_gradient])


def test_save_load_roundtrip(tmp_path):
    net = LeNet()
    path = str(tmp_path / "model.pdparams")
    paddle.save(net.state_dict(), path)
    loaded = paddle.load(path)
    net2 = LeNet()
    net2.set_state_dict(loaded)
    x = paddle.randn([2, 1, 28, 28])
    net.eval()
    net2.eval()
    np.testing.assert_allclose(net(x).numpy(), net2(x).numpy(), rtol=1e-5)


def test_hapi_model_fit():
    paddle.seed(0)
    net = LeNet()
    model = paddle.Model(net)
    model.prepare(
        optimizer=paddle.optimizer.Adam(learning_rate=1e-3,
                                        parameters=net.parameters()),
        loss=nn.CrossEntropyLoss(),
        metrics=[paddle.metric.Accuracy()],
    )

    class Squeeze(paddle.io.Dataset):
        def __init__(self, inner):
            self.inner = inner

        def __getitem__(self, i):
            x, y = self.inner[i]
            return x, y.squeeze()

        def __len__(self):
            return len(self.inner)

    ds = Squeeze(MNIST(mode="train", num_synthetic=128))
    model.fit(ds, epochs=1, batch_size=32, verbose=0)
    res = model.evaluate(Squeeze(MNIST(mode="test", num_synthetic=64)),
                         batch_size=32)
    assert "loss" in res


def test_resnet_nhwc_matches_nchw():
    """data_format="NHWC" runs the net channels-last internally with the
    same params and the SAME numerics (public input stays NCHW)."""
    from paddle_tpu.vision.models import resnet18

    paddle.seed(0)
    m1 = resnet18(num_classes=10)
    paddle.seed(0)
    m2 = resnet18(num_classes=10, data_format="NHWC")
    x = paddle.to_tensor(np.random.default_rng(0)
                         .normal(size=(2, 3, 64, 64)).astype(np.float32))
    m1.eval()
    m2.eval()
    with paddle.no_grad():
        np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy(),
                                   atol=2e-3, rtol=1e-3)


def test_resnet_nhwc_backbone_contract_and_validation():
    """The NCHW contract holds on BOTH ends: a headless/unpooled NHWC
    backbone returns NCHW features matching its NCHW twin; bad
    data_format values raise."""
    import pytest

    from paddle_tpu.vision.models import resnet18

    paddle.seed(1)
    b1 = resnet18(num_classes=0, with_pool=False)
    paddle.seed(1)
    b2 = resnet18(num_classes=0, with_pool=False, data_format="NHWC")
    x = paddle.to_tensor(np.random.default_rng(1)
                         .normal(size=(2, 3, 64, 64)).astype(np.float32))
    b1.eval()
    b2.eval()
    with paddle.no_grad():
        f1 = b1(x).numpy()
        f2 = b2(x).numpy()
    assert f1.shape == f2.shape            # NCHW out either way
    np.testing.assert_allclose(f1, f2, atol=2e-3, rtol=1e-3)

    with pytest.raises(ValueError, match="data_format"):
        resnet18(data_format="nhwc")


def test_resnet18_train_step_parity_across_layouts():
    """ResNet-18 TrainStep losses under the channels-last rewrite
    (FLAGS_jit_channels_last, the default) match the plain NCHW trace over
    two optimizer steps — the end-to-end train-path contract."""
    import paddle_tpu.nn.functional as F
    from paddle_tpu.optimizer import Momentum

    x_np = np.random.default_rng(0).normal(size=(2, 3, 64, 64)) \
        .astype(np.float32)
    y_np = np.arange(2, dtype=np.int64) % 10

    losses = {}
    for flag in (True, False):
        paddle.set_flags({"jit_channels_last": flag})
        try:
            paddle.seed(0)
            m = resnet18(num_classes=10)
            m.train()
            opt = Momentum(learning_rate=0.005, parameters=m.parameters())

            def loss_fn(layer, xb, yb):
                return F.cross_entropy(layer(xb), yb)

            step = TrainStep(m, loss_fn, opt)
            xs = paddle.to_tensor(x_np)
            ys = paddle.to_tensor(y_np)
            losses[flag] = [float(step(xs, ys)) for _ in range(2)]
        finally:
            paddle.set_flags({"jit_channels_last": True})
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-3)


def test_resnet50_fwd_bwd_gradient_parity_across_layouts():
    """ResNet-50 fwd+bwd: the loss and EVERY parameter gradient under the
    channels-last planner match the NCHW trace. Tolerance note: per-op and
    per-block layout parity is ~1e-6 (test_layout.py, bottleneck checks);
    through 53 stacked batch-norms the f32 reassociation noise is amplified
    by the stats' conditioning, so the full-model gate is an L2-relative
    bound per tensor — a real layout bug (wrong axis, wrong transpose)
    produces O(1) errors, far above it."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu.nn.functional as F
    from paddle_tpu.core.random import trace_rng
    from paddle_tpu.core.tensor import no_grad
    from paddle_tpu.jit.functional import bind, buffer_arrays
    from paddle_tpu.nn import layout
    from paddle_tpu.vision.models import resnet50

    B = 8
    x_np = np.random.default_rng(0).normal(size=(B, 3, 64, 64)) \
        .astype(np.float32)
    y_np = (np.arange(B) % 10).astype(np.int64)
    paddle.seed(0)
    m = resnet50(num_classes=10)
    m.train()
    params = {k: p._data for k, p in m.named_parameters()}
    bufs = buffer_arrays(m)

    def make_loss(cl):
        def loss(p):
            b = dict(bufs)
            with trace_rng(jax.random.key(0)), no_grad(), \
                    layout.channels_last_scope(cl):
                with bind(m, p, b):
                    out = F.cross_entropy(m(paddle.to_tensor(x_np)),
                                          paddle.to_tensor(y_np))
            return out._data.astype(jnp.float32), b
        return loss

    (l_ref, _), g_ref = jax.value_and_grad(make_loss(False),
                                           has_aux=True)(params)
    (l_cl, _), g_cl = jax.value_and_grad(make_loss(True),
                                         has_aux=True)(params)
    np.testing.assert_allclose(float(l_cl), float(l_ref), rtol=1e-5)
    for k in g_ref:
        a, b = np.asarray(g_ref[k]), np.asarray(g_cl[k])
        rel = np.linalg.norm(a - b) / (np.linalg.norm(a) + 1e-12)
        assert rel < 0.05, f"{k}: grad L2-relative error {rel:.3f}"


def test_vgg_mobilenet_nhwc_flag_parity():
    """The data_format="NHWC" model flag (VGG/MobileNet) preserves the
    public NCHW contract and the numerics."""
    from paddle_tpu.vision.models import mobilenet_v2, vgg11

    x = paddle.to_tensor(np.random.default_rng(2)
                         .normal(size=(2, 3, 32, 32)).astype(np.float32))
    for ctor, kw in ((vgg11, dict(num_classes=0)),
                     (mobilenet_v2, dict(num_classes=7, scale=0.25))):
        paddle.seed(0)
        a = ctor(**kw)
        paddle.seed(0)
        b = ctor(data_format="NHWC", **kw)
        a.eval()
        b.eval()
        with paddle.no_grad():
            np.testing.assert_allclose(a(x).numpy(), b(x).numpy(),
                                       atol=2e-3, rtol=1e-3)
        with pytest.raises(ValueError, match="data_format"):
            ctor(data_format="nhwc", **kw)


def test_inference_fold_conv_bn_parity():
    """The inference conv+BN weight-folding pass preserves eval outputs
    and removes the BN layers."""
    from paddle_tpu.inference.passes import fold_conv_bn

    paddle.seed(0)
    m = resnet18(num_classes=10)
    x = paddle.to_tensor(np.random.default_rng(3)
                         .normal(size=(2, 3, 64, 64)).astype(np.float32))
    m.train()
    with paddle.no_grad():
        m(x)                               # make EMA stats non-trivial
    m.eval()
    with paddle.no_grad():
        ref = m(x).numpy()
    folded = fold_conv_bn(m)
    assert folded == 20                    # resnet18: 16 block + stem + 3 ds
    with paddle.no_grad():
        np.testing.assert_allclose(m(x).numpy(), ref, atol=2e-3, rtol=1e-3)
