"""Functional control flow (static.nn) + guided tracing errors.

reference parity: fluid/layers/control_flow.py cond(:2323)/while_loop
(:1045) over conditional_block_op/while_op; the AST translator
(program_translator.py:768) handles python `if`/`while` on tensors —
here the python form raises a GUIDED error pointing at the functional
API (tests at bottom).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.core.tensor import Tensor


def test_cond_selects_branch():
    x = paddle.to_tensor(np.array([3.0], np.float32))
    big = static.nn.cond(x.sum() > 2.0, lambda: x * 2, lambda: x - 1)
    small = static.nn.cond(x.sum() > 5.0, lambda: x * 2, lambda: x - 1)
    np.testing.assert_allclose(np.asarray(big._data), [6.0])
    np.testing.assert_allclose(np.asarray(small._data), [2.0])


def test_cond_with_operands_under_jit():
    from paddle_tpu.jit import to_static

    @to_static
    def f(x):
        return static.nn.cond(x.sum() > 0, lambda t: t + 1,
                              lambda t: t - 1, x)

    out = f(paddle.to_tensor(np.ones((2,), np.float32)))
    np.testing.assert_allclose(np.asarray(out._data), [2.0, 2.0])
    out = f(paddle.to_tensor(-np.ones((2,), np.float32)))
    np.testing.assert_allclose(np.asarray(out._data), [-2.0, -2.0])


def test_while_loop_accumulates():
    i = paddle.to_tensor(np.asarray(0, np.int32))
    s = paddle.to_tensor(np.asarray(0.0, np.float32))

    i_out, s_out = static.nn.while_loop(
        lambda i, s: i < 5,
        lambda i, s: (i + 1, s + 2.0),
        [i, s])
    assert int(np.asarray(i_out._data)) == 5
    assert float(np.asarray(s_out._data)) == 10.0


def test_while_loop_structure_mismatch_raises():
    with pytest.raises(ValueError, match="invariant"):
        static.nn.while_loop(lambda i: i < 3, lambda i: (i + 1, i),
                             paddle.to_tensor(np.asarray(0, np.int32)))


def test_switch_case_and_case():
    idx = paddle.to_tensor(np.asarray(1, np.int32))
    out = static.nn.switch_case(idx, [
        lambda: paddle.to_tensor(np.asarray(10.0, np.float32)),
        lambda: paddle.to_tensor(np.asarray(20.0, np.float32)),
    ], default=lambda: paddle.to_tensor(np.asarray(-1.0, np.float32)))
    assert float(np.asarray(out._data)) == 20.0
    out = static.nn.switch_case(
        paddle.to_tensor(np.asarray(7, np.int32)), [
            lambda: paddle.to_tensor(np.asarray(10.0, np.float32)),
            lambda: paddle.to_tensor(np.asarray(20.0, np.float32)),
        ], default=lambda: paddle.to_tensor(np.asarray(-1.0, np.float32)))
    assert float(np.asarray(out._data)) == -1.0

    x = paddle.to_tensor(np.asarray(4.0, np.float32))
    out = static.nn.case(
        [(x > 10.0, lambda: x * 1),
         (x > 2.0, lambda: x * 10)],
        default=lambda: x * 100)
    assert float(np.asarray(out._data)) == 40.0


def test_model_with_cond_compiles():
    """A model whose forward uses the functional API compiles under
    to_static (the 'data-dependent branch compiles' criterion)."""
    from paddle_tpu.jit import to_static

    class Gated(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            return static.nn.cond(h.mean() > 0,
                                  lambda: h * 2.0, lambda: h * 0.5)

    paddle.seed(0)
    model = Gated()
    model.eval()
    f = to_static(model)
    out = model(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert np.isfinite(np.asarray(out._data)).all()


def test_python_if_on_tensor_raises_guided_error():
    """Python `if tensor:` inside a traced forward fails with framework
    guidance naming static.nn.cond (not a bare jax error)."""
    from paddle_tpu.jit import to_static

    class Bad(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.mean() > 0:     # traced bool -> concretization error
                return h * 2
            return h

    import jax.errors
    paddle.seed(0)
    model = Bad()
    model.eval()
    to_static(model)
    with pytest.raises(jax.errors.ConcretizationTypeError,
                       match="static.nn.cond"):
        model(paddle.to_tensor(np.ones((2, 4), np.float32)))


# ---------------------------------------------------------------------------
# dy2static AST pass (reference: dygraph_to_static ifelse/loop transformers)
# ---------------------------------------------------------------------------


def test_ast_tensor_if_compiles_under_to_static():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit import to_static

    @to_static
    def f(x, t):
        if x.sum() > t:          # plain python if over a TENSOR
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    x = paddle.to_tensor(np.ones((3,), np.float32))
    np.testing.assert_allclose(
        f(x, paddle.to_tensor(np.array(2.0, np.float32))).numpy(), 2.0)
    np.testing.assert_allclose(
        f(x, paddle.to_tensor(np.array(10.0, np.float32))).numpy(), 0.0)


def test_ast_tensor_while_compiles_under_to_static():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit import to_static

    @to_static
    def count(x, n):
        i = x * 0.0
        while i.sum() < n:
            x = x + 1.0
            i = i + 1.0
        return x

    x = paddle.to_tensor(np.zeros((1,), np.float32))
    out = count(x, paddle.to_tensor(np.array(5.0, np.float32)))
    np.testing.assert_allclose(out.numpy(), 5.0)


def test_ast_python_bool_semantics_preserved():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    effects = []

    def f(x, flag):
        if flag:
            effects.append("true")     # side effect: must run exactly once
            y = x + 1.0
        else:
            effects.append("false")
            y = x - 1.0
        return y

    g = convert_to_static(f)
    x = paddle.to_tensor(np.zeros((2,), np.float32))
    g(x, True)
    assert effects == ["true"]         # only the taken branch executed


def test_ast_eager_tensor_cond_keeps_python_path():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    seen = []

    def f(x):
        if x.sum() > 0:
            seen.append("pos")
            y = x * 2.0
        else:
            seen.append("neg")
            y = x * -1.0
        return y

    g = convert_to_static(f)
    g(paddle.to_tensor(np.ones((2,), np.float32)))
    assert seen == ["pos"]             # eager: one branch, not lax.cond


def test_ast_early_return_falls_back():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        if x.sum() > 0:
            return x * 2.0             # early return: untransformed
        return x

    g = convert_to_static(f)
    # eager concrete cond still works through Tensor.__bool__
    np.testing.assert_allclose(
        g(paddle.to_tensor(np.ones((2,), np.float32))).numpy(), 2.0)


def test_ast_late_bound_globals_and_fallbacks():
    """Review regressions: live module globals, global-decl fallback,
    one-branch-only assignment fallback, dunder user names."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    x = paddle.to_tensor(np.ones((2,), np.float32))

    def one_branch(x):
        if float(x.sum()) > 0:
            y = x * 2.0
            return y
        return x

    np.testing.assert_allclose(convert_to_static(one_branch)(x).numpy(),
                               2.0)

    def dunder(x, flag):
        if flag:
            __state = x * 5.0
        else:
            __state = x
        return __state

    np.testing.assert_allclose(convert_to_static(dunder)(x, True).numpy(),
                               5.0)

    def while_undef_zero_iter(x):
        while float(x.sum()) > 100:
            t = x * 2.0
            x = t
        return x

    # zero-iteration loop with an inside-only name: python-like NameError
    # is only raised if the name never got bound — here x returns fine
    np.testing.assert_allclose(
        convert_to_static(while_undef_zero_iter)(x).numpy(), 1.0)
