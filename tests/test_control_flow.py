"""Functional control flow (static.nn) + guided tracing errors.

reference parity: fluid/layers/control_flow.py cond(:2323)/while_loop
(:1045) over conditional_block_op/while_op; the AST translator
(program_translator.py:768) handles python `if`/`while` on tensors —
here the python form raises a GUIDED error pointing at the functional
API (tests at bottom).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.core.tensor import Tensor


def test_cond_selects_branch():
    x = paddle.to_tensor(np.array([3.0], np.float32))
    big = static.nn.cond(x.sum() > 2.0, lambda: x * 2, lambda: x - 1)
    small = static.nn.cond(x.sum() > 5.0, lambda: x * 2, lambda: x - 1)
    np.testing.assert_allclose(np.asarray(big._data), [6.0])
    np.testing.assert_allclose(np.asarray(small._data), [2.0])


def test_cond_with_operands_under_jit():
    from paddle_tpu.jit import to_static

    @to_static
    def f(x):
        return static.nn.cond(x.sum() > 0, lambda t: t + 1,
                              lambda t: t - 1, x)

    out = f(paddle.to_tensor(np.ones((2,), np.float32)))
    np.testing.assert_allclose(np.asarray(out._data), [2.0, 2.0])
    out = f(paddle.to_tensor(-np.ones((2,), np.float32)))
    np.testing.assert_allclose(np.asarray(out._data), [-2.0, -2.0])


def test_while_loop_accumulates():
    i = paddle.to_tensor(np.asarray(0, np.int32))
    s = paddle.to_tensor(np.asarray(0.0, np.float32))

    i_out, s_out = static.nn.while_loop(
        lambda i, s: i < 5,
        lambda i, s: (i + 1, s + 2.0),
        [i, s])
    assert int(np.asarray(i_out._data)) == 5
    assert float(np.asarray(s_out._data)) == 10.0


def test_while_loop_structure_mismatch_raises():
    with pytest.raises(ValueError, match="invariant"):
        static.nn.while_loop(lambda i: i < 3, lambda i: (i + 1, i),
                             paddle.to_tensor(np.asarray(0, np.int32)))


def test_switch_case_and_case():
    idx = paddle.to_tensor(np.asarray(1, np.int32))
    out = static.nn.switch_case(idx, [
        lambda: paddle.to_tensor(np.asarray(10.0, np.float32)),
        lambda: paddle.to_tensor(np.asarray(20.0, np.float32)),
    ], default=lambda: paddle.to_tensor(np.asarray(-1.0, np.float32)))
    assert float(np.asarray(out._data)) == 20.0
    out = static.nn.switch_case(
        paddle.to_tensor(np.asarray(7, np.int32)), [
            lambda: paddle.to_tensor(np.asarray(10.0, np.float32)),
            lambda: paddle.to_tensor(np.asarray(20.0, np.float32)),
        ], default=lambda: paddle.to_tensor(np.asarray(-1.0, np.float32)))
    assert float(np.asarray(out._data)) == -1.0

    x = paddle.to_tensor(np.asarray(4.0, np.float32))
    out = static.nn.case(
        [(x > 10.0, lambda: x * 1),
         (x > 2.0, lambda: x * 10)],
        default=lambda: x * 100)
    assert float(np.asarray(out._data)) == 40.0


def test_model_with_cond_compiles():
    """A model whose forward uses the functional API compiles under
    to_static (the 'data-dependent branch compiles' criterion)."""
    from paddle_tpu.jit import to_static

    class Gated(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            return static.nn.cond(h.mean() > 0,
                                  lambda: h * 2.0, lambda: h * 0.5)

    paddle.seed(0)
    model = Gated()
    model.eval()
    f = to_static(model)
    out = model(paddle.to_tensor(np.ones((2, 4), np.float32)))
    assert np.isfinite(np.asarray(out._data)).all()


def test_early_return_on_tensor_condition_compiles():
    """`if tensor: return a; return b` — the return transform (reference:
    return_transformer.py) turns the early return into a flag+value carry
    that compiles and matches eager select semantics."""
    from paddle_tpu.jit import to_static

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.mean() > 0:     # traced bool
                return h * 2
            return h

    paddle.seed(0)
    model = M()
    model.eval()
    ref_pos = model(paddle.to_tensor(np.ones((2, 4), np.float32))).numpy()
    ref_neg = model(paddle.to_tensor(-np.ones((2, 4), np.float32))).numpy()
    to_static(model)
    out_pos = model(paddle.to_tensor(np.ones((2, 4), np.float32)))
    out_neg = model(paddle.to_tensor(-np.ones((2, 4), np.float32)))
    np.testing.assert_allclose(out_pos.numpy(), ref_pos, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(out_neg.numpy(), ref_neg, rtol=1e-5,
                               atol=1e-6)


def test_python_if_on_tensor_raises_guided_error():
    """A python `if tensor:` the AST pass cannot functionalize (here: an
    import statement inside the branch) still fails with framework
    guidance naming static.nn.cond (not a bare jax error)."""
    from paddle_tpu.jit import to_static

    class Bad(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x):
            h = self.fc(x)
            if h.mean() > 0:     # traced bool -> concretization error
                import math
                h = h * math.e
            return h

    import jax.errors
    paddle.seed(0)
    model = Bad()
    model.eval()
    to_static(model)
    with pytest.raises(jax.errors.ConcretizationTypeError,
                       match="static.nn.cond"):
        model(paddle.to_tensor(np.ones((2, 4), np.float32)))


# ---------------------------------------------------------------------------
# dy2static AST pass (reference: dygraph_to_static ifelse/loop transformers)
# ---------------------------------------------------------------------------


def test_ast_tensor_if_compiles_under_to_static():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit import to_static

    @to_static
    def f(x, t):
        if x.sum() > t:          # plain python if over a TENSOR
            y = x * 2.0
        else:
            y = x - 1.0
        return y

    x = paddle.to_tensor(np.ones((3,), np.float32))
    np.testing.assert_allclose(
        f(x, paddle.to_tensor(np.array(2.0, np.float32))).numpy(), 2.0)
    np.testing.assert_allclose(
        f(x, paddle.to_tensor(np.array(10.0, np.float32))).numpy(), 0.0)


def test_ast_tensor_while_compiles_under_to_static():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit import to_static

    @to_static
    def count(x, n):
        i = x * 0.0
        while i.sum() < n:
            x = x + 1.0
            i = i + 1.0
        return x

    x = paddle.to_tensor(np.zeros((1,), np.float32))
    out = count(x, paddle.to_tensor(np.array(5.0, np.float32)))
    np.testing.assert_allclose(out.numpy(), 5.0)


def test_ast_python_bool_semantics_preserved():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    effects = []

    def f(x, flag):
        if flag:
            effects.append("true")     # side effect: must run exactly once
            y = x + 1.0
        else:
            effects.append("false")
            y = x - 1.0
        return y

    g = convert_to_static(f)
    x = paddle.to_tensor(np.zeros((2,), np.float32))
    g(x, True)
    assert effects == ["true"]         # only the taken branch executed


def test_ast_eager_tensor_cond_keeps_python_path():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    seen = []

    def f(x):
        if x.sum() > 0:
            seen.append("pos")
            y = x * 2.0
        else:
            seen.append("neg")
            y = x * -1.0
        return y

    g = convert_to_static(f)
    g(paddle.to_tensor(np.ones((2,), np.float32)))
    assert seen == ["pos"]             # eager: one branch, not lax.cond


def test_ast_early_return_falls_back():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        if x.sum() > 0:
            return x * 2.0             # early return: untransformed
        return x

    g = convert_to_static(f)
    # eager concrete cond still works through Tensor.__bool__
    np.testing.assert_allclose(
        g(paddle.to_tensor(np.ones((2,), np.float32))).numpy(), 2.0)


def test_ast_late_bound_globals_and_fallbacks():
    """Review regressions: live module globals, global-decl fallback,
    one-branch-only assignment fallback, dunder user names."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    x = paddle.to_tensor(np.ones((2,), np.float32))

    def one_branch(x):
        if float(x.sum()) > 0:
            y = x * 2.0
            return y
        return x

    np.testing.assert_allclose(convert_to_static(one_branch)(x).numpy(),
                               2.0)

    def dunder(x, flag):
        if flag:
            __state = x * 5.0
        else:
            __state = x
        return __state

    np.testing.assert_allclose(convert_to_static(dunder)(x, True).numpy(),
                               5.0)

    def while_undef_zero_iter(x):
        while float(x.sum()) > 100:
            t = x * 2.0
            x = t
        return x

    # zero-iteration loop with an inside-only name: python-like NameError
    # is only raised if the name never got bound — here x returns fine
    np.testing.assert_allclose(
        convert_to_static(while_undef_zero_iter)(x).numpy(), 1.0)


# ---------------------------------------------------------------------------
# dy2static loops: for/break/continue/return (reference: loop_transformer,
# break_continue_transformer, return_transformer)
# ---------------------------------------------------------------------------


def test_ast_range_for_over_tensor_bound_compiles():
    """`for i in range(t)` with a tensor bound lowers to lax.while_loop
    under trace; matches python eagerly."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x, n):
        acc = x * 0.0
        for i in range(n):
            acc = acc + x * float(1.0)
        return acc

    g = convert_to_static(f)
    x = paddle.to_tensor(np.full((3,), 2.0, np.float32))
    n = paddle.to_tensor(np.int32(4))
    np.testing.assert_allclose(g(x, n).numpy(), 8.0)          # eager tensor

    # traced: both args traced; loop count is data-dependent
    from paddle_tpu.core.tensor import Tensor

    def pure(xa, na):
        return g(Tensor(xa), Tensor(na))._data

    out = jax.jit(pure)(x._data, n._data)
    np.testing.assert_allclose(np.asarray(out), 8.0)
    out5 = jax.jit(pure)(x._data, jax.numpy.asarray(np.int32(5)))
    np.testing.assert_allclose(np.asarray(out5), 10.0)


def test_ast_range_for_python_semantics():
    """Plain python range loops keep exact semantics (incl. step and the
    loop variable's final value)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        s = 0
        for i in range(1, 10, 3):
            s = s + i
        return x * float(s), i

    g = convert_to_static(f)
    x = paddle.to_tensor(np.ones((2,), np.float32))
    out, last = g(x)
    np.testing.assert_allclose(out.numpy(), 12.0)   # 1+4+7
    assert last == 7


def test_ast_break_continue_in_while():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        i = 0
        s = x * 0.0
        while i < 10:
            i = i + 1
            if i == 3:
                continue
            if i > 5:
                break
            s = s + x * float(1.0)
        return s, i

    g = convert_to_static(f)
    x = paddle.to_tensor(np.ones((2,), np.float32))
    s, i = g(x)
    np.testing.assert_allclose(s.numpy(), 4.0)      # i=1,2,4,5
    assert int(i) == 6

    # pure-python reference agrees
    s_ref, i_ref = f(x)
    np.testing.assert_allclose(s.numpy(), s_ref.numpy())


def test_ast_break_on_tensor_condition_compiles():
    """break guarded by a TRACED condition: the loop starts python-side,
    the flag becomes traced inside lax.cond, and __jst_while__ hands off
    to lax.while_loop mid-flight."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x, limit):
        s = x * 0.0
        i = x.sum() * 0.0        # tensor counter (no closure imports)
        while i < 100:
            s = s + x
            i = i + 1
            if s.sum() > limit:
                break
        return s

    g = convert_to_static(f)
    x = paddle.to_tensor(np.ones((2,), np.float32))
    lim = paddle.to_tensor(np.float32(5.0))
    # eager: sum hits 6 after 3 iters (2 elements * 3)
    np.testing.assert_allclose(g(x, lim).numpy(), 3.0)

    def pure(xa, la):
        return g(Tensor(xa), Tensor(la))._data

    out = jax.jit(pure)(x._data, lim._data)
    np.testing.assert_allclose(np.asarray(out), 3.0)


def test_ast_return_inside_loop():
    """A return inside a while lowers via the return-flag transform and
    matches python semantics eagerly."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        i = 0
        while i < 10:
            x = x * 2.0
            if float(x.sum()) > 10:
                return x, i
            i = i + 1
        return x, -1

    g = convert_to_static(f)
    x = paddle.to_tensor(np.ones((2,), np.float32))
    out, i = g(x)
    # doubles: 2,4,8 -> sums 4,8,16; stops at 16
    np.testing.assert_allclose(out.numpy(), 8.0)
    assert int(i) == 2
    out_ref, i_ref = f(paddle.to_tensor(np.ones((2,), np.float32)))
    np.testing.assert_allclose(out.numpy(), out_ref.numpy())
    assert int(i) == int(i_ref)


def test_ast_single_sided_if_on_tensor():
    """`if cond: x = f(x)` (no else) functionalizes: the false path
    carries the incoming value through."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        if x.mean() > 0:
            x = x * 3.0
        return x

    g = convert_to_static(f)
    xp = paddle.to_tensor(np.ones((2,), np.float32))
    xn = paddle.to_tensor(-np.ones((2,), np.float32))

    def pure(xa):
        return g(Tensor(xa))._data

    np.testing.assert_allclose(np.asarray(jax.jit(pure)(xp._data)), 3.0)
    np.testing.assert_allclose(np.asarray(jax.jit(pure)(xn._data)), -1.0)


def test_ast_decode_loop_to_static():
    """VERDICT r3 done-criterion: a python-for greedy decode loop compiles
    via @to_static and matches eager."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.jit import to_static

    class TinyDecoder(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(16, 8)
            self.proj = nn.Linear(8, 16)

        def forward(self, ids, steps):
            # greedy continuation: feed back argmax `steps` times
            h = self.emb(ids).mean(axis=1)
            outs = h * 0.0
            for i in range(steps):
                logits = self.proj(h)
                nxt = logits.argmax(axis=-1)
                h = 0.5 * h + 0.5 * self.emb(nxt)
                outs = outs + h
            return outs

    paddle.seed(0)
    m = TinyDecoder()
    m.eval()
    ids = paddle.to_tensor(np.array([[1, 2, 3]], np.int64))
    ref = m(ids, 4).numpy()
    to_static(m)
    out = m(ids, 4)
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)


def test_ast_continue_in_for_range():
    """continue inside `for i in range(...)` must not hang: the counter
    increment lives at the TOP of the lowered while body, outside the
    continue guard (round-4 advisor finding: the trailing increment got
    wrapped in the `if not cnt-flag` guard and the loop spun forever)."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        s = x * 0.0
        for i in range(6):
            if i == 2:
                continue
            s = s + x * float(1.0)
        return s, i

    g = convert_to_static(f)
    x = paddle.to_tensor(np.ones((2,), np.float32))
    s, last = g(x)
    np.testing.assert_allclose(s.numpy(), 5.0)       # skips i==2
    assert int(last) == 5
    s_ref, i_ref = f(x)                              # python reference
    np.testing.assert_allclose(s.numpy(), s_ref.numpy())
    assert int(last) == i_ref

    # tensor bound: lowers to lax.while_loop; continue via traced cond
    def h(x, n):
        s = x * 0.0
        for i in range(n):
            if i == 2:
                continue
            s = s + x
        return s

    gh = convert_to_static(h)
    n = paddle.to_tensor(np.int32(6))

    def pure(xa, na):
        return gh(Tensor(xa), Tensor(na))._data

    out = jax.jit(pure)(x._data, n._data)
    np.testing.assert_allclose(np.asarray(out), 5.0)


def test_ast_break_and_continue_in_for_range():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        s = x * 0.0
        for i in range(10):
            if i % 2 == 1:
                continue
            if i > 6:
                break
            s = s + x * float(i)
        return s

    g = convert_to_static(f)
    x = paddle.to_tensor(np.ones((2,), np.float32))
    np.testing.assert_allclose(g(x).numpy(), 12.0)   # 0+2+4+6
    np.testing.assert_allclose(g(x).numpy(), f(x).numpy())


def test_ast_for_over_tensor_rows():
    """`for x in tensor:` iterates the leading axis — eager AND compiled
    (static length, unrolled under trace). Reference:
    dygraph_to_static/loop_transformer.py:45 converts tensor iterables;
    here Tensor.__iter__ + static shapes make the python loop itself
    trace-safe."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(t):
        s = t[0] * 0.0
        for row in t:
            s = s + row * 2.0
        return s

    g = convert_to_static(f)
    t = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    want = np.asarray(t.numpy()).sum(0) * 2.0
    np.testing.assert_allclose(g(t).numpy(), want)

    def pure(a):
        return g(Tensor(a))._data

    out = jax.jit(pure)(t._data)
    np.testing.assert_allclose(np.asarray(out), want)


def test_ast_append_then_stack_decode_loop():
    """Append-then-stack: outputs collected in a python list across a
    for-range loop, stacked after — compiles via @to_static and matches
    eager (the reference's tensor-array pattern)."""
    import jax
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(x):
        ys = []
        h = x
        for i in range(4):
            h = h * 0.5 + float(i)
            ys.append(h)
        return paddle.stack(ys, axis=0)

    g = convert_to_static(f)
    x = paddle.to_tensor(np.ones((3,), np.float32))
    ref = f(x).numpy()
    np.testing.assert_allclose(g(x).numpy(), ref)

    def pure(a):
        return g(Tensor(a))._data

    out = jax.jit(pure)(x._data)
    np.testing.assert_allclose(np.asarray(out), ref)


def test_ast_for_over_list_of_tensors():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.jit.dy2static import convert_to_static

    def f(parts):
        s = parts[0] * 0.0
        for p in parts:
            s = s + p
        return s

    g = convert_to_static(f)
    parts = [paddle.to_tensor(np.full((2,), float(i), np.float32))
             for i in range(3)]
    np.testing.assert_allclose(g(parts).numpy(), [3.0, 3.0])
