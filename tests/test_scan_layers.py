"""Scan-over-layers parity and trace-count tests (ISSUE 2 tentpole).

The decoder/encoder stacks run as ONE jax.lax.scan over layer-stacked
params (nn/scan.py). Contract pinned here:
- scan == loop numerics: forward, backward, and full optimizer steps
  (f32 exact; AMP O1 within bf16 tolerance), incl. under use_recompute
  and a selective checkpoint policy;
- state_dict names and values are unchanged — checkpoints saved from the
  loop stack load into the scanned stack bit-exactly;
- the scan body traces O(1) in the number of layers (the compile-time
  win), pinned via paddle_tpu.utils.CompileCounter so a layer-loop
  re-trace can't silently regress.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.jit.to_static import TrainStep
from paddle_tpu.models.bert import BertForMaskedLM, bert_tiny
from paddle_tpu.models.ernie import ErnieForPretraining, ernie_tiny
from paddle_tpu.models.gpt import (GPTForPretraining, GPTPretrainingCriterion,
                                   gpt_tiny)
from paddle_tpu.optimizer import AdamW


def _gpt_pair(num_layers=3, **kw):
    """Two GPT models with identical weights: loop-stack and scan-stack."""
    paddle.seed(11)
    loop = GPTForPretraining(gpt_tiny(num_layers=num_layers,
                                      scan_layers=False, **kw))
    scan = GPTForPretraining(gpt_tiny(num_layers=num_layers,
                                      scan_layers=True, **kw))
    scan.set_state_dict({k: v.numpy() for k, v in loop.state_dict().items()})
    return loop, scan


def _batch(cfg_vocab=256, B=2, S=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = Tensor(rng.randint(0, cfg_vocab, (B, S)).astype(np.int32))
    labels = Tensor(rng.randint(0, cfg_vocab, (B, S)).astype(np.int32))
    return ids, labels


def test_gpt_scan_forward_backward_parity_f32():
    loop, scan = _gpt_pair()
    ids, labels = _batch()
    crit = GPTPretrainingCriterion()

    l1 = crit(loop(ids), labels)
    l1.backward()
    l2 = crit(scan(ids), labels)
    l2.backward()
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    g1 = {k: np.asarray(p.grad._data) for k, p in loop.named_parameters()}
    g2 = {k: np.asarray(p.grad._data) for k, p in scan.named_parameters()}
    assert set(g1) == set(g2)
    for k in g1:
        np.testing.assert_allclose(g1[k], g2[k], rtol=1e-4, atol=1e-6,
                                   err_msg=k)


@pytest.mark.parametrize("use_recompute,policy", [
    (False, None),
    (True, None),
    (True, "dots_with_no_batch_dims_saveable"),
])
def test_gpt_scan_optimizer_steps_match_loop(use_recompute, policy):
    """Full jitted train steps: scan == loop loss trajectory (f32)."""
    loop, scan = _gpt_pair(use_recompute=use_recompute,
                           recompute_policy=policy)
    ids, labels = _batch(seed=3)
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, i, l):
        return crit(layer(i), l)

    losses = {}
    for tag, m in (("loop", loop), ("scan", scan)):
        paddle.seed(99)          # same TrainStep RNG stream for both
        step = TrainStep(m, loss_fn, AdamW(learning_rate=1e-2))
        losses[tag] = [float(step(ids, labels)) for _ in range(5)]
    np.testing.assert_allclose(losses["loop"], losses["scan"], rtol=2e-5)
    assert losses["scan"][-1] < losses["scan"][0]


def test_gpt_scan_amp_o1_parity():
    """AMP O1: bf16 reassociation differs between the layouts, so parity
    is at bf16 tolerance (one fwd+bwd, not a drifting trajectory)."""
    loop, scan = _gpt_pair()
    ids, labels = _batch(seed=5)
    crit = GPTPretrainingCriterion()

    def loss_fn(layer, i, l):
        with paddle.amp.auto_cast(level="O1"):
            return crit(layer(i), l)

    vals = {}
    for tag, m in (("loop", loop), ("scan", scan)):
        paddle.seed(7)
        step = TrainStep(m, loss_fn, AdamW(learning_rate=1e-3))
        vals[tag] = float(step(ids, labels))
    np.testing.assert_allclose(vals["loop"], vals["scan"], rtol=2e-3)


def test_state_dict_roundtrip_loop_to_scan_bit_exact():
    """Checkpoints from the loop stack load into the scanned stack with
    identical keys and bit-identical arrays (and vice versa)."""
    loop, scan = _gpt_pair(num_layers=4)
    sd_loop = loop.state_dict()
    sd_scan = scan.state_dict()
    assert list(sd_loop.keys()) == list(sd_scan.keys())
    # the per-layer names survive (internal layout contract)
    assert any(k.startswith("gpt.layers.3.") for k in sd_scan)
    for k in sd_loop:
        a = np.asarray(sd_loop[k]._data)
        b = np.asarray(sd_scan[k]._data)
        assert a.dtype == b.dtype and a.shape == b.shape, k
        np.testing.assert_array_equal(a, b, err_msg=k)
    # round-trip through numpy + set_state_dict: loaded values bit-exact
    scan2 = GPTForPretraining(gpt_tiny(num_layers=4, scan_layers=True))
    missing, unexpected = scan2.set_state_dict(
        {k: v.numpy() for k, v in sd_loop.items()})
    assert not missing and not unexpected
    for k, v in scan2.state_dict().items():
        np.testing.assert_array_equal(np.asarray(v._data),
                                      np.asarray(sd_loop[k]._data),
                                      err_msg=k)
    # forward parity between the layouts (float-reassociation tolerance)
    ids, _ = _batch(seed=9)
    with paddle.no_grad():
        np.testing.assert_allclose(loop(ids).numpy(), scan2(ids).numpy(),
                                   rtol=2e-5, atol=2e-6)


def test_scan_body_traces_once_regardless_of_depth():
    """One trace per stack, not per layer: the body-trace count must be
    identical for 2- and 6-layer stacks (CompileCounter pin)."""
    from paddle_tpu.utils import CompileCounter

    crit = GPTPretrainingCriterion()

    def loss_fn(layer, i, l):
        return crit(layer(i), l)

    counts = {}
    for L in (2, 6):
        paddle.seed(0)
        m = GPTForPretraining(gpt_tiny(num_layers=L, scan_layers=True))
        step = TrainStep(m, loss_fn, AdamW(learning_rate=1e-2))
        ids, labels = _batch(seed=L)
        with CompileCounter() as c:
            float(step(ids, labels))
        counts[L] = c.scan_body_traces
        assert c.scan_calls == 1
    assert counts[2] == counts[6] > 0, counts
    # warm call: no new XLA compile, no new body trace
    with CompileCounter() as c:
        float(step(ids, labels))
    assert c.scan_body_traces == 0
    assert c.backend_compiles == 0


def test_bert_and_ernie_scan_matches_loop():
    rng = np.random.RandomState(1)
    ids_np = rng.randint(5, 250, (2, 16)).astype(np.int32)
    pos_np = np.stack([rng.choice(16, 4, replace=False)
                       for _ in range(2)]).astype(np.int32)

    paddle.seed(21)
    b_scan = BertForMaskedLM(bert_tiny(num_layers=3, scan_layers=True))
    b_loop = BertForMaskedLM(bert_tiny(num_layers=3, scan_layers=False))
    b_loop.set_state_dict({k: v.numpy()
                           for k, v in b_scan.state_dict().items()})
    with paddle.no_grad():
        o1 = b_scan(Tensor(ids_np), masked_positions=Tensor(pos_np)).numpy()
        o2 = b_loop(Tensor(ids_np), masked_positions=Tensor(pos_np)).numpy()
    np.testing.assert_allclose(o1, o2, rtol=2e-5, atol=2e-6)

    paddle.seed(22)
    e_scan = ErnieForPretraining(ernie_tiny(num_layers=3, scan_layers=True))
    e_loop = ErnieForPretraining(ernie_tiny(num_layers=3, scan_layers=False))
    e_loop.set_state_dict({k: v.numpy()
                           for k, v in e_scan.state_dict().items()})
    with paddle.no_grad():
        m1, s1 = e_scan(Tensor(ids_np), masked_positions=Tensor(pos_np))
        m2, s2 = e_loop(Tensor(ids_np), masked_positions=Tensor(pos_np))
    np.testing.assert_allclose(m1.numpy(), m2.numpy(), rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(s1.numpy(), s2.numpy(), rtol=2e-5, atol=2e-6)


def test_encoder_scan_with_attention_mask():
    """The broadcast (non-scanned) mask arg reaches every scanned layer."""
    paddle.seed(33)
    m_scan = BertForMaskedLM(bert_tiny(num_layers=2, scan_layers=True))
    m_loop = BertForMaskedLM(bert_tiny(num_layers=2, scan_layers=False))
    m_loop.set_state_dict({k: v.numpy()
                           for k, v in m_scan.state_dict().items()})
    rng = np.random.RandomState(4)
    ids = Tensor(rng.randint(5, 250, (2, 12)).astype(np.int32))
    mask = np.ones((2, 12), np.float32)
    mask[:, 8:] = 0.0
    with paddle.no_grad():
        o1 = m_scan(ids, attention_mask=Tensor(mask)).numpy()
        o2 = m_loop(ids, attention_mask=Tensor(mask)).numpy()
    np.testing.assert_allclose(o1, o2, rtol=2e-5, atol=2e-6)
    # the mask actually masks: different mask => different output
    with paddle.no_grad():
        o3 = m_scan(ids).numpy()
    assert np.abs(o1 - o3).max() > 1e-3


def test_per_layer_config_divergence_vetoes_scan():
    """The scan body runs every layer through block[0]'s forward, so a
    hand-tuned NON-parameter setting on one layer (stochastic-depth-style
    dropout rate, a swapped activation lambda) must veto the scan — param
    signatures can't see it. The config verdict is cached per stack:
    in-place edits AFTER first use need invalidate_scan_cache."""
    from paddle_tpu import nn
    from paddle_tpu.nn.scan import can_scan_layers, invalidate_scan_cache

    paddle.seed(50)
    m = GPTForPretraining(gpt_tiny(num_layers=3))
    m.gpt.layers[1].dropout1.p = 0.42       # customized before first use
    assert not can_scan_layers(m.gpt.layers)
    # the model silently falls back to the (correct) loop path
    ids, _ = _batch(seed=12)
    with paddle.no_grad():
        m(ids)
    # in-place edit after the cached verdict: explicit invalidation
    m.gpt.layers[1].dropout1.p = m.gpt.layers[0].dropout1.p
    invalidate_scan_cache(m.gpt.layers)
    assert can_scan_layers(m.gpt.layers)

    # distinct per-layer lambdas share __qualname__ but are different
    # functions — identity comparison must veto
    enc = nn.TransformerEncoder(
        nn.TransformerEncoderLayer(16, 2, 32, dropout=0.0), 2)
    assert can_scan_layers(enc.layers)
    invalidate_scan_cache(enc.layers)
    enc.layers[1].activation = lambda t: t * 0.0
    assert not can_scan_layers(enc.layers)

    # a hand-frozen subset (per-layer train/eval heterogeneity) must veto:
    # the scan body would apply block[0]'s mode to every layer
    enc2 = nn.TransformerEncoder(
        nn.TransformerEncoderLayer(16, 2, 32, dropout=0.1), 2)
    enc2.train()
    assert can_scan_layers(enc2.layers)
    enc2.layers[1].eval()
    assert not can_scan_layers(enc2.layers)


def test_uniform_config_edit_retraces_cached_scan():
    """An IN-PLACE but homogeneity-preserving config edit (every layer's
    dropout p set to 0) must invalidate the cached eager scan trace — the
    config signature rides in the op-cache token."""
    from paddle_tpu import nn
    from paddle_tpu.nn.scan import invalidate_scan_cache

    paddle.seed(60)
    enc = nn.TransformerEncoder(
        nn.TransformerEncoderLayer(16, 2, 32, dropout=0.9), 3)
    enc.enable_scan = True
    x = Tensor(np.random.RandomState(0).randn(2, 8, 16).astype(np.float32))
    enc.train()
    enc(x)                                    # trace cached with p=0.9
    for lyr in enc.layers:
        for d in (lyr.dropout, lyr.dropout1, lyr.dropout2):
            d.p = 0.0
        lyr.self_attn.dropout = 0.0
    invalidate_scan_cache(enc.layers)
    y_cold = enc(x).numpy()                   # must retrace with p=0.0
    enc.eval()
    y_eval = enc(x).numpy()
    np.testing.assert_allclose(y_cold, y_eval, rtol=1e-5, atol=1e-6)


def test_scan_fallback_paths():
    """KV-cache decode and the kill-switch flag fall back to the loop."""
    from paddle_tpu.nn import scan as nnscan

    paddle.seed(44)
    m = GPTForPretraining(gpt_tiny(num_layers=2, scan_layers=True))
    ids = Tensor(np.random.RandomState(0).randint(0, 256, (1, 8))
                 .astype(np.int32))
    out = m.generate(ids, max_new_tokens=4)
    assert out.shape[1] == 12

    nnscan.reset_scan_stats()
    from paddle_tpu.core.flags import flag_scope
    with flag_scope("scan_layers", False):
        with paddle.no_grad():
            m(ids)
        assert nnscan.SCAN_STATS["scan_calls"] == 0
    with paddle.no_grad():
        m(ids)
    assert nnscan.SCAN_STATS["scan_calls"] == 1
