"""Bench regression gate (tools/check_bench.py) — the analogue of the
reference's op-benchmark CI gate
(/root/reference/tools/check_op_benchmark_result.py:1)."""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import check_bench  # noqa: E402


def _m(name, value, unit):
    return {"metric": name, "value": value, "unit": unit,
            "vs_baseline": 1.0}


def test_throughput_regression_caught():
    old = [_m("bert_tokens_per_sec", 160000.0, "tokens/s")]
    new = [_m("bert_tokens_per_sec", 144000.0 * 0.99, "tokens/s")]  # -10.9%
    problems = check_bench.compare(old, new, tolerance=0.10)
    assert len(problems) == 1 and "bert_tokens_per_sec" in problems[0]


def test_throughput_within_tolerance_ok():
    old = [_m("bert_tokens_per_sec", 160000.0, "tokens/s")]
    new = [_m("bert_tokens_per_sec", 152000.0, "tokens/s")]   # -5%
    assert check_bench.compare(old, new, tolerance=0.10) == []


def test_time_metric_direction():
    """ms metrics regress when they GROW."""
    old = [_m("lenet_ms_per_step", 100.0, "ms")]
    slower = [_m("lenet_ms_per_step", 115.0, "ms")]
    faster = [_m("lenet_ms_per_step", 60.0, "ms")]
    assert check_bench.compare(old, slower, tolerance=0.10)
    assert check_bench.compare(old, faster, tolerance=0.10) == []


def test_disappeared_metric_flagged():
    old = [_m("a", 1.0, "tokens/s"), _m("b", 2.0, "tokens/s")]
    new = [_m("a", 1.0, "tokens/s")]
    problems = check_bench.compare(old, new)
    assert any("disappeared" in p for p in problems)


def test_new_metric_not_gated():
    old = [_m("a", 1.0, "tokens/s")]
    new = [_m("a", 1.0, "tokens/s"), _m("brand_new", 5.0, "img/s")]
    assert check_bench.compare(old, new) == []


def test_parses_driver_record_shapes(tmp_path):
    """Accepts the driver's BENCH_r{N}.json: parsed as single dict (r1-r4)
    and as a list (r5+); scrapes the tail when parsed is absent."""
    old_rec = {"n": 4, "rc": 0,
               "parsed": _m("bert_tokens_per_sec", 160000.0, "tokens/s")}
    new_rec = {"n": 5, "rc": 0,
               "parsed": [_m("bert_tokens_per_sec", 100000.0, "tokens/s"),
                          _m("gpt_tokens_per_sec", 40000.0, "tokens/s")]}
    po = tmp_path / "old.json"
    pn = tmp_path / "new.json"
    po.write_text(json.dumps(old_rec))
    pn.write_text(json.dumps(new_rec))
    rc = check_bench.main([str(po), str(pn)])
    assert rc == 1                                  # -37% regression

    tail_rec = {"n": 3, "rc": 0, "tail":
                'noise\n' + json.dumps(
                    _m("bert_tokens_per_sec", 99000.0, "tokens/s")) + "\n"}
    pt = tmp_path / "tail.json"
    pt.write_text(json.dumps(tail_rec))
    rc = check_bench.main([str(pt), str(pn)])      # 99k -> 100k: fine
    assert rc == 0


def test_cli_synthetic_10pct_regression(tmp_path):
    """End-to-end CLI: a synthetic 10%+ regression exits 1."""
    old = [_m("resnet50_imgs_per_sec", 1650.0, "img/s"),
           _m("gpt_tokens_per_sec", 40000.0, "tokens/s")]
    new = [_m("resnet50_imgs_per_sec", 1480.0, "img/s"),   # -10.3%
           _m("gpt_tokens_per_sec", 40500.0, "tokens/s")]
    po = tmp_path / "o.json"
    pn = tmp_path / "n.json"
    po.write_text(json.dumps(old))
    pn.write_text(json.dumps(new))
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "check_bench.py"), str(po), str(pn)],
        capture_output=True, text=True)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "resnet50_imgs_per_sec" in proc.stdout
    assert "gpt_tokens_per_sec" not in proc.stdout


def test_compare_common_ignores_skipped_benchmarks():
    """The in-run self-gate (bench.py) compares only the intersection: a
    --quick run (BERT only) against a full record logs NO false
    'disappeared' regressions, but a real regression in a common metric
    still fires."""
    full = [_m("bert_tokens_per_sec", 160000.0, "tokens/s"),
            _m("resnet50_imgs_per_sec", 2200.0, "img/s"),
            _m("lenet_eager_ms_per_step", 120.0, "ms")]
    quick_ok = [_m("bert_tokens_per_sec", 158000.0, "tokens/s")]
    assert check_bench.compare_common(full, quick_ok) == []

    quick_bad = [_m("bert_tokens_per_sec", 120000.0, "tokens/s")]  # -25%
    problems = check_bench.compare_common(full, quick_bad)
    assert len(problems) == 1 and "bert_tokens_per_sec" in problems[0]
    assert not any("disappeared" in p for p in problems)


def test_compare_still_flags_disappearance_for_cli_gate():
    """The CLI cross-record gate keeps the disappearance check."""
    old = [_m("a", 1.0, "tokens/s"), _m("b", 2.0, "tokens/s")]
    new = [_m("a", 1.0, "tokens/s")]
    assert any("disappeared" in p for p in check_bench.compare(old, new))
    assert check_bench.compare_common(old, new) == []


def test_weak_scaling_unit_gates_on_absolute_points():
    """weak% (weak-scaling efficiency, MULTICHIP record) is
    higher-is-better and gates on ABSOLUTE points: near-100 baselines
    must trip on a 9-point loss the relative band would hide."""
    old = [_m("multichip_weak_scaling_eff_pp2", 96.0, "weak%")]
    ok = [_m("multichip_weak_scaling_eff_pp2", 88.0, "weak%")]   # -8 pts
    bad = [_m("multichip_weak_scaling_eff_pp2", 85.0, "weak%")]  # -11 pts
    assert check_bench.compare(old, ok, tolerance=0.10) == []
    problems = check_bench.compare(old, bad, tolerance=0.10)
    assert len(problems) == 1 and "-11.0 points" in problems[0]
    # direction: efficiency IMPROVING never trips
    up = [_m("multichip_weak_scaling_eff_pp2", 99.9, "weak%")]
    assert check_bench.compare(old, up, tolerance=0.10) == []


def test_bubble_unit_gates_on_absolute_points_growth():
    """bubble% (pipeline idle share) regresses when it GROWS, on
    absolute points — a 0-baseline (pp=1) stays gateable."""
    old = [_m("multichip_1f1b_bubble_pct", 0.0, "bubble%")]
    ok = [_m("multichip_1f1b_bubble_pct", 9.0, "bubble%")]
    bad = [_m("multichip_1f1b_bubble_pct", 20.0, "bubble%")]
    assert check_bench.compare(old, ok, tolerance=0.10) == []
    problems = check_bench.compare(old, bad, tolerance=0.10)
    assert len(problems) == 1 and "+20.0 points" in problems[0]


def test_moe_balance_unit_gates_on_absolute_points_drop():
    """balance (MoE expert-load balance, BENCH_moe) is higher-is-better
    on ABSOLUTE points: a near-100 healthy baseline must trip when
    routing collapses onto few experts (a relative band would hide a
    9-point loss), and an improvement never trips."""
    old = [_m("moe_gpt2_tiny_8e_balance", 95.0, "balance")]
    ok = [_m("moe_gpt2_tiny_8e_balance", 87.0, "balance")]    # -8 pts
    bad = [_m("moe_gpt2_tiny_8e_balance", 80.0, "balance")]   # -15 pts
    assert check_bench.compare(old, ok, tolerance=0.10) == []
    problems = check_bench.compare(old, bad, tolerance=0.10)
    assert len(problems) == 1 and "-15.0 points" in problems[0]
    up = [_m("moe_gpt2_tiny_8e_balance", 99.0, "balance")]
    assert check_bench.compare(old, up, tolerance=0.10) == []


def test_moe_drop_unit_gates_on_absolute_points_growth():
    """drop% (MoE dropped-assignment share) regresses when it GROWS, on
    absolute points — the healthy 0% baseline stays gateable (a
    relative gate can never fire off a 0 baseline)."""
    old = [_m("moe_gpt2_tiny_8e_drop_pct", 0.0, "drop%")]
    ok = [_m("moe_gpt2_tiny_8e_drop_pct", 8.0, "drop%")]
    bad = [_m("moe_gpt2_tiny_8e_drop_pct", 25.0, "drop%")]
    assert check_bench.compare(old, ok, tolerance=0.10) == []
    problems = check_bench.compare(old, bad, tolerance=0.10)
    assert len(problems) == 1 and "+25.0 points" in problems[0]
    # direction: fewer drops never trips
    down = [_m("moe_gpt2_tiny_8e_drop_pct", 0.0, "drop%")]
    assert check_bench.compare(
        [_m("moe_gpt2_tiny_8e_drop_pct", 10.0, "drop%")], down,
        tolerance=0.10) == []


def test_recsys_hit_rate_unit_gates_on_absolute_points_drop():
    """hit% (recsys tier hit rates, BENCH_recsys) is higher-is-better
    on ABSOLUTE points: a hot tier can legitimately sit anywhere in
    0-100, so a relative band is meaningless and a collapse must trip
    even off a small baseline."""
    old = [_m("recsys_tier_hit_hbm_pct", 40.0, "hit%")]
    ok = [_m("recsys_tier_hit_hbm_pct", 32.0, "hit%")]     # -8 pts
    bad = [_m("recsys_tier_hit_hbm_pct", 25.0, "hit%")]    # -15 pts
    assert check_bench.compare(old, ok, tolerance=0.10) == []
    problems = check_bench.compare(old, bad, tolerance=0.10)
    assert len(problems) == 1 and "-15.0 points" in problems[0]
    # direction: a better hit rate never trips
    up = [_m("recsys_tier_hit_hbm_pct", 90.0, "hit%")]
    assert check_bench.compare(old, up, tolerance=0.10) == []


def test_spec_accept_unit_gates_on_absolute_points_drop():
    """accept% (speculative-decoding draft acceptance, BENCH_serve's
    serve_spec_accept_pct) is higher-is-better on ABSOLUTE points: a
    healthy acceptance rate can sit anywhere in 0-100 depending on how
    self-repetitive the workload is, so a relative band is meaningless
    and a collapse must trip even off a modest baseline."""
    old = [_m("serve_spec_accept_pct", 55.0, "accept%")]
    ok = [_m("serve_spec_accept_pct", 47.0, "accept%")]    # -8 pts
    bad = [_m("serve_spec_accept_pct", 40.0, "accept%")]   # -15 pts
    assert check_bench.compare(old, ok, tolerance=0.10) == []
    problems = check_bench.compare(old, bad, tolerance=0.10)
    assert len(problems) == 1 and "-15.0 points" in problems[0]
    # direction: better acceptance never trips
    up = [_m("serve_spec_accept_pct", 95.0, "accept%")]
    assert check_bench.compare(old, up, tolerance=0.10) == []


def test_serve_prefix_hit_rides_hit_pct_unit():
    """serve_prefix_hit_pct reuses the recsys hit% unit: absolute
    points, drop = regression (a fallen hit rate means shared-prefix
    traffic went back to paying full prefill)."""
    old = [_m("serve_prefix_hit_pct", 60.0, "hit%")]
    bad = [_m("serve_prefix_hit_pct", 45.0, "hit%")]       # -15 pts
    assert check_bench.compare(old, bad, tolerance=0.10)
    assert check_bench.compare(old, old, tolerance=0.10) == []


def test_goodput_unit_gates_on_absolute_points_drop():
    """goodput% (training goodput share, BENCH_train's
    train_goodput_pct) is higher-is-better on ABSOLUTE points: a point
    of wall-clock leaked into a badput bucket is the same loss whether
    the baseline sat at 99 or at 60, so the near-100 healthy baseline
    must trip on a drop the relative band would hide — and an
    improvement never trips."""
    old = [_m("train_goodput_pct", 92.0, "goodput%")]
    ok = [_m("train_goodput_pct", 84.0, "goodput%")]     # -8 pts
    bad = [_m("train_goodput_pct", 78.0, "goodput%")]    # -14 pts
    assert check_bench.compare(old, ok, tolerance=0.10) == []
    problems = check_bench.compare(old, bad, tolerance=0.10)
    assert len(problems) == 1 and "-14.0 points" in problems[0]
    up = [_m("train_goodput_pct", 99.0, "goodput%")]
    assert check_bench.compare(old, up, tolerance=0.10) == []


def test_recsys_examples_per_sec_is_rate_like():
    """examples/s (DLRM training/serving throughput) gates like
    tokens/s: relative, shrink = regression."""
    old = [_m("recsys_dlrm_examples_per_sec", 1000.0, "examples/s")]
    bad = [_m("recsys_dlrm_examples_per_sec", 850.0, "examples/s")]
    ok = [_m("recsys_dlrm_examples_per_sec", 1500.0, "examples/s")]
    assert check_bench.compare(old, bad, tolerance=0.10)
    assert check_bench.compare(old, ok, tolerance=0.10) == []


def test_recsys_dedup_ratio_is_higher_is_better():
    """ratio (dedup ratio — mean ids served per fetched row) regresses
    when it SHRINKS: a fallen ratio means the lookup stopped merging
    duplicate ids and row traffic grew."""
    old = [_m("recsys_dlrm_dedup_ratio", 3.0, "ratio")]
    bad = [_m("recsys_dlrm_dedup_ratio", 2.0, "ratio")]
    ok = [_m("recsys_dlrm_dedup_ratio", 3.2, "ratio")]
    assert check_bench.compare(old, bad, tolerance=0.10)
    assert check_bench.compare(old, ok, tolerance=0.10) == []
