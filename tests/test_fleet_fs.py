"""fleet.utils.fs: LocalFS + HDFSClient shell transport (reference:
distributed/fleet/utils/fs.py)."""

import pytest

from paddle_tpu.distributed.fleet.utils import HDFSClient, LocalFS
from paddle_tpu.distributed.fleet.utils.fs import (ExecuteError,
                                                   FSFileExistsError)


def test_localfs_roundtrip(tmp_path):
    fs = LocalFS()
    d = tmp_path / "ckpt"
    fs.mkdirs(str(d))
    assert fs.is_dir(str(d)) and fs.is_exist(str(d))
    f = d / "model.pdparams"
    fs.touch(str(f))
    assert fs.is_file(str(f))
    with pytest.raises(FSFileExistsError):
        fs.touch(str(f), exist_ok=False)
    (d / "sub").mkdir()
    dirs, files = fs.ls_dir(str(d))
    assert dirs == ["sub"] and files == ["model.pdparams"]
    assert fs.list_dirs(str(d)) == ["sub"]
    f.write_text("abc")
    assert fs.cat(str(f)) == "abc"
    fs.mv(str(f), str(d / "renamed"), overwrite=True)
    assert fs.is_file(str(d / "renamed"))
    with pytest.raises(FSFileExistsError):
        fs.mv(str(d / "sub"), str(d / "renamed"))
    # overwrite=True REPLACES an existing destination directory
    (d / "sub" / "inner.txt").write_text("x")
    (d / "dst").mkdir()
    (d / "dst" / "stale.txt").write_text("old")
    fs.mv(str(d / "sub"), str(d / "dst"), overwrite=True)
    assert fs.is_file(str(d / "dst" / "inner.txt"))
    assert not fs.is_exist(str(d / "dst" / "stale.txt"))
    # upload COPIES (the local source survives)
    src = d / "local.bin"
    src.write_text("data")
    fs.upload(str(src), str(d / "published.bin"))
    assert fs.is_file(str(src)) and fs.is_file(str(d / "published.bin"))
    fs.delete(str(d))
    assert not fs.is_exist(str(d))
    assert fs.need_upload_download() is False


def test_hdfs_client_without_cli_raises_cleanly():
    client = HDFSClient(hadoop_home="/nonexistent")
    with pytest.raises(ExecuteError, match="not found"):
        client.upload("/tmp/x", "/remote/x")
    assert client.need_upload_download() is True
