"""Giant-embedding recsys subsystem tests (ISSUE 12; docs/RECSYS.md).

Coverage map:
- ShardedEmbeddingTable: dedup-vs-naive parity (fwd + sparse grads,
  bitwise), manual shard_map path vs SparseTable reference on a ps-only
  mesh, counted fallbacks (kill switch + incapable mesh), cross-mesh
  checkpoint restore;
- the three-table pull/push parity fuzz (SparseTable vs SSDSparseTable
  vs ShardedEmbeddingTable on one id stream — the satellite pin);
- TieredEmbeddingTable: admission/eviction/promotion mechanics, hot-set
  device fast path, parity vs the untiered table, state_dict residency
  round-trip;
- DLRM + criteo-synthetic: loss decreases, tables stay out of the
  dense parameter set;
- RecsysEngine: deadlines, bounded-queue policies, overload detector
  hysteresis, outcome counters, batched-lookup dedup across requests;
- save/restore through the atomic checkpoint manifest incl. the
  chaos ``ckpt.write.torn`` fallback drill;
- monitor_report --recsys render + per-table HBM census.
"""

import os
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import recsys
from paddle_tpu.core.flags import flag_scope
from paddle_tpu.distributed import env as dist_env
from paddle_tpu.distributed.ps import SparseTable, SSDSparseTable
from paddle_tpu.distributed.spmd import make_mesh
from paddle_tpu.models.dlrm import DLRM, DLRMConfig, dlrm_tiny
from paddle_tpu.monitor import scoped_registry
from paddle_tpu.recsys import (CriteoSynthetic, RECSYS_STATS,
                               RecsysEngine, RecsysRequest,
                               RecsysServingConfig,
                               ShardedEmbeddingTable,
                               TieredEmbeddingTable, load_tables,
                               save_tables)
from paddle_tpu.serving.resilience import ServerOverloaded
from paddle_tpu.testing import chaos

pytestmark = pytest.mark.recsys

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


# ---------------------------------------------------------------------------
# ShardedEmbeddingTable
# ---------------------------------------------------------------------------

def test_dedup_lookup_parity_vs_naive_per_id_gather():
    """The dedup lookup (sort-unique -> one gather -> inverse permute)
    must be BIT-identical to the naive per-id gather, forward and
    through the sparse adagrad update — the kill switch is a parity
    oracle, not an approximation."""
    rng = np.random.default_rng(3)
    ids = rng.integers(0, 50, size=300)              # heavy duplication
    grads = rng.normal(size=(300, 8)).astype(np.float32)
    out = {}
    for dedup in (True, False):
        with flag_scope("recsys_dedup", dedup):
            t = ShardedEmbeddingTable(50, 8, lr=0.1, seed=4)
            rows = t.pull(ids)
            t.push(ids, grads)
            out[dedup] = (rows, t.state_dict())
    np.testing.assert_array_equal(out[True][0], out[False][0])
    np.testing.assert_array_equal(out[True][1]["data"],
                                  out[False][1]["data"])
    np.testing.assert_array_equal(out[True][1]["g2"],
                                  out[False][1]["g2"])
    # and the dedup path really fetched fewer rows
    with flag_scope("recsys_dedup", True):
        t = ShardedEmbeddingTable(50, 8, seed=4)
        t.pull(ids)
        assert t.rows_fetched < ids.size
        assert t.dedup_ratio > 2.0


@pytest.mark.multichip
def test_sharded_manual_path_parity_vs_sparse_table():
    """ps-only mesh: the explicit shard_map gather+psum program runs
    (no fallback) and matches the host SparseTable row-for-row through
    pulls and adagrad pushes."""
    mesh = make_mesh({"ps": 8})
    dist_env.set_mesh(mesh)
    sh = ShardedEmbeddingTable(100, 16, lr=0.1, seed=7)
    assert sh.num_shards == 8
    ref = SparseTable(100, 16, optimizer="adagrad", lr=0.1, seed=7)
    ref.load_state_dict({"data": sh.state_dict()["data"],
                         "g2": np.zeros(100, np.float32)})
    rng = np.random.default_rng(0)
    # pre-update rows are BIT-equal (one gather, no arithmetic)
    np.testing.assert_array_equal(sh.pull(np.arange(100)),
                                  ref.pull(np.arange(100)))
    for step in range(4):
        ids = rng.integers(0, 100, size=40)
        # post-update rows: XLA's row update vs numpy's is 1-ULP
        np.testing.assert_allclose(sh.pull(ids), ref.pull(ids),
                                   rtol=1e-6, atol=1e-7)
        g = rng.normal(size=(40, 16)).astype(np.float32)
        sh.push(ids, g)
        ref.push(ids, g)
    np.testing.assert_allclose(sh.state_dict()["data"], ref.data,
                               atol=5e-7)
    assert RECSYS_STATS["manual_lookups"] >= 4
    assert RECSYS_STATS["manual_updates"] >= 4
    assert RECSYS_STATS["fallbacks"] == 0


@pytest.mark.multichip
def test_sharded_kill_switch_auto_path_counted_and_equal():
    """FLAGS_recsys_sharded_lookup off on a ps mesh: the GSPMD auto
    path serves (counted flag_off fallback) and matches the manual
    program bit-for-bit."""
    mesh = make_mesh({"ps": 8})
    dist_env.set_mesh(mesh)
    ids = np.array([0, 9, 9, 42, 63, 63, 63, 7])
    g = np.random.default_rng(1).normal(size=(8, 8)).astype(np.float32)
    sh_m = ShardedEmbeddingTable(64, 8, lr=0.1, seed=2)
    rows_m = sh_m.pull(ids)
    sh_m.push(ids, g)
    with flag_scope("recsys_sharded_lookup", False), \
            pytest.warns(RuntimeWarning, match="GSPMD auto path"):
        sh_a = ShardedEmbeddingTable(64, 8, lr=0.1, seed=2)
        rows_a = sh_a.pull(ids)
        sh_a.push(ids, g)
    np.testing.assert_array_equal(rows_m, rows_a)
    np.testing.assert_allclose(sh_m.state_dict()["data"],
                               sh_a.state_dict()["data"], atol=5e-7)
    assert RECSYS_STATS["fallbacks"] >= 2          # pull + push
    assert RECSYS_STATS["auto_lookups"] >= 1


@pytest.mark.multichip
def test_sharded_fallback_counted_on_mixed_mesh(recwarn):
    """A mesh with another nontrivial axis cannot compile the manual
    program on XLA:CPU — the auto path serves with a counted
    backend_mesh fallback, results still correct vs the reference."""
    dist_env.set_mesh(make_mesh({"dp": 2, "ps": 4}))
    sh = ShardedEmbeddingTable(40, 4, lr=0.2, seed=9)
    assert sh.num_shards == 4
    ref = SparseTable(40, 4, optimizer="adagrad", lr=0.2, seed=9,
                      num_shards=1)
    ref.load_state_dict({"data": sh.state_dict()["data"],
                         "g2": np.zeros(40, np.float32)})
    ids = np.array([1, 1, 2, 39])
    np.testing.assert_allclose(sh.pull(ids), ref.pull(ids), atol=0)
    assert RECSYS_STATS["fallbacks"] >= 1
    assert RECSYS_STATS["manual_lookups"] == 0
    assert any("GSPMD auto path" in str(w.message) for w in recwarn.list)


@pytest.mark.multichip
def test_sharded_checkpoint_restores_across_mesh_layouts(tmp_path):
    """state_dict is global-row-order: a snapshot written on ps=8
    restores bit-exactly onto a mesh-less single-shard table."""
    dist_env.set_mesh(make_mesh({"ps": 8}))
    sh = ShardedEmbeddingTable(30, 4, lr=0.1, seed=1)
    sh.push([3, 3, 17], np.ones((3, 4), np.float32))
    expect = sh.state_dict()
    save_tables(str(tmp_path), {"emb": sh})
    dist_env.set_mesh(None)
    fresh = ShardedEmbeddingTable(30, 4, lr=0.1, seed=99)
    assert load_tables(str(tmp_path), {"emb": fresh}) is not None
    np.testing.assert_array_equal(fresh.state_dict()["data"],
                                  expect["data"])
    np.testing.assert_array_equal(fresh.state_dict()["g2"],
                                  expect["g2"])


def test_pull_push_parity_fuzz_three_tables(tmp_path):
    """The satellite pin: SparseTable, SSDSparseTable (cache small
    enough to spill) and ShardedEmbeddingTable driven by ONE seeded id
    stream stay row-equal through mixed pulls and pushes."""
    V, D = 64, 8
    rng = np.random.default_rng(42)
    base = rng.uniform(-0.3, 0.3, (V, D)).astype(np.float32)
    sp = SparseTable(V, D, optimizer="adagrad", lr=0.1)
    sp.load_state_dict({"data": base.copy(),
                        "g2": np.zeros(V, np.float32)})
    ssd = SSDSparseTable(V, D, cache_rows=16, optimizer="adagrad",
                         lr=0.1, path=str(tmp_path / "fuzz.log"))
    ssd.load_state_dict({"row_ids": np.arange(V), "data": base.copy(),
                         "g2": np.zeros(V, np.float32)})
    sh = ShardedEmbeddingTable(V, D, optimizer="adagrad", lr=0.1)
    sh.load_state_dict({"data": base.copy(),
                        "g2": np.zeros(V, np.float32)})
    for step in range(25):
        n = int(rng.integers(1, 48))
        ids = rng.integers(0, V, size=n)
        if step % 3 == 2:
            r_sp = sp.pull(ids)
            np.testing.assert_allclose(ssd.pull(ids), r_sp,
                                       rtol=1e-5, atol=2e-6)
            np.testing.assert_allclose(sh.pull(ids), r_sp,
                                       rtol=1e-5, atol=2e-6)
        else:
            g = rng.normal(size=(n, D)).astype(np.float32)
            sp.push(ids, g)
            ssd.push(ids, g)
            sh.push(ids, g)
    assert ssd.evict_count > 0                    # the spill really ran
    np.testing.assert_allclose(
        sh.state_dict()["data"], sp.data, rtol=1e-5, atol=2e-6)
    full = ssd.pull(np.arange(V))
    np.testing.assert_allclose(full, sp.data, rtol=1e-5, atol=2e-6)


# ---------------------------------------------------------------------------
# TieredEmbeddingTable
# ---------------------------------------------------------------------------

def test_tiered_admission_eviction_promotion_counters():
    t = TieredEmbeddingTable(1000, 8, hot_rows=4, admit_after=2,
                             lr=0.1, seed=1, name="tiers")
    recsys.register_table("tiers", t)
    t.pull(np.arange(10))                  # freq 1: nothing admitted
    assert t.stats["promotions"] == 0 and t.resident_hot_rows == 0
    t.pull(np.arange(10))                  # freq 2: admit, 4-slot LRU
    assert t.stats["promotions"] == 10
    # CLEAN rows (never pushed while hot) evict without a write-back:
    # the backing copy is still current, so evictions > demotions
    assert t.stats["evictions"] == 6 and t.stats["demotions"] == 0
    assert t.resident_hot_rows == 4
    # evicted rows still serve correctly from the backing copy
    out = t.pull(np.arange(10))
    assert out.shape == (10, 8)
    rates = t.hit_rates()
    assert abs(sum(rates.values()) - 100.0) < 1e-6
    assert rates["hbm"] > 0


def test_tiered_dirty_rows_demote_clean_rows_do_not():
    """Only a row UPDATED while hot pays the demotion write-back; a
    clean row's eviction is free (its backing copy is current) — and
    the dirty row's updated value survives the round trip."""
    t = TieredEmbeddingTable(1000, 4, hot_rows=2, admit_after=1,
                             optimizer="sgd", lr=1.0, name="dirty")
    base = t.pull([1, 2])                  # promote 1, 2 (clean)
    t.push([1], np.ones((1, 4), np.float32))     # 1 is now dirty
    want1 = t.pull([1]).copy()
    assert t.stats["demotions"] == 0
    t.pull([3, 4])                         # evict 1 AND 2
    assert t.stats["evictions"] == 2
    assert t.stats["demotions"] == 1       # only the dirty row wrote
    np.testing.assert_allclose(t.pull([1]), want1, rtol=1e-6)
    np.testing.assert_allclose(t.pull([2]), base[1:2], rtol=1e-6)


def test_tiered_parity_vs_untiered_sparse_table():
    """Hot rows update on device with the same adagrad math the host
    table applies — tiering must not change a single row's trajectory."""
    V, D = 200, 8
    rng = np.random.default_rng(5)
    base = rng.uniform(-0.2, 0.2, (V, D)).astype(np.float32)
    ref = SparseTable(V, D, optimizer="adagrad", lr=0.1)
    ref.load_state_dict({"data": base.copy(),
                         "g2": np.zeros(V, np.float32)})
    backing = SparseTable(V, D, optimizer="adagrad", lr=0.1)
    backing.load_state_dict({"data": base.copy(),
                             "g2": np.zeros(V, np.float32)})
    t = TieredEmbeddingTable(V, D, hot_rows=8, backing=backing,
                             admit_after=1, lr=0.1, name="par")
    for step in range(12):
        ids = rng.integers(0, V, size=24)
        np.testing.assert_allclose(t.pull(ids), ref.pull(ids),
                                   rtol=1e-5, atol=2e-6)
        g = rng.normal(size=(24, D)).astype(np.float32)
        t.push(ids, g)
        ref.push(ids, g)
    assert t.stats["promotions"] > 0 and t.stats["demotions"] > 0
    np.testing.assert_allclose(t.pull(np.arange(V)),
                               ref.pull(np.arange(V)),
                               rtol=1e-5, atol=3e-6)


def test_tiered_hot_set_serves_from_device():
    """Once the working set is resident, lookup() touches no backing
    tier: pure device gathers (the 'hot set at device speed' claim)."""
    t = TieredEmbeddingTable(100, 4, hot_rows=16, admit_after=1,
                             name="dev")
    ids = np.array([1, 2, 3, 4])
    t.pull(ids)                            # admit-on-first-touch
    before_pulls = t.backing.pull_count
    before_hbm = t.stats["hbm_hits"]
    rows = t.lookup(np.array([1, 2, 3, 4, 4, 1]))
    assert rows.shape == (6, 4)
    assert t.backing.pull_count == before_pulls     # no host fetch
    assert t.stats["hbm_hits"] > before_hbm


def test_tiered_state_dict_roundtrip_preserves_residency(tmp_path):
    """Round trip over a churned table. The restoring table shares the
    SEED (the SSDSparseTable contract: rows never UPDATED re-derive
    from the deterministic initializer rather than being materialized
    — and with clean evictions skipping the write-back, touched-but-
    never-pushed rows stay in that class)."""
    t = TieredEmbeddingTable(300, 4, hot_rows=8, admit_after=1,
                             lr=0.1, seed=3, name="rt")
    rng = np.random.default_rng(0)
    for _ in range(6):
        ids = rng.integers(0, 300, size=16)
        t.pull(ids)
        t.push(ids, rng.normal(size=(16, 4)).astype(np.float32))
    want = t.pull(np.arange(0, 300, 7))
    hot_before = t.resident_hot_rows
    state = t.state_dict()
    t2 = TieredEmbeddingTable(300, 4, hot_rows=8, admit_after=1,
                              lr=0.1, seed=3, name="rt2")
    t2.load_state_dict(state)
    assert t2.resident_hot_rows == hot_before
    np.testing.assert_allclose(t2.pull(np.arange(0, 300, 7)), want,
                               rtol=1e-5, atol=2e-6)


def test_tiered_ssd_ladder_spills_to_disk(tmp_path):
    """Default backing = SSDSparseTable: a working set larger than the
    host cache spills rows to the log and reads them back — all three
    tier counters move."""
    t = TieredEmbeddingTable(5000, 4, hot_rows=8, host_rows=32,
                             admit_after=2, name="ladder")
    rng = np.random.default_rng(2)
    for i in range(8):
        ids = np.concatenate([np.arange(6),               # hot head
                              rng.integers(0, 5000, size=60)])
        t.pull(ids)
        t.push(ids, rng.normal(size=(ids.size, 4)).astype(np.float32))
    s = t.stats
    assert s["hbm_hits"] > 0
    assert s["ssd_reads"] + s["lazy_inits"] > 0
    assert t.backing.evict_count > 0          # host -> ssd spills
    assert s["promotions"] > 0
    rows = t.tier_rows()
    assert rows["hbm"] > 0 and rows["host"] > 0 and rows["ssd"] > 0


# ---------------------------------------------------------------------------
# DLRM + criteo-synthetic
# ---------------------------------------------------------------------------

def test_criteo_synthetic_power_law_and_determinism():
    gen = CriteoSynthetic(num_dense=4, num_sparse=4, vocab_sizes=1000,
                          alpha=1.1, batch_size=512, seed=7)
    d1, i1, l1 = gen.batch(3)
    d2, i2, l2 = gen.batch(3)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_array_equal(d1, d2)
    np.testing.assert_array_equal(l1, l2)
    assert d1.shape == (512, 4) and i1.shape == (512, 4)
    assert set(np.unique(l1)) <= {0.0, 1.0}
    # power law: the top-10 ids take far more than their uniform share
    head_share = (i1 < 10).mean()
    assert head_share > 0.15, head_share          # uniform would be 1%


def test_dlrm_trains_and_tables_stay_sparse():
    paddle.seed(11)
    cfg = dlrm_tiny()
    model = DLRM(cfg, seed=0)
    gen = CriteoSynthetic(num_dense=cfg.num_dense,
                          num_sparse=cfg.num_sparse, vocab_sizes=512,
                          batch_size=64, seed=0)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    losses = []
    for i in range(25):
        dense, ids, labels = gen.batch(i)
        loss = model.loss(dense, ids, labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])
    # embedding tables are NOT dense Parameters: the PS discipline
    assert all("table" not in k for k, _ in model.named_parameters())
    assert all(t.push_count >= 25 for t in model.tables)
    assert model.last_timings["lookup_s"] > 0


def test_dlrm_through_tiered_and_sharded_tables():
    """The model composes with every table kind, including one shared
    table across slots."""
    paddle.seed(12)
    cfg = dlrm_tiny(num_sparse=3, vocab_sizes=256)
    shared = [ShardedEmbeddingTable(256, cfg.embedding_dim, lr=0.05)]
    m1 = DLRM(cfg, tables=shared)
    tiered = [TieredEmbeddingTable(256, cfg.embedding_dim, hot_rows=16,
                                   admit_after=1, name=f"s{f}")
              for f in range(3)]
    m2 = DLRM(cfg, tables=tiered)
    gen = CriteoSynthetic(num_dense=cfg.num_dense, num_sparse=3,
                          vocab_sizes=256, batch_size=32, seed=1)
    dense, ids, labels = gen.batch(0)
    for m in (m1, m2):
        loss = m.loss(dense, ids, labels)
        loss.backward()
        assert np.isfinite(float(loss))
    assert shared[0].push_count == 3          # one push per slot
    assert any(t.stats["promotions"] > 0 for t in tiered) or \
        all(t.backing.pull_count > 0 for t in tiered)


# ---------------------------------------------------------------------------
# serving
# ---------------------------------------------------------------------------

def _mk_model(num_sparse=3, vocab=256):
    cfg = dlrm_tiny(num_sparse=num_sparse, vocab_sizes=vocab)
    tables = [TieredEmbeddingTable(vocab, cfg.embedding_dim,
                                   hot_rows=32, admit_after=1,
                                   name=f"srv{f}")
              for f in range(num_sparse)]
    return DLRM(cfg, tables=tables), cfg


def _req(rng, cfg, K=5, vocab=256, **kw):
    return RecsysRequest(
        rng.normal(size=cfg.num_dense).astype(np.float32),
        rng.integers(0, vocab, size=(K, cfg.num_sparse)).astype(np.int64),
        **kw)


def test_serving_completes_and_ranks():
    model, cfg = _mk_model()
    eng = RecsysEngine(model, RecsysServingConfig(max_batch=4))
    rng = np.random.default_rng(0)
    with scoped_registry() as reg:
        states = [eng.submit(_req(rng, cfg, K=6)) for _ in range(5)]
        eng.run()
        assert all(st.outcome == "completed" for st in states)
        res = states[0].result
        assert res.scores.shape == (6,)
        # order really sorts by score, best first
        assert (np.diff(res.scores[res.order]) <= 1e-7).all()
        c = reg.get("recsys_requests_total")
        assert c.value(event="completed") == 5
        assert reg.get("recsys_lookup_seconds").count() > 0
        assert reg.get("recsys_e2e_seconds").count() == 5
    s = eng.metrics_summary()
    assert s["requests_completed"] == 5
    assert s["candidates_per_sec"] > 0


def test_serving_deadline_expires_before_lookup():
    model, cfg = _mk_model()
    eng = RecsysEngine(model, RecsysServingConfig())
    rng = np.random.default_rng(1)
    pulls_before = sum(t.pull_count for t in model.tables)
    with scoped_registry() as reg:
        st = eng.submit(_req(rng, cfg, deadline_s=-0.001))
        ok = eng.submit(_req(rng, cfg, deadline_s=60.0))
        eng.run()
        assert st.outcome == "expired" and st.result is None
        assert ok.outcome == "completed"
        # a blown deadline spent NO table bandwidth: exactly one pull
        # per table for the one live request's forward
        assert sum(t.pull_count for t in model.tables) - pulls_before \
            == len(model.tables)
        assert reg.get("recsys_requests_total").value(
            event="expired") == 1
        assert reg.get("recsys_deadline_slack_seconds").count() == 1


def test_serving_queue_policies():
    model, cfg = _mk_model()
    rng = np.random.default_rng(2)
    # reject-new: the newcomer bounces with a typed refusal
    eng = RecsysEngine(model, RecsysServingConfig(max_queue=2))
    eng.submit(_req(rng, cfg))
    eng.submit(_req(rng, cfg))
    with pytest.raises(ServerOverloaded) as e:
        eng.submit(_req(rng, cfg))
    assert e.value.reason == "queue_full"
    assert eng.stats["rejected"] == 1
    # drop-oldest: the oldest queued request is shed, newcomer admitted
    eng2 = RecsysEngine(model, RecsysServingConfig(
        max_queue=2, queue_policy="drop-oldest"))
    first = eng2.submit(_req(rng, cfg))
    eng2.submit(_req(rng, cfg))
    eng2.submit(_req(rng, cfg))
    assert first.outcome == "shed"
    assert eng2.stats["shed"] == 1 and eng2.queue_depth == 2


def test_serving_overload_detector_hysteresis():
    model, cfg = _mk_model()
    now = [0.0]
    eng = RecsysEngine(model, RecsysServingConfig(
        max_batch=1, overload_threshold_s=1.0, overload_alpha=1.0,
        overload_exit_frac=0.5), clock=lambda: now[0])
    rng = np.random.default_rng(3)
    eng.submit(_req(rng, cfg))
    eng.submit(_req(rng, cfg))
    now[0] = 5.0                      # head-of-queue delay 5s >> 1s
    eng.step()                        # observes, trips
    assert eng._overload.overloaded
    with pytest.raises(ServerOverloaded) as e:
        eng.submit(_req(rng, cfg))
    assert e.value.reason == "overload"
    eng.run()                         # drain the queue
    # idle engine: the submit-time zero-delay sample recovers it
    st = eng.submit(_req(rng, cfg))
    assert not eng._overload.overloaded
    eng.run()
    assert st.outcome == "completed"


def test_serving_batches_dedup_across_requests():
    """One engine step ranks many requests in ONE forward, so the
    table-level dedup window spans requests: shared hot ids cost one
    row fetch for the whole batch."""
    cfg = dlrm_tiny(num_sparse=2, vocab_sizes=128)
    tab = ShardedEmbeddingTable(128, cfg.embedding_dim)
    model = DLRM(cfg, tables=[tab])
    eng = RecsysEngine(model, RecsysServingConfig(max_batch=8))
    rng = np.random.default_rng(4)
    same = np.zeros((4, 2), np.int64)         # every candidate id 0
    for _ in range(6):
        eng.submit(RecsysRequest(
            rng.normal(size=cfg.num_dense).astype(np.float32),
            same.copy()))
    eng.step()
    # 6 requests x 4 candidates x 2 slots = 48 ids, 1 unique row; the
    # shared table sees 2 lookups (one per slot) of 24 ids each
    assert tab.ids_seen == 48
    assert tab.rows_fetched == 2
    assert eng.stats["completed"] == 6


def test_serving_fault_isolation_poisoned_request_fails_alone():
    """A request whose candidates make the model raise (out-of-range
    ids against a range-validating table) must land outcome 'failed'
    while its batch-mates complete — every submitted request gets a
    terminal outcome (the PR 8 accounting discipline)."""
    cfg = dlrm_tiny(num_sparse=2, vocab_sizes=64)
    model = DLRM(cfg, tables=[ShardedEmbeddingTable(64, cfg.embedding_dim)])
    eng = RecsysEngine(model, RecsysServingConfig(max_batch=8))
    rng = np.random.default_rng(7)
    good = [eng.submit(_req(rng, cfg, K=3, vocab=64)) for _ in range(3)]
    bad_ids = np.array([[1, 64], [2, 3], [4, 5]], np.int64)  # 64 = OOR
    bad = eng.submit(RecsysRequest(
        rng.normal(size=cfg.num_dense).astype(np.float32), bad_ids))
    with scoped_registry() as reg:
        eng.run()
        assert reg.get("recsys_requests_total").value(
            event="failed") == 1
    assert bad.outcome == "failed" and "64" in bad.failure
    assert all(st.outcome == "completed" for st in good)
    assert eng.stats["failed"] == 1
    assert eng.metrics_summary()["requests_failed"] == 1


def test_sharded_push_rejects_out_of_range_ids():
    """push validates like pull: the manual program clips local
    indices for its pad rows, so an out-of-range id would silently
    update the wrong row — it must raise instead."""
    t = ShardedEmbeddingTable(32, 4)
    with pytest.raises(ValueError, match="outside"):
        t.push([32], np.ones((1, 4), np.float32))
    with pytest.raises(ValueError, match="outside"):
        t.pull([-1])


# ---------------------------------------------------------------------------
# checkpoint manifest + chaos
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_table_snapshot_torn_commit_falls_back(tmp_path):
    """A torn write racing the commit (chaos ckpt.write.torn) must not
    pass for a snapshot: load_tables falls back to the previous valid
    one — the PR 5 reader discipline on table state."""
    t = TieredEmbeddingTable(400, 8, hot_rows=16, admit_after=1,
                             lr=0.1, seed=0, name="ck")
    ids = np.arange(12)
    t.pull(ids)
    t.push(ids, np.ones((12, 8), np.float32))
    good = t.pull(ids).copy()
    save_tables(str(tmp_path), {"ck": t})
    t.push(ids, np.ones((12, 8), np.float32))
    with chaos.chaos_scope("ckpt.write.torn@1"):
        save_tables(str(tmp_path), {"ck": t})
    t.push(ids, np.ones((12, 8), np.float32))
    fresh = TieredEmbeddingTable(400, 8, hot_rows=16, admit_after=1,
                                 lr=0.1, seed=9, name="ck2")
    path = load_tables(str(tmp_path), {"ck": fresh})
    assert path is not None and path.endswith("tables_1")
    np.testing.assert_allclose(fresh.pull(ids), good, rtol=1e-5,
                               atol=2e-6)


def test_load_tables_empty_root_is_noop(tmp_path):
    t = ShardedEmbeddingTable(10, 4, seed=0)
    before = t.state_dict()["data"].copy()
    assert load_tables(str(tmp_path / "nothing"), {"t": t}) is None
    np.testing.assert_array_equal(t.state_dict()["data"], before)


# ---------------------------------------------------------------------------
# telemetry / tools
# ---------------------------------------------------------------------------

def test_tier_metrics_publish_and_report_render(tmp_path):
    sys.path.insert(0, TOOLS)
    try:
        import monitor_report
    finally:
        sys.path.remove(TOOLS)
    t = TieredEmbeddingTable(500, 8, hot_rows=4, admit_after=1,
                             name="rpt")
    recsys.register_table("rpt", t)
    rng = np.random.default_rng(0)
    for _ in range(4):
        t.pull(rng.integers(0, 500, size=32))
    with scoped_registry() as reg:
        t.publish_tier_metrics()
        recsys.publish_table_hbm()
        assert reg.get("recsys_table_rows") is not None
        assert reg.get("recsys_tier_hits_total") is not None
        hbm = reg.get("recsys_table_hbm_bytes")
        assert hbm.value(table="rpt") == t.hbm_bytes() > 0
        path = str(tmp_path / "m.jsonl")
        reg.dump_jsonl(path)
    from paddle_tpu.monitor import load_jsonl
    out = monitor_report.render(load_jsonl(path), recsys=True)
    assert "Recsys embedding tiers" in out
    assert "rpt" in out
    # counters are delta-published: a second publish with no new
    # traffic must not double-count
    with scoped_registry() as reg:
        t.publish_tier_metrics()
        t.publish_tier_metrics()
        c = reg.get("recsys_tier_promotions_total")
        assert c is None or c.value(table="rpt") == 0


def test_publish_table_hbm_skips_dead_arrays():
    t = TieredEmbeddingTable(100, 8, hot_rows=4, name="dead")
    recsys.register_table("dead", t)
    t._hot = None                 # drop the device buffer
    t._hot_g2 = None
    with scoped_registry():
        out = recsys.publish_table_hbm()
    assert out["dead"] == 0


@pytest.mark.slow
def test_bench_recsys_full_leg_contract():
    """The FULL DLRM bench leg (dlrm_criteo_small: 8 x 200k-row tables
    over a hot-tier-exceeding budget + the serving leg) — multi-minute,
    hence `slow`; tier-1 runs the unit-level pins above instead. The
    record contract: every metric line carries the units check_bench
    knows, the dedup parity pin ran, and spill/promotion activity is
    nonzero (bench_recsys raises otherwise)."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, root)
    try:
        import bench
    finally:
        sys.path.remove(root)
    lines = bench.bench_recsys(quick=False)
    by_name = {m["metric"]: m for m in lines}
    assert by_name["recsys_dlrm_criteo_small_examples_per_sec"][
        "unit"] == "examples/s"
    assert by_name["recsys_tier_hit_hbm_pct"]["unit"] == "hit%"
    assert by_name["recsys_dlrm_criteo_small_dedup_ratio"]["value"] > 1.0
    assert by_name["recsys_serve_availability_pct"]["value"] > 0


def test_recsys_reset_closes_registered_tables(tmp_path):
    t = TieredEmbeddingTable(100, 4, hot_rows=4, name="closing")
    path = t.backing.path
    recsys.register_table("closing", t)
    assert os.path.exists(path)
    recsys.reset()
    assert not os.path.exists(path)       # owned tmp SSD log removed
    assert recsys.tables() == {}
