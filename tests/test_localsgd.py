"""LocalSGD / adaptive LocalSGD (reference:
fleet/meta_optimizers/localsgd_optimizer.py): k local steps between
parameter averages over the dp axis, compiled as shard_map programs with
per-replica parameter copies."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import functional as F


def _mesh(n, axis="dp"):
    import jax
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:n])
    return Mesh(devs, (axis,))


def _data(rng, B=32):
    x = rng.normal(size=(B, 4)).astype(np.float32)
    y = (x.sum(axis=1) > 0).astype(np.int64)
    return x, y


def _model():
    paddle.seed(3)
    return nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))


def test_localsgd_k1_equals_sync_sgd():
    """k=1 LocalSGD (local step then average) is EXACTLY synchronous SGD
    for linear optimizers: avg(p - lr*g_i) == p - lr*avg(g_i)."""
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        LocalSGDTrainStep)
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.optimizer import SGD

    def loss_fn(layer, x, y):
        return F.cross_entropy(layer(x), y)

    m1 = _model()
    local = LocalSGDTrainStep(m1, loss_fn,
                              SGD(learning_rate=0.1), _mesh(4),
                              k_steps=1)
    m2 = _model()
    sync = TrainStep(m2, loss_fn, SGD(learning_rate=0.1))

    rng = np.random.default_rng(0)
    for _ in range(4):
        x, y = _data(rng)
        l_local = float(local(x, y))
        l_sync = float(sync(x, y))
        np.testing.assert_allclose(l_local, l_sync, rtol=1e-5, atol=1e-6)
    local.sync_to_layer()
    for (k, p1), (_, p2) in zip(m1.named_parameters(),
                                m2.named_parameters()):
        np.testing.assert_allclose(np.asarray(p1._data),
                                   np.asarray(sync.params.get(
                                       k, p2._data)),
                                   rtol=1e-4, atol=1e-5)


def test_localsgd_k4_converges():
    """k=4 LocalSGD diverges between syncs but still learns the task —
    final loss tracks synchronous SGD (reference's acceptance bar)."""
    import jax
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 virtual devices")
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        LocalSGDTrainStep)
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.optimizer import SGD

    def loss_fn(layer, x, y):
        return F.cross_entropy(layer(x), y)

    local = LocalSGDTrainStep(_model(), loss_fn,
                              SGD(learning_rate=0.2), _mesh(4),
                              k_steps=4)
    sync = TrainStep(_model(), loss_fn, SGD(learning_rate=0.2))

    rng = np.random.default_rng(1)
    l_loc = l_syn = None
    first = None
    for i in range(24):
        x, y = _data(rng, B=64)
        l_loc = float(local(x, y))
        l_syn = float(sync(x, y))
        if first is None:
            first = l_loc
    assert l_loc < first * 0.7, (first, l_loc)
    assert l_loc < l_syn * 1.5 + 0.1, (l_loc, l_syn)


def test_adaptive_localsgd_adjusts_k():
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 virtual devices")
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        LocalSGDTrainStep)
    from paddle_tpu.optimizer import SGD

    def loss_fn(layer, x, y):
        return F.cross_entropy(layer(x), y)

    step = LocalSGDTrainStep(_model(), loss_fn, SGD(learning_rate=0.3),
                             _mesh(2), k_steps=8, adaptive=True,
                             max_k_steps=8)
    rng = np.random.default_rng(2)
    for _ in range(32):
        x, y = _data(rng, B=64)
        step(x, y)
    # as the loss falls, AdaComm shrinks the sync interval
    assert step.k_steps < 8, step.k_steps


def test_strategy_localsgd_wires_trainstep():
    """The full fleet path: strategy.localsgd=True → fleet.init →
    distributed_optimizer → TrainStep builds a LocalSGDTrainStep; at k=1
    it matches synchronous SGD exactly (no decorative config keys)."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 virtual devices")
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        LocalSGDTrainStep)
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.optimizer import SGD

    def loss_fn(layer, x, y):
        return F.cross_entropy(layer(x), y)

    strategy = fleet.DistributedStrategy()
    strategy.localsgd = True
    strategy.localsgd_configs = {"k_steps": 1}
    fleet.init(is_collective=True, strategy=strategy)
    opt = fleet.distributed_optimizer(SGD(learning_rate=0.1))
    m1 = _model()
    step = TrainStep(m1, loss_fn, opt)
    assert isinstance(step, LocalSGDTrainStep)
    assert step.k_steps == 1

    m2 = _model()
    sync = TrainStep(m2, loss_fn, SGD(learning_rate=0.1))
    assert not isinstance(sync, LocalSGDTrainStep)

    rng = np.random.default_rng(7)
    for _ in range(3):
        x, y = _data(rng)
        l_local = float(step(x, y))
        l_sync = float(sync(x, y))
        np.testing.assert_allclose(l_local, l_sync, rtol=1e-5, atol=1e-6)


def test_localsgd_updates_buffers():
    """BN running stats must not freeze under LocalSGD training — buffer
    writes thread through the shard_map carry and are replica-averaged."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs >=2 virtual devices")
    from paddle_tpu.distributed.fleet.meta_optimizers import (
        LocalSGDTrainStep)
    from paddle_tpu.optimizer import SGD

    paddle.seed(5)
    model = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8),
                          nn.ReLU(), nn.Linear(8, 2))

    def loss_fn(layer, x, y):
        return F.cross_entropy(layer(x), y)

    step = LocalSGDTrainStep(model, loss_fn, SGD(learning_rate=0.1),
                             _mesh(2), k_steps=2)
    mean0 = {k: np.asarray(v) for k, v in step.buffers.items()
             if "_mean" in k}
    assert mean0, "model has no BN running-mean buffer?"
    rng = np.random.default_rng(9)
    for _ in range(2):
        x, y = _data(rng)
        step(x, y)
    moved = any(not np.array_equal(np.asarray(step.buffers[k]), v)
                for k, v in mean0.items())
    assert moved, "BN running stats froze during LocalSGD training"
