"""hapi StaticGraphAdapter + fleet-distributed fit (reference:
python/paddle/hapi/model.py:247 StaticGraphAdapter, :666
DynamicGraphAdapter's fleet wrapping)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit.input_spec import InputSpec


class _ToyDS(paddle.io.Dataset):
    """Linearly-separable 2-class blobs: converges fast and exactly."""

    def __init__(self, n=256, seed=0):
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(n, 8)).astype(np.float32)
        self.y = (self.x[:, :4].sum(axis=1) >
                  self.x[:, 4:].sum(axis=1)).astype(np.int64)

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


def _net():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 2))


class _LossCb(paddle.callbacks.Callback):
    def __init__(self):
        self.losses = []

    def on_train_batch_end(self, step, logs=None):
        self.losses.append(float(logs["loss"]))


def _fit(static: bool, epochs=2):
    try:
        if static:
            paddle.enable_static()
        net = _net()
        model = paddle.Model(net,
                             inputs=[InputSpec([None, 8], "float32", "x")],
                             labels=[InputSpec([None], "int64", "y")])
        cb = _LossCb()
        model.prepare(
            optimizer=paddle.optimizer.SGD(learning_rate=0.5),
            loss=nn.CrossEntropyLoss())
        model.fit(_ToyDS(), epochs=epochs, batch_size=32, verbose=0,
                  shuffle=False, callbacks=[cb])
        return model, cb.losses
    finally:
        paddle.disable_static()


def test_static_fit_trains_and_matches_eager():
    """MNIST-style fit parity: the SAME init/data/optimizer trained via the
    recorded-Program Executor path and via the eager TrainStep path produce
    the SAME loss curve, step for step."""
    m_static, losses_s = _fit(static=True)
    assert m_static._adapter is not None        # static path actually used
    m_eager, losses_e = _fit(static=False)
    assert m_eager._adapter is None
    assert len(losses_s) == len(losses_e) > 0
    np.testing.assert_allclose(losses_s, losses_e, rtol=1e-4, atol=1e-5)
    assert losses_s[-1] < losses_s[0] * 0.5     # it actually learned


def test_static_evaluate_and_predict():
    try:
        paddle.enable_static()
        net = _net()
        model = paddle.Model(net,
                             inputs=[InputSpec([None, 8], "float32", "x")],
                             labels=[InputSpec([None], "int64", "y")])
        model.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.5),
                      loss=nn.CrossEntropyLoss(),
                      metrics=[paddle.metric.Accuracy()])
        ds = _ToyDS(n=128)
        model.fit(ds, epochs=3, batch_size=32, verbose=0)
        res = model.evaluate(_ToyDS(n=64, seed=1), batch_size=32,
                             verbose=0)
        assert "loss" in res and "acc" in res
        assert res["acc"] > 0.8, res
        preds = model.predict(_ToyDS(n=32, seed=2), batch_size=16,
                              stack_outputs=True)
        assert preds[0].shape == (32, 2)
    finally:
        paddle.disable_static()


def test_static_mode_requires_input_specs():
    try:
        paddle.enable_static()
        model = paddle.Model(_net())
        with pytest.raises(ValueError, match="InputSpec"):
            model.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1),
                          loss=nn.CrossEntropyLoss())
    finally:
        paddle.disable_static()


def test_fleet_distributed_fit():
    """fleet.init + Model.fit: the train step runs SPMD over the hybrid
    mesh with the batch sharded on dp (reference: hapi/model.py:666)."""
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.distributed import fleet

    fleet.init(is_collective=True)
    net = _net()
    model = paddle.Model(net)
    cb = _LossCb()
    model.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.5),
                  loss=nn.CrossEntropyLoss())
    assert model._train_step.mesh is not None
    assert tuple(model._train_step.data_spec) == tuple(P("dp"))
    model.fit(_ToyDS(), epochs=2, batch_size=32, verbose=0, shuffle=False,
              callbacks=[cb], drop_last=True)
    assert cb.losses[-1] < cb.losses[0] * 0.5

    # loss parity vs a single-device fit from the same init/data
    m2, losses2 = _fit(static=False)
    np.testing.assert_allclose(cb.losses[:4], losses2[:4], rtol=1e-4,
                               atol=1e-5)
