"""Recorded static Program: program_guard op capture + Executor feed/fetch
replay + minimize training (reference: fluid/framework.py Program,
executor.py, the classic declarative workflow)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, static


def test_feed_fetch_replay():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = nn.Linear(4, 3)
        y = lin(x)
        z = y * 2.0
    exe = static.Executor()
    feed_x = np.random.default_rng(0).normal(size=(5, 4)).astype(np.float32)
    out, out2 = exe.run(main, feed={"x": feed_x}, fetch_list=[y, z])
    ref = feed_x @ np.asarray(lin.weight._data) + np.asarray(lin.bias._data)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(out2, 2 * ref, atol=1e-5, rtol=1e-5)
    # different batch size than the build-time placeholder (None -> 1)
    feed_b = np.ones((7, 4), np.float32)
    (outb,) = exe.run(main, feed={"x": feed_b}, fetch_list=[y])
    assert outb.shape == (7, 3)


def test_minimize_trains_linear_regression():
    paddle.seed(0)
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 2], "float32")
        yt = static.data("y", [None, 1], "float32")
        lin = nn.Linear(2, 1)
        pred = lin(x)
        loss = ((pred - yt) ** 2).mean()
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)

    exe = static.Executor()
    exe.run(startup)                    # no-op, API parity
    rng = np.random.default_rng(1)
    true_w = np.array([[2.0], [-3.0]], np.float32)
    losses = []
    for _ in range(60):
        xb = rng.normal(size=(32, 2)).astype(np.float32)
        yb = xb @ true_w + 1.0
        (lv,) = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.05, losses[::20]
    np.testing.assert_allclose(np.asarray(lin.weight._data), true_w,
                               atol=0.2)


def test_unknown_feed_and_bad_fetch_errors():
    import pytest

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        y = x + 1.0
    exe = static.Executor()
    with pytest.raises(KeyError):
        exe.run(main, feed={"bogus": np.ones((2, 2), np.float32)},
                fetch_list=[y])
    with pytest.raises(TypeError):
        exe.run(main, feed={"x": np.ones((2, 2), np.float32)},
                fetch_list=["y"])


def test_recording_does_not_leak_outside_guard():
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [2, 2], "float32")
        _ = x * 3.0
    n = len(main._ops)
    t = paddle.to_tensor(np.ones((2, 2), np.float32))
    _ = t * 5.0                          # outside: must NOT be recorded
    assert len(main._ops) == n


def test_missing_feed_raises_and_leaves_stay_fresh():
    import pytest

    main = static.Program()
    with static.program_guard(main):
        a = static.data("a", [2, 2], "float32")
        b = static.data("b", [2, 2], "float32")
        z = a + b
    exe = static.Executor()
    with pytest.raises(KeyError, match="were not fed"):
        exe.run(main, feed={"a": np.ones((2, 2), np.float32)},
                fetch_list=[z])

    # a captured (leaf) tensor is re-read each run, not baked at trace
    main2 = static.Program()
    scale = paddle.to_tensor(np.ones((2, 2), np.float32))
    with static.program_guard(main2):
        x = static.data("x", [2, 2], "float32")
        y = x * scale
    (o1,) = exe.run(main2, feed={"x": np.ones((2, 2), np.float32)},
                    fetch_list=[y])
    scale._data = scale._data * 3.0
    (o2,) = exe.run(main2, feed={"x": np.ones((2, 2), np.float32)},
                    fetch_list=[y])
    np.testing.assert_allclose(o1, 1.0)
    np.testing.assert_allclose(o2, 3.0)


def test_bn_buffer_writes_replay_under_executor():
    """A BN conv net trained via Executor.run must update running stats
    exactly as its eager twin (VERDICT r3 #2; reference executor.cc:170
    runs the stat-update ops of the program like any other op)."""
    from paddle_tpu.nn import functional as F

    def make():
        paddle.seed(7)
        return nn.Sequential(
            nn.Conv2D(1, 4, 3, padding=1), nn.BatchNorm2D(4), nn.ReLU(),
            nn.Flatten(), nn.Linear(4 * 8 * 8, 2))

    net_s = make()
    net_e = make()
    net_s.train()
    net_e.train()

    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 1, 8, 8], "float32")
        yt = static.data("y", [None], "int64")
        loss = F.cross_entropy(net_s(x), yt)
        paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)

    # the build pass ran on placeholder zeros: recorded state must be
    # untouched (reference Program building does not execute)
    bn_s = net_s[1]
    np.testing.assert_allclose(bn_s._mean.numpy(), 0.0)
    np.testing.assert_allclose(bn_s._variance.numpy(), 1.0)

    exe = static.Executor()
    opt_e = paddle.optimizer.SGD(learning_rate=0.05,
                                 parameters=net_e.parameters())
    rng = np.random.default_rng(0)
    for i in range(5):
        xb = rng.normal(size=(16, 1, 8, 8)).astype(np.float32)
        yb = rng.integers(0, 2, (16,)).astype(np.int64)
        (ls,) = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        le = F.cross_entropy(net_e(paddle.to_tensor(xb)),
                             paddle.to_tensor(yb))
        le.backward()
        opt_e.step()
        opt_e.clear_grad()
        np.testing.assert_allclose(float(ls), float(le), rtol=1e-4,
                                   atol=1e-5)

    bn_e = net_e[1]
    # stats moved off their init AND match the eager twin step for step
    assert not np.allclose(bn_s._mean.numpy(), 0.0)
    assert not np.allclose(bn_s._variance.numpy(), 1.0)
    np.testing.assert_allclose(bn_s._mean.numpy(), bn_e._mean.numpy(),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(bn_s._variance.numpy(),
                               bn_e._variance.numpy(), rtol=1e-4, atol=1e-6)

    # eval-mode replay of the SAME weights agrees once stats are synced
    net_s.eval()
    infer = static.Program()
    with static.program_guard(infer):
        xi = static.data("x", [None, 1, 8, 8], "float32")
        logits = net_s(xi)
    xb = rng.normal(size=(4, 1, 8, 8)).astype(np.float32)
    (out_s,) = exe.run(infer, feed={"x": xb}, fetch_list=[logits])
    net_e.eval()
    out_e = net_e(paddle.to_tensor(xb)).numpy()
    np.testing.assert_allclose(out_s, out_e, rtol=1e-4, atol=1e-5)


def test_clone_for_test_swaps_train_ops():
    """clone(for_test=True) must strip stat writes AND swap BN/dropout to
    eval behavior (reference: Program.clone flips is_test), so repeated
    inference neither corrupts running stats nor applies dropout."""
    from paddle_tpu.nn import functional as F

    paddle.seed(5)
    net = nn.Sequential(nn.Linear(3, 8), nn.BatchNorm1D(8), nn.ReLU(),
                        nn.Dropout(0.5), nn.Linear(8, 2))
    net.train()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 3], "float32")
        yt = static.data("y", [None], "int64")
        logits = net(x)
        loss = F.cross_entropy(logits, yt)
        paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)

    test_prog = main.clone(for_test=True)
    exe = static.Executor()
    rng = np.random.default_rng(4)
    xb = rng.normal(size=(8, 3)).astype(np.float32)
    yb = rng.integers(0, 2, (8,)).astype(np.int64)
    exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
    bn = net[1]
    mean_after_train = bn._mean.numpy().copy()

    # eval runs: deterministic (no dropout), stats untouched, and BN
    # normalizes with RUNNING stats (eager eval twin agrees)
    (o1,) = exe.run(test_prog, feed={"x": xb, "y": yb},
                    fetch_list=[logits])
    (o2,) = exe.run(test_prog, feed={"x": xb, "y": yb},
                    fetch_list=[logits])
    np.testing.assert_allclose(o1, o2)
    np.testing.assert_allclose(bn._mean.numpy(), mean_after_train)
    net.eval()
    np.testing.assert_allclose(o1, net(paddle.to_tensor(xb)).numpy(),
                               rtol=1e-4, atol=1e-5)
    net.train()


def test_bn_convergence_under_executor():
    """Book-style convergence: BN net under Executor.run learns a separable
    task and its eval accuracy uses the trained running stats."""
    from paddle_tpu.nn import functional as F

    paddle.seed(11)
    net = nn.Sequential(nn.Linear(2, 16), nn.BatchNorm1D(16), nn.ReLU(),
                        nn.Linear(16, 2))
    net.train()
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        yt = static.data("y", [None], "int64")
        loss = F.cross_entropy(net(x), yt)
        paddle.optimizer.Adam(learning_rate=0.01).minimize(loss)
    exe = static.Executor()
    rng = np.random.default_rng(2)
    losses = []
    for _ in range(60):
        xb = rng.normal(size=(64, 2)).astype(np.float32) + 0.5
        yb = (xb[:, 0] + xb[:, 1] > 1.0).astype(np.int64)
        (lv,) = exe.run(main, feed={"x": xb, "y": yb}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < 0.25 < losses[0], (losses[0], losses[-1])
    # running stats converged near the true feed distribution (mean ~0.5)
    bn = net[1]
    assert not np.allclose(bn._mean.numpy(), 0.0)


def test_empty_program_fetch_errors():
    import pytest

    empty = static.Program()
    exe = static.Executor()
    assert exe.run(empty) == []
    with pytest.raises(ValueError, match="no recorded ops"):
        t = paddle.to_tensor(np.ones((1,), np.float32))
        exe.run(empty, fetch_list=[t])


def test_recorded_cond_replays_under_executor():
    """A tensor-dependent branch records as ONE op replaying both
    sub-programs inside lax.cond (reference: conditional_block_op.cc:1
    sub-block execution); eager build and Executor replay agree and the
    branch responds to the FED predicate, not the build-time one."""
    paddle.seed(0)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        lin = nn.Linear(4, 4)
        h = lin(x)
        pred = (h.sum() > 0.0)
        out = static.nn.cond(pred,
                             lambda: h * 2.0,
                             lambda: h - 1.0)
    exe = static.Executor()
    rng = np.random.default_rng(0)
    xb = rng.normal(size=(3, 4)).astype(np.float32)
    (o,) = exe.run(main, feed={"x": xb}, fetch_list=[out])
    hb = xb @ np.asarray(lin.weight._data) + np.asarray(lin.bias._data)
    want = hb * 2.0 if hb.sum() > 0 else hb - 1.0
    np.testing.assert_allclose(o, want, atol=1e-5, rtol=1e-5)
    # the OTHER branch: feed driving the predicate negative/positive
    xb2 = -xb if hb.sum() > 0 else xb
    (o2,) = exe.run(main, feed={"x": xb2}, fetch_list=[out])
    hb2 = xb2 @ np.asarray(lin.weight._data) + np.asarray(lin.bias._data)
    want2 = hb2 * 2.0 if hb2.sum() > 0 else hb2 - 1.0
    np.testing.assert_allclose(o2, want2, atol=1e-5, rtol=1e-5)


def test_recorded_while_replays_under_executor():
    """A while_loop records as one op replaying cond/body sub-programs in
    lax.while_loop (reference: while_op.cc:1); the iteration count follows
    the FED value at replay time."""
    main = static.Program()
    with static.program_guard(main):
        n = static.data("n", [], "int32")
        i = paddle.to_tensor(np.int32(0))
        s = paddle.to_tensor(np.float32(0.0))
        i_out, s_out = static.nn.while_loop(
            lambda i, s: i < n,
            lambda i, s: [i + 1, s + 2.0],
            [i, s])
    exe = static.Executor()
    (iv, sv) = exe.run(main, feed={"n": np.int32(5)},
                       fetch_list=[i_out, s_out])
    assert int(iv) == 5 and float(sv) == 10.0
    (iv2, sv2) = exe.run(main, feed={"n": np.int32(3)},
                         fetch_list=[i_out, s_out])
    assert int(iv2) == 3 and float(sv2) == 6.0


def test_recorded_cond_trains_through_branch():
    """Gradients flow to parameters captured inside a recorded branch:
    minimize over a program whose loss passes through static.nn.cond."""
    paddle.seed(3)
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 2], "float32")
        yt = static.data("y", [None, 1], "float32")
        lin = nn.Linear(2, 1)
        use_double = static.data("d", [], "bool")
        pred_v = static.nn.cond(use_double,
                                lambda: lin(x) * 2.0,
                                lambda: lin(x))
        loss = ((pred_v - yt) ** 2).mean()
        paddle.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = static.Executor()
    rng = np.random.default_rng(4)
    true_w = np.array([[1.5], [-0.5]], np.float32)
    losses = []
    for _ in range(80):
        xb = rng.normal(size=(32, 2)).astype(np.float32)
        yb = 2.0 * (xb @ true_w)
        (lv,) = exe.run(main, feed={"x": xb, "y": yb,
                                    "d": np.bool_(True)},
                        fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < losses[0] * 0.05, losses[::20]
    np.testing.assert_allclose(np.asarray(lin.weight._data), true_w,
                               atol=0.25)


def test_recorded_branch_rejects_buffer_writes():
    import pytest
    main = static.Program()
    with static.program_guard(main):
        x = static.data("x", [None, 4], "float32")
        bn = nn.BatchNorm1D(4)
        bn.train()
        with pytest.raises(NotImplementedError, match="buffer writes"):
            static.nn.cond(x.sum() > 0,
                           lambda: bn(x),
                           lambda: x)


def test_recorded_nested_cond_inside_while():
    """cond nested inside a while body records into the while's
    SUB-program (the recorder stack nests, matching the reference's
    nested sub-blocks) and replays correctly for different feeds."""
    main = static.Program()
    with static.program_guard(main):
        n = static.data("n", [], "int32")
        i = paddle.to_tensor(np.int32(0))
        s = paddle.to_tensor(np.float32(0.0))

        def body(i, s):
            # +2 on even steps, +10 on odd steps
            inc = static.nn.cond(i % 2 == 0,
                                 lambda: paddle.to_tensor(np.float32(2.0)),
                                 lambda: paddle.to_tensor(np.float32(10.0)))
            return [i + 1, s + inc]

        i_out, s_out = static.nn.while_loop(lambda i, s: i < n, body,
                                            [i, s])
    exe = static.Executor()

    def ref(k):
        return float(sum(2.0 if j % 2 == 0 else 10.0 for j in range(k)))

    for k in (4, 7):
        (iv, sv) = exe.run(main, feed={"n": np.int32(k)},
                           fetch_list=[i_out, s_out])
        assert int(iv) == k and float(sv) == ref(k), (k, sv, ref(k))
