"""Aux-subsystem tests: static Executor, GradScaler dynamic loop, profiler,
NaN/Inf debug under jit (SURVEY §5; VERDICT round-1 'test-free surface').

reference analogues: test_executor_and_use_program_cache.py,
test_grad_scaler.py / test_amp_*.py dynamic-loss-scaling asserts,
test_profiler.py, test_nan_inf.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn, static


def test_static_executor_runs_callable_jitted():
    lin = nn.Linear(4, 2)

    def program(x):
        return lin(x)

    exe = static.Executor()
    x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    (out,) = exe.run(program, feed={"x": paddle.to_tensor(x)})
    with paddle.no_grad():
        ref = lin(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_static_compiled_program_caches():
    calls = []

    def program(x):
        calls.append(1)            # traced once per signature
        return x * 2

    cp = static.CompiledProgram(program)
    exe = static.Executor()
    x = np.ones((2, 2), np.float32)
    a = exe.run(cp, feed={"x": x})
    b = exe.run(cp, feed={"x": x + 1})
    assert len(calls) == 1         # second run hit the jit cache
    np.testing.assert_allclose(a[0], 2 * x)
    np.testing.assert_allclose(b[0], 2 * (x + 1))


def test_static_executor_rejects_non_callable():
    with pytest.raises(TypeError, match="callables"):
        static.Executor().run(object())


def test_grad_scaler_dynamic_scale_update():
    from paddle_tpu.amp import GradScaler

    model = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    scaler = GradScaler(init_loss_scaling=1024.0, incr_every_n_steps=2,
                        incr_ratio=2.0, decr_ratio=0.5)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))

    # two good steps -> scale doubles once (incr_every_n_steps=2)
    for _ in range(2):
        loss = scaler.scale(model(x).sum())
        loss.backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
    assert scaler.get_loss_scaling() == 2048.0

    # a NaN gradient step: update is skipped and the scale halves
    w_before = np.asarray(model.weight._data).copy()
    bad = model(x).sum() * float("nan")
    scaler.scale(bad).backward()
    scaler.step(opt)
    scaler.update()
    opt.clear_grad()
    assert scaler.get_loss_scaling() == 1024.0
    np.testing.assert_allclose(np.asarray(model.weight._data), w_before)


def test_profiler_event_table():
    from paddle_tpu import profiler as prof

    prof.start_profiler()
    with prof.RecordEvent("my_region"):
        _ = paddle.to_tensor(np.ones((4, 4), np.float32)) * 2
    prof.stop_profiler()
    table = prof.summary()
    assert "my_region" in table and "Calls" in table


def test_trainstep_nan_check_under_jit():
    from paddle_tpu.jit.to_static import TrainStep

    model = nn.Linear(4, 2)

    def loss_fn(layer, x, y):
        return F.mse_loss(layer(x), y)

    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    step = TrainStep(model, loss_fn, opt)
    x = np.ones((2, 4), np.float32)
    y = np.zeros((2, 2), np.float32)
    paddle.set_flags({"check_nan_inf": True})
    try:
        float(step(x, y))                     # clean step passes
        x_bad = x.copy()
        x_bad[0, 0] = np.nan
        with pytest.raises(RuntimeError, match="NaN/Inf detected"):
            step(x_bad, y)
    finally:
        paddle.set_flags({"check_nan_inf": False})


def test_compilation_cache_flag_default_on(tmp_path):
    """FLAGS_compilation_cache (on by default) wires jax's persistent
    compile cache to a user cache dir; disabling returns None."""
    from paddle_tpu.core.flags import (apply_compilation_cache, get_flag,
                                       set_flags)
    assert get_flag("compilation_cache") is True
    set_flags({"compilation_cache_dir": str(tmp_path / "cc")})
    try:
        d = apply_compilation_cache()
        assert d == str(tmp_path / "cc")
        import os
        assert os.path.isdir(d)
        set_flags({"compilation_cache": False})
        assert apply_compilation_cache() is None
    finally:
        set_flags({"compilation_cache": True,
                   "compilation_cache_dir": ""})
        # restore the suite's cache dir (conftest set it at session start)
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          "/tmp/jax_test_cache")


def test_profiler_eager_op_table():
    """Per-op eager aggregation: profiled eager ops appear in summary()
    with counts (reference: per-op RecordEvent in imperative/tracer.cc)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import profiler

    x = paddle.to_tensor(np.ones((8, 8), np.float32))
    profiler.start_profiler()
    try:
        y = x * 2 + 1
        z = y.sum()
        float(z)
    finally:
        profiler.stop_profiler()
    table = profiler.summary()
    assert "op::" in table
    # hook removed after stop: no further accumulation
    before = table
    _ = x * 3
    assert profiler.summary() == before


def test_profiler_trace_save(tmp_path):
    """Trace capture writes an XPlane trace dir (device_tracer.cc:464
    analogue) usable with TensorBoard."""
    import os

    import jax
    import jax.numpy as jnp
    from paddle_tpu import profiler

    d = str(tmp_path / "trace")
    profiler.start_profiler(log_dir=d)
    try:
        jax.jit(lambda a: (a @ a).sum())(jnp.ones((64, 64))).block_until_ready()
    finally:
        profiler.stop_profiler()
    files = [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]
    assert files, "no trace files written"


def test_profile_train_step_breakdown():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn, profiler
    from paddle_tpu.jit.to_static import TrainStep
    from paddle_tpu.optimizer import SGD

    paddle.seed(0)
    model = nn.Linear(8, 4)

    def loss_fn(layer, x, y):
        return ((layer(x) - y) ** 2).mean()

    step = TrainStep(model, loss_fn, SGD(learning_rate=0.1))
    rng = np.random.default_rng(0)
    batch = (paddle.to_tensor(rng.standard_normal((16, 8)).astype(np.float32)),
             paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32)))
    br = profiler.profile_train_step(step, batch, iters=3, warmup=1)
    assert set(br) == {"compile_s", "host_ms", "dispatch_ms", "step_ms",
                       "device_ms_est"}
    assert br["compile_s"] > 0 and br["step_ms"] > 0
    assert br["device_ms_est"] >= 0


def test_profiler_chrome_trace_export(tmp_path):
    """reference: platform/device_tracer.cc GenProfile chrome timeline."""
    import json

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import profiler as prof

    prof.start_profiler()
    with prof.RecordEvent("outer_block"):
        x = paddle.to_tensor(np.ones((8, 8), np.float32))
        (x * x).sum().numpy()
    prof.stop_profiler()
    path = prof.export_chrome_tracing(str(tmp_path / "trace.json"))
    data = json.load(open(path))
    names = {e["name"] for e in data["traceEvents"]}
    assert "outer_block" in names
    assert any(n.startswith("op::") for n in names)
    for e in data["traceEvents"]:
        assert e["ph"] == "X" and e["dur"] >= 0
