"""Fleet observability plane (ISSUE 18): cross-process trace merging,
the metrics federator + its admin plane, SLO-fed incident capture with
rate limiting, windowed histogram quantiles on the timeseries ring, and
the zero-overhead contract (docs/OBSERVABILITY.md "Fleet
observability")."""

import json
import os
import sys
import threading
import urllib.error
import urllib.request

import pytest

from paddle_tpu.core.flags import flag_scope
from paddle_tpu.monitor import trace as trace_mod
from paddle_tpu.monitor.fleet import (SCRAPE_THREAD_PREFIX,
                                      FederatorConfig, FleetFederator,
                                      FleetTarget, get_federator,
                                      local_registry_target,
                                      maybe_start_from_flags,
                                      merge_fleet_traces, parse_targets)
from paddle_tpu.monitor.metrics import MetricsRegistry, lint_exposition
from paddle_tpu.monitor.timeseries import (TimeseriesRing,
                                           parse_prometheus)

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)


class ManualClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# timeseries ring: bucket series + windowed quantiles
# ---------------------------------------------------------------------------


def test_ring_snapshots_bucket_series_and_quantile():
    clock = ManualClock()
    reg = MetricsRegistry()
    ring = TimeseriesRing(clock=clock)
    h = reg.histogram("lat_seconds", "x", buckets=(0.1, 0.5, 1.0))
    h.observe(0.05)
    ring.snapshot(reg)
    clock.advance(10.0)
    for _ in range(20):
        h.observe(0.3)
    ring.snapshot(reg)
    # the bucket grid became per-le counter series
    assert ring.kind("lat_seconds_bucket") == "counter"
    assert ring.latest("lat_seconds_bucket", le="+Inf") == 21.0
    # windowed quantile: all 20 in-window observations sit in (0.1, .5]
    q50 = ring.quantile("lat_seconds", 0.5)
    assert q50 is not None and 0.0 < q50 <= 0.5
    assert ring.quantile("lat_seconds", 1.0) == pytest.approx(0.5)
    with pytest.raises(ValueError):
        ring.quantile("lat_seconds", 1.5)
    # no matching bucket series -> None, not 0.0
    assert ring.quantile("nope", 0.5) is None


def test_ring_quantile_folds_counter_resets():
    """A restarted writer (bucket counters drop) must shrink the
    window's mass, never go negative or corrupt the interpolation."""
    clock = ManualClock()
    ring = TimeseriesRing(clock=clock)

    def rows(n_count, le_counts):
        out = [{"name": "lat_seconds_bucket", "type": "counter",
                "labels": {"le": le}, "value": float(v)}
               for le, v in le_counts]
        out.append({"name": "lat_seconds_bucket", "type": "counter",
                    "labels": {"le": "+Inf"}, "value": float(n_count)})
        return out

    ring.ingest_rows(rows(100, [("0.1", 100.0)]))
    clock.advance(1.0)
    # restart: counters fall back to near zero, then 4 obs in (0.1, 1]
    ring.ingest_rows(rows(0, [("0.1", 0.0)]))
    clock.advance(1.0)
    ring.ingest_rows(rows(4, [("0.1", 0.0), ("1.0", 4.0)]))
    q = ring.quantile("lat_seconds", 0.5)
    assert q is not None and 0.0 < q <= 1.0


def test_parse_prometheus_types_histogram_suffixes():
    reg = MetricsRegistry()
    reg.histogram("h_seconds", "x", buckets=(0.5,)).observe(0.2)
    rows = parse_prometheus(reg.to_prometheus())
    by = {(r["name"], r["labels"].get("le")): r for r in rows}
    assert by[("h_seconds_bucket", "0.5")]["type"] == "counter"
    assert by[("h_seconds_count", None)]["type"] == "counter"
    assert by[("h_seconds_sum", None)]["type"] == "counter"


# ---------------------------------------------------------------------------
# trace merging
# ---------------------------------------------------------------------------


def _doc(trace_id, ctx, process, spans, parent_ctx=None, **kw):
    d = {"trace_id": trace_id, "name": spans[0]["name"], "ctx": ctx,
         "process": process,
         "head_sampled": kw.get("head_sampled", True),
         "anomaly": kw.get("anomaly"),
         "finished": kw.get("finished", True), "spans": spans}
    if parent_ctx is not None:
        d["parent_ctx"] = parent_ctx
    return d


def _span(span_id, parent_id, name, t0=0.0, t1=1.0, **attrs):
    return {"span_id": span_id, "parent_id": parent_id, "name": name,
            "t0": t0, "t1": t1, "attrs": attrs}


def test_merge_single_doc_passes_through_untouched():
    d = _doc("t1", "a.1", None, [_span(0, None, "serve.request")])
    out = merge_fleet_traces([d])
    assert out == [d] and out[0] is d
    assert out[0]["spans"][0]["span_id"] == 0    # integer ids intact


def test_merge_qualifies_ids_and_resolves_parent_ctx():
    router = _doc("t1", "a.1", "router",
                  [_span(0, None, "fleet.request"),
                   _span(1, 0, "route")])
    rep = _doc("t1", "b.9", "r0", [_span(0, None, "serve.request")],
               parent_ctx="a.1/1", finished=False, anomaly="expired")
    out = merge_fleet_traces([rep, router])    # order must not matter
    assert len(out) == 1
    doc = out[0]
    assert doc["name"] == "fleet.request"
    assert doc["merged_from"] == 2
    assert doc["processes"] == ["router", "r0"]
    assert doc["anomaly"] == "expired" and doc["finished"] is False
    by_id = {s["span_id"]: s for s in doc["spans"]}
    assert set(by_id) == {"a.1/0", "a.1/1", "b.9/0"}
    assert by_id["b.9/0"]["parent_id"] == "a.1/1"
    assert by_id["b.9/0"]["process"] == "r0"
    assert by_id["a.1/1"]["parent_id"] == "a.1/0"


def test_merge_unresolvable_parent_stays_root():
    """The upstream buffer was lost (process died before dumping): the
    orphan subtree still renders, parented at nothing."""
    a = _doc("t1", "a.1", "r0", [_span(0, None, "serve.request")],
             parent_ctx="gone.7/3")
    b = _doc("t1", "b.2", "r1", [_span(0, None, "serve.request")],
             parent_ctx="a.1/0")
    doc = merge_fleet_traces([a, b])[0]
    by_id = {s["span_id"]: s for s in doc["spans"]}
    assert by_id["a.1/0"]["parent_id"] is None
    assert by_id["b.2/0"]["parent_id"] == "a.1/0"


def test_perfetto_renders_one_pid_per_process():
    router = _doc("t1", "a.1", "router",
                  [_span(0, None, "fleet.request")])
    rep = _doc("t1", "b.9", "r0", [_span(0, None, "serve.request")],
               parent_ctx="a.1/0")
    doc = merge_fleet_traces([router, rep])[0]
    perf = trace_mod.perfetto_doc([doc], include_host_timeline=False)
    names = {e["args"]["name"] for e in perf["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"paddle_tpu.trace:router", "paddle_tpu.trace:r0"}
    slices = [e for e in perf["traceEvents"] if e.get("ph") == "X"]
    assert len({e["pid"] for e in slices}) == 2


# ---------------------------------------------------------------------------
# federation
# ---------------------------------------------------------------------------


def test_parse_targets_spec():
    ts = parse_targets("a=http://h:1, http://h2:2/ ,")
    assert [(t.name, t.url) for t in ts] \
        == [("a", "http://h:1"), ("h2:2", "http://h2:2")]
    assert parse_targets("") == []


def test_federator_rejects_bad_target_sets():
    with pytest.raises(ValueError, match="target"):
        FleetFederator([])
    t = FleetTarget("a", fetch_metrics=lambda: "")
    with pytest.raises(ValueError, match="duplicate"):
        FleetFederator([t, FleetTarget("a", fetch_metrics=lambda: "")])


def test_federator_sums_pages_under_host_labels():
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.counter("serve_requests_total", "x").inc(10, event="completed")
    r2.counter("serve_requests_total", "x").inc(5, event="completed")
    r2.gauge("serve_queue_depth", "x").set(3)
    fed = FleetFederator(
        [FleetTarget("a", fetch_metrics=r1.to_prometheus,
                     fetch_ready=lambda: True),
         FleetTarget("b", fetch_metrics=r2.to_prometheus,
                     fetch_ready=lambda: False)],
        FederatorConfig(), clock=ManualClock(100.0))
    s = fed.scrape_once()
    assert s["targets_scraped"] == 2 and s["incident"] is None
    by_host = {lb["host"]: v for lb, v in
               fed.registry.get("serve_requests_total").samples()}
    assert by_host == {"a": 10.0, "b": 5.0}
    assert sum(by_host.values()) == 15.0      # page == sum of pages
    assert fed._target_state == {"a": "ready", "b": "not_ready"}
    states = {lb["state"]: v for lb, v in
              fed.registry.get("fleet_replicas").samples()}
    assert states["ready"] == 1 and states["not_ready"] == 1
    assert lint_exposition(fed.registry.to_prometheus()) == []
    # a later scrape REBUILDS: cumulative pages never double-count
    fed.scrape_once()
    assert fed.registry.get("serve_requests_total").value(
        host="a", event="completed") == 10.0


def test_federator_scrape_error_isolates_target():
    good = MetricsRegistry()
    good.counter("serve_requests_total", "x").inc(2, event="completed")

    def boom():
        raise OSError("connection refused")

    fed = FleetFederator(
        [FleetTarget("up", fetch_metrics=good.to_prometheus,
                     fetch_ready=lambda: True),
         FleetTarget("down", fetch_metrics=boom)],
        FederatorConfig(), clock=ManualClock(1.0))
    s = fed.scrape_once()
    assert s["targets_scraped"] == 1
    assert fed._target_state["down"] == "unreachable"
    assert fed.registry.get("fleet_scrape_errors_total").value(
        host="down") == 1.0
    assert fed.registry.get("serve_requests_total").value(
        host="up", event="completed") == 2.0


def test_fleet_admin_quorum_readyz_and_statusz():
    reg = MetricsRegistry()
    reg.gauge("serve_queue_depth", "x").set(3)
    reg.counter("serve_prefix_hits_total", "x").inc(3)
    reg.counter("serve_prefix_misses_total", "x").inc(1)

    def boom():
        raise OSError("down")

    fed = FleetFederator(
        [FleetTarget("good", fetch_metrics=reg.to_prometheus,
                     fetch_ready=lambda: True),
         FleetTarget("dead", fetch_metrics=boom)],
        FederatorConfig(quorum=2), port=0)
    fed.start()
    try:
        fed.scrape_once()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(fed.url + "/readyz", timeout=10)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert body["reasons"]["fleet_quorum"]["ready"] == 1
        with urllib.request.urlopen(fed.url + "/statusz",
                                    timeout=10) as r:
            doc = json.loads(r.read())
        rows = doc["sections"]["fleet"]["targets"]
        assert rows["good"]["state"] == "ready"
        assert rows["good"]["queue_depth"] == 3.0
        assert rows["good"]["prefix_hit_pct"] == pytest.approx(75.0)
        assert rows["dead"]["state"] == "unreachable"
        with urllib.request.urlopen(fed.url + "/metrics",
                                    timeout=10) as r:
            page = r.read().decode()
        assert 'fleet_replicas{state="ready"} 1' in page
    finally:
        fed.close()
    assert not [t for t in threading.enumerate()
                if t.name.startswith(SCRAPE_THREAD_PREFIX)]


def test_fleet_admin_serves_merged_traces():
    """/debug/trace on the fleet plane returns MERGED docs — the
    router's doc and a replica doc sharing a trace_id come back as one
    tree (and ?format=perfetto renders per-process tracks)."""
    tracer = trace_mod.get_tracer()
    root = tracer.start_trace("fleet.request", process="router",
                              sample=True)
    child = tracer.start_trace("serve.request", trace_id=root.trace_id,
                               process="r0", sample=True,
                               parent=root.context_for())
    tracer.finish_trace(child)
    tracer.finish_trace(root)
    fed = FleetFederator([local_registry_target()], FederatorConfig(),
                         port=0)
    fed.start()
    try:
        with urllib.request.urlopen(fed.url + "/debug/trace",
                                    timeout=10) as r:
            doc = json.loads(r.read())
        merged = [t for t in doc["traces"]
                  if t.get("trace_id") == root.trace_id]
        assert len(merged) == 1
        assert merged[0]["merged_from"] == 2
        assert merged[0]["processes"] == ["router", "r0"]
    finally:
        fed.close()


# ---------------------------------------------------------------------------
# incident capture
# ---------------------------------------------------------------------------


def test_incident_capture_rate_limited(tmp_path):
    reg = MetricsRegistry()
    fed = FleetFederator(
        [FleetTarget("a", fetch_metrics=reg.to_prometheus)],
        FederatorConfig(incident_dir=str(tmp_path),
                        incident_min_interval_s=300.0),
        clock=ManualClock(1000.0))
    fed.scrape_once()
    d1 = fed.capture_incident("slo_burn", t=1000.0)
    assert d1 is not None and os.path.isdir(d1)
    assert fed.capture_incident("anomaly_trace", t=1100.0) is None
    d3 = fed.capture_incident("anomaly_trace", t=1400.0)
    assert d3 is not None
    trig = {lb["trigger"]: v for lb, v in
            fed._own.get("fleet_incidents_total").samples()}
    assert trig == {"slo_burn": 1.0, "anomaly_trace": 1.0}
    assert fed.incidents == [d1, d3]
    for d in (d1, d3):
        files = set(os.listdir(d))
        assert {"incident.json", "statusz.json",
                "metrics.prom"} <= files


def test_incident_capture_off_without_dir(tmp_path):
    fed = FleetFederator(
        [FleetTarget("a", fetch_metrics=MetricsRegistry()
                     .to_prometheus)],
        FederatorConfig(), clock=ManualClock(1.0))
    assert fed.capture_incident("slo_burn") is None
    assert fed.incidents == []


def test_anomaly_trace_triggers_incident(tmp_path):
    """A tail-retained anomaly trace (the tracer kept an unsampled
    trace because something went wrong) triggers one bundle on the next
    scrape."""
    fed = FleetFederator(
        [FleetTarget("a", fetch_metrics=MetricsRegistry()
                     .to_prometheus)],
        FederatorConfig(incident_dir=str(tmp_path)),
        clock=ManualClock(50.0))
    fed.scrape_once()
    tracer = trace_mod.get_tracer()
    tr = tracer.start_trace("serve.request", sample=False)
    tr.mark_anomaly("watchdog")
    tracer.finish_trace(tr)
    s = fed.scrape_once()
    assert s["anomalies"] == 1
    assert s["incident"] is not None \
        and s["incident"].endswith("anomaly_trace")
    # steady state: no new anomaly, no new bundle wanted
    fed.config.incident_min_interval_s = 0.0
    assert fed.scrape_once()["incident"] is None


# ---------------------------------------------------------------------------
# SLO feed over federated counters
# ---------------------------------------------------------------------------


def test_slo_feeds_from_federated_deltas_with_reset_folding():
    reg = MetricsRegistry()
    c = reg.counter("serve_requests_total", "x")
    c.inc(90, event="completed")
    c.inc(10, event="failed")
    clock = ManualClock(0.0)
    fed = FleetFederator(
        [FleetTarget("a", fetch_metrics=reg.to_prometheus)],
        FederatorConfig(slo_availability=0.99,
                        slo_windows=(60.0, 600.0),
                        alert_pairs=((600.0, 60.0, 1.0),)),
        clock=clock)
    s = fed.scrape_once()
    assert fed.slo.total_good == 90 and fed.slo.total_bad == 10
    assert s["alerts"]                      # 10% bad on a 1% budget
    # replica restart: counters shrink; the fold records only the
    # post-reset baseline, never a negative delta
    reg.clear()
    reg.counter("serve_requests_total", "x").inc(3, event="completed")
    clock.advance(10.0)
    fed.scrape_once()
    assert fed.slo.total_good == 93 and fed.slo.total_bad == 10
    # burn gauges rode into the federated page
    assert fed.registry.get("slo_burn_rate") is not None


# ---------------------------------------------------------------------------
# flag gating / zero overhead
# ---------------------------------------------------------------------------


def test_fleet_plane_zero_overhead_when_off():
    assert maybe_start_from_flags() is None
    assert get_federator() is None
    assert not [t for t in threading.enumerate()
                if t.name.startswith(SCRAPE_THREAD_PREFIX)]


def test_maybe_start_from_flags_ephemeral_port():
    with flag_scope("fleet_monitor_port", -1), \
            flag_scope("fleet_monitor_interval_s", 30.0):
        fed = maybe_start_from_flags()
        assert fed is not None and fed.running
        assert fed.url is not None
        assert maybe_start_from_flags() is fed     # idempotent
        # default targets: the local process registry under one host
        assert [t.name for t in fed.targets] == ["fleet"]
        fed.scrape_once()
        with urllib.request.urlopen(fed.url + "/metrics",
                                    timeout=10) as r:
            page = r.read().decode()
        assert "fleet_scrapes_total" in page
    # the autouse _fleet_monitor_isolation fixture tears it down


# ---------------------------------------------------------------------------
# monitor_top --fleet pane
# ---------------------------------------------------------------------------


def test_monitor_top_fleet_pane():
    import monitor_top
    clock = ManualClock()
    ring = TimeseriesRing(clock=clock)
    reg = MetricsRegistry()
    reg.counter("serve_tokens_generated_total", "x").inc(100, host="r0")
    reg.counter("serve_tokens_generated_total", "x").inc(40, host="r1")
    reg.gauge("serve_queue_depth", "x").set(4, host="r0")
    reg.gauge("serve_overload", "x").set(1, host="r1")
    reg.gauge("fleet_replicas", "x").set(2, state="ready")
    ring.ingest_rows(parse_prometheus(reg.to_prometheus()))
    clock.advance(2.0)
    reg.counter("serve_tokens_generated_total", "x").inc(60, host="r0")
    ring.ingest_rows(parse_prometheus(reg.to_prometheus()))
    frame = monitor_top.render_frame(ring, "http://f/metrics",
                                     fleet=True)
    assert "replica" in frame and "r0" in frame and "r1" in frame
    assert "30.0" in frame                      # r0: 60 tokens over 2s
    assert "OVERLOADED" in frame                # r1's state column
    assert "ready 2" in frame


def test_monitor_top_fleet_pane_empty_without_host_labels():
    import monitor_top
    ring = TimeseriesRing(clock=ManualClock())
    reg = MetricsRegistry()
    reg.counter("serve_tokens_generated_total", "x").inc(5)
    ring.ingest_rows(parse_prometheus(reg.to_prometheus()))
    assert monitor_top.render_fleet_pane(ring) == []
