"""Cross-framework training parity vs torch (CPU build baked into the
image) — the BASELINE criterion is "loss-curve parity vs the GPU
reference"; torch serves as the independent numerical oracle.

Weights are COPIED (not re-initialized) into structurally identical torch
models; then both sides train with plain SGD on identical data and the
loss curves must track within f32 tolerance.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as paddle  # noqa: E402
import paddle_tpu.nn.functional as F  # noqa: E402
from paddle_tpu import nn  # noqa: E402


def test_mlp_classifier_loss_curve_matches_torch():
    rng = np.random.RandomState(0)
    D, H, C, B = 16, 32, 4, 8
    x_np = rng.randn(B, D).astype(np.float32)
    y_np = rng.randint(0, C, (B,)).astype(np.int64)

    paddle.seed(0)
    ours = nn.Sequential(nn.Linear(D, H), nn.Tanh(), nn.Linear(H, C))
    theirs = torch.nn.Sequential(torch.nn.Linear(D, H), torch.nn.Tanh(),
                                 torch.nn.Linear(H, C))
    # copy weights ours -> torch (our Linear weight is [in, out])
    with torch.no_grad():
        theirs[0].weight.copy_(torch.tensor(
            np.asarray(ours[0].weight._data).T))
        theirs[0].bias.copy_(torch.tensor(np.asarray(ours[0].bias._data)))
        theirs[2].weight.copy_(torch.tensor(
            np.asarray(ours[2].weight._data).T))
        theirs[2].bias.copy_(torch.tensor(np.asarray(ours[2].bias._data)))

    opt_o = paddle.optimizer.SGD(learning_rate=0.1,
                                 parameters=ours.parameters())
    opt_t = torch.optim.SGD(theirs.parameters(), lr=0.1)

    ours_losses, torch_losses = [], []
    xt = torch.tensor(x_np)
    yt = torch.tensor(y_np)
    for _ in range(20):
        loss = F.cross_entropy(ours(paddle.to_tensor(x_np)),
                               paddle.to_tensor(y_np))
        loss.backward()
        opt_o.step()
        opt_o.clear_grad()
        ours_losses.append(float(loss))

        tl = torch.nn.functional.cross_entropy(theirs(xt), yt)
        opt_t.zero_grad()
        tl.backward()
        opt_t.step()
        torch_losses.append(float(tl))

    np.testing.assert_allclose(ours_losses, torch_losses, rtol=2e-4,
                               atol=2e-5)


def test_transformer_encoder_layer_forward_matches_torch():
    # one encoder layer, weights copied, same input -> same output
    rng = np.random.RandomState(1)
    D, Hh, FF, B, S = 16, 4, 32, 2, 10
    x_np = rng.randn(B, S, D).astype(np.float32)

    paddle.seed(1)
    ours = nn.TransformerEncoderLayer(D, Hh, FF, dropout=0.0,
                                      activation="relu", attn_dropout=0.0,
                                      act_dropout=0.0,
                                      normalize_before=False)
    ours.eval()
    theirs = torch.nn.TransformerEncoderLayer(
        D, Hh, dim_feedforward=FF, dropout=0.0, activation="relu",
        batch_first=True, norm_first=False)
    theirs.eval()

    def t(a):
        return torch.tensor(np.asarray(a))

    with torch.no_grad():
        sa = ours.self_attn
        wq = np.asarray(sa.q_proj.weight._data)   # [D, D] in->out
        wk = np.asarray(sa.k_proj.weight._data)
        wv = np.asarray(sa.v_proj.weight._data)
        theirs.self_attn.in_proj_weight.copy_(
            t(np.concatenate([wq.T, wk.T, wv.T], axis=0)))
        theirs.self_attn.in_proj_bias.copy_(t(np.concatenate([
            np.asarray(sa.q_proj.bias._data),
            np.asarray(sa.k_proj.bias._data),
            np.asarray(sa.v_proj.bias._data)])))
        theirs.self_attn.out_proj.weight.copy_(
            t(np.asarray(sa.out_proj.weight._data).T))
        theirs.self_attn.out_proj.bias.copy_(
            t(np.asarray(sa.out_proj.bias._data)))
        theirs.linear1.weight.copy_(t(np.asarray(ours.linear1.weight._data).T))
        theirs.linear1.bias.copy_(t(np.asarray(ours.linear1.bias._data)))
        theirs.linear2.weight.copy_(t(np.asarray(ours.linear2.weight._data).T))
        theirs.linear2.bias.copy_(t(np.asarray(ours.linear2.bias._data)))
        theirs.norm1.weight.copy_(t(np.asarray(ours.norm1.weight._data)))
        theirs.norm1.bias.copy_(t(np.asarray(ours.norm1.bias._data)))
        theirs.norm2.weight.copy_(t(np.asarray(ours.norm2.weight._data)))
        theirs.norm2.bias.copy_(t(np.asarray(ours.norm2.bias._data)))

    with paddle.no_grad():
        got = ours(paddle.to_tensor(x_np)).numpy()
    with torch.no_grad():
        ref = theirs(torch.tensor(x_np)).numpy()
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_layernorm_gelu_softmax_semantics_match_torch():
    rng = np.random.RandomState(2)
    x = rng.randn(4, 12).astype(np.float32)
    np.testing.assert_allclose(
        F.gelu(paddle.to_tensor(x)).numpy(),
        torch.nn.functional.gelu(torch.tensor(x)).numpy(),
        rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        F.gelu(paddle.to_tensor(x), approximate=True).numpy(),
        torch.nn.functional.gelu(torch.tensor(x), approximate="tanh")
        .numpy(), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        F.softmax(paddle.to_tensor(x), axis=-1).numpy(),
        torch.softmax(torch.tensor(x), dim=-1).numpy(),
        rtol=1e-5, atol=1e-6)
    ln = nn.LayerNorm(12)
    tln = torch.nn.LayerNorm(12)
    with torch.no_grad():
        tln.weight.copy_(torch.tensor(np.asarray(ln.weight._data)))
        tln.bias.copy_(torch.tensor(np.asarray(ln.bias._data)))
    np.testing.assert_allclose(
        ln(paddle.to_tensor(x)).numpy(),
        tln(torch.tensor(x)).detach().numpy(), rtol=1e-5, atol=1e-5)
