"""Custom-operator extension tests.

reference analogues: tests/custom_op/test_custom_relu_op_setup.py (build
custom_relu_op.cc, run fwd/bwd vs paddle.nn.functional.relu) and the
PD_BUILD_OP registration checks.
"""

import textwrap

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.utils import cpp_extension

RELU_CC = textwrap.dedent("""
    #include <cstdint>
    extern "C" {
    void custom_relu(const float* x, float* y, int64_t n) {
      for (int64_t i = 0; i < n; ++i) y[i] = x[i] > 0.f ? x[i] : 0.f;
    }
    void custom_relu_grad(const float* x, const float* gy, float* gx,
                          int64_t n) {
      for (int64_t i = 0; i < n; ++i) gx[i] = x[i] > 0.f ? gy[i] : 0.f;
    }
    }
""")


@pytest.fixture(scope="module")
def relu_ext(tmp_path_factory):
    d = tmp_path_factory.mktemp("ext")
    src = d / "custom_relu.cc"
    src.write_text(RELU_CC)
    return cpp_extension.load("custom_relu_mod", [str(src)],
                              functions=["custom_relu"],
                              build_directory=str(d))


def test_cpp_op_forward(relu_ext):
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    y = relu_ext.custom_relu(paddle.to_tensor(x))
    np.testing.assert_allclose(y.numpy(), np.maximum(x, 0), rtol=1e-6)


def test_cpp_op_backward(relu_ext):
    x = paddle.to_tensor(np.random.RandomState(1).randn(3, 3)
                         .astype(np.float32))
    x.stop_gradient = False
    relu_ext.custom_relu(x).sum().backward()
    g = np.asarray(x.grad._data)
    np.testing.assert_allclose(g, (np.asarray(x._data) > 0)
                               .astype(np.float32), rtol=1e-6)


def test_cpp_op_inside_jit(relu_ext):
    import jax
    import jax.numpy as jnp
    # pure_callback keeps the host op usable under jit
    f = jax.jit(lambda a: relu_ext.custom_relu(
        paddle.to_tensor(a))._data * 2)
    out = f(jnp.asarray(np.array([-1.0, 2.0], np.float32)))
    np.testing.assert_allclose(np.asarray(out), [0.0, 4.0], rtol=1e-6)


def test_register_python_op_with_custom_vjp():
    import jax.numpy as jnp

    # clipped-square with a deliberately custom gradient (2x everywhere,
    # ignoring the clip) to prove the custom vjp is used
    myop = cpp_extension.register_op(
        "clip_sq",
        lambda x: jnp.clip(x, -1, 1) ** 2,
        vjp=lambda primals, g: (2.0 * primals[0] * g,))
    x = paddle.to_tensor(np.array([0.5, 3.0], np.float32))
    x.stop_gradient = False
    y = myop(x)
    np.testing.assert_allclose(y.numpy(), [0.25, 1.0], rtol=1e-6)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._data), [1.0, 6.0],
                               rtol=1e-6)


def test_register_op_default_autodiff():
    import jax.numpy as jnp
    myop = cpp_extension.register_op("cube", lambda x: x ** 3)
    x = paddle.to_tensor(np.array([2.0], np.float32))
    x.stop_gradient = False
    myop(x).backward()
    np.testing.assert_allclose(np.asarray(x.grad._data), [12.0], rtol=1e-6)
