"""nn.quant fake-quant layers + nn.utils reparametrizations
(reference: nn/quant/quant_layers.py, nn/utils/weight_norm_hook.py)."""

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.nn import quant as Q


def test_fake_quant_absmax_forward_and_ste_grad():
    x = paddle.to_tensor(np.linspace(-1, 1, 32).astype(np.float32))
    x.stop_gradient = False
    fq = Q.FakeQuantAbsMax(quant_bits=8)
    y = fq(x)
    # quantized to the 8-bit grid of absmax=1
    grid = np.round(np.linspace(-1, 1, 32) * 127) / 127
    np.testing.assert_allclose(y.numpy(), grid.astype(np.float32),
                               atol=1e-6)
    y.sum().backward()
    np.testing.assert_allclose(np.asarray(x.grad._data), 1.0)  # STE


def test_fake_quant_channel_wise():
    w = paddle.to_tensor(np.stack([np.linspace(-1, 1, 8),
                                   np.linspace(-4, 4, 8)]).astype(np.float32))
    fq = Q.FakeQuantChannelWiseAbsMax(quant_axis=0)
    y = fq(w).numpy()
    assert abs(y[0].max() - 1.0) < 1e-3 and abs(y[1].max() - 4.0) < 1e-2
    # each channel keeps its own scale: row 1 error 4x row 0 error
    assert np.abs(y[1] - w.numpy()[1]).max() <= 4 / 127 + 1e-6


def test_moving_average_fake_quant_updates_in_train_only():
    fq = Q.FakeQuantMovingAverageAbsMax(moving_rate=0.5)
    x = paddle.to_tensor(np.full((4,), 2.0, np.float32))
    fq.train()
    fq(x)
    s1 = float(fq.scale._data)
    assert s1 > 1.0                      # moved toward absmax=2
    fq.eval()
    fq(paddle.to_tensor(np.full((4,), 100.0, np.float32)))
    assert float(fq.scale._data) == s1   # frozen in eval


def test_quantized_linear_and_conv_wrappers_train():
    paddle.seed(0)
    lin = nn.Linear(16, 8)
    qlin = Q.QuantizedLinear(lin)
    x = paddle.to_tensor(np.random.default_rng(0)
                         .normal(size=(4, 16)).astype(np.float32))
    ref = lin(x).numpy()
    qlin.train()
    for _ in range(30):      # warm the moving-average activation range
        qlin(x)
    out = qlin(x).numpy()
    assert np.abs(out - ref).max() < 0.15 * np.abs(ref).max() + 1e-3

    conv = nn.Conv2D(3, 6, 3)
    qconv = Q.QuantizedConv2D(conv)
    xi = paddle.to_tensor(np.random.default_rng(1)
                          .normal(size=(2, 3, 8, 8)).astype(np.float32))
    refc = conv(xi).numpy()
    qconv.train()
    for _ in range(30):
        qconv(xi)
    outc = qconv(xi).numpy()
    assert np.abs(outc - refc).max() < 0.15 * np.abs(refc).max() + 1e-3


def test_output_scale_layers():
    lin = nn.Linear(4, 4)
    wrapped = Q.MAOutputScaleLayer(lin)
    wrapped.train()
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    out = wrapped(x)
    np.testing.assert_allclose(out.numpy(), lin(x).numpy())  # observe only
    assert float(wrapped._scale.scale._data) != 1.0  # EMA actually moved


def test_weight_norm_roundtrip():
    paddle.seed(1)
    lin = nn.Linear(8, 4)
    w0 = np.asarray(lin.weight._data).copy()
    nn.utils.weight_norm(lin, dim=0)
    names = dict(lin.named_parameters())
    assert "weight_g" in names and "weight_v" in names \
        and "weight" not in names
    x = paddle.to_tensor(np.random.default_rng(2)
                         .normal(size=(3, 8)).astype(np.float32))
    out1 = lin(x).numpy()
    # reconstruction: g*v/||v|| == original weight right after wrapping
    ref = x.numpy() @ w0 + np.asarray(lin.bias._data)
    np.testing.assert_allclose(out1, ref, atol=1e-5, rtol=1e-5)
    # g is trainable: grads flow to g and v, not to a dense weight
    loss = lin(x).sum()
    loss.backward()
    assert lin.weight_g.grad is not None and lin.weight_v.grad is not None
    nn.utils.remove_weight_norm(lin)
    names = dict(lin.named_parameters())
    assert "weight" in names and "weight_g" not in names
    np.testing.assert_allclose(lin(x).numpy(), ref, atol=1e-5, rtol=1e-5)


def test_parameters_vector_roundtrip():
    paddle.seed(2)
    lin = nn.Linear(6, 3)
    vec = nn.utils.parameters_to_vector(lin.parameters())
    assert vec.shape[0] == 6 * 3 + 3
    new = [p for p in nn.Linear(6, 3).parameters()]
    nn.utils.vector_to_parameters(vec, new)
    for a, b in zip(lin.parameters(), new):
        np.testing.assert_allclose(np.asarray(a._data),
                                   np.asarray(b._data))


def test_spectral_norm_bounds_sigma():
    paddle.seed(3)
    lin = nn.Linear(12, 12)
    lin.weight._data = lin.weight._data * 10.0     # big spectral norm
    nn.utils.spectral_norm(lin, n_power_iterations=5)
    w = np.asarray(lin.weight._data if hasattr(lin.weight, "_data")
                   else lin.weight)
    sigma = np.linalg.svd(w, compute_uv=False)[0]
    assert abs(sigma - 1.0) < 0.2, sigma
