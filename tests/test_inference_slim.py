"""Inference engine (Config/create_predictor) + slim quantization
(reference: inference/api/analysis_predictor.cc, contrib/slim)."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import inference, slim
from paddle_tpu.jit.input_spec import InputSpec
from paddle_tpu.nn import Linear


class MLP(paddle.nn.Layer):
    def __init__(self, din=64, dh=128, dout=10):
        super().__init__()
        self.fc1 = Linear(din, dh)
        self.fc2 = Linear(dh, dout)

    def forward(self, x):
        from paddle_tpu.nn import functional as F
        return self.fc2(F.relu(self.fc1(x)))


def _x(b=4, d=64, seed=0):
    return np.random.default_rng(seed).normal(size=(b, d)).astype(np.float32)


def test_predictor_from_saved_export(tmp_path):
    paddle.seed(0)
    model = MLP()
    ref = model(paddle.to_tensor(_x())).numpy()
    from paddle_tpu.jit.to_static import save as jsave
    jsave(model, str(tmp_path / "m"), input_spec=[InputSpec((4, 64),
                                                            "float32")])
    cfg = inference.Config(str(tmp_path / "m"))
    pred = inference.create_predictor(cfg)
    # zero-copy handle surface
    assert pred.get_input_names() == ["x0"]
    h = pred.get_input_handle("x0")
    h.copy_from_cpu(_x())
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
    # direct surface
    outs = pred.run([_x()])
    np.testing.assert_allclose(outs[0], ref, atol=1e-5, rtol=1e-5)


def test_predictor_from_layer_bf16_and_int8(tmp_path):
    paddle.seed(1)
    model = MLP()
    x = _x(seed=3)
    ref = model(paddle.to_tensor(x)).numpy()

    cfg = inference.Config.from_layer(model, [InputSpec((4, 64), "float32")])
    cfg.enable_tpu_bf16()
    cfg.enable_int8()
    pred = inference.create_predictor(cfg)
    out = pred.run([x])[0]
    # quantized+bf16: close but not bitwise
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert rel < 0.1, rel
    # the layer really got quantized in place
    assert type(model.fc1).__name__ == "QuantizedLinear"
    # optimized re-export loads as a plain predictor
    pred.save_optimized_model(str(tmp_path / "opt"))
    pred2 = inference.create_predictor(inference.Config(str(tmp_path /
                                                            "opt")))
    out2 = pred2.run([x])[0]
    np.testing.assert_allclose(out2, out, atol=2e-2, rtol=2e-2)


def test_weight_only_quant_accuracy():
    paddle.seed(2)
    model = MLP(128, 256, 16)
    x = _x(8, 128, seed=5)
    ref = model(paddle.to_tensor(x)).numpy()
    n = slim.quantize_weights(model, min_params=1)
    assert n == 2
    out = model(paddle.to_tensor(x)).numpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert rel < 0.05, rel     # int8 per-channel: ~1% typical
    # int8 buffers actually stored
    assert str(model.fc1.weight_q.dtype) == "int8"


def test_static_ptq_runs_int8_matmul():
    paddle.seed(3)
    model = MLP(64, 128, 10)
    x = _x(16, 64, seed=7)
    ref = model(paddle.to_tensor(x)).numpy()
    ptq = slim.PostTrainingQuantization(model, min_params=1)
    for s in range(4):
        ptq.collect(paddle.to_tensor(_x(16, 64, seed=s)))
    q = ptq.run()
    assert q.fc1.act_scale is not None and q.fc1.act_scale > 0
    out = q(paddle.to_tensor(x)).numpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert rel < 0.15, rel     # full int8 act x weight path


def test_qat_trains_and_converts():
    paddle.seed(4)
    model = MLP(32, 64, 4)
    qat = slim.QAT(min_params=1)
    qat.quantize(model)
    assert type(model.fc1).__name__ == "_QATLinear"
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=model.parameters())
    x = paddle.to_tensor(_x(16, 32, seed=9))
    y = paddle.to_tensor(np.zeros((16,), np.int64))
    from paddle_tpu.nn import functional as F
    losses = []
    for _ in range(20):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses[::5]
    ref = model(x).numpy()
    qat.convert(model)
    assert type(model.fc1).__name__ == "QuantizedLinear"
    out = model(x).numpy()
    rel = np.abs(out - ref).max() / (np.abs(ref).max() + 1e-6)
    assert rel < 0.1, rel      # QAT-trained weights survive real quant


def test_static_save_load_inference_model(tmp_path):
    paddle.seed(5)
    model = MLP()
    x = _x(seed=11)
    ref = model(paddle.to_tensor(x)).numpy()
    path = paddle.static.save_inference_model(
        str(tmp_path / "infer"), [InputSpec((4, 64), "float32")], model)
    prog, feeds, fetches = paddle.static.load_inference_model(path)
    assert feeds == ["x0"] and fetches == ["out0"]
    out = prog(x)
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-5, rtol=1e-5)


def test_executor_runs_loaded_inference_model(tmp_path):
    """The documented Executor.run(program, feed=...) path (keyword feeds
    into a TranslatedLayer)."""
    paddle.seed(6)
    model = MLP()
    x = _x(seed=13)
    ref = model(paddle.to_tensor(x)).numpy()
    path = paddle.static.save_inference_model(
        str(tmp_path / "exe"), [InputSpec((4, 64), "float32")], model)
    prog, feeds, _ = paddle.static.load_inference_model(path)
    exe = paddle.static.Executor()
    outs = exe.run(prog, feed={feeds[0]: x})
    np.testing.assert_allclose(outs[0], ref, atol=1e-5, rtol=1e-5)
