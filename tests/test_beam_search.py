"""BeamSearchDecoder + dynamic_decode + gather_tree
(reference: fluid/layers/rnn.py:866,1583, operators/gather_tree_op.cc)."""

import itertools

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn


class ToyLM(paddle.nn.Layer):
    """Deterministic 'cell': logits depend only on the previous token
    (a first-order Markov LM) — lets us brute-force the best sequence."""

    def __init__(self, table):
        super().__init__()
        self.register_buffer("table", paddle.Tensor(table))

    def forward(self, inputs, states):
        # inputs: [B*beam] int token ids wrapped in Tensor; states: counter
        import jax.numpy as jnp
        from paddle_tpu.core.tensor import apply
        logits = apply(lambda t, i: jnp.take(t, i.astype(jnp.int32), axis=0),
                       self.table, inputs, name="toylm")
        return logits, states


def _brute_force(table, start, end, steps):
    V = table.shape[0]
    best, best_seq = -1e30, None
    logp = np.log(np.exp(table) / np.exp(table).sum(-1, keepdims=True))
    for seq in itertools.product(range(V), repeat=steps):
        s, prev, done = 0.0, start, False
        for t in seq:
            if done:
                if t != end:
                    s = -1e30
                    break
                continue
            s += logp[prev, t]
            prev = t
            if t == end:
                done = True
        if s > best:
            best, best_seq = s, seq
    return best, list(best_seq)


def test_beam_search_finds_optimal_markov_path():
    rng = np.random.default_rng(0)
    V, steps, beam = 5, 4, 5      # beam == V: exact search on a Markov LM
    table = rng.normal(size=(V, V)).astype(np.float32) * 2.0
    cell = ToyLM(table)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                               beam_size=beam)
    import jax.numpy as jnp
    inits = jnp.zeros((2, 1), jnp.float32)      # dummy per-batch state
    out, final = nn.dynamic_decode(dec, inits, max_step_num=steps)
    ids = out.numpy()                           # [B, T, beam]
    assert ids.shape == (2, steps, beam)
    bs, bseq = _brute_force(table, 0, 1, steps)
    # top beam (index 0) must equal the brute-force optimum for batch 0
    got = ids[0, :, 0].tolist()
    # trim to the brute-force convention (eos-extended)
    assert got == bseq, (got, bseq)
    np.testing.assert_allclose(float(np.asarray(final.log_probs)[0, 0]),
                               bs, rtol=1e-4)


def test_beam_search_with_rnn_cell_and_embedding():
    paddle.seed(0)
    V, H, beam, steps, B = 16, 8, 3, 6, 2
    emb = nn.Embedding(V, H)
    cell = nn.GRUCell(H, H)
    proj = nn.Linear(H, V)
    dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                               beam_size=beam, embedding_fn=emb,
                               output_fn=proj)
    import jax.numpy as jnp
    h0 = jnp.zeros((B, H), jnp.float32)
    out, final, lens = nn.dynamic_decode(dec, h0, max_step_num=steps,
                                         return_length=True)
    assert out.numpy().shape == (B, steps, beam)
    assert np.asarray(final.log_probs).shape == (B, beam)
    assert lens.numpy().shape == (B, beam)
    # scores sorted descending across beams
    lp = np.asarray(final.log_probs)
    assert (np.diff(lp, axis=1) <= 1e-5).all()


def test_gather_tree_backtrace():
    # T=3, B=1, beam=2: paths stored with parent pointers
    ids = paddle.to_tensor(np.array(
        [[[2, 3]], [[4, 5]], [[6, 7]]], np.int64))
    parents = paddle.to_tensor(np.array(
        [[[0, 0]], [[0, 0]], [[1, 0]]], np.int64))
    out = nn.gather_tree(ids, parents).numpy()
    # final beam 0 came from parent 1 at t=2: path 2(t0,p0) 5(t1) 6(t2)
    assert out[:, 0, 0].tolist() == [2, 5, 6]
    assert out[:, 0, 1].tolist() == [2, 4, 7]
