"""DataLoader multiprocess path through the native C++ blocking queue.

reference analogue: test_multiprocess_dataloader_static/dynamic.py —
worker processes + blocking-queue transport deliver every batch exactly
once, in order, including error propagation.
"""

import numpy as np
import pytest

from paddle_tpu.io.dataloader import DataLoader
from paddle_tpu.io.dataset import Dataset


class _Range(Dataset):
    def __init__(self, n=64):
        self.n = n

    def __getitem__(self, i):
        return (np.full((4,), i, np.float32), np.int64(i % 4))

    def __len__(self):
        return self.n


class _Faulty(_Range):
    def __getitem__(self, i):
        if i == 13:
            raise ValueError("poison sample")
        return super().__getitem__(i)


def test_multiworker_through_native_queue():
    from paddle_tpu.io.native_queue import native_available

    dl = DataLoader(_Range(64), batch_size=8, num_workers=2, shuffle=False,
                    use_buffer_reader=False)
    it = iter(dl)
    if native_available():
        # the native path actually engaged
        assert it.it._native_q is not None
    batches = list(it)
    assert len(batches) == 8
    xs = np.concatenate([b[0].numpy() for b in batches])
    # in-order, exactly-once delivery
    np.testing.assert_array_equal(xs[:, 0], np.arange(64, dtype=np.float32))


def test_worker_exception_propagates():
    dl = DataLoader(_Faulty(32), batch_size=8, num_workers=2,
                    use_buffer_reader=False)
    with pytest.raises(ValueError, match="poison"):
        list(iter(dl))


def test_shared_memory_disabled_falls_back():
    dl = DataLoader(_Range(16), batch_size=4, num_workers=1,
                    use_shared_memory=False, use_buffer_reader=False)
    it = iter(dl)
    assert it.it._native_q is None
    assert len(list(it)) == 4
