"""Elastic manager + restartable-training tests.

reference analogue: test_fleet_elastic_manager.py (watch-state
classification) + the restart model of fleet/elastic/manager.py; here the
resume path is TrainStep checkpoints, verified to continue mid-training.
"""

import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                  ElasticStatus, run_elastic)


def test_watch_states(tmp_path):
    mgr = ElasticManager(root=str(tmp_path), rank=0, np_=2, min_np=1,
                         max_np=2, timeout=60)
    # nobody alive -> ERROR
    assert mgr.watch() == ElasticStatus.ERROR
    # self alive only (np=2, min=1) -> RESTART (degraded but viable)
    mgr.beat()
    assert mgr.watch() == ElasticStatus.RESTART
    # both alive -> HOLD
    other = ElasticManager(root=str(tmp_path), rank=1, np_=2, min_np=1,
                           max_np=2, timeout=60)
    other.beat()
    assert mgr.watch() == ElasticStatus.HOLD
    assert mgr.alive_workers() == [0, 1]
    # completion marker wins
    mgr.mark_completed()
    assert mgr.watch() == ElasticStatus.COMPLETED


def test_stale_heartbeat_detected(tmp_path):
    mgr = ElasticManager(root=str(tmp_path), rank=0, np_=1, min_np=1,
                         max_np=1, timeout=0.0)      # everything is stale
    mgr.beat()
    assert mgr.alive_workers() == []
    assert mgr.watch() == ElasticStatus.ERROR


def test_run_elastic_resumes_from_checkpoint(tmp_path):
    from paddle_tpu.jit.to_static import TrainStep

    ckpt = str(tmp_path / "ck.pkl")
    mgr = ElasticManager(root=str(tmp_path / "hb"), rank=0, np_=1,
                         min_np=1, max_np=1)
    crash_at = {"step": 4}
    seen = {"resumes": [], "steps": []}

    x = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 4, (8,)).astype(np.int64)

    def train(resume):
        seen["resumes"].append(resume is not None)
        paddle.seed(3)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        step = TrainStep(model, lambda l, a, b: F.cross_entropy(l(a), b),
                         paddle.optimizer.Adam(
                             learning_rate=1e-2,
                             parameters=model.parameters()))
        if resume:
            step.load(resume)
        while step.step_count < 8:
            loss = float(step(x, y))
            seen["steps"].append(step.step_count)
            step.save(ckpt)
            if step.step_count == crash_at["step"] and crash_at["step"]:
                crash_at["step"] = 0          # crash exactly once
                raise RuntimeError("injected worker failure")
        return float(loss)

    final = run_elastic(train, ckpt, max_restarts=2, manager=mgr)
    assert np.isfinite(final)
    # first attempt cold, second resumed from the step-4 checkpoint
    assert seen["resumes"] == [False, True]
    assert seen["steps"] == [1, 2, 3, 4, 5, 6, 7, 8]


def test_run_elastic_gives_up_after_max_restarts(tmp_path):
    mgr = ElasticManager(root=str(tmp_path / "hb"), rank=0, np_=1,
                         min_np=1, max_np=1)

    def always_fail(resume):
        raise RuntimeError("boom")

    with pytest.raises(RuntimeError, match="boom"):
        run_elastic(always_fail, str(tmp_path / "none.pkl"),
                    max_restarts=1, manager=mgr)
